"""Word error rate. Extension beyond the reference snapshot (later
torchmetrics ships ``WER``).

Two evaluation paths:

- ``wer(preds, target)``: host API over strings / token lists (tokenization
  is host work regardless), numpy DP.
- ``edit_distance_padded(pred_ids, target_ids, pred_len, target_len)``: a
  device-evaluable batched Levenshtein kernel — the DP recurrence runs as a
  ``lax.scan`` over the padded target axis with the row as carry, so a whole
  batch of sequences evaluates in one fused XLA program (vmap over the batch).
"""
from typing import Callable, List, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

TokenSeq = Union[str, Sequence[str]]


def _tokens(x: TokenSeq) -> List[str]:
    return x.split() if isinstance(x, str) else list(x)


def _np_edit_distance(a: List[str], b: List[str]) -> int:
    """Host DP (numpy row recurrence)."""
    if not a:
        return len(b)
    b_arr = np.array(b)
    prev = np.arange(len(b) + 1)
    for i, tok in enumerate(a, 1):
        cur = np.empty(len(b) + 1, dtype=np.int64)
        cur[0] = i
        sub = prev[:-1] + (b_arr != tok)
        for j in range(1, len(b) + 1):
            cur[j] = min(prev[j] + 1, cur[j - 1] + 1, sub[j - 1])
        prev = cur
    return int(prev[-1])


def _np_edit_distance_hits(a: List[str], b: List[str]) -> Tuple[int, int]:
    """(edit distance, aligned matches) via a host DP.

    ``hits`` is the number of matched tokens in a minimum-edit alignment,
    maximized over all minimum-distance alignments (a deterministic
    definition; jiwer-style MER/WIP derive from exactly these two numbers:
    ``S + D = len(b) - hits``, ``I = dist - S - D``).
    """
    if not a:
        return len(b), 0
    if not b:
        return len(a), 0
    # lexicographic DP over (distance, -hits)
    prev = [(j, 0) for j in range(len(b) + 1)]
    for i, tok in enumerate(a, 1):
        cur = [(i, 0)] + [None] * len(b)
        for j in range(1, len(b) + 1):
            d_diag, h_diag = prev[j - 1]
            if tok == b[j - 1]:
                best = (d_diag, h_diag + 1)
            else:
                best = (d_diag + 1, h_diag)
            d_up, h_up = prev[j]
            d_left, h_left = cur[j - 1]
            for cand in ((d_up + 1, h_up), (d_left + 1, h_left)):
                if cand[0] < best[0] or (cand[0] == best[0] and cand[1] > best[1]):
                    best = cand
            cur[j] = best
        prev = cur
    return prev[-1]


def _chars(x: TokenSeq) -> List[str]:
    return list(x) if isinstance(x, str) else [c for tok in x for c in tok]


def _sequence_stats(
    preds: Union[str, Sequence[TokenSeq]],
    target: Union[str, Sequence[TokenSeq]],
    tokenize: Callable[[TokenSeq], List[str]],
    need_hits: bool = True,
) -> Tuple[int, int, int, int]:
    """(edit errors, hits, target length, pred length) summed over pairs.

    ``need_hits=False`` (CER: distance only) takes the faster vectorized DP
    and reports hits as 0 — character-level tables are large, and the tuple
    DP costs a Python allocation per cell.
    """
    if isinstance(preds, str):
        preds = [preds]
    if isinstance(target, str):
        target = [target]
    if len(preds) != len(target):
        raise ValueError("`preds` and `target` must have the same number of sequences")
    errors = hits = total_t = total_p = 0
    for p, t in zip(preds, target):
        pt, tt = tokenize(p), tokenize(t)
        if need_hits:
            d, h = _np_edit_distance_hits(pt, tt)
            hits += h
        else:
            d = _np_edit_distance(pt, tt)
        errors += d
        total_t += len(tt)
        total_p += len(pt)
    return errors, hits, total_t, total_p


def cer(preds: Union[str, Sequence[str]], target: Union[str, Sequence[str]]) -> float:
    """Character error rate: character-level edit distance / reference chars.

    Characters are taken from the strings as-is (spaces included);
    pre-tokenized input concatenates its tokens' characters.

    Example:
        >>> cer("abcd", "abce")
        0.25
    """
    errors, _, total, _ = _sequence_stats(preds, target, _chars, need_hits=False)
    if total == 0:
        return 0.0 if errors == 0 else float("inf")
    return errors / total


def match_error_rate(preds: Union[str, Sequence[TokenSeq]], target: Union[str, Sequence[TokenSeq]]) -> float:
    """MER: ``(S + D + I) / (H + S + D + I)`` over all word pairs.

    Example:
        >>> round(match_error_rate("the cat sat", "the cat sat on the mat"), 4)
        0.5
    """
    errors, hits, _, _ = _sequence_stats(preds, target, _tokens)
    denom = errors + hits
    if denom == 0:
        return 0.0
    return errors / denom


def word_information_preserved(
    preds: Union[str, Sequence[TokenSeq]], target: Union[str, Sequence[TokenSeq]]
) -> float:
    """WIP: ``(H / N_target) * (H / N_pred)``.

    Example:
        >>> round(word_information_preserved("the cat sat", "the cat sat on the mat"), 4)
        0.5
    """
    _, hits, total_t, total_p = _sequence_stats(preds, target, _tokens)
    if total_t == 0 or total_p == 0:
        return 0.0
    return (hits / total_t) * (hits / total_p)


def word_information_lost(
    preds: Union[str, Sequence[TokenSeq]], target: Union[str, Sequence[TokenSeq]]
) -> float:
    """WIL: ``1 - WIP``.

    Example:
        >>> round(word_information_lost("the cat sat", "the cat sat on the mat"), 4)
        0.5
    """
    return 1.0 - word_information_preserved(preds, target)


def _wer_update(preds: Union[str, Sequence[TokenSeq]], target: Union[str, Sequence[TokenSeq]]) -> Tuple[int, int]:
    if isinstance(preds, str):
        preds = [preds]
    if isinstance(target, str):
        target = [target]
    if len(preds) != len(target):
        raise ValueError("`preds` and `target` must have the same number of sequences")
    errors = total = 0
    for p, t in zip(preds, target):
        pt, tt = _tokens(p), _tokens(t)
        errors += _np_edit_distance(pt, tt)
        total += len(tt)
    return errors, total


def wer(preds: Union[str, Sequence[TokenSeq]], target: Union[str, Sequence[TokenSeq]]) -> float:
    """Word error rate: edit distance / reference length, over all pairs.

    ``preds``/``target`` are a single sentence string or a sequence of
    sentences, where each sentence is a string (whitespace-tokenized) or a
    pre-tokenized token list — i.e. pre-tokenized input nests one level:
    ``wer([["the", "cat"]], [["the", "cat", "sat"]])``. A flat list is
    always read as a BATCH of sentences, never as one token list.

    With no reference words the result is 0.0 for a perfect empty match and
    ``inf`` when there are errors.

    Example:
        >>> wer("the cat sat", "the cat sat on the mat")
        0.5
    """
    errors, total = _wer_update(preds, target)
    if total == 0:
        return 0.0 if errors == 0 else float("inf")
    return errors / total


def _edit_distance_single(pred: Array, target: Array, pred_len: Array, target_len: Array) -> Array:
    """Levenshtein distance of one padded id sequence pair (device)."""
    m = target.shape[0]
    cols = jnp.arange(1, m + 1)
    init_row = jnp.arange(m + 1, dtype=jnp.int32)

    def step(row, inp):
        i, tok = inp
        active = i < pred_len
        sub_cost = row[:-1] + (target != tok).astype(jnp.int32)
        del_cost = row[1:] + 1

        def inner(carry, triple):
            sub, dele, col = triple
            best = jnp.minimum(jnp.minimum(sub, dele), carry + 1)
            return best, best

        _, rest = jax.lax.scan(inner, i + 1, (sub_cost, del_cost, cols))
        new_row = jnp.concatenate([jnp.array([i + 1]), rest])
        return jnp.where(active, new_row, row), None

    n = pred.shape[0]
    final, _ = jax.lax.scan(step, init_row, (jnp.arange(n, dtype=jnp.int32), pred))
    return final[target_len]


def edit_distance_padded(pred_ids: Array, target_ids: Array, pred_len: Array, target_len: Array) -> Array:
    """Batched Levenshtein over padded token-id arrays, fully on device.

    Args:
        pred_ids: (B, N) int token ids, padded.
        target_ids: (B, M) int token ids, padded.
        pred_len: (B,) true lengths of ``pred_ids`` rows.
        target_len: (B,) true lengths of ``target_ids`` rows.

    Returns:
        (B,) int32 edit distances.

    Lengths must satisfy ``0 <= pred_len[i] <= N`` and
    ``0 <= target_len[i] <= M``. Concrete out-of-range lengths raise a
    ``ValueError``; under tracing (where values are unknown) they are clamped
    into range, so a traced out-of-range length yields the distance at the
    clamp boundary rather than an error.

    Example:
        >>> import jax.numpy as jnp
        >>> p = jnp.array([[1, 2, 3, 0]])
        >>> t = jnp.array([[1, 9, 3, 4]])
        >>> int(edit_distance_padded(p, t, jnp.array([3]), jnp.array([4]))[0])
        2
    """
    from metrics_tpu.utils.data import is_concrete

    n, m = pred_ids.shape[1], target_ids.shape[1]
    for name, lens, hi in (("pred_len", pred_len, n), ("target_len", target_len, m)):
        if is_concrete(lens):
            vals = np.asarray(lens)
            if vals.size and (vals.min() < 0 or vals.max() > hi):
                raise ValueError(
                    f"`{name}` must lie in [0, {hi}] (the padded axis length); "
                    f"got range [{vals.min()}, {vals.max()}]"
                )
    pred_len = jnp.clip(pred_len, 0, n)
    target_len = jnp.clip(target_len, 0, m)
    return jax.vmap(_edit_distance_single)(pred_ids, target_ids, pred_len, target_len)


def _lcs_single(pred: Array, target: Array, pred_len: Array, target_len: Array) -> Array:
    """LCS length of one padded id sequence pair (device).

    Unlike Levenshtein (whose left-dependency forces a serial inner scan),
    the LCS recurrence admits the identity ``L(i,j) = max(L(i-1,j),
    L(i,j-1), L(i-1,j-1) + match)`` — taking the extra maxes is always
    valid because skipping characters never decreases an LCS. The
    ``L(i,j-1)`` running max is then one ``cummax`` per row: the whole row
    update is vectorized, O(rows) scan steps of O(cols) vector work.
    """
    m = target.shape[0]
    init_row = jnp.zeros(m + 1, dtype=jnp.int32)
    valid_t = jnp.arange(m) < target_len  # padded target slots never match

    def step(row, inp):
        i, tok = inp
        active = i < pred_len
        match = ((target == tok) & valid_t).astype(jnp.int32)
        candidate = jnp.maximum(row[1:], row[:-1] + match)
        new_row = jnp.concatenate([jnp.zeros(1, jnp.int32), jax.lax.cummax(candidate)])
        return jnp.where(active, new_row, row), None

    n = pred.shape[0]
    final, _ = jax.lax.scan(step, init_row, (jnp.arange(n, dtype=jnp.int32), pred))
    return final[target_len]


def lcs_length_padded(pred_ids: Array, target_ids: Array, pred_len: Array, target_len: Array) -> Array:
    """Batched longest-common-subsequence length over padded token-id
    arrays, fully on device (the ROUGE-L kernel; mirrors
    ``edit_distance_padded``'s contract).

    Args:
        pred_ids: (B, N) int token ids, padded.
        target_ids: (B, M) int token ids, padded.
        pred_len: (B,) true lengths of ``pred_ids`` rows.
        target_len: (B,) true lengths of ``target_ids`` rows.

    Returns:
        (B,) int32 LCS lengths.

    Concrete out-of-range lengths raise a ``ValueError``; under tracing
    they are clamped into range (same policy as ``edit_distance_padded``).

    Example:
        >>> import jax.numpy as jnp
        >>> p = jnp.array([[1, 2, 3, 4, 0]])
        >>> t = jnp.array([[1, 9, 3, 4]])
        >>> int(lcs_length_padded(p, t, jnp.array([4]), jnp.array([4]))[0])
        3
    """
    from metrics_tpu.utils.data import is_concrete

    n, m = pred_ids.shape[1], target_ids.shape[1]
    for name, lens, hi in (("pred_len", pred_len, n), ("target_len", target_len, m)):
        if is_concrete(lens):
            vals = np.asarray(lens)
            if vals.size and (vals.min() < 0 or vals.max() > hi):
                raise ValueError(
                    f"`{name}` must lie in [0, {hi}] (the padded axis length); "
                    f"got range [{vals.min()}, {vals.max()}]"
                )
    pred_len = jnp.clip(pred_len, 0, n)
    target_len = jnp.clip(target_len, 0, m)
    return jax.vmap(_lcs_single)(pred_ids, target_ids, pred_len, target_len)
