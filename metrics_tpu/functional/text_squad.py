"""SQuAD exact-match / F1 (Rajpurkar et al. 2016).

Extension beyond the reference snapshot (later torchmetrics ships
``SQuAD``). Host-side text metric using the official evaluation
normalization: lowercase, strip punctuation and articles (a/an/the),
whitespace-split; EM is string equality of the normalized answers, F1 the
token-multiset overlap. With several reference answers per question the best
score over the references counts (the official convention).
"""
import re
import string
from collections import Counter
from typing import Dict, List, Sequence, Tuple, Union

_ARTICLES = re.compile(r"\b(a|an|the)\b")
_PUNCT = set(string.punctuation)


def _normalize_answer(text: str) -> List[str]:
    text = "".join(ch for ch in text.lower() if ch not in _PUNCT)
    text = _ARTICLES.sub(" ", text)
    return text.split()


def _pair_em_f1(pred: str, answers: Sequence[str]) -> Tuple[float, float]:
    p_tok = _normalize_answer(pred)
    best_em = best_f1 = 0.0
    for ans in answers:
        a_tok = _normalize_answer(ans)
        best_em = max(best_em, float(p_tok == a_tok))
        # v1.1 script semantics: zero token overlap -> F1 0, including pairs
        # that normalize to nothing (EM can still be 100 there)
        overlap = sum((Counter(p_tok) & Counter(a_tok)).values())
        if overlap == 0:
            continue
        precision = overlap / len(p_tok)
        recall = overlap / len(a_tok)
        best_f1 = max(best_f1, 2 * precision * recall / (precision + recall))
    return best_em, best_f1


def _squad_batch_sums(
    preds: Union[str, Sequence[str]],
    target: Union[str, Sequence[str], Sequence[Sequence[str]]],
) -> Tuple[float, float, int]:
    """(EM sum, F1 sum, question count) — shared by the functional one-shot
    and the streaming module."""
    if isinstance(preds, str):
        preds = [preds]
        # a single question: a FLAT string sequence can only mean its
        # acceptable reference answers; an already-nested sequence is the
        # 1-question batch form and needs no wrapping
        if not isinstance(target, str) and all(isinstance(x, str) for x in target):
            target = [target]
    if isinstance(target, str):
        target = [target]
    if len(preds) != len(target):
        raise ValueError("`preds` and `target` must have the same number of questions")
    em_sum = f1_sum = 0.0
    for p, refs in zip(preds, target):
        answers = [refs] if isinstance(refs, str) else list(refs)
        em, f1 = _pair_em_f1(p, answers)
        em_sum += em
        f1_sum += f1
    return em_sum, f1_sum, len(preds)


def squad(
    preds: Union[str, Sequence[str]],
    target: Union[str, Sequence[str], Sequence[Sequence[str]]],
) -> Dict[str, float]:
    """Mean exact-match and F1 over (prediction, reference answers) pairs.

    ``target`` entries may be a single reference string or a sequence of
    acceptable reference answers (best score counts). Returns percentages
    in [0, 100] with official v1.1 script semantics (in particular, a pair
    whose normalized answers are both empty scores EM 100 but F1 0).

    Example:
        >>> out = squad(["the cat"], [["The cat!", "a dog"]])
        >>> (out["exact_match"], out["f1"])
        (100.0, 100.0)
    """
    em_sum, f1_sum, n = _squad_batch_sums(preds, target)
    if n == 0:
        return {"exact_match": 0.0, "f1": 0.0}
    return {"exact_match": 100.0 * em_sum / n, "f1": 100.0 * f1_sum / n}
