"""Image gradients via 1-step finite differences.

Parity target: reference ``torchmetrics/functional/image_gradients.py:20-82``.
"""
from typing import Tuple

import jax.numpy as jnp
from jax import Array


def _image_gradients_validate(img: Array) -> None:
    if not hasattr(img, "ndim"):
        raise TypeError(f"The `img` expects an array type but got {type(img)}")
    if img.ndim != 4:
        raise RuntimeError(f"The `img` expects a 4D tensor but got {img.ndim}D tensor")


def _compute_image_gradients(img: Array) -> Tuple[Array, Array]:
    dy = jnp.pad(img[..., 1:, :] - img[..., :-1, :], ((0, 0), (0, 0), (0, 1), (0, 0)))
    dx = jnp.pad(img[..., :, 1:] - img[..., :, :-1], ((0, 0), (0, 0), (0, 0), (0, 1)))
    return dy, dx


def image_gradients(img: Array) -> Tuple[Array, Array]:
    """(dy, dx) finite-difference gradients of an ``(N, C, H, W)`` image batch.

    Example:
        >>> import jax.numpy as jnp
        >>> image = jnp.arange(0, 1*1*5*5, dtype=jnp.float32).reshape(1, 1, 5, 5)
        >>> dy, dx = image_gradients(image)
        >>> dy[0, 0, :, :]
        Array([[5., 5., 5., 5., 5.],
               [5., 5., 5., 5., 5.],
               [5., 5., 5., 5., 5.],
               [5., 5., 5., 5., 5.],
               [0., 0., 0., 0., 0.]], dtype=float32)
    """
    _image_gradients_validate(img)
    return _compute_image_gradients(img)
