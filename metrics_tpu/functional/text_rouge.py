"""ROUGE score (rouge-1 / rouge-2 / rouge-L).

Extension beyond the reference snapshot (later torchmetrics ships
``ROUGEScore``). Host-side text metric (tokenization and per-pair n-gram /
LCS counting are host work); the accumulated form streams per-pair
precision/recall/F1 sums, so the module metric is O(1) memory and the
aggregate is the MEAN of per-sentence scores (the rouge_score convention).

Tokenization follows the standard rouge_score default: lowercase,
non-alphanumeric characters become separators.
"""
import re
from collections import Counter
from typing import Dict, List, Sequence, Tuple, Union

_TOKEN_RE = re.compile(r"[^a-z0-9]+")

ROUGE_KEYS = ("rouge1", "rouge2", "rougeL")


def _rouge_tokens(text: str) -> List[str]:
    return [t for t in _TOKEN_RE.split(text.lower()) if t]


def _ngrams(tokens: List[str], n: int) -> Counter:
    return Counter(tuple(tokens[i:i + n]) for i in range(len(tokens) - n + 1))


def _prf(overlap: int, pred_total: int, target_total: int) -> Tuple[float, float, float]:
    precision = overlap / pred_total if pred_total else 0.0
    recall = overlap / target_total if target_total else 0.0
    f1 = 2 * precision * recall / (precision + recall) if precision + recall else 0.0
    return precision, recall, f1


def _lcs_len(a: List[str], b: List[str]) -> int:
    if not a or not b:
        return 0
    prev = [0] * (len(b) + 1)
    for tok in a:
        cur = [0] * (len(b) + 1)
        for j, other in enumerate(b, 1):
            cur[j] = prev[j - 1] + 1 if tok == other else max(prev[j], cur[j - 1])
        prev = cur
    return prev[-1]


def _pair_scores(pred: str, target: str, keys: Sequence[str]) -> Dict[str, Tuple[float, float, float]]:
    p_tok = _rouge_tokens(pred)
    t_tok = _rouge_tokens(target)
    out = {}
    for key in keys:
        if key == "rougeL":
            out[key] = _prf(_lcs_len(p_tok, t_tok), len(p_tok), len(t_tok))
            continue
        n = int(key[5:])
        p_ngrams, t_ngrams = _ngrams(p_tok, n), _ngrams(t_tok, n)
        overlap = sum((p_ngrams & t_ngrams).values())
        out[key] = _prf(overlap, sum(p_ngrams.values()), sum(t_ngrams.values()))
    return out


def _check_rouge_keys(rouge_keys: Sequence[str]) -> Tuple[str, ...]:
    keys = tuple(rouge_keys)
    for key in keys:
        if key == "rougeL" or (key.startswith("rouge") and key[5:].isdigit() and int(key[5:]) >= 1):
            continue
        raise ValueError(f"rouge key must be 'rougeN' (N >= 1) or 'rougeL', got {key!r}")
    return keys


def _batch_sums(
    preds: Union[str, Sequence[str]],
    target: Union[str, Sequence[str]],
    keys: Sequence[str],
) -> Tuple[Dict[str, List[float]], int]:
    """Per-key [P, R, F] sums over the pairs plus the pair count (shared by
    the functional one-shot and the streaming module)."""
    if isinstance(preds, str):
        preds = [preds]
    if isinstance(target, str):
        target = [target]
    if len(preds) != len(target):
        raise ValueError("`preds` and `target` must have the same number of sentences")
    sums = {k: [0.0, 0.0, 0.0] for k in keys}
    for p, t in zip(preds, target):
        for k, prf in _pair_scores(p, t, keys).items():
            for i in range(3):
                sums[k][i] += prf[i]
    return sums, len(preds)


def rouge_score(
    preds: Union[str, Sequence[str]],
    target: Union[str, Sequence[str]],
    rouge_keys: Sequence[str] = ROUGE_KEYS,
) -> Dict[str, float]:
    """Mean per-sentence ROUGE precision/recall/F1 over the pairs.

    Returns ``{f"{key}_precision" | f"{key}_recall" | f"{key}_fmeasure": value}``.

    Example:
        >>> out = rouge_score("the cat sat on the mat", "the cat was on the mat")
        >>> round(out["rouge1_fmeasure"], 4)
        0.8333
        >>> round(out["rougeL_fmeasure"], 4)
        0.8333
    """
    keys = _check_rouge_keys(rouge_keys)
    sums, n = _batch_sums(preds, target, keys)
    if n == 0:
        return {f"{k}_{stat}": 0.0 for k in keys for stat in ("precision", "recall", "fmeasure")}
    return {
        f"{k}_{stat}": sums[k][i] / n
        for k in keys
        for i, stat in enumerate(("precision", "recall", "fmeasure"))
    }
