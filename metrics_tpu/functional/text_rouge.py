"""ROUGE score (rouge-1 / rouge-2 / rouge-L).

Extension beyond the reference snapshot (later torchmetrics ships
``ROUGEScore``). Host-side text metric (tokenization and per-pair n-gram /
LCS counting are host work); the accumulated form streams per-pair
precision/recall/F1 sums, so the module metric is O(1) memory and the
aggregate is the MEAN of per-sentence scores (the rouge_score convention).

Tokenization follows the standard rouge_score default: lowercase,
non-alphanumeric characters become separators.
"""
import re
from collections import Counter
from typing import Dict, List, Sequence, Tuple, Union

_TOKEN_RE = re.compile(r"[^a-z0-9]+")

ROUGE_KEYS = ("rouge1", "rouge2", "rougeL")


def _rouge_tokens(text: str) -> List[str]:
    return [t for t in _TOKEN_RE.split(text.lower()) if t]


def _ngrams(tokens: List[str], n: int) -> Counter:
    return Counter(tuple(tokens[i:i + n]) for i in range(len(tokens) - n + 1))


def _prf(overlap: int, pred_total: int, target_total: int) -> Tuple[float, float, float]:
    precision = overlap / pred_total if pred_total else 0.0
    recall = overlap / target_total if target_total else 0.0
    f1 = 2 * precision * recall / (precision + recall) if precision + recall else 0.0
    return precision, recall, f1


def _lcs_len(a: List[str], b: List[str]) -> int:
    """Host DP oracle (small pairs; the device kernel covers corpus scale)."""
    if not a or not b:
        return 0
    prev = [0] * (len(b) + 1)
    for tok in a:
        cur = [0] * (len(b) + 1)
        for j, other in enumerate(b, 1):
            cur[j] = prev[j - 1] + 1 if tok == other else max(prev[j], cur[j - 1])
        prev = cur
    return prev[-1]


# batches whose total DP cell count clears this run the LCS on device (one
# fused batched kernel, functional/text.py lcs_length_padded); below it the
# host loop wins — a device dispatch costs ~ms through a remote tunnel while
# small-string host DP is microseconds
_DEVICE_LCS_MIN_CELLS = 50_000


def _lcs_lens(pairs: List[Tuple[List[str], List[str]]]) -> List[int]:
    """LCS length per tokenized pair — host DP for small batches, the
    batched device kernel at corpus scale (the WER posture, applied to
    ROUGE-L: tokenization stays host work, the O(N*M) counting doesn't)."""
    cells = sum(len(a) * len(b) for a, b in pairs)
    if cells < _DEVICE_LCS_MIN_CELLS:
        return [_lcs_len(a, b) for a, b in pairs]

    import jax.numpy as jnp
    import numpy as np

    from metrics_tpu.functional.text import lcs_length_padded

    batch = len(pairs)
    n = max(max((len(a) for a, _ in pairs), default=0), 1)
    m = max(max((len(b) for _, b in pairs), default=0), 1)
    pred_ids = np.zeros((batch, n), dtype=np.int32)
    target_ids = np.full((batch, m), -1, dtype=np.int32)  # distinct pads never match
    for k, (a, b) in enumerate(pairs):
        vocab: Dict[str, int] = {}
        pred_ids[k, : len(a)] = [vocab.setdefault(t, len(vocab) + 1) for t in a]
        target_ids[k, : len(b)] = [vocab.setdefault(t, len(vocab) + 1) for t in b]
    out = lcs_length_padded(
        jnp.asarray(pred_ids),
        jnp.asarray(target_ids),
        jnp.asarray(np.array([len(a) for a, _ in pairs], dtype=np.int32)),
        jnp.asarray(np.array([len(b) for _, b in pairs], dtype=np.int32)),
    )
    return [int(x) for x in np.asarray(out)]


def _check_rouge_keys(rouge_keys: Sequence[str]) -> Tuple[str, ...]:
    keys = tuple(rouge_keys)
    for key in keys:
        if key == "rougeL" or (key.startswith("rouge") and key[5:].isdigit() and int(key[5:]) >= 1):
            continue
        raise ValueError(f"rouge key must be 'rougeN' (N >= 1) or 'rougeL', got {key!r}")
    return keys


def _batch_sums(
    preds: Union[str, Sequence[str]],
    target: Union[str, Sequence[str]],
    keys: Sequence[str],
) -> Tuple[Dict[str, List[float]], int]:
    """Per-key [P, R, F] sums over the pairs plus the pair count (shared by
    the functional one-shot and the streaming module)."""
    if isinstance(preds, str):
        preds = [preds]
    if isinstance(target, str):
        target = [target]
    if len(preds) != len(target):
        raise ValueError("`preds` and `target` must have the same number of sentences")
    sums = {k: [0.0, 0.0, 0.0] for k in keys}
    tok_pairs = [(_rouge_tokens(p), _rouge_tokens(t)) for p, t in zip(preds, target)]
    ngram_keys = [k for k in keys if k != "rougeL"]
    for p_tok, t_tok in tok_pairs:
        for k in ngram_keys:
            n = int(k[5:])
            p_ngrams, t_ngrams = _ngrams(p_tok, n), _ngrams(t_tok, n)
            overlap = sum((p_ngrams & t_ngrams).values())
            prf = _prf(overlap, sum(p_ngrams.values()), sum(t_ngrams.values()))
            for i in range(3):
                sums[k][i] += prf[i]
    if "rougeL" in keys:
        # all pairs' LCS in one pass: batched device kernel at corpus scale
        for (p_tok, t_tok), lcs in zip(tok_pairs, _lcs_lens(tok_pairs)):
            prf = _prf(lcs, len(p_tok), len(t_tok))
            for i in range(3):
                sums["rougeL"][i] += prf[i]
    return sums, len(preds)


def rouge_score(
    preds: Union[str, Sequence[str]],
    target: Union[str, Sequence[str]],
    rouge_keys: Sequence[str] = ROUGE_KEYS,
) -> Dict[str, float]:
    """Mean per-sentence ROUGE precision/recall/F1 over the pairs.

    Returns ``{f"{key}_precision" | f"{key}_recall" | f"{key}_fmeasure": value}``.

    Example:
        >>> out = rouge_score("the cat sat on the mat", "the cat was on the mat")
        >>> round(out["rouge1_fmeasure"], 4)
        0.8333
        >>> round(out["rougeL_fmeasure"], 4)
        0.8333
    """
    keys = _check_rouge_keys(rouge_keys)
    sums, n = _batch_sums(preds, target, keys)
    if n == 0:
        return {f"{k}_{stat}": 0.0 for k in keys for stat in ("precision", "recall", "fmeasure")}
    return {
        f"{k}_{stat}": sums[k][i] / n
        for k in keys
        for i, stat in enumerate(("precision", "recall", "fmeasure"))
    }
