"""RetrievalMRR — mean reciprocal rank on the RetrievalMetric base pattern.

Extension beyond the reference snapshot (it ships only RetrievalMAP,
reference torchmetrics/retrieval/__init__.py); evaluated with the same
vectorized sort + segment-op kernel as the other retrieval metrics.
"""
from jax import Array

from metrics_tpu.functional.retrieval.segments import grouped_reciprocal_rank
from metrics_tpu.retrieval.retrieval_metric import RetrievalMetric


class RetrievalMRR(RetrievalMetric):
    r"""Mean reciprocal rank over queries.

    Example:
        >>> import jax.numpy as jnp
        >>> indexes = jnp.array([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.array([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.2])
        >>> target = jnp.array([False, False, True, False, True, False, False])
        >>> mrr = RetrievalMRR()
        >>> float(mrr(indexes, preds, target))
        0.75
    """

    def _grouped_metric(self, dense_idx: Array, preds: Array, target: Array, num_queries: int, valid=None) -> Array:
        return grouped_reciprocal_rank(dense_idx, preds, target, num_queries)
