"""RetrievalRPrecision — extension beyond the reference snapshot."""
from jax import Array

from metrics_tpu.functional.retrieval.segments import grouped_r_precision
from metrics_tpu.retrieval.retrieval_metric import RetrievalMetric


class RetrievalRPrecision(RetrievalMetric):
    r"""Mean R-precision over queries (precision at each query's own relevant
    count R — the cutoff where precision equals recall).

    Example:
        >>> import jax.numpy as jnp
        >>> indexes = jnp.array([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.array([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.2])
        >>> target = jnp.array([False, False, True, False, True, False, True])
        >>> rp = RetrievalRPrecision()
        >>> float(rp(indexes, preds, target))
        0.75
    """

    def _grouped_metric(self, dense_idx: Array, preds: Array, target: Array, num_queries: int, valid=None) -> Array:
        return grouped_r_precision(dense_idx, preds, target, num_queries)
