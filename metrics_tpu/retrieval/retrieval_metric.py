"""Retrieval metric base class.

Parity target: reference ``torchmetrics/retrieval/retrieval_metric.py:27`` —
cat-states ``idx``/``preds``/``target`` (:94-96), flatten-append update
(:98-108), per-query grouping with the ``query_without_relevant_docs`` policy
(:110-146), ``IGNORE_IDX=-100`` sentinel (:24).

TPU-native compute: instead of the reference's host dict-loop + per-query
Python loop, subclasses provide a *vectorized* ``_grouped_metric`` (sort +
segment ops, see ``functional/retrieval/segments.py``) evaluating every query
in one fused XLA program. The policy semantics are reproduced exactly, incl.
the reference quirk that the empty-query check sums *raw* targets (so ``-100``
exclude sentinels make a query count as non-empty, reference :121).
"""
from abc import ABC, abstractmethod
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.parallel.buffer import as_values
from metrics_tpu.utils.data import is_concrete

IGNORE_IDX = -100

# jitted epoch-compute shared across config-identical instances (fresh metric
# per eval epoch must not retrace); bounded FIFO like the core step cache
_COMPUTE_JIT_CACHE: Dict[Any, Callable] = {}
_COMPUTE_JIT_CACHE_MAX = 64
_EAGER_ONLY = object()  # cache sentinel: this config's compute cannot trace


def _validate_k(k: Optional[int]) -> Optional[int]:
    """Shared constructor check for the top-k retrieval modules."""
    from metrics_tpu.functional.retrieval.utils import check_topk

    check_topk(k)
    return k


class RetrievalMetric(Metric, ABC):
    r"""Accumulate (indexes, preds, target) rows; compute the mean of a
    per-query metric over all queries.

    Args:
        query_without_relevant_docs: policy for queries with no positive
            target: 'skip' (default) | 'error' | 'pos' (count 1.0) | 'neg' (0.0).
        exclude: target value marking rows to ignore (default -100).
        capacity: fixed row capacity for the epoch cat-states; makes them
            jit-safe PaddedBuffers. Place the states with
            ``metrics_tpu.parallel.row_sharded(mesh)`` and ``compute()``
            dispatches the exact sharded ``all_to_all`` engine
            (``parallel/sharded_epoch.py``) — O(capacity/n) per-device
            memory. ``regroup_capacity`` (settable attribute, default
            auto) bounds the per-destination routing buckets; a skewed
            query-id distribution that overflows them raises loudly.
    """

    def __init__(
        self,
        query_without_relevant_docs: str = "skip",
        exclude: int = IGNORE_IDX,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
        capacity: Optional[int] = None,
        jit: Optional[bool] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
            capacity=capacity,
            jit=jit,
        )
        self.regroup_capacity: Optional[int] = None

        query_without_relevant_docs_options = ("error", "skip", "pos", "neg")
        if query_without_relevant_docs not in query_without_relevant_docs_options:
            raise ValueError(
                f"`query_without_relevant_docs` received a wrong value {query_without_relevant_docs}. "
                f"Allowed values are {query_without_relevant_docs_options}"
            )

        self.query_without_relevant_docs = query_without_relevant_docs
        self.exclude = exclude

        self.add_state("idx", default=[], dist_reduce_fx=None)
        self.add_state("preds", default=[], dist_reduce_fx=None)
        self.add_state("target", default=[], dist_reduce_fx=None)

    # The whole retrieval family shares this base flatten-append update, and
    # every family-specific knob is COMPUTE-only — so inside a
    # ``MetricCollection`` any retrieval members with matching ``capacity``
    # form ONE compute group (one idx/preds/target append per step, one
    # state pytree on the pure/sync plane). Declared via the exclusion form
    # (``Metric._GROUP_COMPUTE_ONLY_ATTRS``): a subclass that adds
    # update-relevant config is automatically included in the group key and
    # conservatively splits off, while a new compute-only knob just extends
    # this tuple instead of re-declaring ``_GROUP_UPDATE_ATTRS = ()``.
    _GROUP_COMPUTE_ONLY_ATTRS = (
        "k",
        "query_without_relevant_docs",
        "exclude",
        "regroup_capacity",
    )

    def update(self, idx: Array, preds: Array, target: Array) -> None:
        if not (idx.shape == target.shape == preds.shape):
            raise ValueError("`idx`, `preds` and `target` must be of the same shape")

        self._append("idx", jnp.asarray(idx, dtype=jnp.int32).reshape(-1))
        self._append("preds", jnp.asarray(preds, dtype=jnp.float32).reshape(-1))
        self._append("target", jnp.asarray(target, dtype=jnp.int32).reshape(-1))

    def _states_own_sync(self) -> bool:
        from metrics_tpu.parallel.sharded_dispatch import retrieval_applicable

        return retrieval_applicable(self) is not None

    def compute(self) -> Array:
        from metrics_tpu.parallel.sharded_dispatch import retrieval_sharded

        sharded = retrieval_sharded(self)  # row-sharded epoch states
        if sharded is not None:
            return sharded
        idx = as_values(self.idx)
        preds = as_values(self.preds)
        target = as_values(self.target)

        if idx.shape[0] == 0:
            return jnp.asarray(0.0)

        # Eager dispatch pays per-op latency through the device tunnel
        # (~25ms/op under load), so when jit is enabled the whole epoch
        # compute runs as ONE dispatch. Gate on the jit *setting*, not
        # _jittable: list cat-states make the UPDATE un-jittable, but compute
        # receives concatenated fixed-shape arrays and is always jit-safe.
        # The jitted callable is shared across config-identical instances
        # (fresh metric per eval epoch must not pay a retrace).
        fn = self._device_compute
        if self._jit is not False and not self.__dict__.get("_compute_jit_failed"):
            from metrics_tpu.core.metric import _bounded_insert

            key = self._compute_cache_key()
            fn = _COMPUTE_JIT_CACHE.get(key)
            if fn is _EAGER_ONLY:
                # a previous instance of this config failed to trace
                self.__dict__["_compute_jit_failed"] = True
                fn = self._device_compute
            elif fn is None:
                # close over a detached reset copy, not the live instance:
                # the cache must pin only empty default states, never an
                # epoch's worth of accumulated cat-state buffers. The live
                # states are swapped out around the deepcopy so the copy
                # never clones accumulated buffers either.
                from copy import deepcopy

                saved = self._current_state()
                self._set_state(self.init_state())
                try:
                    carrier = deepcopy(self)
                finally:
                    self._set_state(saved)
                fn = jax.jit(carrier._device_compute)
                _bounded_insert(_COMPUTE_JIT_CACHE, key, fn, _COMPUTE_JIT_CACHE_MAX)
            try:
                result, flag = fn(idx, preds, target)
            except self._TRACER_ERRORS:
                # a subclass with value-dependent control flow keeps the
                # previous eager-compute semantics. The flag is COMPUTE-only
                # (not _jit_failed, which would also demote the fused
                # forward/update of capacity-buffer metrics), and the broken
                # entry is replaced by a sentinel so config-identical fresh
                # instances skip straight to eager instead of re-tracing.
                self.__dict__["_compute_jit_failed"] = True
                _COMPUTE_JIT_CACHE[key] = _EAGER_ONLY
                result, flag = self._device_compute(idx, preds, target)
        else:
            result, flag = fn(idx, preds, target)

        if self.query_without_relevant_docs == "error" and bool(flag):
            raise ValueError(
                f"`{self.__class__.__name__}.compute()` was provided with a query {self._EMPTY_QUERY_ERROR}"
            )
        return result

    def _device_compute(self, idx: Array, preds: Array, target: Array):
        """(result, empty-query flag) as one static-shape device program.

        Query ids densify via sort+cumsum (no jnp.unique host sync), the
        segment count is the row count N (an upper bound — absent segments
        are masked), and sentinel rows are neutralized by masking instead of
        boolean filtering, so the whole body is jit-safe.
        """
        total, n_kept, flag = self._device_sums(idx, preds, target)
        return jnp.where(n_kept == 0, 0.0, total / jnp.maximum(n_kept, 1)), flag

    def _device_sums(self, idx: Array, preds: Array, target: Array, pad: Optional[Array] = None):
        """(score total, query count, empty-query flag) — the pre-division
        epoch sums, so distributed callers can psum partials across shards
        before the final mean (``metrics_tpu.parallel.sharded_epoch``).

        ``pad`` marks ghost rows (sharded-regroup padding): unlike user
        ``exclude`` sentinels — which keep their query visible by reference
        parity (:121) — pad rows must not make a query exist at all.
        """
        n = int(idx.shape[0])
        order = jnp.argsort(idx, stable=True)
        sorted_ids = idx[order]
        new_segment = jnp.concatenate(
            [jnp.zeros((1,), jnp.int32), (sorted_ids[1:] != sorted_ids[:-1]).astype(jnp.int32)]
        )
        dense = jnp.zeros((n,), jnp.int32).at[order].set(jnp.cumsum(new_segment))

        real = jnp.ones((n,), jnp.float32) if pad is None else (~pad).astype(jnp.float32)
        counts = jax.ops.segment_sum(real, dense, n)
        exists = counts > 0

        if pad is not None:
            target = jnp.where(pad, 0, target)
        empty = self._empty_query_mask(dense, target, exists, n)
        flag = jnp.any(empty)
        if self.query_without_relevant_docs == "error" and is_concrete(flag):
            # eager path: start the readback now so it overlaps with the
            # grouped-metric computation below (one ~200ms tunnel round)
            try:
                flag.copy_to_host_async()
            except (AttributeError, RuntimeError):
                pass

        # sentinel rows must not rank, hit, or grade: -inf scores sink them
        # below every real row of their query, zero targets null their gain
        # (reference filters them out per query, retrieval_metric.py:126-142)
        excluded = target == self.exclude
        if pad is not None:
            excluded = excluded | pad
        preds_m = jnp.where(excluded, -jnp.inf, preds)
        target_m = jnp.where(excluded, 0, target)
        scores = self._grouped_metric(dense, preds_m, target_m, n, valid=~excluded)

        if self.query_without_relevant_docs == "pos":
            scores = jnp.where(empty, 1.0, scores)
        elif self.query_without_relevant_docs == "neg":
            scores = jnp.where(empty, 0.0, scores)
        elif self.query_without_relevant_docs == "skip":
            kept = exists & ~empty
            return jnp.sum(jnp.where(kept, scores, 0.0)), jnp.sum(kept), flag

        present = jnp.sum(jnp.where(exists, scores, 0.0))
        return present, jnp.sum(exists), flag

    def _compute_cache_key(self) -> tuple:
        """Key for sharing the jitted compute across instances.

        Covers every attribute the traced ``_device_compute`` reads; a
        subclass that adds trace-affecting config beyond ``k`` MUST extend
        this, or config-identical-looking instances would share one trace.
        """
        return (type(self), self.query_without_relevant_docs, self.exclude, getattr(self, "k", None))

    # what the 'error' policy reports; subclasses overriding _empty_query_mask
    # override this to match their condition
    _EMPTY_QUERY_ERROR = "without positive targets"

    def _empty_query_mask(self, dense_idx: Array, target: Array, exists: Array, num_queries: int) -> Array:
        """Queries the ``query_without_relevant_docs`` policy applies to.

        Default: no positive rows, judged on RAW target sums (reference :121
        quirk — exclude sentinels make a query count as non-empty). Metrics
        whose per-query score is undefined for a different reason (e.g.
        fall-out needs non-relevant rows) override this.
        """
        raw_sums = jax.ops.segment_sum(target.astype(jnp.float32), dense_idx, num_queries)
        return (raw_sums == 0) & exists

    @abstractmethod
    def _grouped_metric(
        self,
        dense_idx: Array,
        preds: Array,
        target: Array,
        num_queries: int,
        valid: Optional[Array] = None,
    ) -> Array:
        """Vectorized per-query scores, shape (num_queries,).

        ``valid`` marks rows that are real documents (False = exclude
        sentinel rows, already neutralized to score -inf / target 0).
        """
