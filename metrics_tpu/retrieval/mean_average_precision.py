"""RetrievalMAP (reference torchmetrics/retrieval/mean_average_precision.py:21)."""
from jax import Array

from metrics_tpu.functional.retrieval.segments import grouped_average_precision
from metrics_tpu.retrieval.retrieval_metric import RetrievalMetric


class RetrievalMAP(RetrievalMetric):
    r"""Mean average precision over queries.

    Example:
        >>> import jax.numpy as jnp
        >>> indexes = jnp.array([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.array([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.2])
        >>> target = jnp.array([False, False, True, False, True, False, False])
        >>> map = RetrievalMAP()
        >>> float(map(indexes, preds, target))
        0.75
        >>> float(map.compute())
        0.75
    """

    def _grouped_metric(self, dense_idx: Array, preds: Array, target: Array, num_queries: int, valid=None) -> Array:
        ap, _ = grouped_average_precision(dense_idx, preds, target.astype(bool), num_queries)
        return ap
