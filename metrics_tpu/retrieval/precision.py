"""RetrievalPrecision — precision@k on the RetrievalMetric base pattern.

Extension beyond the reference snapshot; per-query semantics match the later
torchmetrics ``RetrievalPrecision`` (hits in top-k / k).
"""
from typing import Any, Callable, Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.retrieval.segments import grouped_topk_hits
from metrics_tpu.retrieval.retrieval_metric import RetrievalMetric, _validate_k


class RetrievalPrecision(RetrievalMetric):
    r"""Mean precision@k over queries.

    Shares the ``RetrievalMetric`` flatten-append update (and so the
    regrouped per-query plane) with the other retrieval metrics: inside a
    ``MetricCollection``, RetrievalPrecision/Recall/MRR with matching
    ``capacity`` form ONE compute group — one idx/preds/target append per
    step, one state pytree on the pure/sync plane. ``k`` is compute-only
    and deliberately absent from the group key.

    With ``k=None`` each query uses its own document count as k (i.e. plain
    precision of the whole ranking).

    Example:
        >>> import jax.numpy as jnp
        >>> indexes = jnp.array([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.array([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.2])
        >>> target = jnp.array([False, False, True, False, True, False, True])
        >>> p2 = RetrievalPrecision(k=2)
        >>> float(p2(indexes, preds, target))
        0.5
    """

    def __init__(
        self,
        query_without_relevant_docs: str = "skip",
        exclude: int = -100,
        k: Optional[int] = None,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
        capacity: Optional[int] = None,
        jit: Optional[bool] = None,
    ):
        super().__init__(
            query_without_relevant_docs=query_without_relevant_docs,
            exclude=exclude,
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
            capacity=capacity,
            jit=jit,
        )
        self.k = _validate_k(k)

    def _grouped_metric(self, dense_idx: Array, preds: Array, target: Array, num_queries: int, valid=None) -> Array:
        hits, _, n_valid = grouped_topk_hits(dense_idx, preds, target, num_queries, self.k, valid)
        denom = n_valid if self.k is None else jnp.full_like(n_valid, float(self.k))
        return hits / jnp.maximum(denom, 1.0)
