"""RetrievalFallOut — extension beyond the reference snapshot.

Fall-out is the false-positive analogue of recall: the fraction of
NON-relevant documents that rank in the top-k. The empty-query policy
(``query_without_relevant_docs``) therefore applies to queries with no
non-relevant documents — the inverse of the other retrieval metrics.
"""
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.retrieval.segments import grouped_fall_out
from metrics_tpu.retrieval.retrieval_metric import RetrievalMetric, _validate_k


class RetrievalFallOut(RetrievalMetric):
    r"""Mean fall-out@k (non-relevant docs in the top-k / total non-relevant).

    Example:
        >>> import jax.numpy as jnp
        >>> indexes = jnp.array([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.array([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.2])
        >>> target = jnp.array([False, False, True, False, True, False, True])
        >>> fo1 = RetrievalFallOut(k=1)
        >>> float(fo1(indexes, preds, target))
        0.25
    """

    def __init__(
        self,
        query_without_relevant_docs: str = "skip",
        exclude: int = -100,
        k: Optional[int] = None,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
        capacity: Optional[int] = None,
        jit: Optional[bool] = None,
    ):
        super().__init__(
            query_without_relevant_docs=query_without_relevant_docs,
            exclude=exclude,
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
            capacity=capacity,
            jit=jit,
        )
        self.k = _validate_k(k)

    _EMPTY_QUERY_ERROR = "without non-relevant targets"

    def _empty_query_mask(self, dense_idx: Array, target: Array, exists: Array, num_queries: int) -> Array:
        # fall-out is undefined for queries with no NON-relevant valid rows
        valid_neg = ((target <= 0) & (target != self.exclude)).astype(jnp.float32)
        neg_counts = jax.ops.segment_sum(valid_neg, dense_idx, num_queries)
        return (neg_counts == 0) & exists

    def _grouped_metric(self, dense_idx: Array, preds: Array, target: Array, num_queries: int, valid=None) -> Array:
        return grouped_fall_out(dense_idx, preds, target, num_queries, self.k, valid)
