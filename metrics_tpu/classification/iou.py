"""IoU module (subclass of ConfusionMatrix).

Parity target: reference ``torchmetrics/classification/iou.py:23``.
"""
from typing import Any, Optional

from jax import Array

from metrics_tpu.classification.confusion_matrix import ConfusionMatrix
from metrics_tpu.functional.classification.iou import _iou_from_confmat


class IoU(ConfusionMatrix):
    r"""Jaccard index accumulated over batches via the confusion matrix.

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([1, 1, 0, 0])
        >>> preds = jnp.array([0, 1, 0, 0])
        >>> iou = IoU(num_classes=2)
        >>> round(float(iou(preds, target)), 4)
        0.5833
    """

    def __init__(
        self,
        num_classes: int,
        ignore_index: Optional[int] = None,
        absent_score: float = 0.0,
        threshold: float = 0.5,
        reduction: str = "elementwise_mean",
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
    ):
        super().__init__(
            num_classes=num_classes,
            normalize=None,
            threshold=threshold,
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
        )
        self.reduction = reduction
        self.ignore_index = ignore_index
        self.absent_score = absent_score

    def compute(self) -> Array:
        return _iou_from_confmat(self.confmat, self.num_classes, self.ignore_index, self.absent_score, self.reduction)


class JaccardIndex(IoU):
    r"""Alias of :class:`IoU` under its set-theory name (later torchmetrics
    renamed ``IoU`` to ``JaccardIndex``; both names resolve here).

    Example:
        >>> import jax.numpy as jnp
        >>> jaccard = JaccardIndex(num_classes=2)
        >>> round(float(jaccard(jnp.array([0, 1, 0, 0]), jnp.array([1, 1, 0, 0]))), 4)
        0.5833
    """
