"""ConfusionMatrix module.

Parity target: reference ``torchmetrics/classification/confusion_matrix.py:23``
(``confmat`` zeros(C,C) "sum" state at :97).
"""
from typing import Any, Callable, Optional

import numpy as np
import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.classification.confusion_matrix import (
    _confusion_matrix_compute,
    _confusion_matrix_update,
)
from metrics_tpu.utils.data import accum_int_dtype


class ConfusionMatrix(Metric):
    """Accumulate a (C, C) confusion matrix over batches.

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([1, 1, 0, 0])
        >>> preds = jnp.array([0, 1, 0, 0])
        >>> confmat = ConfusionMatrix(num_classes=2)
        >>> confmat(preds, target)
        Array([[2., 0.],
               [1., 1.]], dtype=float32)
    """

    # compute-group key: ``normalize`` is compute-only, so e.g. a raw and a
    # row-normalized ConfusionMatrix over the same classes share one update
    _GROUP_UPDATE_ATTRS = ("num_classes", "threshold")

    def __init__(
        self,
        num_classes: int,
        normalize: Optional[str] = None,
        threshold: float = 0.5,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        self.num_classes = num_classes
        self.normalize = normalize
        self.threshold = threshold

        allowed_normalize = ("true", "pred", "all", "none", None)
        if self.normalize not in allowed_normalize:
            raise ValueError(f"Argument average needs to one of the following: {allowed_normalize}")

        # integer accumulator: keeps pair counts exact past float32's 2^24
        # (the per-batch kernel is exact bf16-matmul, counts accumulate in int)
        self.add_state(
            "confmat", default=np.zeros((num_classes, num_classes), dtype=accum_int_dtype()), dist_reduce_fx="sum"
        )

    def update(self, preds: Array, target: Array) -> None:
        confmat = _confusion_matrix_update(preds, target, self.num_classes, self.threshold)
        self.confmat = self.confmat + confmat

    def compute(self) -> Array:
        return _confusion_matrix_compute(self.confmat, self.normalize)
