"""Capacity-backed curve-vector compute for ``ROC`` / ``PrecisionRecallCurve``.

The reference computes curve vectors with data-dependent output shapes
(reference functional/classification/precision_recall_curve.py:114-160 /
roc.py:35-85) — host-bound extraction that XLA cannot stage, and through a
remote-device tunnel a single readback degrades every later dispatch. When
the metric was built with a ``capacity`` (PaddedBuffer states), compute
routes here instead: the static-shape padded kernels
(``functional/classification/curve_static.py``) run as ONE jitted dispatch
with zero readbacks, returning capacity-length curves plus a valid count.
"""
from typing import Any, Dict, Optional

import jax

from metrics_tpu.functional.classification.curve_static import (
    precision_recall_curve_padded,
    roc_padded,
)
from metrics_tpu.parallel.buffer import PaddedBuffer, buffer_mask

_KERNELS = {"roc": roc_padded, "prc": precision_recall_curve_padded}
# one jitted callable per kernel, shared across instances (jax.jit caches
# by abstract signature, so shapes/pos_label variants coexist under it)
_JITTED: Dict[str, Any] = {}


def padded_curve_compute(metric: Any, kind: str) -> Optional[tuple]:
    """Static-shape curve compute when the epoch states are PaddedBuffers;
    ``None`` -> caller keeps the reference-shaped dynamic path."""
    if not isinstance(metric.preds, PaddedBuffer):
        return None
    from metrics_tpu.parallel.sharded_dispatch import _check_counts, curve_sharded

    sharded = curve_sharded(metric, kind)  # row-sharded states: ring + key-sort
    if sharded is not None:
        return sharded
    _check_counts(metric, metric.preds, metric.target)

    fn = _JITTED.get(kind)
    if fn is None:
        fn = jax.jit(_KERNELS[kind], static_argnames=("pos_label",))
        _JITTED[kind] = fn

    pos_label = metric.pos_label if metric.pos_label is not None else 1
    return fn(
        metric.preds.data,
        metric.target.data,
        None,
        pos_label=int(pos_label),
        row_mask=buffer_mask(metric.preds),
    )
