"""Binned curve metric modules — O(1)-state, jit-safe, psum-syncable.

TPU-native additions with no reference counterpart (see
``metrics_tpu/functional/classification/binned_curves.py``): instead of
storing every prediction (the reference's cat-state AUROC/AP, reference
torchmetrics/classification/auroc.py:142-143), these keep per-threshold
TP/FP/TN/FN count states of shape ``(T,)`` / ``(C, T)`` — "sum"-reducible, so
they work inside jitted/pjit-ed training loops and sync with one ``psum``.
"""
from typing import Any, Callable, Optional, Tuple, Union

import numpy as np
import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.utils.data import accum_int_dtype
from metrics_tpu.functional.classification.binned_curves import (
    _as_thresholds,
    binned_stat_curve_update,
)


class _BinnedCurveMetric(Metric):
    """Shared machinery: accumulate per-threshold confusion counts."""

    def __init__(
        self,
        num_classes: Optional[int] = None,
        thresholds: Union[int, Array, None] = None,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        self.num_classes = num_classes
        self.thresholds = _as_thresholds(thresholds)
        num_t = self.thresholds.shape[0]
        shape = (num_t,) if num_classes is None else (num_classes, num_t)
        # int32 state: per-batch float32 counts are exact below 2**24 and the
        # integer accumulator then holds exact totals to 2**31 (the core
        # warns on approach — see Metric._check_accumulator_overflow)
        for name in ("tp", "fp", "tn", "fn"):
            self.add_state(name, default=np.zeros(shape, dtype=accum_int_dtype()), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        if self.num_classes is not None and preds.ndim == 1:
            raise ValueError(f"Expected per-class predictions (N, {self.num_classes}), got 1d input.")
        if self.num_classes is None and preds.ndim > 1:
            raise ValueError(
                "Got 2d per-class predictions but `num_classes` was not set; "
                "construct the metric with num_classes=C for multiclass/multilabel input."
            )
        tp, fp, tn, fn = binned_stat_curve_update(preds.astype(jnp.float32), target, self.thresholds)
        dt = self.tp.dtype
        self.tp = self.tp + tp.astype(dt)
        self.fp = self.fp + fp.astype(dt)
        self.tn = self.tn + tn.astype(dt)
        self.fn = self.fn + fn.astype(dt)


class BinnedPrecisionRecallCurve(_BinnedCurveMetric):
    """PR curve on a fixed threshold grid.

    Example:
        >>> import jax.numpy as jnp
        >>> m = BinnedPrecisionRecallCurve(thresholds=jnp.array([0.0, 0.5, 1.0]))
        >>> p, r, t = m(jnp.array([0.1, 0.4, 0.6, 0.8]), jnp.array([0, 1, 1, 1]))
        >>> p.tolist()
        [0.75, 1.0, 0.0]
    """

    def compute(self) -> Tuple[Array, Array, Array]:
        denom_p = self.tp + self.fp
        denom_r = self.tp + self.fn
        precision = jnp.where(denom_p == 0, 0.0, self.tp / jnp.where(denom_p == 0, 1.0, denom_p))
        recall = jnp.where(denom_r == 0, 0.0, self.tp / jnp.where(denom_r == 0, 1.0, denom_r))
        # thresholds are stored host-side (config); the public API returns arrays
        return precision, recall, jnp.asarray(self.thresholds)


class BinnedROC(_BinnedCurveMetric):
    """ROC on a fixed threshold grid."""

    def compute(self) -> Tuple[Array, Array, Array]:
        tpr = self.tp / jnp.maximum(self.tp + self.fn, 1.0)
        fpr = self.fp / jnp.maximum(self.fp + self.tn, 1.0)
        return fpr, tpr, jnp.asarray(self.thresholds)


class BinnedAUROC(_BinnedCurveMetric):
    """AUROC from binned counts (converges to exact as the grid refines)."""

    def compute(self) -> Array:
        tpr = self.tp / jnp.maximum(self.tp + self.fn, 1.0)
        fpr = self.fp / jnp.maximum(self.fp + self.tn, 1.0)
        return -jnp.trapezoid(tpr, fpr, axis=-1)


class BinnedAveragePrecision(_BinnedCurveMetric):
    """Average precision from binned counts."""

    def compute(self) -> Array:
        denom_p = self.tp + self.fp
        denom_r = self.tp + self.fn
        precision = jnp.where(denom_p == 0, 0.0, self.tp / jnp.where(denom_p == 0, 1.0, denom_p))
        recall = jnp.where(denom_r == 0, 0.0, self.tp / jnp.where(denom_r == 0, 1.0, denom_r))
        return -jnp.sum((recall[..., 1:] - recall[..., :-1]) * precision[..., :-1], axis=-1)
