"""CriticalSuccessIndex module. Extension beyond the reference snapshot
(later torchmetrics ``regression/csi.py``): the threat score
TP / (TP + FN + FP) used in forecast verification — predictions and
targets are thresholded to events, correct negatives are ignored."""
from typing import Any, Callable, Optional

import numpy as np
import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.classification.csi import _csi_compute, _csi_update
from metrics_tpu.utils.data import accum_int_dtype


class CriticalSuccessIndex(Metric):
    """Accumulated CSI: integer TP and (FP + FN) sums stream across batches
    and psum-sync; nan when no event is predicted or observed.

    Example:
        >>> import jax.numpy as jnp
        >>> metric = CriticalSuccessIndex(threshold=0.5)
        >>> float(metric(jnp.array([0.9, 0.4]), jnp.array([1.0, 0.0])))
        1.0
    """

    def __init__(
        self,
        threshold: float = 0.5,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
        jit: Optional[bool] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
            jit=jit,
        )
        self.threshold = float(threshold)
        self.add_state("tp", default=np.zeros((), dtype=accum_int_dtype()), dist_reduce_fx="sum")
        self.add_state("fp_fn", default=np.zeros((), dtype=accum_int_dtype()), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        tp, fp_fn = _csi_update(preds, target, self.threshold)
        self.tp = self.tp + tp
        self.fp_fn = self.fp_fn + fp_fn

    def compute(self) -> Array:
        return _csi_compute(jnp.asarray(self.tp), jnp.asarray(self.fp_fn))
