"""Deprecated import location (parity with reference
``torchmetrics/classification/checks.py:1-9``, which re-exports the input
checks from ``utilities.checks`` with a deprecation warning)."""
from metrics_tpu.utils.checks import (  # noqa: F401
    _check_classification_inputs,
    _input_format_classification,
    _input_format_classification_one_hot,
)
from metrics_tpu.utils.prints import rank_zero_warn

rank_zero_warn(
    "`metrics_tpu.classification.checks` is deprecated; import from `metrics_tpu.utils.checks` instead.",
    DeprecationWarning,
)
