"""Specificity module (subclass of StatScores).

Extension beyond the reference snapshot (later torchmetrics ships it);
mirrors the Precision/Recall pattern in classification/precision_recall.py.
"""
from typing import Any, Callable, Optional

from jax import Array

from metrics_tpu.classification.stat_scores import StatScores
from metrics_tpu.functional.classification.precision_recall import _ALLOWED_AVERAGE
from metrics_tpu.functional.classification.specificity import _specificity_compute


class Specificity(StatScores):
    r"""Specificity = TN / (TN + FP), accumulated over batches.

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.array([2, 0, 2, 1])
        >>> target = jnp.array([1, 1, 2, 0])
        >>> spec = Specificity(average='macro', num_classes=3)
        >>> round(float(spec(preds, target)), 4)
        0.6111
        >>> spec = Specificity(average='micro')
        >>> float(spec(preds, target))
        0.625
    """

    def __init__(
        self,
        num_classes: Optional[int] = None,
        threshold: float = 0.5,
        average: str = "micro",
        mdmc_average: Optional[str] = None,
        ignore_index: Optional[int] = None,
        top_k: Optional[int] = None,
        is_multiclass: Optional[bool] = None,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ):
        if average not in _ALLOWED_AVERAGE:
            raise ValueError(f"The `average` has to be one of {_ALLOWED_AVERAGE}, got {average}.")

        super().__init__(
            reduce="macro" if average in ["weighted", "none", None] else average,
            mdmc_reduce=mdmc_average,
            threshold=threshold,
            top_k=top_k,
            num_classes=num_classes,
            is_multiclass=is_multiclass,
            ignore_index=ignore_index,
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        self.average = average

    def compute(self) -> Array:
        tp, fp, tn, fn = self._get_final_stats()
        return _specificity_compute(tp, fp, tn, fn, self.average, self.mdmc_reduce)
