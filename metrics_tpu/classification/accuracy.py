"""Accuracy module.

Parity target: reference ``torchmetrics/classification/accuracy.py:23`` —
``correct``/``total`` "sum" states (:121-122), update via ``_accuracy_update``.
"""
from typing import Any, Callable, Optional

import numpy as np
import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.classification.accuracy import _accuracy_compute, _accuracy_update
from metrics_tpu.utils.data import accum_int_dtype


class Accuracy(Metric):
    r"""Fraction of correctly classified samples, accumulated over batches.

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([0, 1, 2, 3])
        >>> preds = jnp.array([0, 2, 1, 3])
        >>> accuracy = Accuracy()
        >>> float(accuracy(preds, target))
        0.5
    """

    # compute-group key: two Accuracy instances with the same thresholding
    # config share one update delta inside a MetricCollection
    _GROUP_UPDATE_ATTRS = ("threshold", "top_k", "subset_accuracy")

    def __init__(
        self,
        threshold: float = 0.5,
        top_k: Optional[int] = None,
        subset_accuracy: bool = False,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )

        self.add_state("correct", default=np.zeros((), dtype=accum_int_dtype()), dist_reduce_fx="sum")
        self.add_state("total", default=np.zeros((), dtype=accum_int_dtype()), dist_reduce_fx="sum")

        if not 0 < threshold < 1:
            raise ValueError(f"The `threshold` should be a float in the (0,1) interval, got {threshold}")

        if top_k is not None and (not isinstance(top_k, int) or top_k <= 0):
            raise ValueError(f"The `top_k` should be an integer larger than 0, got {top_k}")

        self.threshold = threshold
        self.top_k = top_k
        self.subset_accuracy = subset_accuracy

    def update(self, preds: Array, target: Array) -> None:
        correct, total = _accuracy_update(preds, target, self.threshold, self.top_k, self.subset_accuracy)
        self.correct = self.correct + correct
        self.total = self.total + total

    def compute(self) -> Array:
        return _accuracy_compute(self.correct, self.total)
