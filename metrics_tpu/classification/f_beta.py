"""FBeta / F1 modules (subclasses of StatScores).

Parity target: reference ``torchmetrics/classification/f_beta.py``
(``FBeta`` :23-172, ``F1`` :175-301).
"""
from typing import Any, Callable, Optional

from jax import Array

from metrics_tpu.classification.stat_scores import StatScores
from metrics_tpu.functional.classification.f_beta import _fbeta_compute
from metrics_tpu.functional.classification.precision_recall import _ALLOWED_AVERAGE


class FBeta(StatScores):
    r"""F-beta score, accumulated over batches.

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([0, 1, 2, 0, 1, 2])
        >>> preds = jnp.array([0, 2, 1, 0, 0, 1])
        >>> f_beta = FBeta(num_classes=3, beta=0.5)
        >>> round(float(f_beta(preds, target)), 4)
        0.3333
    """

    def __init__(
        self,
        num_classes: Optional[int] = None,
        beta: float = 1.0,
        threshold: float = 0.5,
        average: str = "micro",
        mdmc_average: Optional[str] = None,
        ignore_index: Optional[int] = None,
        top_k: Optional[int] = None,
        is_multiclass: Optional[bool] = None,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ):
        self.beta = beta
        if average not in _ALLOWED_AVERAGE:
            raise ValueError(f"The `average` has to be one of {_ALLOWED_AVERAGE}, got {average}.")

        super().__init__(
            reduce="macro" if average in ["weighted", "none", None] else average,
            mdmc_reduce=mdmc_average,
            threshold=threshold,
            top_k=top_k,
            num_classes=num_classes,
            is_multiclass=is_multiclass,
            ignore_index=ignore_index,
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        self.average = average

    def compute(self) -> Array:
        tp, fp, tn, fn = self._get_final_stats()
        return _fbeta_compute(tp, fp, tn, fn, self.beta, self.ignore_index, self.average, self.mdmc_reduce)


class F1(FBeta):
    r"""F1 score (FBeta with beta=1), accumulated over batches.

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([0, 1, 2, 0, 1, 2])
        >>> preds = jnp.array([0, 2, 1, 0, 0, 1])
        >>> f1 = F1(num_classes=3)
        >>> round(float(f1(preds, target)), 4)
        0.3333
    """

    def __init__(
        self,
        num_classes: Optional[int] = None,
        threshold: float = 0.5,
        average: str = "micro",
        mdmc_average: Optional[str] = None,
        ignore_index: Optional[int] = None,
        top_k: Optional[int] = None,
        is_multiclass: Optional[bool] = None,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ):
        super().__init__(
            num_classes=num_classes,
            beta=1.0,
            threshold=threshold,
            average=average,
            mdmc_average=mdmc_average,
            ignore_index=ignore_index,
            top_k=top_k,
            is_multiclass=is_multiclass,
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )


class Dice(F1):
    r"""Dice coefficient, accumulated over batches.

    ``Dice = 2 TP / (2 TP + FP + FN)`` — numerically identical to F1; this
    class exists for the segmentation-community name (later torchmetrics
    ships ``Dice`` with exactly these semantics). The reference snapshot
    ships only the per-call functional ``dice_score``.

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([0, 1, 2, 0, 1, 2])
        >>> preds = jnp.array([0, 2, 1, 0, 0, 1])
        >>> dice = Dice(num_classes=3)
        >>> round(float(dice(preds, target)), 4)
        0.3333
    """
