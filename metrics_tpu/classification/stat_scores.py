"""StatScores module + the shared score-reduction helper.

Parity target: reference ``torchmetrics/classification/stat_scores.py``
(``StatScores`` at :25, state registration at :179-189, ``_reduce_stat_scores``
at :277-340).
"""
from typing import Any, Callable, Optional, Tuple

import numpy as np
import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.classification.stat_scores import _stat_scores_compute, _stat_scores_update
from metrics_tpu.utils.data import accum_int_dtype, dim_zero_cat
from metrics_tpu.utils.enums import AverageMethod, MDMCAverageMethod


class StatScores(Metric):
    """Accumulate tp/fp/tn/fn (+support at compute) over batches.

    State layout mirrors reference :179-189: scalar / ``(C,)`` int "sum" states
    for global reductions, cat-states when per-sample scores are requested.

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.array([1, 0, 2, 1])
        >>> target = jnp.array([1, 1, 2, 0])
        >>> stat_scores = StatScores(reduce='macro', num_classes=3)
        >>> stat_scores(preds, target)
        Array([[0, 1, 2, 1, 1],
               [1, 1, 1, 1, 2],
               [1, 0, 3, 0, 1]], dtype=int32)
    """

    # MetricCollection compute groups: every StatScores-family metric
    # (Precision, Recall, F1/FBeta, Specificity, ...) runs the SAME
    # ``_stat_scores_update`` over tp/fp/tn/fn; only compute differs. These
    # are the update-relevant config attrs — matching values (and matching
    # state schema) let a whole collection share one update per step.
    # Compute-only config (FBeta.beta, the subclasses' ``average``) is
    # deliberately absent.
    _GROUP_UPDATE_ATTRS = (
        "reduce", "mdmc_reduce", "num_classes", "threshold", "is_multiclass",
        "ignore_index", "top_k",
    )

    def __init__(
        self,
        threshold: float = 0.5,
        top_k: Optional[int] = None,
        reduce: str = "micro",
        num_classes: Optional[int] = None,
        ignore_index: Optional[int] = None,
        mdmc_reduce: Optional[str] = None,
        is_multiclass: Optional[bool] = None,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
        capacity: Optional[int] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
            capacity=capacity,
        )

        self.reduce = reduce
        self.mdmc_reduce = mdmc_reduce
        self.num_classes = num_classes
        self.threshold = threshold
        self.is_multiclass = is_multiclass
        self.ignore_index = ignore_index
        self.top_k = top_k

        if not 0 < threshold < 1:
            raise ValueError(f"The `threshold` should be a float in the (0,1) interval, got {threshold}")
        if reduce not in ["micro", "macro", "samples"]:
            raise ValueError(f"The `reduce` {reduce} is not valid.")
        if mdmc_reduce not in [None, "samplewise", "global"]:
            raise ValueError(f"The `mdmc_reduce` {mdmc_reduce} is not valid.")
        if reduce == "macro" and (not num_classes or num_classes < 1):
            raise ValueError("When you set `reduce` as 'macro', you have to provide the number of classes.")
        if num_classes and ignore_index is not None and (not 0 <= ignore_index < num_classes or num_classes == 1):
            raise ValueError(f"The `ignore_index` {ignore_index} is not valid for inputs with {num_classes} classes")

        if mdmc_reduce != "samplewise" and reduce != "samples":
            zeros_shape = () if reduce == "micro" else (num_classes,)
            for s in ("tp", "fp", "tn", "fn"):
                self.add_state(s, default=np.zeros(zeros_shape, dtype=accum_int_dtype()), dist_reduce_fx="sum")
        else:
            for s in ("tp", "fp", "tn", "fn"):
                self.add_state(s, default=[], dist_reduce_fx=None)

    def update(self, preds: Array, target: Array) -> None:
        tp, fp, tn, fn = _stat_scores_update(
            preds,
            target,
            reduce=self.reduce,
            mdmc_reduce=self.mdmc_reduce,
            threshold=self.threshold,
            num_classes=self.num_classes,
            top_k=self.top_k,
            is_multiclass=self.is_multiclass,
            ignore_index=self.ignore_index,
        )

        if self.reduce != "samples" and self.mdmc_reduce != "samplewise":
            self.tp = self.tp + tp
            self.fp = self.fp + fp
            self.tn = self.tn + tn
            self.fn = self.fn + fn
        else:
            self._append("tp", tp)
            self._append("fp", fp)
            self._append("tn", tn)
            self._append("fn", fn)

    def _get_final_stats(self) -> Tuple[Array, Array, Array, Array]:
        if isinstance(self.tp, list):
            return (
                dim_zero_cat(self.tp),
                dim_zero_cat(self.fp),
                dim_zero_cat(self.tn),
                dim_zero_cat(self.fn),
            )
        return self.tp, self.fp, self.tn, self.fn

    def compute(self) -> Array:
        tp, fp, tn, fn = self._get_final_stats()
        return _stat_scores_compute(tp, fp, tn, fn)


def _reduce_stat_scores(
    numerator: Array,
    denominator: Array,
    weights: Optional[Array],
    average: Optional[str],
    mdmc_average: Optional[str],
    zero_division: int = 0,
) -> Array:
    """Reduce per-class ``numerator/denominator`` scores with micro/macro/
    weighted/none/samples averaging, zero-division handling and ignored
    (negative-denominator) classes — reference :277-340."""
    numerator = numerator.astype(jnp.float32)
    denominator = denominator.astype(jnp.float32)
    zero_div_mask = denominator == 0
    ignore_mask = denominator < 0

    weights = jnp.ones_like(denominator) if weights is None else weights.astype(jnp.float32)

    numerator = jnp.where(zero_div_mask, float(zero_division), numerator)
    denominator = jnp.where(zero_div_mask | ignore_mask, 1.0, denominator)
    weights = jnp.where(ignore_mask, 0.0, weights)

    if average not in (AverageMethod.MICRO, AverageMethod.NONE, None):
        weights = weights / jnp.sum(weights, axis=-1, keepdims=True)

    scores = weights * (numerator / denominator)
    # sum(weights) == 0 (only present class ignored with average='weighted') -> 0/0
    scores = jnp.where(jnp.isnan(scores), float(zero_division), scores)

    if mdmc_average == MDMCAverageMethod.SAMPLEWISE:
        scores = jnp.mean(scores, axis=0)
        ignore_mask = jnp.sum(ignore_mask, axis=0).astype(bool)

    if average in (AverageMethod.NONE, None):
        scores = jnp.where(ignore_mask, jnp.nan, scores)
    else:
        scores = jnp.sum(scores)

    return scores
