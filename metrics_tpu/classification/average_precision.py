"""AveragePrecision module (reference torchmetrics/classification/average_precision.py:27,
cat-states :93-94)."""
from typing import Any, Callable, List, Optional, Union

from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.classification.average_precision import (
    _average_precision_compute,
    _average_precision_update,
)
from metrics_tpu.parallel.buffer import as_values
from metrics_tpu.utils.prints import rank_zero_warn, rank_zero_warn_once


class AveragePrecision(Metric):
    """Average precision over all data seen.

    At pod scale, construct with a ``capacity`` and place the states with
    ``metrics_tpu.parallel.row_sharded(mesh)``: ``compute()`` then runs the
    exact sharded ring engine with O(capacity/n) per-device memory.

    Example (binary):
        >>> import jax.numpy as jnp
        >>> pred = jnp.array([0, 1, 2, 3])
        >>> target = jnp.array([0, 1, 1, 1])
        >>> average_precision = AveragePrecision(pos_label=1)
        >>> float(average_precision(pred, target))
        1.0
    """

    def __init__(
        self,
        num_classes: Optional[int] = None,
        pos_label: Optional[int] = None,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
        capacity: Optional[int] = None,
        jit: Optional[bool] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
            capacity=capacity,
            jit=jit,
        )

        self.num_classes = num_classes
        self.pos_label = pos_label

        self.add_state("preds", default=[], dist_reduce_fx=None)
        self.add_state("target", default=[], dist_reduce_fx=None)

        rank_zero_warn_once(
            "Metric `AveragePrecision` will save all targets and predictions in buffer."
            " For large datasets this may lead to large memory footprint."
        )

    def update(self, preds: Array, target: Array) -> None:
        preds, target, num_classes, pos_label = _average_precision_update(
            preds, target, self.num_classes, self.pos_label
        )
        self._append("preds", preds)
        self._append("target", target)
        self.num_classes = num_classes
        self.pos_label = pos_label

    def _states_own_sync(self) -> bool:
        from metrics_tpu.parallel.sharded_dispatch import average_precision_applicable

        return average_precision_applicable(self) is not None

    def compute(self) -> Union[List[Array], Array]:
        from metrics_tpu.parallel.sharded_dispatch import average_precision_sharded

        sharded = average_precision_sharded(self)  # row-sharded epoch states
        if sharded is not None:
            return sharded
        preds = as_values(self.preds)
        target = as_values(self.target)
        return _average_precision_compute(preds, target, self.num_classes, self.pos_label)
