"""AveragePrecision module (reference torchmetrics/classification/average_precision.py:27,
cat-states :93-94)."""
from typing import Any, Callable, List, Optional, Tuple, Union

from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.classification.average_precision import (
    _average_precision_compute,
    _average_precision_update,
)
from metrics_tpu.parallel.buffer import as_values
from metrics_tpu.parallel.qsketch import (
    QSKETCH_CURVE_ALPHA,
    QuantileSketch,
    qsketch_curve_group_key,
    qsketch_curve_spec,
    qsketch_curve_update,
)
from metrics_tpu.parallel.sketch import (
    HistogramSketch,
    average_precision_from_histogram,
    canonicalize_approx,
    curve_collision_bound,
    curve_sketch_group_key,
    curve_sketch_spec,
    sketch_curve_update,
)
from metrics_tpu.utils.prints import rank_zero_warn, rank_zero_warn_once


class AveragePrecision(Metric):
    """Average precision over all data seen.

    At pod scale, construct with a ``capacity`` and place the states with
    ``metrics_tpu.parallel.row_sharded(mesh)``: ``compute()`` then runs the
    exact sharded ring engine with O(capacity/n) per-device memory.

    Example (binary):
        >>> import jax.numpy as jnp
        >>> pred = jnp.array([0, 1, 2, 3])
        >>> target = jnp.array([0, 1, 1, 1])
        >>> average_precision = AveragePrecision(pos_label=1)
        >>> float(average_precision(pred, target))
        1.0
    """

    def __init__(
        self,
        num_classes: Optional[int] = None,
        pos_label: Optional[int] = None,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
        capacity: Optional[int] = None,
        jit: Optional[bool] = None,
        approx: Optional[str] = None,
        num_bins: int = 2048,
        sketch_range: Tuple[float, float] = (0.0, 1.0),
        alpha: float = QSKETCH_CURVE_ALPHA,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
            capacity=capacity,
            jit=jit,
        )

        self.num_classes = num_classes
        self.pos_label = pos_label
        self.approx = canonicalize_approx(approx, allowed=("sketch", "qsketch"))
        self.num_bins = num_bins
        self.sketch_range = tuple(sketch_range)
        self.alpha = float(alpha)

        if self.approx == "qsketch":
            # constant-memory AUTO-RANGED mode: scores bin on the log-bucketed
            # relative-accuracy grid — no sketch_range=(0, 1) assumption on
            # un-sigmoided scores; same step-integral AP over the counts
            self.add_state(
                "hist",
                default=qsketch_curve_spec(self.alpha, num_classes),
                dist_reduce_fx="sum",
            )
            return
        if self.approx == "sketch":
            # constant-memory mode: AP from the step integral over the
            # sketched PR curve, psum-synced HistogramSketch state
            self.add_state(
                "hist",
                default=curve_sketch_spec(num_bins, num_classes, *self.sketch_range),
                dist_reduce_fx="sum",
            )
            return
        self.add_state("preds", default=[], dist_reduce_fx=None)
        self.add_state("target", default=[], dist_reduce_fx=None)

        rank_zero_warn_once(
            "Metric `AveragePrecision` stores every prediction and target in an"
            " O(samples) buffer state, so memory and sync traffic grow with the"
            " dataset. Construct with `approx=\"qsketch\"` for a constant-memory"
            " AUTO-RANGED histogram sketch (no sketch_range assumption on raw"
            " scores) that syncs with one psum, `approx=\"sketch\"` for the"
            " fixed-grid variant, or use `BinnedAveragePrecision`; exact"
            " buffers remain the default."
        )

    def update(self, preds: Array, target: Array) -> None:
        if self.approx == "qsketch":
            pos_label = 1 if self.pos_label is None else self.pos_label
            spec = self._defaults["hist"]
            self.hist = QuantileSketch(
                qsketch_curve_update(
                    self.hist.counts, preds, target,
                    spec.alpha, spec.min_value, spec.max_value, pos_label,
                )
            )
            return
        if self.approx == "sketch":
            pos_label = 1 if self.pos_label is None else self.pos_label
            self.hist = HistogramSketch(
                sketch_curve_update(self.hist.counts, preds, target, *self.sketch_range, pos_label)
            )
            return
        preds, target, num_classes, pos_label = _average_precision_update(
            preds, target, self.num_classes, self.pos_label
        )
        self._append("preds", preds)
        self._append("target", target)
        self.num_classes = num_classes
        self.pos_label = pos_label

    def _group_fingerprint(self) -> Optional[Any]:
        if self.approx == "qsketch":
            return qsketch_curve_group_key(self)  # shared curve-family update
        if self.approx == "sketch":
            return curve_sketch_group_key(self)  # shared curve-family update
        return super()._group_fingerprint()

    def _states_own_sync(self) -> bool:
        if self.approx in ("sketch", "qsketch"):
            return False
        from metrics_tpu.parallel.sharded_dispatch import average_precision_applicable

        return average_precision_applicable(self) is not None

    def collision_bound(self) -> Array:
        """Data-dependent resolution certificate of the sketch modes: the
        unresolved positive/negative cross-pair fraction
        (``sketch.curve_collision_bound``) driving the step integral's
        deviation — grid-agnostic (fixed grid and qsketch alike)."""
        if self.approx not in ("sketch", "qsketch"):
            raise ValueError("collision_bound() needs approx='sketch' or 'qsketch'")
        return curve_collision_bound(self.hist.counts)

    def compute(self) -> Union[List[Array], Array]:
        from metrics_tpu.parallel.sharded_dispatch import average_precision_sharded

        if self.approx in ("sketch", "qsketch"):
            return average_precision_from_histogram(self.hist.counts)
        sharded = average_precision_sharded(self)  # row-sharded epoch states
        if sharded is not None:
            return sharded
        preds = as_values(self.preds)
        target = as_values(self.target)
        return _average_precision_compute(preds, target, self.num_classes, self.pos_label)
