"""MatthewsCorrcoef module.

Parity target: reference ``torchmetrics/classification/matthews_corrcoef.py:26``
(``confmat`` "sum" state at :97).
"""
from typing import Any, Callable, Optional

import numpy as np
import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.classification.matthews_corrcoef import (
    _matthews_corrcoef_compute,
    _matthews_corrcoef_update,
)
from metrics_tpu.utils.data import accum_int_dtype


class MatthewsCorrcoef(Metric):
    r"""Matthews correlation coefficient, accumulated via the confusion matrix.

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([1, 1, 0, 0])
        >>> preds = jnp.array([0, 1, 0, 0])
        >>> matthews_corrcoef = MatthewsCorrcoef(num_classes=2)
        >>> round(float(matthews_corrcoef(preds, target)), 4)
        0.5774
    """

    def __init__(
        self,
        num_classes: int,
        threshold: float = 0.5,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        self.num_classes = num_classes
        self.threshold = threshold

        self.add_state(
            "confmat", default=np.zeros((num_classes, num_classes), dtype=accum_int_dtype()), dist_reduce_fx="sum"
        )

    def update(self, preds: Array, target: Array) -> None:
        confmat = _matthews_corrcoef_update(preds, target, self.num_classes, self.threshold)
        self.confmat = self.confmat + confmat

    def compute(self) -> Array:
        return _matthews_corrcoef_compute(self.confmat)
