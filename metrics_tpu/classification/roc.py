"""ROC module (reference torchmetrics/classification/roc.py:24, cat-states :132-133)."""
from typing import Any, Callable, List, Optional, Tuple, Union

import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.classification.roc import _roc_compute, _roc_update
from metrics_tpu.parallel.buffer import as_values
from metrics_tpu.parallel.sketch import (
    HistogramSketch,
    canonicalize_approx,
    curve_sketch_group_key,
    curve_sketch_spec,
    roc_from_histogram,
    sketch_curve_update,
    sketch_thresholds,
)
from metrics_tpu.utils.prints import rank_zero_warn, rank_zero_warn_once


class ROC(Metric):
    """Receiver operating characteristic over all data seen.

    With a ``capacity``, the epoch states are jit-safe PaddedBuffers and
    ``compute()`` returns STATIC-shape padded curves — ``(fpr, tpr,
    thresholds, count)`` with the curve in the first ``count`` positions and
    the final point repeated after (multiclass/multilabel: leading class
    axis, per-class counts). The curve extraction is ONE fused device
    dispatch with no data readbacks (only the epoch-end scalar overflow
    check reads the row counts); the underlying kernels
    (``functional.classification.curve_static``) are fully jit/vmap-safe
    for in-jit use. Without ``capacity`` the reference-shaped dynamic
    3-tuple is returned unchanged.

    Example (binary):
        >>> import jax.numpy as jnp
        >>> pred = jnp.array([0, 1, 2, 3])
        >>> target = jnp.array([0, 1, 1, 1])
        >>> roc = ROC(pos_label=1)
        >>> fpr, tpr, thresholds = roc(pred, target)
        >>> fpr
        Array([0., 0., 0., 0., 1.], dtype=float32)
        >>> thresholds
        Array([4, 3, 2, 1, 0], dtype=int32)
    """

    def __init__(
        self,
        num_classes: Optional[int] = None,
        pos_label: Optional[int] = None,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
        capacity: Optional[int] = None,
        jit: Optional[bool] = None,
        approx: Optional[str] = None,
        num_bins: int = 2048,
        sketch_range: Tuple[float, float] = (0.0, 1.0),
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
            capacity=capacity,
            jit=jit,
        )

        self.num_classes = num_classes
        self.pos_label = pos_label
        self.approx = canonicalize_approx(approx)
        self.num_bins = num_bins
        self.sketch_range = tuple(sketch_range)

        if self.approx == "sketch":
            # constant-memory mode: the ROC is evaluated on the num_bins + 1
            # threshold grid (bin edges + the (0, 0) terminal anchor) from a
            # psum-synced HistogramSketch
            self.add_state(
                "hist",
                default=curve_sketch_spec(num_bins, num_classes, *self.sketch_range),
                dist_reduce_fx="sum",
            )
            return
        self.add_state("preds", default=[], dist_reduce_fx=None)
        self.add_state("target", default=[], dist_reduce_fx=None)

        rank_zero_warn_once(
            "Metric `ROC` stores every prediction and target in an O(samples)"
            " buffer state, so memory and sync traffic grow with the dataset."
            " Construct with `approx=\"sketch\"` for a constant-memory"
            " fixed-grid curve (one psum to sync), or use `BinnedROC`; for the"
            " scalar area on raw un-sigmoided scores, `AUROC(approx="
            "\"qsketch\")` is the RANGE-FREE fix (auto-ranged log-bucketed"
            " grid, no sketch_range assumption). Exact buffers remain the"
            " default."
        )

    def update(self, preds: Array, target: Array) -> None:
        if self.approx == "sketch":
            pos_label = 1 if self.pos_label is None else self.pos_label
            self.hist = HistogramSketch(
                sketch_curve_update(self.hist.counts, preds, target, *self.sketch_range, pos_label)
            )
            return
        preds, target, num_classes, pos_label = _roc_update(preds, target, self.num_classes, self.pos_label)
        self._append("preds", preds)
        self._append("target", target)
        self.num_classes = num_classes
        self.pos_label = pos_label

    def _group_fingerprint(self) -> Optional[Any]:
        if self.approx == "sketch":
            return curve_sketch_group_key(self)  # shared curve-family update
        return super()._group_fingerprint()

    def _states_own_sync(self) -> bool:
        if self.approx == "sketch":
            return False
        from metrics_tpu.parallel.sharded_dispatch import curve_applicable

        return curve_applicable(self) is not None

    def compute(
        self,
    ) -> Union[
        Tuple[Array, Array, Array],
        Tuple[List[Array], List[Array], List[Array]],
        Tuple[Array, Array, Array, Array],  # capacity path: padded curves + count
    ]:
        from metrics_tpu.classification._padded_curves import padded_curve_compute

        if self.approx == "sketch":
            fpr, tpr = roc_from_histogram(self.hist.counts)
            return fpr, tpr, jnp.asarray(sketch_thresholds(self.num_bins, *self.sketch_range))
        padded = padded_curve_compute(self, "roc")  # capacity-backed: static shapes
        if padded is not None:
            return padded
        preds = as_values(self.preds)
        target = as_values(self.target)
        return _roc_compute(preds, target, self.num_classes, self.pos_label)
