"""ExactMatch module (subset accuracy). Extension beyond the reference
snapshot (later torchmetrics ``classification/exact_match.py``)."""
from typing import Any, Callable, Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.classification.exact_match import (
    _exact_match_compute,
    _exact_match_update,
)


class ExactMatch(Metric):
    """Accumulated exact-match ratio: a sample is correct only when every
    position (all labels of a multilabel row, all elements of a multidim
    multiclass sample) agrees with the target.

    Two scalar sum-states — streams, shards, and psum-syncs like every
    sum-state metric.

    Example:
        >>> import jax.numpy as jnp
        >>> metric = ExactMatch(num_classes=3)
        >>> preds = jnp.array([[0, 1], [2, 1]])
        >>> target = jnp.array([[0, 1], [1, 1]])
        >>> float(metric(preds, target))
        0.5
    """

    def __init__(
        self,
        threshold: float = 0.5,
        num_classes: Optional[int] = None,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
        jit: Optional[bool] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
            jit=jit,
        )
        self.threshold = threshold
        self.num_classes = num_classes
        self.add_state("correct", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        correct, total = _exact_match_update(preds, target, self.threshold, self.num_classes)
        self.correct = self.correct + correct
        self.total = self.total + total

    def compute(self) -> Array:
        return _exact_match_compute(self.correct, self.total)
