"""AUROC module (reference torchmetrics/classification/auroc.py:25, cat-states :142-143)."""
from typing import Any, Callable, Optional, Tuple

import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.classification.auroc import _auroc_compute, _auroc_update
from metrics_tpu.parallel.buffer import as_values
from metrics_tpu.parallel.qsketch import (
    QSKETCH_CURVE_ALPHA,
    QuantileSketch,
    qsketch_curve_group_key,
    qsketch_curve_spec,
    qsketch_curve_update,
)
from metrics_tpu.parallel.sketch import (
    HistogramSketch,
    auroc_error_bound,
    auroc_from_histogram,
    canonicalize_approx,
    curve_sketch_group_key,
    curve_sketch_spec,
    sketch_curve_update,
)
from metrics_tpu.utils.prints import rank_zero_warn, rank_zero_warn_once


class AUROC(Metric):
    """Area under the ROC curve, over all data seen.

    At pod scale, keep the epoch sharded instead of gathered: construct with
    a ``capacity`` and place the states with
    ``metrics_tpu.parallel.row_sharded(mesh)`` — ``compute()`` then
    dispatches the exact ring engine (``parallel/sharded_epoch.py``) with
    O(capacity/n) per-device memory, through this same interface. (The
    raw in-``shard_map`` form remains available as
    ``metrics_tpu.parallel.sharded_auroc``.)

    Or drop the O(samples) state entirely: ``approx="sketch"`` replaces the
    prediction buffers with a constant-memory :class:`~metrics_tpu.parallel.
    sketch.HistogramSketch` of ``num_bins`` score bins per class over
    ``sketch_range`` — ``update`` is one jittable scatter-add, ``sync`` is
    one ``psum`` riding the coalesced sum buckets (zero gathers, bit-exact
    merge), and ``compute`` derives the AUROC from the sketched ROC with
    error bounded by the in-bin collision mass
    (:func:`~metrics_tpu.parallel.sketch.auroc_error_bound`). Multiclass /
    multilabel sketch mode needs ``num_classes`` at construction;
    ``max_fpr`` needs the exact mode.

    ``approx="qsketch"`` is the AUTO-RANGED variant: scores bin on the
    log-bucketed relative-accuracy grid of
    :mod:`~metrics_tpu.parallel.qsketch` (``alpha``; ``num_bins`` /
    ``sketch_range`` do not apply) — raw logits, un-sigmoided scores and
    drifting calibration outputs keep per-decade resolution with NO
    ``sketch_range=(0, 1)`` assumption. The thresholded-count derivation
    only ever needed a monotone grid, so the same curve math, the same
    one-psum sync and the same :meth:`error_bound` certificate apply.

    Example (binary):
        >>> import jax.numpy as jnp
        >>> preds = jnp.array([0.13, 0.26, 0.08, 0.19, 0.34])
        >>> target = jnp.array([0, 0, 1, 1, 1])
        >>> auroc = AUROC(pos_label=1)
        >>> float(auroc(preds, target))
        0.5
    """

    def __init__(
        self,
        num_classes: Optional[int] = None,
        pos_label: Optional[int] = None,
        average: Optional[str] = "macro",
        max_fpr: Optional[float] = None,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
        capacity: Optional[int] = None,
        jit: Optional[bool] = None,
        approx: Optional[str] = None,
        num_bins: int = 2048,
        sketch_range: Tuple[float, float] = (0.0, 1.0),
        alpha: float = QSKETCH_CURVE_ALPHA,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
            capacity=capacity,
            jit=jit,
        )

        self.num_classes = num_classes
        self.pos_label = pos_label
        self.average = average
        self.max_fpr = max_fpr
        self.approx = canonicalize_approx(approx, allowed=("sketch", "qsketch"))
        self.num_bins = num_bins
        self.sketch_range = tuple(sketch_range)
        self.alpha = float(alpha)

        allowed_average = (None, "macro", "weighted", "micro")
        if self.average not in allowed_average:
            raise ValueError(
                f"Argument `average` expected to be one of the following: {allowed_average} but got {average}"
            )

        if self.max_fpr is not None:
            if not isinstance(max_fpr, float) or not 0 < max_fpr <= 1:
                raise ValueError(f"`max_fpr` should be a float in range (0, 1], got: {max_fpr}")

        self.mode = None
        if self.approx in ("sketch", "qsketch"):
            if self.max_fpr is not None:
                raise ValueError(
                    f"`max_fpr` (partial AUC) is not supported with approx={self.approx!r};"
                    " use the exact buffer mode."
                )
            if self.approx == "qsketch":
                self.add_state(
                    "hist",
                    default=qsketch_curve_spec(self.alpha, num_classes),
                    dist_reduce_fx="sum",
                )
            else:
                self.add_state(
                    "hist",
                    default=curve_sketch_spec(num_bins, num_classes, *self.sketch_range),
                    dist_reduce_fx="sum",
                )
            return
        self.add_state("preds", default=[], dist_reduce_fx=None)
        self.add_state("target", default=[], dist_reduce_fx=None)

        rank_zero_warn_once(
            "Metric `AUROC` stores every prediction and target in an O(samples)"
            " buffer state, so memory and sync traffic grow with the dataset."
            " Construct with `approx=\"qsketch\"` for a constant-memory"
            " AUTO-RANGED histogram sketch (no sketch_range assumption on raw"
            " logits) that syncs with one psum, `approx=\"sketch\"` for the"
            " fixed-grid variant, or use `BinnedAUROC`; exact buffers remain"
            " the default."
        )

    def update(self, preds: Array, target: Array) -> None:
        if self.approx == "qsketch":
            pos_label = 1 if self.pos_label is None else self.pos_label
            spec = self._defaults["hist"]
            self.hist = QuantileSketch(
                qsketch_curve_update(
                    self.hist.counts, preds, target,
                    spec.alpha, spec.min_value, spec.max_value, pos_label,
                )
            )
            return
        if self.approx == "sketch":
            pos_label = 1 if self.pos_label is None else self.pos_label
            self.hist = HistogramSketch(
                sketch_curve_update(self.hist.counts, preds, target, *self.sketch_range, pos_label)
            )
            return
        preds, target, mode = _auroc_update(preds, target)

        self._append("preds", preds)
        self._append("target", target)

        if self.mode is not None and self.mode != mode:
            raise ValueError(
                "The mode of data (binary, multi-label, multi-class) should be constant, but changed"
                f" between batches from {self.mode} to {mode}"
            )
        self.mode = mode

    def _group_fingerprint(self) -> Optional[Any]:
        # sketch-mode curve metrics share ONE update plane (the scatter-add of
        # sketch_curve_update / qsketch_curve_update) across the curve family —
        # equal sketch config means one compute-group delta serves them all
        if self.approx == "qsketch":
            return qsketch_curve_group_key(self)
        if self.approx == "sketch":
            return curve_sketch_group_key(self)
        return super()._group_fingerprint()

    def _sketch_compute(self) -> Array:
        counts = self.hist.counts
        if counts.ndim == 2:
            return auroc_from_histogram(counts)
        if self.average == "micro":
            return auroc_from_histogram(jnp.sum(counts, axis=0))
        per_class = auroc_from_histogram(counts)  # (C,)
        if self.average == "macro":
            return jnp.mean(per_class)
        if self.average == "weighted":
            support = jnp.sum(counts[:, 0, :], axis=-1).astype(jnp.float32)
            return jnp.sum(per_class * support / jnp.maximum(jnp.sum(support), 1.0))
        return per_class

    def _states_own_sync(self) -> bool:
        if self.approx in ("sketch", "qsketch"):
            return False  # sketch sync IS the psum plane; nothing to suppress
        from metrics_tpu.parallel.sharded_dispatch import auroc_applicable

        return auroc_applicable(self) is not None

    def error_bound(self) -> Array:
        """Data-dependent certificate of the sketch modes:
        ``|sketch AUROC - exact AUROC| <= bound``, half the in-bin collision
        mass (``sketch.auroc_error_bound``) — grid-agnostic, so it covers
        both the fixed ``sketch_range`` grid and the auto-ranged qsketch
        grid. Per-class for multiclass/multilabel layouts."""
        if self.approx not in ("sketch", "qsketch"):
            raise ValueError("error_bound() needs approx='sketch' or 'qsketch'")
        return auroc_error_bound(self.hist.counts)

    def compute(self) -> Array:
        from metrics_tpu.observability.trace import TRACE, span
        from metrics_tpu.parallel.sharded_dispatch import auroc_sharded

        if self.approx in ("sketch", "qsketch"):
            return self._sketch_compute()
        sharded = auroc_sharded(self)  # row-sharded epoch states: exact ring
        if sharded is not None:
            return sharded
        # the gather path materializes the epoch on every device — the span
        # makes that O(dataset) cost visible next to the sharded launches
        if TRACE.enabled:
            with span("auroc.gather_compute", {"rows": len(self.preds) if isinstance(self.preds, list) else -1}):
                preds = as_values(self.preds)
                target = as_values(self.target)
                return _auroc_compute(
                    preds, target, self.mode, self.num_classes, self.pos_label,
                    self.average, self.max_fpr,
                )
        preds = as_values(self.preds)
        target = as_values(self.target)
        return _auroc_compute(
            preds,
            target,
            self.mode,
            self.num_classes,
            self.pos_label,
            self.average,
            self.max_fpr,
        )
