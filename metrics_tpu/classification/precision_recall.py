"""Precision / Recall modules (subclasses of StatScores).

Parity target: reference ``torchmetrics/classification/precision_recall.py``
(``Precision`` :23-170, ``Recall`` :173-321).
"""
from typing import Any, Callable, Optional

from jax import Array

from metrics_tpu.classification.stat_scores import StatScores
from metrics_tpu.functional.classification.precision_recall import (
    _ALLOWED_AVERAGE,
    _precision_compute,
    _recall_compute,
)


class Precision(StatScores):
    r"""Precision = TP / (TP + FP), accumulated over batches.

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.array([2, 0, 2, 1])
        >>> target = jnp.array([1, 1, 2, 0])
        >>> precision = Precision(average='macro', num_classes=3)
        >>> round(float(precision(preds, target)), 4)
        0.1667
        >>> precision = Precision(average='micro')
        >>> float(precision(preds, target))
        0.25
    """

    def __init__(
        self,
        num_classes: Optional[int] = None,
        threshold: float = 0.5,
        average: str = "micro",
        mdmc_average: Optional[str] = None,
        ignore_index: Optional[int] = None,
        top_k: Optional[int] = None,
        is_multiclass: Optional[bool] = None,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ):
        if average not in _ALLOWED_AVERAGE:
            raise ValueError(f"The `average` has to be one of {_ALLOWED_AVERAGE}, got {average}.")

        super().__init__(
            reduce="macro" if average in ["weighted", "none", None] else average,
            mdmc_reduce=mdmc_average,
            threshold=threshold,
            top_k=top_k,
            num_classes=num_classes,
            is_multiclass=is_multiclass,
            ignore_index=ignore_index,
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        self.average = average

    def compute(self) -> Array:
        tp, fp, tn, fn = self._get_final_stats()
        return _precision_compute(tp, fp, tn, fn, self.average, self.mdmc_reduce)


class Recall(StatScores):
    r"""Recall = TP / (TP + FN), accumulated over batches.

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.array([2, 0, 2, 1])
        >>> target = jnp.array([1, 1, 2, 0])
        >>> recall = Recall(average='macro', num_classes=3)
        >>> round(float(recall(preds, target)), 4)
        0.3333
        >>> recall = Recall(average='micro')
        >>> float(recall(preds, target))
        0.25
    """

    def __init__(
        self,
        num_classes: Optional[int] = None,
        threshold: float = 0.5,
        average: str = "micro",
        mdmc_average: Optional[str] = None,
        ignore_index: Optional[int] = None,
        top_k: Optional[int] = None,
        is_multiclass: Optional[bool] = None,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ):
        if average not in _ALLOWED_AVERAGE:
            raise ValueError(f"The `average` has to be one of {_ALLOWED_AVERAGE}, got {average}.")

        super().__init__(
            reduce="macro" if average in ["weighted", "none", None] else average,
            mdmc_reduce=mdmc_average,
            threshold=threshold,
            top_k=top_k,
            num_classes=num_classes,
            is_multiclass=is_multiclass,
            ignore_index=ignore_index,
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        self.average = average

    def compute(self) -> Array:
        tp, fp, tn, fn = self._get_final_stats()
        return _recall_compute(tp, fp, tn, fn, self.average, self.mdmc_reduce)
