"""HammingDistance module.

Parity target: reference ``torchmetrics/classification/hamming_distance.py:23``
(``correct``/``total`` "sum" states at :86-87).
"""
from typing import Any, Callable, Optional

import numpy as np
import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.classification.hamming_distance import (
    _hamming_distance_compute,
    _hamming_distance_update,
)
from metrics_tpu.utils.data import accum_int_dtype


class HammingDistance(Metric):
    r"""Average Hamming loss, accumulated over batches.

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([[0, 1], [1, 1]])
        >>> preds = jnp.array([[0, 1], [0, 1]])
        >>> hamming_distance = HammingDistance()
        >>> float(hamming_distance(preds, target))
        0.25
    """

    _GROUP_UPDATE_ATTRS = ("threshold",)

    def __init__(
        self,
        threshold: float = 0.5,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )

        self.add_state("correct", default=np.zeros((), dtype=accum_int_dtype()), dist_reduce_fx="sum")
        self.add_state("total", default=np.zeros((), dtype=accum_int_dtype()), dist_reduce_fx="sum")

        if not 0 < threshold < 1:
            raise ValueError(f"The `threshold` should be a float in the (0,1) interval, got {threshold}")
        self.threshold = threshold

    def update(self, preds: Array, target: Array) -> None:
        correct, total = _hamming_distance_update(preds, target, self.threshold)
        self.correct = self.correct + correct
        self.total = self.total + total

    def compute(self) -> Array:
        return _hamming_distance_compute(self.correct, self.total)
