"""Stateful multilabel ranking metrics. Extension beyond the reference snapshot.

All three stream two scalar sum-states (per-sample total + count), so the
distributed story is a single fused psum — no cat-state growth with dataset
size. Semantics (ties, degenerate rows) match sklearn; see
``functional/classification/ranking.py``.
"""
from typing import Any, Callable, Optional, Tuple

import numpy as np
import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.classification.ranking import (
    _coverage_error_update,
    _label_ranking_ap_update,
    _label_ranking_loss_update,
)
from metrics_tpu.utils.data import accum_int_dtype


class _RankingMetric(Metric):
    """Shared streaming base: accumulate (per-sample total, sample count)."""

    _update_fn: Optional[Callable[[Array, Array], Tuple[Array, Array]]] = None

    def __init__(
        self,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        self.add_state("measure", default=np.zeros((), dtype=np.float32), dist_reduce_fx="sum")
        self.add_state("total", default=np.zeros((), dtype=accum_int_dtype()), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        measure, n = type(self)._update_fn(preds, target)
        self.measure = self.measure + measure
        self.total = self.total + n

    def compute(self) -> Array:
        return self.measure / jnp.maximum(self.total.astype(jnp.float32), 1.0)


class CoverageError(_RankingMetric):
    """Multilabel coverage error (sklearn ``coverage_error``).

    Example:
        >>> import jax.numpy as jnp
        >>> metric = CoverageError()
        >>> _ = metric(jnp.array([[0.9, 0.1, 0.5]]), jnp.array([[1, 0, 1]]))
        >>> _ = metric(jnp.array([[0.2, 0.8, 0.6]]), jnp.array([[0, 1, 0]]))
        >>> float(metric.compute())
        1.5
    """

    _update_fn = staticmethod(_coverage_error_update)


class LabelRankingAveragePrecision(_RankingMetric):
    """Label-ranking average precision
    (sklearn ``label_ranking_average_precision_score``).

    Example:
        >>> import jax.numpy as jnp
        >>> metric = LabelRankingAveragePrecision()
        >>> _ = metric(jnp.array([[0.75, 0.5, 1.0]]), jnp.array([[1, 0, 0]]))
        >>> _ = metric(jnp.array([[1.0, 0.2, 0.1]]), jnp.array([[0, 0, 1]]))
        >>> round(float(metric.compute()), 4)
        0.4167
    """

    _update_fn = staticmethod(_label_ranking_ap_update)


class LabelRankingLoss(_RankingMetric):
    """Label ranking loss (sklearn ``label_ranking_loss``).

    Example:
        >>> import jax.numpy as jnp
        >>> metric = LabelRankingLoss()
        >>> _ = metric(jnp.array([[0.2, 0.8, 0.6]]), jnp.array([[0, 1, 0]]))
        >>> _ = metric(jnp.array([[0.9, 0.6, 0.5]]), jnp.array([[1, 0, 1]]))
        >>> float(metric.compute())
        0.25
    """

    _update_fn = staticmethod(_label_ranking_loss_update)
