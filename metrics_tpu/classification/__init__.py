from metrics_tpu.classification.accuracy import Accuracy
from metrics_tpu.classification.csi import CriticalSuccessIndex
from metrics_tpu.classification.exact_match import ExactMatch
from metrics_tpu.classification.auc import AUC
from metrics_tpu.classification.auroc import AUROC
from metrics_tpu.classification.average_precision import AveragePrecision
from metrics_tpu.classification.binned import (
    BinnedAUROC,
    BinnedAveragePrecision,
    BinnedPrecisionRecallCurve,
    BinnedROC,
)
from metrics_tpu.classification.cohen_kappa import CohenKappa
from metrics_tpu.classification.confusion_matrix import ConfusionMatrix
from metrics_tpu.classification.f_beta import Dice, F1, FBeta
from metrics_tpu.classification.hamming_distance import HammingDistance
from metrics_tpu.classification.iou import IoU, JaccardIndex
from metrics_tpu.classification.specificity import Specificity
from metrics_tpu.classification.matthews_corrcoef import MatthewsCorrcoef
from metrics_tpu.classification.precision_recall import Precision, Recall
from metrics_tpu.classification.precision_recall_curve import PrecisionRecallCurve
from metrics_tpu.classification.roc import ROC
from metrics_tpu.classification.stat_scores import StatScores
from metrics_tpu.classification.calibration_error import CalibrationError
from metrics_tpu.classification.hinge import HingeLoss
from metrics_tpu.classification.ranking import (
    CoverageError,
    LabelRankingAveragePrecision,
    LabelRankingLoss,
)
