"""AUC module (reference torchmetrics/classification/auc.py:24, cat-states :64-65)."""
from typing import Any, Callable, Optional

from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.classification.auc import _auc_compute, _auc_update
from metrics_tpu.parallel.buffer import as_values
from metrics_tpu.utils.prints import rank_zero_warn, rank_zero_warn_once


class AUC(Metric):
    """Area under an accumulated (x, y) curve."""

    def __init__(
        self,
        reorder: bool = False,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )

        self.reorder = reorder

        self.add_state("x", default=[], dist_reduce_fx=None)
        self.add_state("y", default=[], dist_reduce_fx=None)

        rank_zero_warn_once(
            "Metric `AUC` stores every (x, y) point in an O(samples) buffer"
            " state, so memory and sync traffic grow with the dataset. For"
            " score curves, prefer the constant-memory sketch modes of the"
            " curve metrics — `AUROC(approx=\"qsketch\")` is the RANGE-FREE"
            " fix (auto-ranged log-bucketed grid, no sketch_range assumption"
            " on raw scores); `AUROC(approx=\"sketch\")` / `BinnedAUROC`"
            " integrate on a fixed grid — all syncing with one psum."
        )

    def update(self, x: Array, y: Array) -> None:
        x, y = _auc_update(x, y)
        self._append("x", x)
        self._append("y", y)

    def compute(self) -> Array:
        x = as_values(self.x)
        y = as_values(self.y)
        return _auc_compute(x, y, reorder=self.reorder)
