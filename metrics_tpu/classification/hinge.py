"""HingeLoss module. Extension beyond the reference snapshot (later
torchmetrics ``classification/hinge.py``)."""
from typing import Any, Callable, Optional, Tuple

from jax import Array

from metrics_tpu.core.streaming import SumCountMetric
from metrics_tpu.functional.classification.hinge import _hinge_update


class HingeLoss(SumCountMetric):
    r"""Accumulated mean (squared) hinge loss, sklearn-compatible.

    Binary inputs are ``(N,)`` decision values with ``{0, 1}`` (or
    ``{-1, +1}``) targets; multiclass ``(N, C)`` scores use the
    Crammer-Singer margin.

    Example:
        >>> import jax.numpy as jnp
        >>> metric = HingeLoss()
        >>> round(float(metric(jnp.array([0.5, -1.5, 2.0]), jnp.array([1, 0, 1]))), 4)
        0.1667
    """

    def __init__(
        self,
        squared: bool = False,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        self.squared = squared

    def _update_stats(self, preds: Array, target: Array) -> Tuple[Array, Any]:
        return _hinge_update(preds, target, self.squared)
