"""CalibrationError module. Extension beyond the reference snapshot (later
torchmetrics ``torchmetrics/classification/calibration_error.py``).

Streaming state is three ``(n_bins,)`` ``"sum"`` vectors — the binned design
means the epoch statistic is EXACT while staying O(bins) memory with a single
fused ``psum`` for cross-device sync (contrast the curve metrics, which need
the full score set for exactness).
"""
from typing import Any, Callable, Optional

import numpy as np
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.classification.calibration_error import (
    _NORMS,
    _calibration_compute,
    _calibration_update,
)


class CalibrationError(Metric):
    r"""Accumulated top-1 calibration error (ECE / RMSCE / MCE).

    Args:
        n_bins: number of uniform confidence bins over [0, 1].
        norm: "l1" (ECE, default), "l2" (RMS), or "max" (MCE).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.array([[0.9, 0.1], [0.6, 0.4], [0.2, 0.8]])
        >>> target = jnp.array([0, 1, 1])
        >>> ce = CalibrationError(n_bins=4)
        >>> round(float(ce(preds, target)), 4)
        0.3
    """

    def __init__(
        self,
        n_bins: int = 15,
        norm: str = "l1",
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        if norm not in _NORMS:
            raise ValueError(f"`norm` must be one of {_NORMS}, got {norm!r}")
        if not isinstance(n_bins, int) or n_bins <= 0:
            raise ValueError(f"`n_bins` must be a positive integer, got {n_bins!r}")
        from metrics_tpu.utils.data import accum_int_dtype

        self.n_bins = n_bins
        self.norm = norm
        for name in ("conf_sum", "acc_sum"):
            self.add_state(name, default=np.zeros((n_bins,), dtype=np.float32), dist_reduce_fx="sum")
        # integer counts: float32 stops incrementing at 2^24, and int states
        # get the shared overflow warning
        self.add_state("count", default=np.zeros((n_bins,), dtype=accum_int_dtype()), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        conf_sum, acc_sum, count = _calibration_update(preds, target, self.n_bins)
        self.conf_sum = self.conf_sum + conf_sum
        self.acc_sum = self.acc_sum + acc_sum
        self.count = self.count + count

    def compute(self) -> Array:
        return _calibration_compute(self.conf_sum, self.acc_sum, self.count, self.norm)
