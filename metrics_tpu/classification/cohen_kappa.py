"""CohenKappa module.

Parity target: reference ``torchmetrics/classification/cohen_kappa.py:23``
(``confmat`` "sum" state at :102).
"""
from typing import Any, Callable, Optional

import numpy as np
import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.classification.cohen_kappa import _cohen_kappa_compute, _cohen_kappa_update
from metrics_tpu.utils.data import accum_int_dtype


class CohenKappa(Metric):
    r"""Cohen's kappa, accumulated over batches via the confusion matrix.

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([1, 1, 0, 0])
        >>> preds = jnp.array([0, 1, 0, 0])
        >>> cohenkappa = CohenKappa(num_classes=2)
        >>> float(cohenkappa(preds, target))
        0.5
    """

    def __init__(
        self,
        num_classes: int,
        weights: Optional[str] = None,
        threshold: float = 0.5,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        self.num_classes = num_classes
        self.weights = weights
        self.threshold = threshold

        allowed_weights = ("linear", "quadratic", "none", None)
        if self.weights not in allowed_weights:
            raise ValueError(f"Argument weights needs to one of the following: {allowed_weights}")

        self.add_state(
            "confmat", default=np.zeros((num_classes, num_classes), dtype=accum_int_dtype()), dist_reduce_fx="sum"
        )

    def update(self, preds: Array, target: Array) -> None:
        confmat = _cohen_kappa_update(preds, target, self.num_classes, self.threshold)
        self.confmat = self.confmat + confmat

    def compute(self) -> Array:
        weights = None if self.weights == "none" else self.weights
        return _cohen_kappa_compute(self.confmat, weights)
