"""Streaming-plane core: sum/count metric base + the windowed-runtime math.

Two things live here:

1. :class:`SumCountMetric` — the shared base for metrics that reduce to
   "sum of per-sample statistics divided by a count": two states, both plain
   ``"sum"`` reductions — O(1) memory, one fused psum to sync, counts in the
   package integer accumulator dtype (float32 counts stop incrementing at
   2^24; int states get the overflow warning and widen to int64 under
   ``jax_enable_x64``).

2. The **windowed serving-plane math**: :class:`WindowSpec` (tumbling
   windows of ``window_s`` seconds over a ring of ``num_windows`` slots,
   with an ``allowed_lateness_s`` grace), :func:`route_events` (the
   watermark-advancing event router every ``Windowed.update`` call runs),
   and :func:`decay_scale` (the exponential time-decay accumulator's per-
   batch scale). These are pure host-side numpy functions — the routing
   decision is data-dependent host work by construction (the same argument
   as the LRU slot table in ``parallel/slab.py``), while the scatter that
   CONSUMES the resolved slot ids stays an XLA ``segment_sum``.

Routing contract (what makes the windowed plane testable): for one batch,
the watermark first advances to ``max(old watermark, max(event_time))``;
an event is then accepted iff its WINDOW is still open — ``window_start +
window_s + allowed_lateness_s > watermark`` (a window stays open for
``allowed_lateness_s`` past its end; head-window events are never late).
Accepted events route to ``window % num_windows`` (the head window scatters
normally, late-but-within-lateness events land in their still-open prior
slot); rejected events get slot ``-1`` — DROPPED by the slab scatter's XLA
out-of-bounds semantics, never misrouted — and are counted
(``slab_dropped_samples``). Because a verdict depends only on the event's
window and the running watermark maximum, shuffling a stream whose every
event stays within the allowed lateness of the stream maximum changes
neither verdicts nor slot ids, and the scatter-adds commute: in-order and
shuffled streams produce bit-exact window slabs
(``tests/wrappers/test_windowed.py`` pins it).

Two generalizations of that contract live here too:

- **Sliding windows** (``WindowSpec(slide_s=...)`` with ``slide_s <
  window_s``): windows start every ``slide_s`` seconds and span ``window_s``
  — window ``w`` covers ``[w*slide_s, w*slide_s + window_s)`` — so each
  event belongs to ``window_s / slide_s`` consecutive windows and
  :func:`route_events` emits that many slot rows per batch (the newest
  covering window in ``slot_ids``, the older coverings in
  ``overlap_slots``). Each row is judged by the SAME open rule, so a
  partially-late event still lands in every covering window that is open.
  Tumbling windows are the ``slide_s == window_s`` special case (one row).
- **The agreed clock** (``route_events(..., agreed=)``): on a multi-rank
  stream each rank's local running max is only ITS view of event time — a
  skewed producer can run 30 s ahead of honest peers. Passing the agreed
  (global-min, :class:`WatermarkAgreement`) watermark makes the open/late
  verdict a pure function of ``(window, agreed watermark)``: "late" means
  the same thing on every rank, a fast rank cannot close a window its peers
  still feed, and a slow rank's events are judged by the clock the fleet
  actually agreed on. The LOCAL watermark still advances (it is the rank's
  contribution to the next agreement round) and still drives ring-slot
  residency — an event whose window is open by the agreed clock but whose
  slot the local ring already recycled is dropped-and-counted, never
  misrouted (size the ring for the tolerated skew).
"""
import itertools
import math
import threading
import time
from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.observability.counters import (
    record_watermark_agreement,
    record_wm_exchange,
    record_wm_straggler,
)
from metrics_tpu.utils.data import accum_int_dtype

__all__ = [
    "RouteResult",
    "SumCountMetric",
    "WatermarkAgreement",
    "WindowSpec",
    "decay_scale",
    "route_events",
    "window_index",
]


class SumCountMetric(Metric):
    """``compute() = f(total / count)`` over streaming sum states.

    Subclasses implement ``_update_stats(*args, **kwargs) -> (sum, count)``
    (count may be a static int or a traced integer array) and optionally
    ``_finalize(mean) -> value``.
    """

    def __init__(
        self,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        self.add_state("total", default=np.zeros(()), dist_reduce_fx="sum")
        self.add_state("count", default=np.zeros((), dtype=accum_int_dtype()), dist_reduce_fx="sum")

    def _update_stats(self, *args: Any, **kwargs: Any) -> Tuple[Array, Any]:
        raise NotImplementedError  # pragma: no cover - subclasses define the kernel

    def _finalize(self, mean: Array) -> Array:
        return mean

    def update(self, *args: Any, **kwargs: Any) -> None:
        total, count = self._update_stats(*args, **kwargs)
        self.total = self.total + total
        self.count = self.count + count

    def compute(self) -> Array:
        return self._finalize(self.total / jnp.maximum(self.count, 1).astype(jnp.float32))


# --------------------------------------------------- windowed serving plane
class WindowSpec(NamedTuple):
    """Tumbling- or sliding-window layout of the windowed serving plane.

    ``window_s`` seconds per window over a ring of ``num_windows`` slots;
    window ``w`` covers ``[w*stride, w*stride + window_s)`` and lives in slot
    ``w % num_windows``, where the stride is ``slide_s`` when set (SLIDING
    windows: a new window opens every ``slide_s`` seconds, each event covers
    ``window_s / slide_s`` consecutive windows) and ``window_s`` otherwise
    (tumbling: disjoint windows, one covering window per event).
    ``allowed_lateness_s`` is how far behind the watermark an event may
    arrive and still be routed to its (still-open) window. Lateness is
    capped at ``num_windows * stride - window_s`` (for tumbling windows:
    ``(num_windows - 1) * window_s``) — beyond that a within-lateness
    event's slot could already be recycled, which would misroute it into a
    newer window (the one failure mode the plane promises never happens).
    """

    window_s: float
    num_windows: int
    allowed_lateness_s: float = 0.0
    slide_s: Optional[float] = None

    @property
    def stride(self) -> float:
        """Seconds between consecutive window starts (= ``window_s`` for
        tumbling windows)."""
        return float(self.window_s if self.slide_s is None else self.slide_s)

    @property
    def overlap(self) -> int:
        """How many consecutive windows cover one event
        (``window_s / stride``; 1 for tumbling windows)."""
        return int(round(float(self.window_s) / self.stride))

    def window_start(self, window: int) -> float:
        """Event-time start of window ``window`` (``window * stride``)."""
        return window * self.stride

    def validate(self) -> "WindowSpec":
        if not (isinstance(self.window_s, (int, float)) and self.window_s > 0):
            raise ValueError(f"`window_s` must be a positive number, got {self.window_s!r}")
        if not (isinstance(self.num_windows, int) and self.num_windows >= 1):
            raise ValueError(f"`num_windows` must be a positive int, got {self.num_windows!r}")
        if not (isinstance(self.allowed_lateness_s, (int, float)) and self.allowed_lateness_s >= 0):
            raise ValueError(
                f"`allowed_lateness_s` must be >= 0, got {self.allowed_lateness_s!r}"
            )
        if self.slide_s is not None:
            if not (isinstance(self.slide_s, (int, float)) and 0 < self.slide_s <= self.window_s):
                raise ValueError(
                    f"`slide_s` must be a positive number <= window_s ({self.window_s}),"
                    f" got {self.slide_s!r}"
                )
            ratio = float(self.window_s) / float(self.slide_s)
            if abs(ratio - round(ratio)) > 1e-9:
                raise ValueError(
                    f"`window_s` ({self.window_s}) must be an integer multiple of"
                    f" `slide_s` ({self.slide_s}) so each event covers a whole number"
                    " of windows"
                )
            if self.num_windows < self.overlap:
                raise ValueError(
                    f"num_windows={self.num_windows} is smaller than the overlap"
                    f" factor window_s/slide_s={self.overlap}; one event's covering"
                    " windows would collide in the ring"
                )
        horizon = self.num_windows * self.stride - float(self.window_s)
        if self.allowed_lateness_s > horizon:
            raise ValueError(
                f"allowed_lateness_s={self.allowed_lateness_s} exceeds the ring's"
                f" still-open horizon (num_windows x stride - window_s ="
                f" {horizon}s); a within-lateness event"
                " could land in a recycled slot. Raise num_windows or shrink the"
                " lateness."
            )
        return self


def window_index(event_times: Any, window_s: float) -> np.ndarray:
    """Window index of each event time: ``floor(t / window_s)`` (int64)."""
    t = np.asarray(event_times, dtype=np.float64)
    return np.floor_divide(t, float(window_s)).astype(np.int64)


class RouteResult(NamedTuple):
    """One batch's routing verdict (see the module docstring contract).

    ``slot_ids``: int32 per-sample slot for the NEWEST covering window,
    ``-1`` for dropped events — the slab scatter drops them by XLA
    out-of-bounds semantics. ``watermark``/``head``: the advanced LOCAL
    stream position AFTER this batch (the head is in stride units).
    ``opened``: window indices newly opened by this batch, oldest first —
    their ring slots hold expired windows and must be reset BEFORE the
    scatter. ``n_dropped``/``n_late``: fully-dropped events (no covering
    window accepted) vs accepted-but-late ROUTINGS — (event, window) pairs
    whose window span had already ended by the judging clock (the agreed
    watermark when one governs the stream), summed across the newest and
    overlap rows. ``min_window``: the oldest
    window this batch accepted an event into (``None`` if every event
    dropped) — the wrapper's stream-origin bookkeeping, so windows before
    the first event are never reported as resident. ``overlap_slots``: for
    sliding windows, one additional int32 slot row per OLDER covering window
    (``overlap - 1`` rows, each judged independently by the open rule);
    empty for tumbling windows.
    """

    slot_ids: np.ndarray
    watermark: float
    head: int
    opened: Tuple[int, ...]
    n_dropped: int
    n_late: int
    min_window: Optional[int]
    overlap_slots: Tuple[np.ndarray, ...] = ()


def route_events(
    event_times: Any,
    watermark: Optional[float],
    head: Optional[int],
    spec: WindowSpec,
    agreed: Optional[float] = None,
    judge_prefix: Optional[Any] = None,
) -> RouteResult:
    """Route one batch of event times through the advancing watermark.

    ``watermark``/``head`` are the LOCAL stream position before the batch
    (``None`` on the very first batch). ``agreed`` is the cross-rank agreed
    (global-min) watermark when a :class:`WatermarkAgreement` governs the
    stream: open/late verdicts are then judged against IT instead of the
    local running max — "late" means the same thing on every rank, and a
    rank whose local clock runs ahead cannot close a window its peers still
    feed. The local watermark (and the ring head it implies) still advances
    as before: it is this rank's contribution to the next agreement round,
    and ring-slot residency must follow the events this rank actually holds.
    Pure host numpy — deterministic, thread-free, and independently
    recomputable (the service gates' oracles replay the same arithmetic from
    the raw stream).

    ``judge_prefix`` is the coalesced-ingest form: a ``(N,)`` float64 array
    of PER-EVENT judging watermarks — for a concatenation of k sequential
    batches, every event of batch i carries the running max the sequential
    plane would have judged batch i by (``max(watermark, t_1.max(), ...,
    t_i.max())``, batch-granular and non-decreasing). Open/late verdicts are
    then judged per event against that prefix clock instead of one scalar,
    which makes routing the concatenation bit-exact vs routing the k batches
    one at a time — PROVIDED the concatenation does not advance the ring
    head or the close horizon mid-span (the service's coalescer splits spans
    at exactly those boundaries; residency is judged against the final head,
    which equals every per-batch head within such a span). Mutually
    exclusive with ``agreed``: under an agreed clock every batch is judged
    by the same scalar and coalescing's prefix form is a no-op.
    """
    stride = spec.stride
    t = np.asarray(event_times, dtype=np.float64).reshape(-1)
    if agreed is not None and judge_prefix is not None:
        raise ValueError(
            "judge_prefix and agreed are mutually exclusive: an agreed clock "
            "judges every event by the same scalar"
        )
    if t.size == 0:
        return RouteResult(
            np.empty((0,), dtype=np.int32),
            -math.inf if watermark is None else watermark,
            -1 if head is None else head,
            (),
            0,
            0,
            None,
        )
    if not np.isfinite(t).all():
        raise ValueError("event_time must be finite (got NaN/inf timestamps)")
    new_wm = float(t.max()) if watermark is None else max(float(watermark), float(t.max()))
    # the judging clock: the agreed watermark when one governs the stream
    # (verdicts are a pure function of (window, agreed)), the local running
    # max otherwise
    judge_wm: Any = new_wm if agreed is None else float(agreed)
    if judge_prefix is not None:
        jp = np.asarray(judge_prefix, dtype=np.float64).reshape(-1)
        if jp.shape != t.shape:
            raise ValueError(
                f"judge_prefix must match event_times: {jp.shape} vs {t.shape}"
            )
        if jp.size and (np.diff(jp) < 0).any():
            raise ValueError("judge_prefix must be non-decreasing (a running max)")
        if float(jp[-1]) != new_wm:
            raise ValueError(
                f"judge_prefix must end at the batch watermark: {float(jp[-1])}"
                f" != {new_wm}"
            )
        judge_wm = jp
    new_head = int(math.floor(new_wm / stride))
    w = window_index(t, stride)  # the NEWEST window covering each event

    def verdict(cover: np.ndarray) -> np.ndarray:
        # a covering window is accepted iff it is still open — it stays open
        # for allowed_lateness_s past its end, judged by the agreed clock
        # when there is one — AND its ring slot is still resident. The
        # validated lateness cap makes an open window's slot resident by
        # construction on a single clock; with an agreed clock behind the
        # local head, an open window can have fallen off the local ring —
        # the residency guard then drops (and counts) instead of misrouting.
        open_ = cover * stride + spec.window_s + spec.allowed_lateness_s > judge_wm
        return open_ & (cover > new_head - spec.num_windows)

    def late(cover: np.ndarray, ok: np.ndarray) -> int:
        # a late routing: an accepted (event, window) pair whose window span
        # had already ended by the JUDGING clock — the same clock the open
        # verdict used, so "late" means the same thing on every rank under
        # an agreement (and nothing is late before one forms: pre-agreement
        # judge_wm is -inf, no span has ended yet)
        return int((ok & (cover * stride + spec.window_s <= judge_wm)).sum())

    accepted = verdict(w)
    slot_ids = np.where(accepted, w % spec.num_windows, -1).astype(np.int32)
    any_accepted = accepted
    min_w = w[accepted].min() if accepted.any() else None
    n_late = late(w, accepted)
    overlap_rows = []
    for j in range(1, spec.overlap):
        cover = w - j
        ok = verdict(cover)
        overlap_rows.append(np.where(ok, cover % spec.num_windows, -1).astype(np.int32))
        any_accepted = any_accepted | ok
        n_late += late(cover, ok)
        if ok.any():
            older = cover[ok].min()
            min_w = older if min_w is None else min(min_w, older)
    n_dropped = int((~any_accepted).sum())
    min_window = None if min_w is None else int(min_w)
    if head is None or head < new_head - spec.num_windows:
        # first batch, or a jump past the whole ring: every slot the new
        # horizon can see starts fresh
        opened = tuple(range(new_head - spec.num_windows + 1, new_head + 1))
    else:
        opened = tuple(range(head + 1, new_head + 1))
    return RouteResult(
        slot_ids, new_wm, new_head, opened, n_dropped, n_late, min_window,
        tuple(overlap_rows),
    )


def decay_scale(dt_s: Any, half_life_s: float) -> Any:
    """Exponential time-decay factor ``0.5 ** (dt / half_life)``.

    The decay accumulator's two uses: scaling the whole accumulator forward
    by the watermark advance, and weighting each sample's delta by its age
    relative to the new watermark (``dt = watermark - event_time``).
    """
    return 0.5 ** (np.asarray(dt_s, dtype=np.float64) / float(half_life_s))


# ------------------------------------------------ cross-rank watermark plane
class WatermarkAgreement:
    """Cross-rank low-watermark agreement: the Dataflow-style fix for skewed
    and stalled event clocks on a multi-rank stream.

    Each rank of a distributed stream reports its LOCAL running-max
    watermark (:meth:`report`); the AGREED watermark (:meth:`agreed`) is the
    minimum over every participating rank — so a window closes, publishes,
    or recycles only once *every* rank's clock has passed it, and a skewed
    rank can no longer close a window its peers still feed. The agreed value
    is monotone non-decreasing by construction (a restored or lagging report
    can never regress it).

    **Transport.** Within one process the registry IS the agreement — every
    ``report`` is a dict store, and ``agreed()`` is a min over the registry
    (deterministic, lock-cheap). Across processes the registry's local min
    rides the packed host plane: :meth:`exchange` dispatches ONE min-gather
    of a single float64 through the deferred executor
    (:func:`~metrics_tpu.parallel.deferred.deferred_host_gather` — the
    submission-ordered background worker, so agreement overlaps ingest and
    costs the step nothing), and the fold lands on the worker via the
    gather's ``finish`` hook. The exchange is HOST-PLANE ONLY: it stages
    zero in-jit collectives, which ``bench.py --check-watermark`` pins by
    counters. Cadence: every ``exchange_every_s`` seconds of wall clock
    (0 = every report), with at most one exchange in flight.

    **Stragglers.** Agreement must never deadlock the fleet: a rank whose
    watermark stops advancing for ``deadline_s`` wall-clock seconds is
    EXCLUDED from the min (policy ``"degrade"``, the default) — the
    process-wide ``wm_stragglers`` counter bumps once per exclusion episode,
    :attr:`degraded` latches True so affected publishes can stamp
    ``degraded=True``, and window closing proceeds on the surviving ranks'
    clocks. A rank that reports an ADVANCING watermark again rejoins
    automatically (its fresh value re-enters the min — which cannot regress
    the agreed high-water), and so does a rank that RE-REGISTERS — a
    recovered participant re-attaching under its old rank rejoins even
    though its restored report equals the pre-crash value. Policy
    ``"raise"`` throws
    :class:`~metrics_tpu.utils.exceptions.SyncTimeoutError` from
    ``agreed()`` instead, for callers that prefer failing loudly over
    publishing degraded values.

    Args:
        deadline_s: how long a rank's watermark may stall before exclusion
            (``None`` disables exclusion — a stalled rank then holds the
            agreed clock forever; only safe when something else bounds it).
        policy: ``"degrade"`` (exclude + count + latch) or ``"raise"``.
        exchange_every_s: minimum wall-clock spacing between cross-process
            exchange rounds (0 dispatches one per report, subject to the
            single-in-flight guard).
        guard: the :class:`~metrics_tpu.parallel.sync.SyncGuard` the
            exchange gather runs under (default: the process-wide guard at
            dispatch time). A dead/stalling exchange degrades to the local
            registry's min — agreement never wedges on its own transport.
        label: gauge label (``watermark_agreement`` in counters snapshots);
            auto-indexed when omitted.
    """

    _ids = itertools.count()

    def __init__(
        self,
        deadline_s: Optional[float] = 30.0,
        policy: str = "degrade",
        exchange_every_s: float = 0.0,
        guard: Optional[Any] = None,
        label: Optional[str] = None,
    ) -> None:
        if deadline_s is not None and not (
            isinstance(deadline_s, (int, float)) and deadline_s > 0
        ):
            raise ValueError(f"`deadline_s` must be a positive number or None, got {deadline_s!r}")
        if policy not in ("degrade", "raise"):
            raise ValueError(f"`policy` must be 'degrade' or 'raise', got {policy!r}")
        if not (isinstance(exchange_every_s, (int, float)) and exchange_every_s >= 0):
            raise ValueError(f"`exchange_every_s` must be >= 0, got {exchange_every_s!r}")
        self.deadline_s = None if deadline_s is None else float(deadline_s)
        self.policy = policy
        self.exchange_every_s = float(exchange_every_s)
        self.guard = guard
        self.label = label or f"WatermarkAgreement#{next(WatermarkAgreement._ids)}"
        self._lock = threading.RLock()
        # rank -> {"wm": float|None, "stamp": monotonic seconds of last ADVANCE}
        self._ranks: Dict[Any, Dict[str, Any]] = {}
        self._excluded: set = set()
        self._agreed: Optional[float] = None  # monotone high-water of the min
        self._remote: Optional[float] = None  # last exchanged cross-process min
        self._inflight: Optional[Any] = None  # at most one exchange in flight
        self._last_exchange = time.monotonic()  # cadence counts from construction
        self.stragglers = 0  # lifetime exclusion episodes
        self.exchanges = 0  # lifetime exchange rounds dispatched

    # ------------------------------------------------------------ reporting
    def register(self, rank: Any) -> None:
        """Declare a participant before its first report. A registered rank
        with no watermark yet HOLDS the agreement open (``agreed()`` stays at
        its last value) until it reports or stalls past the deadline — the
        "window held open by a peer that has not spoken yet" case.

        Re-registering an EXISTING rank (a recovered shard re-attaching
        under its old rank) is a liveness signal: the deadline stamp
        refreshes and any straggler exclusion lifts immediately. The
        restored report typically EQUALS the pre-crash watermark —
        ``report`` alone would not treat it as an advance, and the
        recovered-and-healthy rank would otherwise stay excluded until a
        strictly newer event arrives (forever, on an ended stream)."""
        with self._lock:
            entry = self._ranks.get(rank)
            if entry is None:
                self._ranks[rank] = {"wm": None, "stamp": time.monotonic()}
                return
            entry["stamp"] = time.monotonic()
            if rank in self._excluded:
                self._excluded.discard(rank)
                self._note_gauge_locked()

    def report(self, rank: Any, watermark: float) -> None:
        """Fold one rank's local running-max watermark into the registry
        (monotone per rank: a lower report is a no-op, never a regression)
        and dispatch an exchange round if the cadence is due."""
        wm = float(watermark)
        with self._lock:
            entry = self._ranks.setdefault(rank, {"wm": None, "stamp": time.monotonic()})
            if entry["wm"] is None or wm > entry["wm"]:
                entry["wm"] = wm
                entry["stamp"] = time.monotonic()
        self._maybe_exchange()

    def ranks(self) -> Tuple[Any, ...]:
        with self._lock:
            return tuple(self._ranks)

    def local_watermarks(self) -> Dict[Any, Optional[float]]:
        """Every participant's last reported local watermark (the gate's
        publish-ordering assertions read this)."""
        with self._lock:
            return {rank: entry["wm"] for rank, entry in self._ranks.items()}

    # ------------------------------------------------------------ agreement
    def agreed(self) -> Optional[float]:
        """The agreed (global-min) watermark: min over every included rank's
        report, folded with the last cross-process exchange, monotone
        non-decreasing. ``None`` until a first agreement forms (no rank has
        reported yet, or a registered rank is still silent within its
        deadline)."""
        with self._lock:
            candidate = self._included_min_locked()
            if candidate is not None:
                if self._remote is not None:
                    candidate = min(candidate, self._remote)
                if self._agreed is None or candidate > self._agreed:
                    self._agreed = candidate
            return self._agreed

    def _included_min_locked(self) -> Optional[float]:
        """Min over non-straggling ranks, running the exclusion scan (the
        deadline judgment) as a side effect. ``None`` when no agreement can
        form yet."""
        now = time.monotonic()
        values = []
        pending = False
        for rank, entry in self._ranks.items():
            stale = (
                self.deadline_s is not None
                and now - entry["stamp"] > self.deadline_s
            )
            if stale:
                if self.policy == "raise":
                    from metrics_tpu.utils.exceptions import SyncTimeoutError

                    raise SyncTimeoutError(
                        f"watermark agreement {self.label!r}: rank {rank!r} stalled"
                        f" past deadline_s={self.deadline_s} (policy='raise')"
                    )
                if rank not in self._excluded:
                    self._excluded.add(rank)
                    self.stragglers += 1
                    record_wm_straggler()
                    self._note_gauge_locked()
                continue
            if rank in self._excluded:
                # a fresh advance within the deadline: the straggler rejoins
                self._excluded.discard(rank)
                self._note_gauge_locked()
            if entry["wm"] is None:
                pending = True
                continue
            values.append(entry["wm"])
        if pending or not values:
            return None
        return min(values)

    @property
    def degraded(self) -> bool:
        """True while any participant is excluded as a straggler — publishes
        judged by a clock a rank no longer feeds should say so."""
        with self._lock:
            return bool(self._excluded)

    def excluded(self) -> Tuple[Any, ...]:
        """The currently-excluded (straggling) ranks."""
        with self._lock:
            return tuple(sorted(self._excluded, key=repr))

    # -------------------------------------------------------------- exchange
    def exchange(self) -> Optional[Any]:
        """Dispatch one cross-process min-exchange round onto the background
        host plane; returns the :class:`SyncHandle` (or ``None`` when a
        round is already in flight or no local min exists yet). The fold
        lands on the worker — nobody needs to fence the handle for the
        agreement to advance."""
        with self._lock:
            if self._inflight is not None and not self._inflight.done():
                return None
            local_min = self._included_min_locked()
            if local_min is None:
                return None
            self.exchanges += 1
            self._last_exchange = time.monotonic()
            self._note_gauge_locked()
        from metrics_tpu.parallel.deferred import deferred_host_gather

        record_wm_exchange()
        handle = deferred_host_gather(
            {"wm": np.asarray(local_min, dtype=np.float64)},
            {"wm": "min"},
            guard=self.guard,
            label="wm_exchange",
            finish=self._fold_exchange,
        )
        with self._lock:
            self._inflight = handle
        return handle

    def _fold_exchange(self, result: Dict[str, Any]) -> Dict[str, Any]:
        """The exchange's ``finish`` hook (runs on the host-plane worker):
        fold the gathered cross-process min into the registry. On a single
        process the gather is the identity and the fold is skipped — the
        registry already IS the world, and folding a stale echo of our own
        min would only lag the (deterministic) agreed clock."""
        import jax

        if jax.process_count() > 1:
            with self._lock:
                self._remote = float(np.asarray(result["wm"]))
        return result

    def _maybe_exchange(self) -> None:
        if self.exchange_every_s > 0:
            with self._lock:
                if time.monotonic() - self._last_exchange < self.exchange_every_s:
                    return
        self.exchange()

    def drain(self, timeout_s: float = 30.0) -> None:
        """Bounded barrier over the in-flight exchange (shutdown must never
        hang on a dead exchange: a failed resolve degrades to the local
        registry, which is exactly what the guard's degrade policy means)."""
        with self._lock:
            handle = self._inflight
        if handle is None:
            return
        try:
            handle.result(timeout_s)
        except BaseException:  # noqa: BLE001 - degrade to the local registry
            pass

    # ------------------------------------------------------------ lifecycle
    def __deepcopy__(self, memo: dict) -> "WatermarkAgreement":
        # the agreement IS the process-wide clock registry: a deep-copied
        # participant (the service's shadow twin, a cloned rank) must keep
        # talking to the SAME registry, not a frozen private copy — and the
        # live lock/in-flight handle could not travel anyway
        return self

    def __reduce__(self):
        raise TypeError(
            "WatermarkAgreement is a live process-wide registry (locks, an"
            " in-flight exchange) and cannot be pickled; checkpoint the"
            " participating metrics (their state_dict carries the agreed"
            " high-water) and re-attach on restore"
        )

    # --------------------------------------------------------------- gauges
    def _note_gauge_locked(self) -> None:
        record_watermark_agreement(
            self.label, self._agreed, len(self._ranks), self._excluded, self.exchanges
        )

    def __repr__(self) -> str:
        with self._lock:
            return (
                f"WatermarkAgreement(label={self.label!r}, ranks={len(self._ranks)},"
                f" agreed={self._agreed}, excluded={sorted(map(repr, self._excluded))},"
                f" exchanges={self.exchanges})"
            )
