"""Streaming-plane core: sum/count metric base + the windowed-runtime math.

Two things live here:

1. :class:`SumCountMetric` — the shared base for metrics that reduce to
   "sum of per-sample statistics divided by a count": two states, both plain
   ``"sum"`` reductions — O(1) memory, one fused psum to sync, counts in the
   package integer accumulator dtype (float32 counts stop incrementing at
   2^24; int states get the overflow warning and widen to int64 under
   ``jax_enable_x64``).

2. The **windowed serving-plane math**: :class:`WindowSpec` (tumbling
   windows of ``window_s`` seconds over a ring of ``num_windows`` slots,
   with an ``allowed_lateness_s`` grace), :func:`route_events` (the
   watermark-advancing event router every ``Windowed.update`` call runs),
   and :func:`decay_scale` (the exponential time-decay accumulator's per-
   batch scale). These are pure host-side numpy functions — the routing
   decision is data-dependent host work by construction (the same argument
   as the LRU slot table in ``parallel/slab.py``), while the scatter that
   CONSUMES the resolved slot ids stays an XLA ``segment_sum``.

Routing contract (what makes the windowed plane testable): for one batch,
the watermark first advances to ``max(old watermark, max(event_time))``;
an event is then accepted iff its WINDOW is still open — ``(window + 1) *
window_s + allowed_lateness_s > watermark`` (a window stays open for
``allowed_lateness_s`` past its end; head-window events are never late).
Accepted events route to ``window % num_windows`` (the head window scatters
normally, late-but-within-lateness events land in their still-open prior
slot); rejected events get slot ``-1`` — DROPPED by the slab scatter's XLA
out-of-bounds semantics, never misrouted — and are counted
(``slab_dropped_samples``). Because a verdict depends only on the event's
window and the running watermark maximum, shuffling a stream whose every
event stays within the allowed lateness of the stream maximum changes
neither verdicts nor slot ids, and the scatter-adds commute: in-order and
shuffled streams produce bit-exact window slabs
(``tests/wrappers/test_windowed.py`` pins it).
"""
import math
from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.utils.data import accum_int_dtype

__all__ = [
    "RouteResult",
    "SumCountMetric",
    "WindowSpec",
    "decay_scale",
    "route_events",
    "window_index",
]


class SumCountMetric(Metric):
    """``compute() = f(total / count)`` over streaming sum states.

    Subclasses implement ``_update_stats(*args, **kwargs) -> (sum, count)``
    (count may be a static int or a traced integer array) and optionally
    ``_finalize(mean) -> value``.
    """

    def __init__(
        self,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        self.add_state("total", default=np.zeros(()), dist_reduce_fx="sum")
        self.add_state("count", default=np.zeros((), dtype=accum_int_dtype()), dist_reduce_fx="sum")

    def _update_stats(self, *args: Any, **kwargs: Any) -> Tuple[Array, Any]:
        raise NotImplementedError  # pragma: no cover - subclasses define the kernel

    def _finalize(self, mean: Array) -> Array:
        return mean

    def update(self, *args: Any, **kwargs: Any) -> None:
        total, count = self._update_stats(*args, **kwargs)
        self.total = self.total + total
        self.count = self.count + count

    def compute(self) -> Array:
        return self._finalize(self.total / jnp.maximum(self.count, 1).astype(jnp.float32))


# --------------------------------------------------- windowed serving plane
class WindowSpec(NamedTuple):
    """Tumbling-window layout of the windowed serving plane.

    ``window_s`` seconds per window over a ring of ``num_windows`` slots
    (window ``w`` covers ``[w*window_s, (w+1)*window_s)`` and lives in slot
    ``w % num_windows``); ``allowed_lateness_s`` is how far behind the
    watermark an event may arrive and still be routed to its (still-open)
    window. Lateness is capped at ``(num_windows - 1) * window_s`` — beyond
    that a within-lateness event's slot could already be recycled, which
    would misroute it into a newer window (the one failure mode the plane
    promises never happens).
    """

    window_s: float
    num_windows: int
    allowed_lateness_s: float = 0.0

    def validate(self) -> "WindowSpec":
        if not (isinstance(self.window_s, (int, float)) and self.window_s > 0):
            raise ValueError(f"`window_s` must be a positive number, got {self.window_s!r}")
        if not (isinstance(self.num_windows, int) and self.num_windows >= 1):
            raise ValueError(f"`num_windows` must be a positive int, got {self.num_windows!r}")
        if not (isinstance(self.allowed_lateness_s, (int, float)) and self.allowed_lateness_s >= 0):
            raise ValueError(
                f"`allowed_lateness_s` must be >= 0, got {self.allowed_lateness_s!r}"
            )
        if self.allowed_lateness_s > (self.num_windows - 1) * self.window_s:
            raise ValueError(
                f"allowed_lateness_s={self.allowed_lateness_s} exceeds the ring's"
                f" still-open horizon ({self.num_windows - 1} x window_s ="
                f" {(self.num_windows - 1) * self.window_s}s); a within-lateness event"
                " could land in a recycled slot. Raise num_windows or shrink the"
                " lateness."
            )
        return self


def window_index(event_times: Any, window_s: float) -> np.ndarray:
    """Window index of each event time: ``floor(t / window_s)`` (int64)."""
    t = np.asarray(event_times, dtype=np.float64)
    return np.floor_divide(t, float(window_s)).astype(np.int64)


class RouteResult(NamedTuple):
    """One batch's routing verdict (see the module docstring contract).

    ``slot_ids``: int32 per-sample slot, ``-1`` for dropped (too-late)
    events — the slab scatter drops them by XLA out-of-bounds semantics.
    ``watermark``/``head``: the advanced stream position AFTER this batch.
    ``opened``: window indices newly opened by this batch, oldest first —
    their ring slots hold expired windows and must be reset BEFORE the
    scatter. ``n_dropped``/``n_late``: dropped vs accepted-but-late counts.
    ``min_window``: the oldest window this batch accepted an event into
    (``None`` if every event dropped) — the wrapper's stream-origin
    bookkeeping, so windows before the first event are never reported as
    resident.
    """

    slot_ids: np.ndarray
    watermark: float
    head: int
    opened: Tuple[int, ...]
    n_dropped: int
    n_late: int
    min_window: Optional[int]


def route_events(
    event_times: Any,
    watermark: Optional[float],
    head: Optional[int],
    spec: WindowSpec,
) -> RouteResult:
    """Route one batch of event times through the advancing watermark.

    ``watermark``/``head`` are the stream position before the batch
    (``None`` on the very first batch). Pure host numpy — deterministic,
    thread-free, and independently recomputable (the service gate's oracle
    replays the same arithmetic from the raw stream).
    """
    t = np.asarray(event_times, dtype=np.float64).reshape(-1)
    if t.size == 0:
        return RouteResult(
            np.empty((0,), dtype=np.int32),
            -math.inf if watermark is None else watermark,
            -1 if head is None else head,
            (),
            0,
            0,
            None,
        )
    if not np.isfinite(t).all():
        raise ValueError("event_time must be finite (got NaN/inf timestamps)")
    new_wm = float(t.max()) if watermark is None else max(float(watermark), float(t.max()))
    new_head = int(math.floor(new_wm / spec.window_s))
    w = window_index(t, spec.window_s)
    # an event is accepted iff its window is still open: a window stays open
    # for allowed_lateness_s past its end, and the head window can never be
    # late. The validated lateness cap makes an open window's slot resident
    # by construction; keep the residency guard so a hand-built spec can
    # never scatter into a recycled slot.
    accepted = (w + 1) * spec.window_s + spec.allowed_lateness_s > new_wm
    accepted &= w > new_head - spec.num_windows
    slot_ids = np.where(accepted, w % spec.num_windows, -1).astype(np.int32)
    n_dropped = int((~accepted).sum())
    n_late = int((accepted & (w < new_head)).sum())
    min_window = int(w[accepted].min()) if accepted.any() else None
    if head is None or head < new_head - spec.num_windows:
        # first batch, or a jump past the whole ring: every slot the new
        # horizon can see starts fresh
        opened = tuple(range(new_head - spec.num_windows + 1, new_head + 1))
    else:
        opened = tuple(range(head + 1, new_head + 1))
    return RouteResult(slot_ids, new_wm, new_head, opened, n_dropped, n_late, min_window)


def decay_scale(dt_s: Any, half_life_s: float) -> Any:
    """Exponential time-decay factor ``0.5 ** (dt / half_life)``.

    The decay accumulator's two uses: scaling the whole accumulator forward
    by the watermark advance, and weighting each sample's delta by its age
    relative to the new watermark (``dt = watermark - event_time``).
    """
    return 0.5 ** (np.asarray(dt_s, dtype=np.float64) / float(half_life_s))
