"""Shared base for sum/count streaming metrics.

Many metrics reduce to "sum of per-sample statistics divided by a count":
two states, both plain ``"sum"`` reductions — O(1) memory, one fused psum to
sync, counts in the package integer accumulator dtype (float32 counts stop
incrementing at 2^24; int states get the overflow warning and widen to int64
under ``jax_enable_x64``).
"""
from typing import Any, Callable, Optional, Tuple

import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.utils.data import accum_int_dtype


class SumCountMetric(Metric):
    """``compute() = f(total / count)`` over streaming sum states.

    Subclasses implement ``_update_stats(*args, **kwargs) -> (sum, count)``
    (count may be a static int or a traced integer array) and optionally
    ``_finalize(mean) -> value``.
    """

    def __init__(
        self,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        self.add_state("total", default=np.zeros(()), dist_reduce_fx="sum")
        self.add_state("count", default=np.zeros((), dtype=accum_int_dtype()), dist_reduce_fx="sum")

    def _update_stats(self, *args: Any, **kwargs: Any) -> Tuple[Array, Any]:
        raise NotImplementedError  # pragma: no cover - subclasses define the kernel

    def _finalize(self, mean: Array) -> Array:
        return mean

    def update(self, *args: Any, **kwargs: Any) -> None:
        total, count = self._update_stats(*args, **kwargs)
        self.total = self.total + total
        self.count = self.count + count

    def compute(self) -> Array:
        return self._finalize(self.total / jnp.maximum(self.count, 1).astype(jnp.float32))
