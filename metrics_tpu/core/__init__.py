from metrics_tpu.core.collections import MetricCollection
from metrics_tpu.core.metric import CompositionalMetric, Metric, PureMetric
