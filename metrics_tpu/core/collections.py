"""MetricCollection: several metrics sharing one update/forward call.

Parity target: reference ``torchmetrics/collections.py:23-156`` (dict/list
construction, per-metric kwarg filtering, output-key prefix, clone/persistent/
reset). TPU-native extras: a fused ``update_state``/pure view over the joint
state pytree so a whole collection updates inside one jitted step, and
``device_put`` for mesh placement of every state (BASELINE.json north star:
"make MetricCollection place states on the TPU mesh").
"""
from collections import OrderedDict
from copy import deepcopy
from typing import Any, Dict, List, Optional, Tuple, Union

from metrics_tpu.core.metric import Metric, PureMetric


class MetricCollection(OrderedDict):
    """Chain metrics with the same call pattern into a single object.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import MetricCollection, Accuracy, Precision, Recall
        >>> target = jnp.array([0, 2, 0, 2, 0, 1, 0, 2])
        >>> preds = jnp.array([2, 1, 2, 0, 1, 2, 2, 2])
        >>> metrics = MetricCollection([Accuracy(),
        ...                             Precision(num_classes=3, average='macro'),
        ...                             Recall(num_classes=3, average='macro')])
        >>> {k: float(v) for k, v in metrics(preds, target).items()}  # doctest: +ELLIPSIS
        {'Accuracy': 0.125, 'Precision': 0.066..., 'Recall': 0.111...}
    """

    def __init__(
        self,
        metrics: Union[List[Metric], Tuple[Metric, ...], Dict[str, Metric]],
        prefix: Optional[str] = None,
    ):
        super().__init__()
        if isinstance(metrics, dict):
            for name, metric in metrics.items():
                if not isinstance(metric, Metric):
                    raise ValueError(f"Value {metric} belonging to key {name} is not an instance of `Metric`")
                self[name] = metric
        elif isinstance(metrics, (tuple, list)):
            for metric in metrics:
                if not isinstance(metric, Metric):
                    raise ValueError(f"Input {metric} to `MetricCollection` is not a instance of `Metric`")
                name = metric.__class__.__name__
                if name in self:
                    raise ValueError(f"Encountered two metrics both named {name}")
                self[name] = metric
        else:
            raise ValueError("Unknown input to MetricCollection.")

        self.prefix = self._check_prefix_arg(prefix)

    def forward(self, *args: Any, **kwargs: Any) -> Dict[str, Any]:
        """Call forward on every metric; kwargs are filtered per metric signature."""
        return {self._set_prefix(k): m(*args, **m._filter_kwargs(**kwargs)) for k, m in self.items()}

    def __call__(self, *args: Any, **kwargs: Any) -> Dict[str, Any]:
        return self.forward(*args, **kwargs)

    def update(self, *args: Any, **kwargs: Any) -> None:
        for _, m in self.items():
            m.update(*args, **m._filter_kwargs(**kwargs))

    def compute(self) -> Dict[str, Any]:
        return {self._set_prefix(k): m.compute() for k, m in self.items()}

    def reset(self) -> None:
        for _, m in self.items():
            m.reset()

    def clone(self, prefix: Optional[str] = None) -> "MetricCollection":
        mc = deepcopy(self)
        mc.prefix = self._check_prefix_arg(prefix)
        return mc

    def __deepcopy__(self, memo: dict) -> "MetricCollection":
        # dict-subclass default reduce would re-invoke __init__ with an items
        # iterator; rebuild explicitly (type(self) keeps subclasses intact)
        new = type(self)({k: deepcopy(m, memo) for k, m in self.items()}, prefix=self.prefix)
        memo[id(self)] = new
        for key, value in self.__dict__.items():
            if key not in new.__dict__:
                new.__dict__[key] = deepcopy(value, memo)
        return new

    def __reduce__(self):
        return (type(self), (dict(self), self.prefix), self.__dict__.copy())

    def __setstate__(self, state: dict) -> None:
        self.__dict__.update(state)

    def persistent(self, mode: bool = True) -> None:
        for _, m in self.items():
            m.persistent(mode)

    def _set_prefix(self, k: str) -> str:
        return k if self.prefix is None else self.prefix + k

    @staticmethod
    def _check_prefix_arg(prefix: Optional[str]) -> Optional[str]:
        if prefix is not None and not isinstance(prefix, str):
            raise ValueError("Expected input `prefix` to be a string")
        return prefix

    # ------------------------------------------------------- TPU-native extras
    def device_put(self, device_or_sharding: Any) -> "MetricCollection":
        """Place every metric's states on a device/sharding (mesh placement)."""
        for _, m in self.items():
            m.device_put(device_or_sharding)
        return self

    def init_state(self) -> Dict[str, Dict[str, Any]]:
        """Joint state pytree of the whole collection (for in-jit training loops)."""
        return {k: m.init_state() for k, m in self.items()}

    def update_state(self, state: Dict[str, Dict[str, Any]], *args: Any, **kwargs: Any) -> Dict[str, Dict[str, Any]]:
        """Pure joint update: one call updates every metric — jit this once so the
        whole collection's update fuses into a single XLA computation."""
        return {k: m.update_state(state[k], *args, **m._filter_kwargs(**kwargs)) for k, m in self.items()}

    def compute_from_state(self, state: Dict[str, Dict[str, Any]]) -> Dict[str, Any]:
        return {self._set_prefix(k): m.compute_from_state(state[k]) for k, m in self.items()}

    def merge_states(self, a: Dict[str, Dict[str, Any]], b: Dict[str, Dict[str, Any]]) -> Dict[str, Dict[str, Any]]:
        return {k: m.merge_states(a[k], b[k]) for k, m in self.items()}

    def sync_state(self, state: Dict[str, Dict[str, Any]], axis_name: str) -> Dict[str, Dict[str, Any]]:
        """In-jit sync of the joint state over a mesh axis — one fused collective
        program instead of the reference's per-metric NCCL calls."""
        return {k: m.sync_state(state[k], axis_name) for k, m in self.items()}

    def pure(self) -> PureMetric:
        return PureMetric(
            init=self.init_state,
            update=self.update_state,
            compute=self.compute_from_state,
            merge=self.merge_states,
            sync=self.sync_state,
        )

    def __repr__(self) -> str:
        inner = ",\n  ".join(f"{k}: {repr(m)}" for k, m in self.items())
        return f"MetricCollection(\n  {inner}\n)"
