"""MetricCollection: several metrics sharing one update/forward call.

Parity target: reference ``torchmetrics/collections.py:23-156`` (dict/list
construction, per-metric kwarg filtering, output-key prefix, clone/persistent/
reset). TPU-native extras: a fused ``update_state``/pure view over the joint
state pytree so a whole collection updates inside one jitted step, and
``device_put`` for mesh placement of every state (BASELINE.json north star:
"make MetricCollection place states on the TPU mesh").
"""
from collections import OrderedDict
from copy import deepcopy
from typing import Any, Dict, List, Optional, Tuple, Union

from metrics_tpu.core.metric import Metric, PureMetric
from metrics_tpu.observability.counters import (
    record_cache,
    record_deferred_depth,
    record_states_synced,
)
from metrics_tpu.observability.devtime import DEVTIME as _DEVTIME, fence as _fence
from metrics_tpu.observability.trace import TRACE, span as _span
from metrics_tpu.parallel.buffer import PaddedBuffer
from metrics_tpu.utils.checks import shared_input_format
from metrics_tpu.utils.prints import rank_zero_warn_once

# process-wide fused-step sharing for config-identical collections (same
# shape as the per-metric _JITTED_STEP_CACHE): a fresh collection per eval
# epoch must replay the compiled step, not retrace it
import threading as _threading

_COL_STEP_CACHE: Dict[Any, Any] = {}
_COL_STEP_CACHE_MAX = 64
_COL_STEP_CACHE_LOCK = _threading.Lock()


def _state_write_ids(metric: Metric) -> tuple:
    """Identity fingerprint of a metric's current state arrays.

    Any state write replaces the bound arrays (jax arrays are immutable, and
    every setter rebinds the attribute), so comparing these ids between two
    points in time detects intervening writes without reading a single device
    value. Same convention as ``Metric.__hash__``.
    """
    ids = []
    for name in metric._defaults:
        value = getattr(metric, name)
        if isinstance(value, list):
            ids.append(tuple(id(v) for v in value))
        elif isinstance(value, PaddedBuffer):
            ids.append((id(value.data), id(value.count)))
        else:
            ids.append(id(value))
    return tuple(ids)


def _dedupe_donated_buffers(states: Dict[str, Any]) -> Dict[str, Any]:
    """Defensive copies for repeated buffers in a to-be-donated state tree.

    The fused collection step DONATES its state argument so XLA updates the
    slabs in place — and XLA rejects the same buffer donated twice. Members
    normally own distinct arrays, but ``load_state_dict``/manual state wiring
    can alias one buffer across members (or across two states of one member);
    second and later occurrences get a copy so donation stays legal.
    """
    import jax

    seen: set = set()

    def uniq(leaf: Any) -> Any:
        if id(leaf) in seen:
            return leaf.copy() if hasattr(leaf, "copy") else leaf
        seen.add(id(leaf))
        return leaf

    return jax.tree_util.tree_map(uniq, states)


def _col_cache_key(collection: "MetricCollection", kind: str) -> Optional[Tuple[Any, list]]:
    """(cache key, pinned referents) from the children's config fingerprints.

    The compute-groups flag is part of the key: a grouped and an ungrouped
    collection over identical children trace DIFFERENT programs (one vs N
    updates per group) and must never share a compiled step. The group
    structure itself needs no extra key material — it is a pure function of
    the child classes and config fingerprints already in the key.
    """
    parts = []
    pins: list = []
    for name, metric in collection.items():
        fp = metric._config_fingerprint()
        if fp is None:
            return None
        key_body, child_pins = fp
        parts.append((name, key_body))
        pins.extend(child_pins)
    return (kind, getattr(collection, "_enable_compute_groups", True), tuple(parts)), pins


class MetricCollection(OrderedDict):
    """Chain metrics with the same call pattern into a single object.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import MetricCollection, Accuracy, Precision, Recall
        >>> target = jnp.array([0, 2, 0, 2, 0, 1, 0, 2])
        >>> preds = jnp.array([2, 1, 2, 0, 1, 2, 2, 2])
        >>> metrics = MetricCollection([Accuracy(),
        ...                             Precision(num_classes=3, average='macro'),
        ...                             Recall(num_classes=3, average='macro')])
        >>> {k: float(v) for k, v in metrics(preds, target).items()}  # doctest: +ELLIPSIS
        {'Accuracy': 0.125, 'Precision': 0.066..., 'Recall': 0.111...}
    """

    def __init__(
        self,
        metrics: Union[List[Metric], Tuple[Metric, ...], Dict[str, Metric]],
        prefix: Optional[str] = None,
        compute_groups: bool = True,
    ):
        super().__init__()
        # compute groups: children whose update+state plane is identical
        # (same update impl, state schema, update-relevant config — see
        # Metric._group_fingerprint) share ONE update delta per step and ONE
        # state entry in the pure/sync plane. ``compute_groups=False`` is the
        # escape hatch restoring fully independent per-child execution.
        self._enable_compute_groups = bool(compute_groups)
        if isinstance(metrics, dict):
            for name, metric in metrics.items():
                if not isinstance(metric, Metric):
                    raise ValueError(f"Value {metric} belonging to key {name} is not an instance of `Metric`")
                self[name] = metric
        elif isinstance(metrics, (tuple, list)):
            for metric in metrics:
                if not isinstance(metric, Metric):
                    raise ValueError(f"Input {metric} to `MetricCollection` is not a instance of `Metric`")
                name = metric.__class__.__name__
                if name in self:
                    raise ValueError(f"Encountered two metrics both named {name}")
                self[name] = metric
        else:
            raise ValueError("Unknown input to MetricCollection.")

        self.prefix = self._check_prefix_arg(prefix)
        self._lockstep_init()

    def __setitem__(self, key: str, value: Metric) -> None:
        # generation guards the fused-step cache against id() reuse: a freed
        # child's address can be recycled by its replacement, which would make
        # the (key, id) membership tuple compare equal across a swap
        self.__dict__["_col_generation"] = self.__dict__.get("_col_generation", 0) + 1
        super().__setitem__(key, value)
        ids = self.__dict__.get("_lockstep_ids")
        if ids is not None:
            # a member that accumulated before joining cannot be assumed in
            # lockstep with its group until the next collection-level reset
            if value._count_bound > 0:
                self.__dict__.setdefault("_lockstep_diverged", set()).add(key)
            ids[key] = _state_write_ids(value)

    def __delitem__(self, key: str) -> None:
        self.__dict__["_col_generation"] = self.__dict__.get("_col_generation", 0) + 1
        super().__delitem__(key)
        ids = self.__dict__.get("_lockstep_ids")
        if ids is not None:
            ids.pop(key, None)
            self.__dict__.get("_lockstep_diverged", set()).discard(key)

    # ------------------------------------------------------ lockstep tracking
    # The host-plane analogue of the pure plane's one-state-per-group dedup
    # needs a guarantee the pure plane gets by construction: that every group
    # member holds the SAME state values. The collection tracks it host-side,
    # with zero device work: after every collection-level state write it
    # records the identity of each member's state arrays; any op that later
    # finds a member's arrays swapped out from under it (an out-of-collection
    # ``update``/``forward``/``load_state_dict``) marks that member DIVERGED,
    # permanently until the next collection-level ``reset``. Only never-
    # diverged members share their group's single host gather in ``compute``.
    # Tracking is armed only when a host sync is possible at construction
    # (multi-process, or a member with a custom ``dist_sync_fn``) so the
    # single-process hot path pays one attribute check per op.
    def _lockstep_init(self) -> None:
        import jax

        active = jax.process_count() > 1 or any(m.dist_sync_fn is not None for m in self.values())
        if not active:
            self.__dict__["_lockstep_ids"] = None
            self.__dict__["_lockstep_diverged"] = set()
            return
        self.__dict__["_lockstep_diverged"] = {k for k, m in self.items() if m._count_bound > 0}
        self.__dict__["_lockstep_ids"] = {k: _state_write_ids(m) for k, m in self.items()}

    def _lockstep_check(self) -> None:
        """Mark members whose states were written outside the collection."""
        ids = self.__dict__.get("_lockstep_ids")
        if ids is None:
            return
        diverged = self.__dict__.setdefault("_lockstep_diverged", set())
        for k, m in self.items():
            if ids.get(k) != _state_write_ids(m):
                diverged.add(k)

    def _lockstep_record(self) -> None:
        if self.__dict__.get("_lockstep_ids") is not None:
            self.__dict__["_lockstep_ids"] = {k: _state_write_ids(m) for k, m in self.items()}

    def forward(self, *args: Any, **kwargs: Any) -> Dict[str, Any]:
        """Call forward on every metric; kwargs are filtered per metric signature.

        When every child has fixed-shape states and per-step cross-process
        sync is off, the whole collection runs as ONE jitted program —
        every update, accumulator merge, and batch value in a single
        dispatch (the reference pays N forwards; a naive port would pay N
        dispatches). When the fused step is unavailable (dist_sync_on_step,
        unfingerprintable members, tracer failures), compute groups still
        share ONE update delta per group on the eager per-member path."""
        self._lockstep_check()
        fused = self._forward_fused_collection(*args, **kwargs)
        if fused is None:
            fused = self._forward_eager_grouped(*args, **kwargs)
        self._lockstep_record()
        return fused

    def _eager_shared_groups(self) -> Dict[str, str]:
        """member name -> representative, for groups that can share an eager
        update delta: >= 2 members and delta-mergeable states (``_fusable``).
        Singleton groups and non-mergeable members keep their own path."""
        gm = self._group_map()
        sizes: Dict[str, int] = {}
        for rep in gm.values():
            sizes[rep] = sizes.get(rep, 0) + 1
        return {k: rep for k, rep in gm.items() if sizes[rep] > 1 and self[rep]._fusable}

    def _group_delta(self, rep: str, args: tuple, kwargs: dict, use_jit: bool):
        """ONE batch delta for a compute group, from the representative.

        The jitted per-metric step is reused when available (it returns the
        rep's merged accumulator alongside the delta, so the rep pays one
        dispatch exactly as its own ``forward`` would); tracer failures fall
        back to the eager pure update, permanently for that member. Returns
        ``(delta, rep_merged_state_or_None)``.
        """
        rm = self[rep]
        kw = rm._filter_kwargs(**kwargs)
        if use_jit and rm._jittable:
            if rm._jitted_step is None:
                rm._jitted_step = rm._lookup_or_build_jitted_step()
            try:
                merged, delta = rm._jitted_step(rm._current_state(), *args, **kw)
                return delta, merged
            except Metric._TRACER_ERRORS:
                rm._jit_failed = True
        return rm._run_update_on_state(rm.init_state(), *args, **kw), None

    def _step_sync_shares(self, shared: Dict[str, str]) -> Dict[str, str]:
        """member -> group representative, for ``dist_sync_on_step`` members
        whose per-step delta gather can ride ONE host plane per group.

        Group members compute their batch value from the SAME shared delta;
        with ``dist_sync_on_step`` each member then used to host-gather that
        identical delta through its own compute — the per-step analogue of
        the epoch-level redundancy ``_grouped_host_sync`` eliminates.
        Eligibility mirrors it: the member must sync through the same gather
        configuration as the group's first eligible member (same
        ``dist_sync_fn`` identity, same ``process_group``), with no
        sharded-engine self-sync. Groups with < 2 eligible members keep the
        per-member path — nothing is saved. ``sync_lag >= 1`` members are
        excluded: their per-step gathers are DEFERRED dispatches whose
        handles live on the member's lag-k ring (``Metric._handle_ring``) —
        they defer through their own compute path instead of the shared
        eager gather.
        """
        import jax

        multiproc = jax.process_count() > 1
        by_rep: Dict[str, list] = {}
        for k, rep in shared.items():
            m = self[k]
            if (
                m.dist_sync_on_step
                and m.compute_on_step
                and not getattr(m, "sync_lag", 0)
                and not m._states_own_sync()
                and (m.dist_sync_fn is not None or multiproc)
            ):
                by_rep.setdefault(rep, []).append(k)
        out: Dict[str, str] = {}
        for rep, members in by_rep.items():
            leader = self[members[0]]
            share = [
                k
                for k in members
                if self[k].dist_sync_fn is leader.dist_sync_fn
                and self[k].process_group == leader.process_group
            ]
            if len(share) >= 2:
                out.update({k: rep for k in share})
        return out

    def _synced_step_delta(
        self, rep: str, member: str, delta: Any, cache: Dict[str, Any]
    ) -> Any:
        """The group's batch delta after ONE shared host-plane gather."""
        if rep in cache:
            return cache[rep]
        from metrics_tpu.parallel.sync import host_gather

        m = self[member]
        gather_fn = m.dist_sync_fn if m.dist_sync_fn is not None else m._default_gather()
        record_states_synced(len(m._reductions))
        if TRACE.enabled:
            with _span("collection.step_sync", {"group": rep}):
                synced = host_gather(delta, m._reductions, gather_fn=gather_fn)
                if _DEVTIME.enabled:
                    _fence(synced)
        else:
            synced = host_gather(delta, m._reductions, gather_fn=gather_fn)
        cache[rep] = synced
        return synced

    def _forward_eager_grouped(self, *args: Any, **kwargs: Any) -> Dict[str, Any]:
        """Per-member fallback forward with the compute-group delta SHARED.

        The eager analogue of the fused collection step's grouping: the
        group representative computes the batch delta once, every member
        merges it into its OWN accumulator and computes its batch value
        from the shared delta — including ``dist_sync_on_step`` members
        (sync-compatible group members additionally share ONE per-step
        delta gather, see ``_step_sync_shares``; members with per-member
        sync config still sync through their own compute) and configs whose
        fingerprint keeps the fused step off. Mirrors
        ``Metric._forward_fused``'s contract member by member.
        """
        with shared_input_format():
            return self._forward_eager_body(*args, **kwargs)

    def _forward_eager_body(self, *args: Any, **kwargs: Any) -> Dict[str, Any]:
        shared = self._eager_shared_groups()
        step_shares = self._step_sync_shares(shared)
        deltas: Dict[str, Any] = {}
        merged_rep: Dict[str, Any] = {}
        synced_deltas: Dict[str, Any] = {}
        out: Dict[str, Any] = {}
        for k, m in self.items():
            rep = shared.get(k)
            if rep is None:
                out[self._set_prefix(k)] = m(*args, **m._filter_kwargs(**kwargs))
                continue
            if rep not in deltas:
                if TRACE.enabled:
                    with _span("collection.group_update", {"group": rep}):
                        delta, merged = self._group_delta(rep, args, kwargs, use_jit=True)
                        if _DEVTIME.enabled:
                            _fence(delta)
                else:
                    delta, merged = self._group_delta(rep, args, kwargs, use_jit=True)
                deltas[rep] = delta
                if merged is not None:
                    merged_rep[rep] = merged
            delta = deltas[rep]
            m._computed = None
            m._forward_cache = None
            m._note_rows(args, m._filter_kwargs(**kwargs))
            if k == rep and rep in merged_rep:
                m._set_state(merged_rep[rep])  # jitted step already merged
            else:
                m._set_state(m.merge_states(m._current_state(), delta))
            value = None
            if m.compute_on_step:
                # the _forward_fused tail: batch value from the shared delta,
                # with per-member dist_sync_on_step honored by its compute —
                # pre-synced ONCE per group for sync-compatible members
                if k in step_shares:
                    value_state = self._synced_step_delta(rep, k, delta, synced_deltas)
                    m._to_sync = False
                else:
                    value_state = delta
                    m._to_sync = m.dist_sync_on_step
                m._in_forward = True
                acc = m._current_state()
                m._set_state(value_state)
                try:
                    m._forward_cache = m.compute()
                finally:
                    m._set_state(acc)
                    m._to_sync = True
                    m._in_forward = False
                m._computed = None
                value = m._forward_cache
            out[self._set_prefix(k)] = value
        return out

    def _collection_fusable(self) -> bool:
        return all(
            m._fusable
            and m._jittable
            and m.compute_on_step
            and not m.dist_sync_on_step
            and m.dist_sync_fn is None  # custom host gather: per-member path
            and m._config_fingerprint() is not None  # update/compute write states only
            for m in self.values()
        )

    def _warn_unfused(self) -> None:
        """Name every member (and the attribute) that keeps fusion off.

        Emitted once per message for the process lifetime — the point is a
        single actionable pointer at the config that broke fingerprinting,
        not a per-step nag."""
        for k, m in self.items():
            reason = m._unfusable_reason()
            if reason is not None:
                rank_zero_warn_once(
                    f"MetricCollection member {k!r} ({type(m).__name__}) is excluded "
                    f"from the fused collection step by {reason}; the collection "
                    "falls back to the per-group eager path. Fix the member's "
                    "config to restore single-dispatch forwards."
                )

    def _refresh_col_cache(self) -> None:
        # cheap per-forward staleness key: child identity, not just names —
        # replacing a child under the same key must drop the cached steps AND
        # any cached negative verdict (unfusable / fuse-failed)
        membership = (self.__dict__.get("_col_generation", 0),) + tuple(
            (k, id(m)) for k, m in self.items()
        )
        if self.__dict__.get("_col_membership") != membership:
            self.__dict__["_col_membership"] = membership
            self.__dict__["_col_step"] = None
            self.__dict__["_col_batched_step"] = None
            self.__dict__["_col_fuse_failed"] = False
            self.__dict__["_col_batched_failed"] = False
            self.__dict__["_col_unfusable"] = False
            # group assignment is membership-derived: any child swap (including
            # same-key replacement, caught by the generation counter) rebuilds it
            self.__dict__["_col_groups"] = None

    # ---------------------------------------------------------- compute groups
    def _group_map(self) -> Dict[str, str]:
        """member name -> group representative name (identity map when off).

        The representative is the group's first member in collection order;
        cached under the same membership/generation guard as the fused steps,
        so ``__setitem__``/``__delitem__`` rebuild it and clones re-derive it.
        """
        self._refresh_col_cache()
        groups = self.__dict__.get("_col_groups")
        record_cache("group", groups is not None)
        if groups is None:
            groups = {}
            if getattr(self, "_enable_compute_groups", True):
                reps: Dict[Any, str] = {}
                for name, metric in self.items():
                    key = metric._group_fingerprint()
                    groups[name] = name if key is None else reps.setdefault(key, name)
            else:
                groups = {name: name for name in self.keys()}
            self.__dict__["_col_groups"] = groups
        return groups

    @property
    def compute_groups(self) -> Dict[str, Tuple[str, ...]]:
        """The resolved groups: representative name -> member names."""
        by_rep: "OrderedDict[str, list]" = OrderedDict()
        for name, rep in self._group_map().items():
            by_rep.setdefault(rep, []).append(name)
        return {rep: tuple(members) for rep, members in by_rep.items()}

    def _forward_fused_collection(self, *args: Any, **kwargs: Any) -> Optional[Dict[str, Any]]:
        self._refresh_col_cache()
        if self.__dict__.get("_col_fuse_failed") or self.__dict__.get("_col_unfusable"):
            return None
        step = self.__dict__.get("_col_step")
        if step is None:
            # the full fusability/fingerprint gate runs only at (re)build time;
            # steady-state forwards (fused or not) never re-run it
            if not self._collection_fusable():
                self.__dict__["_col_unfusable"] = True
                self._warn_unfused()
                return None
            step = self._lookup_or_build_col_step("fused", self._build_collection_step)
            self.__dict__["_col_step"] = step
        # the step donates its state argument: deduplicate aliased buffers
        # so XLA never sees one buffer donated twice
        states = _dedupe_donated_buffers({k: m._current_state() for k, m in self.items()})
        try:
            if TRACE.enabled:
                with _span("collection.fused_step", {"members": len(self)}):
                    new_states, values = step(states, *args, **kwargs)
                    if _DEVTIME.enabled:
                        _fence((new_states, values))
            else:
                new_states, values = step(states, *args, **kwargs)
        except Metric._TRACER_ERRORS:
            # some update/compute needs concrete values: per-metric forwards
            # handle their own fallbacks from here on. The verdict stays
            # INSTANCE-local: tracer failures are input-signature-specific,
            # so a global negative verdict could clobber a compiled step that
            # works for other callers of the same config.
            self.__dict__["_col_fuse_failed"] = True
            self.__dict__["_col_step"] = None
            return None
        for k, m in self.items():
            m._note_rows(args, m._filter_kwargs(**kwargs))
            m._computed = None
            m._set_state(new_states[k])
            m._forward_cache = values[k]
        return {self._set_prefix(k): values[k] for k in self.keys()}

    def _lookup_or_build_col_step(self, kind: str, build):
        """Share the compiled collection step across config-identical
        collections (the collection analogue of the per-metric jitted-step
        cache): a fresh collection per eval epoch replays, never retraces.
        Only successful builds are cached — tracer failures are
        input-signature-specific, so negative verdicts stay instance-local."""
        keyed = _col_cache_key(self, kind)
        if keyed is None:
            return build()
        key, pins = keyed
        with _COL_STEP_CACHE_LOCK:
            hit = _COL_STEP_CACHE.get(key)
            record_cache("fused_step", hit is not None)
            if hit is None:
                from metrics_tpu.core.metric import _bounded_insert

                hit = (pins, build())
                _bounded_insert(_COL_STEP_CACHE, key, hit, _COL_STEP_CACHE_MAX)
        return hit[1]

    def _build_collection_step(self):
        import threading

        import jax

        # detached reset copies: retraces never touch the live children
        # (children passed the write-only-states fingerprint gate)
        carriers = {k: deepcopy(m) for k, m in self.items()}
        for c in carriers.values():
            c.reset()
        group_of = dict(self._group_map())
        lock = threading.Lock()

        def step(states, *args, **kwargs):
            # one update per compute group; the shared delta merges into each
            # member's OWN accumulator (members stay individually correct even
            # if one was also updated outside the collection) and each member
            # computes its batch value from the shared delta. The
            # shared_input_format window memoizes input canonicalization, so
            # groups with equivalent (preds, target) handling reuse ONE
            # canonicalized pair instead of re-running the format pass each.
            deltas: Dict[str, Any] = {}
            new_states, values = {}, {}
            with shared_input_format():
                for k, c in carriers.items():
                    rep = group_of[k]
                    if rep not in deltas:
                        rc = carriers[rep]
                        kw = rc._filter_kwargs(**kwargs)
                        with lock:
                            deltas[rep] = rc._run_update_on_state(rc.init_state(), *args, **kw)
                    new_states[k] = c.merge_states(states[k], deltas[rep])
                    with lock:
                        values[k] = c.compute_from_state(deltas[rep])
            return new_states, values

        # states donate off CPU: in-place slab updates are the point of the
        # megafused step, and the caller dedupes aliased buffers + rebinds
        # every member attr right after the call. XLA:CPU executables
        # DESERIALIZED from the persistent compilation cache mishandle
        # input-output aliasing (state reads flakily see freed memory), so on
        # CPU the step keeps the copy — same gate as the routed-scatter and
        # bootstrap steps.
        donate = (0,) if jax.default_backend() != "cpu" else ()
        return jax.jit(step, donate_argnums=donate)

    def __call__(self, *args: Any, **kwargs: Any) -> Dict[str, Any]:
        return self.forward(*args, **kwargs)

    def forward_batched(self, *args: Any, **kwargs: Any) -> Dict[str, Any]:
        """Accumulate a whole stack of batches in one collection dispatch.

        The leading axis of every argument is the step axis.

        The batched analogue of the fused collection forward: per-batch
        deltas come from a vmap-ed update per child, the stack folds into
        each accumulator with one reduction per state, per-step values come
        back stacked, and each child's epoch value is pre-seeded so a
        following ``compute()`` is free. Falls back to per-child
        ``forward_batched`` (which itself falls back to per-step forwards)
        when a child cannot take the vmap path.
        """
        import jax

        self._lockstep_check()
        self._refresh_col_cache()
        step = self.__dict__.get("_col_batched_step")
        if step is None and not (
            self.__dict__.get("_col_batched_failed") or self.__dict__.get("_col_unfusable")
        ):
            # the full fusability/fingerprint gate runs only at (re)build
            # time, mirroring the fused per-step path
            if self._collection_fusable() and all(m._stack_mergeable for m in self.values()):
                step = self._lookup_or_build_col_step("batched", self._build_collection_batched_step)
                self.__dict__["_col_batched_step"] = step
            else:
                self.__dict__["_col_batched_failed"] = True
        if step is not None:
            states = {k: m._current_state() for k, m in self.items()}
            try:
                if TRACE.enabled:
                    with _span("collection.forward_batched", {"members": len(self)}):
                        new_states, values, epochs = step(states, *args, **kwargs)
                        if _DEVTIME.enabled:
                            _fence((new_states, values, epochs))
                else:
                    new_states, values, epochs = step(states, *args, **kwargs)
            except Metric._TRACER_ERRORS:
                # batched-path verdict only (and instance-local, see above):
                # the fused per-step program is a DIFFERENT trace and may
                # still work
                self.__dict__["_col_batched_failed"] = True
                self.__dict__["_col_batched_step"] = None
            else:
                seed_epoch = jax.process_count() == 1
                steps = args[0].shape[0] if args else next(iter(kwargs.values())).shape[0]
                for k, m in self.items():
                    m._note_rows(args, m._filter_kwargs(**kwargs))  # watermark +1 ...
                    m._epoch_watermark += steps - 1  # ... for a stack of steps
                    m._set_state(new_states[k])
                    m._forward_cache = jax.tree_util.tree_map(lambda v: v[-1], values[k])
                    m._computed = epochs[k] if seed_epoch and m.dist_sync_fn is None else None
                self._lockstep_record()
                return {self._set_prefix(k): values[k] for k in self.keys()}
        out = {
            self._set_prefix(k): m.forward_batched(*args, **m._filter_kwargs(**kwargs))
            for k, m in self.items()
        }
        self._lockstep_record()
        return out

    def _build_collection_batched_step(self):
        import threading

        import jax

        from metrics_tpu.parallel.sync import merge_values_stacked

        carriers = {k: deepcopy(m) for k, m in self.items()}
        for c in carriers.values():
            c.reset()
        group_of = dict(self._group_map())
        donate = (0,) if jax.default_backend() == "tpu" else ()
        lock = threading.Lock()

        def step(states, *args, **kwargs):
            # the batched analogue of the grouped per-step program: ONE
            # vmap-ed update per compute group, its stacked deltas shared by
            # every member for the fold, the per-step values, and the epoch
            group_deltas: Dict[str, Any] = {}
            new_states, values, epochs = {}, {}, {}
            for k, c in carriers.items():
                rep = group_of[k]
                if rep not in group_deltas:
                    rc = carriers[rep]
                    kw = rc._filter_kwargs(**kwargs)

                    def one(*batch, _c=rc, _kw_keys=tuple(kw)):
                        batch_args = batch[: len(args)]
                        batch_kw = dict(zip(_kw_keys, batch[len(args):]))
                        with lock:
                            return _c._run_update_on_state(_c.init_state(), *batch_args, **batch_kw)

                    group_deltas[rep] = jax.vmap(one)(*args, *kw.values())
                deltas = group_deltas[rep]
                new_states[k] = {
                    name: merge_values_stacked(c._reductions[name], states[k][name], deltas[name])
                    for name in c._defaults
                }
                with lock:
                    values[k] = jax.vmap(c.compute_from_state)(deltas)
                    epochs[k] = c.compute_from_state(new_states[k])
            return new_states, values, epochs

        return jax.jit(step, donate_argnums=donate)

    def update(self, *args: Any, **kwargs: Any) -> None:
        """Eager accumulate: one update PER COMPUTE GROUP, not per member.

        The group representative computes the batch delta once and every
        member merges it into its own accumulator — the eager-path analogue
        of the fused step's shared update (``dist_sync_on_step`` and
        unfingerprintable configs share the delta too). Singleton groups and
        non-mergeable members run their own ``update`` unchanged.
        """
        self._lockstep_check()
        shared = self._eager_shared_groups()
        deltas: Dict[str, Any] = {}
        for k, m in self.items():
            rep = shared.get(k)
            if rep is None:
                m.update(*args, **m._filter_kwargs(**kwargs))
                continue
            if rep not in deltas:
                if TRACE.enabled:
                    with _span("collection.group_update", {"group": rep}):
                        deltas[rep], _ = self._group_delta(rep, args, kwargs, use_jit=False)
                        if _DEVTIME.enabled:
                            _fence(deltas[rep])
                else:
                    deltas[rep], _ = self._group_delta(rep, args, kwargs, use_jit=False)
            m._computed = None
            m._note_rows(args, m._filter_kwargs(**kwargs))
            m._set_state(m.merge_states(m._current_state(), deltas[rep]))
        self._lockstep_record()

    # -------------------------------------------------- preemption-safe resume
    @property
    def epoch_watermark(self) -> int:
        """The collection's resume point: the MINIMUM member watermark (a
        step counts as applied only once every member holds it). Members
        advance in lockstep through collection-level updates, so the min is
        normally also the max; after a restore from a consistent checkpoint
        they are equal by construction."""
        return min((m._epoch_watermark for m in self.values()), default=0)

    def guarded_update(self, step_index: int, *args: Any, **kwargs: Any) -> bool:
        """Idempotent collection update (see ``Metric.guarded_update``):
        applies the batch to every member only if ``step_index`` is not
        already below the collection watermark — replaying the step that was
        in flight at a preemption is a no-op after restore."""
        if step_index < self.epoch_watermark:
            return False
        self.update(*args, **kwargs)
        return True

    def compute(self) -> Dict[str, Any]:
        if TRACE.enabled:
            with _span("collection.compute", {"members": len(self)}):
                out = self._compute_all()
                if _DEVTIME.enabled:
                    _fence(out)
                return out
        return self._compute_all()

    def _compute_all(self) -> Dict[str, Any]:
        shared = self._grouped_host_sync()
        return {
            self._set_prefix(k): shared[k] if shared is not None and k in shared else m.compute()
            for k, m in self.items()
        }

    # Epoch-gather deferral: the shared per-group gathers dispatch through
    # ``deferred_host_gather`` so a collection's epoch compute OVERLAPS the
    # gathers of groups it has not read yet (attribute convention, like
    # ``Metric.sync_lag``: flip to False for the fully synchronous plane).
    deferred_epoch_sync: bool = True

    def _grouped_host_sync(self, deferred: Optional[bool] = None) -> Optional[Dict[str, Any]]:
        """Group-aware host-plane sync: ONE ``process_allgather`` plane per
        compute group instead of one per member.

        Group members accrue identical states when every write went through
        the collection (the lockstep tracking above proves it host-side), so
        gathering each member's state separately moves the same payload over
        DCN once per member — the host-plane analogue of the redundancy the
        pure plane already eliminates. For every group whose members are in
        lockstep and share the same sync configuration, the group's first
        lockstep member is gathered once and every such member computes from
        that single synced state; its compute cache and ``_after_compute``
        hook behave exactly as in the individual path. Diverged members,
        members with per-member sync config, and sharded-engine metrics fall
        back to their own ``compute``. Returns {member name: computed value}
        for the members handled here, or None.

        DEFERRED form (default — :attr:`deferred_epoch_sync`): every group's
        gather is submitted up front through
        :func:`~metrics_tpu.parallel.deferred.deferred_host_gather` (the
        single-worker host plane runs them in submission order, so the
        collective entry order — and every peer's rendezvous pairing — is
        IDENTICAL to the synchronous plane's), then the handles resolve in
        that same order: while group ``i``'s members compute from their
        resolved view, group ``i+1``'s gather is already moving on the
        background plane. Same gathers, same guard, same chaos sites, same
        per-call collective counts — only the epoch's critical path shrinks.
        Per-member syncs for members NOT handled here still run after every
        handle has resolved, exactly where the synchronous plane ran them.
        """
        ids = self.__dict__.get("_lockstep_ids")
        if ids is None:
            return None
        import jax

        from metrics_tpu.parallel.sync import host_gather

        self._lockstep_check()
        diverged = self.__dict__.get("_lockstep_diverged", set())
        multiproc = jax.process_count() > 1
        plans = []  # (rep, share member names, gather source metric, gather_fn)
        for rep, members in self.compute_groups.items():
            if len(members) < 2:
                continue
            rep_m = self[rep]
            gather_fn = rep_m.dist_sync_fn
            if gather_fn is None and multiproc:
                gather_fn = rep_m._default_gather()
            if gather_fn is None or rep_m._states_own_sync():
                continue
            share = [
                k
                for k in members
                if k not in diverged
                and self[k]._to_sync
                and self[k]._computed is None
                and self[k].dist_sync_fn is rep_m.dist_sync_fn
                and self[k].process_group == rep_m.process_group
                and not self[k]._states_own_sync()
            ]
            if len(share) < 2:
                continue  # nothing saved by sharing; individual path
            plans.append((rep, share, self[share[0]], gather_fn))
        if not plans:
            return None

        deferred = self.deferred_epoch_sync if deferred is None else deferred
        handles = None
        if deferred:
            from metrics_tpu.parallel.deferred import deferred_host_gather

            # phase 1: dispatch EVERY group's gather (entry order == the
            # synchronous plane's group order); phase 2 below resolves them
            # in the same order, overlapping each resolve's member computes
            # with the still-in-flight gathers behind it
            handles = []
            for rep, share, src, gather_fn in plans:
                record_states_synced(len(src._defaults))
                handles.append(deferred_host_gather(
                    src._current_state(), src._reductions, gather_fn=gather_fn,
                    label="epoch_gather",
                    attrs={"group": rep} if TRACE.enabled else None,
                ))
            record_deferred_depth(f"{type(self).__name__}.epoch", len(handles))

        out: Dict[str, Any] = {}
        for i, (rep, share, src, gather_fn) in enumerate(plans):
            if handles is not None:
                if TRACE.enabled:
                    attrs = {"group": rep, "shared": len(share), "deferred": "yes"}
                    with _span("collection.host_sync", attrs):
                        synced = handles[i].result()
                        if _DEVTIME.enabled:
                            _fence(synced)
                else:
                    synced = handles[i].result()
            else:
                record_states_synced(len(src._defaults))
                if TRACE.enabled:
                    with _span("collection.host_sync", {"group": rep, "shared": len(share)}):
                        synced = host_gather(src._current_state(), src._reductions, gather_fn=gather_fn)
                        if _DEVTIME.enabled:
                            _fence(synced)
                else:
                    synced = host_gather(src._current_state(), src._reductions, gather_fn=gather_fn)
            for k in share:
                m = self[k]
                cache = m._current_state()
                m._set_state(synced)
                m._to_sync = False
                try:
                    out[k] = m.compute()
                finally:
                    m._set_state(cache)
                    m._to_sync = True
        if handles is not None:
            record_deferred_depth(f"{type(self).__name__}.epoch", 0)
        return out or None

    def reset(self) -> None:
        for _, m in self.items():
            m.reset()
        # a collection-level reset restores every member to defaults: group
        # members are in lockstep again by construction
        self.__dict__.get("_lockstep_diverged", set()).clear()
        self._lockstep_record()

    def clone(self, prefix: Optional[str] = None) -> "MetricCollection":
        mc = deepcopy(self)
        mc.prefix = self._check_prefix_arg(prefix)
        return mc

    # fused-step cache attrs never travel to copies/pickles: the copy's
    # membership key differs, so it re-derives its own verdict lazily
    # (group assignment included — it is membership-derived state)
    _COL_CACHE_ATTRS = (
        "_col_step", "_col_batched_step", "_col_membership", "_col_fuse_failed",
        "_col_batched_failed", "_col_unfusable", "_col_groups",
        # lockstep tracking is identity-based: array ids are meaningless on a
        # copy, so copies re-derive it in __init__ (conservatively: members
        # with accumulated state start diverged until the next reset)
        "_lockstep_ids", "_lockstep_diverged",
    )

    def __deepcopy__(self, memo: dict) -> "MetricCollection":
        # dict-subclass default reduce would re-invoke __init__ with an items
        # iterator; rebuild explicitly (type(self) keeps subclasses intact).
        # The compute-groups flag must ride the constructor: __init__ writes
        # its default into new.__dict__, which the not-in-new.__dict__ guard
        # below would then never overwrite.
        new = type(self)(
            {k: deepcopy(m, memo) for k, m in self.items()},
            prefix=self.prefix,
            compute_groups=getattr(self, "_enable_compute_groups", True),
        )
        memo[id(self)] = new
        for key, value in self.__dict__.items():
            if key not in new.__dict__ and key not in self._COL_CACHE_ATTRS:
                new.__dict__[key] = deepcopy(value, memo)
        return new

    def __reduce__(self):
        state = {k: v for k, v in self.__dict__.items() if k not in self._COL_CACHE_ATTRS}
        return (type(self), (dict(self), self.prefix), state)

    def __setstate__(self, state: dict) -> None:
        self.__dict__.update(state)
        self.__dict__.setdefault("_enable_compute_groups", True)

    def persistent(self, mode: bool = True) -> None:
        for _, m in self.items():
            m.persistent(mode)

    # ----------------------------------------------------------- checkpoint
    # Group-aware shard merging: compute-group members accrue identical
    # states when every write went through the collection, so persisting
    # each member's copy writes the same arrays once per member. state_dict
    # writes ONE copy per group plus a membership manifest and fans back out
    # on load. Sharing is decided by VALUE at checkpoint time (host-side
    # numpy equality, epoch-rate cost) — never assumed from the group
    # structure alone, so out-of-collection writes can't corrupt a restore.
    _GROUP_MANIFEST_KEY = "_compute_group_manifest"

    @staticmethod
    def _entries_equal(a: Any, b: Any) -> bool:
        import numpy as np
        if type(a) is not type(b):
            return False
        if isinstance(a, dict):  # PaddedBuffer entries: {"data", "count"}
            return set(a) == set(b) and all(
                MetricCollection._entries_equal(a[k], b[k]) for k in a
            )
        if isinstance(a, list):
            return len(a) == len(b) and all(
                np.array_equal(x, y) for x, y in zip(a, b)
            )
        return np.array_equal(a, b)

    def _states_match(self, rep: Metric, member: Metric) -> bool:
        """Whether two members' persisted entries are value-identical."""
        a, b = rep.state_dict(), member.state_dict()
        return set(a) == set(b) and all(self._entries_equal(a[k], b[k]) for k in a)

    def state_dict(self, destination: Optional[dict] = None, prefix: str = "") -> dict:
        """Persistent states of every member, with compute-group shards
        MERGED: one full copy per group (the representative's), a
        ``{member: representative}`` manifest for the rest, and each shared
        member's host metadata (``_count_bound``) kept per member. Members
        whose values diverged from their representative (out-of-collection
        writes) keep their own full entry. Orbax/pickle-friendly numpy,
        like ``Metric.state_dict``.
        """
        destination = {} if destination is None else destination
        import numpy as np

        gm = self._group_map()
        manifest: Dict[str, str] = {}
        for name, m in self.items():
            rep = gm[name]
            if rep != name and self._states_match(self[rep], m):
                manifest[name] = rep
                # host-side metadata is per-member: the overflow bound rides
                # outside the shared entry so a restore keeps warning, and
                # the epoch watermark so a restored member replays
                # idempotently (guarded_update)
                destination[f"{prefix}{name}._count_bound"] = np.asarray(
                    m._count_bound, dtype=np.int64
                )
                destination[f"{prefix}{name}._epoch_watermark"] = np.asarray(
                    m._epoch_watermark, dtype=np.int64
                )
            else:
                m.state_dict(destination, prefix=f"{prefix}{name}.")
        destination[prefix + self._GROUP_MANIFEST_KEY] = dict(manifest)
        return destination

    def load_state_dict(self, state_dict: dict, prefix: str = "") -> None:
        """Load a (possibly group-merged) collection checkpoint: manifest
        members fan out from their representative's single copy; everyone
        else loads their own entry. Old per-member checkpoints (no
        manifest) load unchanged."""
        manifest = state_dict.get(prefix + self._GROUP_MANIFEST_KEY, {})
        diverged = self.__dict__.get("_lockstep_diverged", set())
        for name, m in self.items():
            src = manifest.get(name, name)
            m.load_state_dict(state_dict, prefix=f"{prefix}{src}.")
            if src != name:
                key = f"{prefix}{name}._count_bound"
                if key in state_dict:
                    m._count_bound = int(state_dict[key])
                wm_key = f"{prefix}{name}._epoch_watermark"
                if wm_key in state_dict:
                    m._epoch_watermark = int(state_dict[wm_key])
                # fanned-out members hold the representative's exact values:
                # back in lockstep with their group
                diverged.discard(name)
            elif name in self._group_map() and self._group_map()[name] != name:
                # a grouped member restored from its OWN entry diverged at
                # save time; stay conservative until the next reset
                diverged.add(name)
        self._lockstep_record()

    def _set_prefix(self, k: str) -> str:
        return k if self.prefix is None else self.prefix + k

    @staticmethod
    def _check_prefix_arg(prefix: Optional[str]) -> Optional[str]:
        if prefix is not None and not isinstance(prefix, str):
            raise ValueError("Expected input `prefix` to be a string")
        return prefix

    # ------------------------------------------------------- TPU-native extras
    def device_put(self, device_or_sharding: Any) -> "MetricCollection":
        """Place every metric's states on a device/sharding (mesh placement)."""
        for _, m in self.items():
            m.device_put(device_or_sharding)
        return self

    def init_state(self) -> Dict[str, Dict[str, Any]]:
        """Joint state pytree of the collection (for in-jit training loops).

        With compute groups active, the pytree is DEDUPLICATED: one entry per
        group representative, since every member of a group accrues an
        identical state. ``update_state`` / ``merge_states`` / ``sync_state``
        operate on whatever entries the given pytree has (so full per-member
        pytrees from older callers still work), and ``compute_from_state``
        computes every member from its group's entry — the collection's whole
        pure plane (and its sync payload) shrinks to one state per group.
        """
        gm = self._group_map()
        return {k: m.init_state() for k, m in self.items() if gm[k] == k}

    def update_state(self, state: Dict[str, Dict[str, Any]], *args: Any, **kwargs: Any) -> Dict[str, Dict[str, Any]]:
        """Pure joint update: one call updates every state entry — jit this once
        so the whole collection's update fuses into a single XLA computation
        (with compute groups, one update per group). Input canonicalization is
        memoized across entries (``shared_input_format``), so distinct groups
        over the same ``(preds, target)`` pair run the format pass ONCE."""
        with shared_input_format():
            return {k: self[k].update_state(state[k], *args, **self[k]._filter_kwargs(**kwargs)) for k in state}

    def compute_from_state(self, state: Dict[str, Dict[str, Any]]) -> Dict[str, Any]:
        gm = self._group_map()
        return {
            self._set_prefix(k): m.compute_from_state(state[k] if k in state else state[gm[k]])
            for k, m in self.items()
        }

    def merge_states(self, a: Dict[str, Dict[str, Any]], b: Dict[str, Dict[str, Any]]) -> Dict[str, Dict[str, Any]]:
        return {k: self[k].merge_states(a[k], b[k]) for k in a}

    def sync_state(
        self,
        state: Dict[str, Dict[str, Any]],
        axis_name: Any,
        deferred: bool = False,
        mesh: Any = None,
    ) -> Dict[str, Dict[str, Any]]:
        """In-jit sync of the joint state over a mesh axis — leaves across
        ALL entries coalesce into per-dtype bucketed collectives (see
        ``parallel.sync.coalesced_sync_state``): one ``psum``/``pmin``/
        ``pmax`` per reduce bucket (``mean`` folds into the sum bucket), one
        ``all_gather`` per gather bucket, and ONE ``all_gather`` per
        PaddedBuffer bucket (counts bitcast into the data payload for
        4-byte dtypes) — a buffer-state collection (AUROC +
        AveragePrecision + Spearman) stages 1 gather per dtype instead of
        2 per buffer. Sketch states and keyed ``(K, *shape)`` slab states
        (``wrappers/keyed.py``) are ordinary reduce-bucket leaves here, so
        a 10,000-segment member adds payload to an existing bucket, never a
        collective. Pass a ``parallel.placement.MeshHierarchy`` as
        ``axis_name`` on a 2-level (ici x dcn) mesh to stage every bucket
        hierarchically (only per-slice payloads cross DCN).

        ``deferred=True`` is the FUTURE-RETURNING form (eager callers only;
        same contract as ``Metric.sync_state``): the joint state — every
        leaf stacked over the mesh axis on its leading dimension — is
        snapshotted and the compiled bucketed sync is dispatched WITHOUT
        fencing; the returned :class:`~metrics_tpu.parallel.deferred.
        SyncHandle` resolves to the same nested ``{member: {state: value}}``
        dict the synchronous call returns, staging the IDENTICAL
        collectives."""
        from metrics_tpu.parallel.sync import coalesced_sync_state

        flat = {(k, n): v for k, s in state.items() for n, v in s.items()}
        reductions = {(k, n): self[k]._reductions[n] for k, s in state.items() for n in s}
        if deferred:
            from metrics_tpu.parallel.deferred import deferred_sync_state

            structure = {k: tuple(s) for k, s in state.items()}
            return deferred_sync_state(
                flat, reductions, axis_name, mesh=mesh,
                watermark=self.epoch_watermark,
                finish=lambda synced: {
                    k: {n: synced[(k, n)] for n in names} for k, names in structure.items()
                },
            )
        synced = coalesced_sync_state(flat, reductions, axis_name)
        return {k: {n: synced[(k, n)] for n in s} for k, s in state.items()}

    def pure(self) -> PureMetric:
        return PureMetric(
            init=self.init_state,
            update=self.update_state,
            compute=self.compute_from_state,
            merge=self.merge_states,
            sync=self.sync_state,
        )

    def __repr__(self) -> str:
        inner = ",\n  ".join(f"{k}: {repr(m)}" for k, m in self.items())
        return f"MetricCollection(\n  {inner}\n)"
