"""Core metric runtime: stateful wrapper over a pure functional core.

Parity target: reference ``torchmetrics/metric.py`` — ``Metric`` (metric.py:29),
``add_state`` (:88-148), ``forward`` (:150-177), ``_sync_dist`` (:179-197),
update/compute wrapping (:199-239), ``reset/clone/persistent/state_dict``
(:256-319), ``_filter_kwargs`` (:321-336), ``__hash__`` (:338-350), operator
overloads (:352-450) and ``CompositionalMetric`` (:457-536).

TPU-native redesign (not a port):

* **The state is a pytree, the update is a pure function.** Every metric also
  exposes ``init_state / update_state / compute_from_state / merge_states /
  sync_state`` — pure functions over a ``{name: array|PaddedBuffer}`` dict that
  can be ``jit``-ed, ``scan``-ned, donated, checkpointed with orbax, and used
  directly inside a ``pjit``-ed training step (see ``Metric.pure()``).
* **One fused update per ``forward``.** The reference runs ``update()`` twice
  per ``forward`` (once into the accumulator, once on a fresh state for the
  batch value — reference metric.py:156-177). Here ``forward`` computes the
  batch-delta state once and *merges* it into the accumulator with the same
  per-state reduction that powers distributed sync; the batch value is computed
  from the delta. Metrics whose reductions have no pairwise merge fall back to
  the reference's double-update path automatically.
* **XLA collectives instead of NCCL.** Host-plane sync mirrors the reference's
  gather-then-reduce exactly (over ``process_allgather`` when multi-host); the
  in-jit plane syncs with ``psum``/``pmin``/``pmax``/``all_gather`` over a
  named mesh axis (see ``metrics_tpu/parallel/sync.py``).
* **Allocation-free hot loop.** When every state is a fixed-shape array the
  fused step is compiled once with buffer donation on TPU, so per-step metric
  update costs one fused XLA kernel and no host sync.
"""
import functools
import inspect
import threading
import time
from abc import ABC, abstractmethod
from collections import deque
from copy import deepcopy
from typing import Any, Callable, Dict, NamedTuple, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.observability.counters import (
    COUNTERS as _COUNTERS,
    record_cache,
    record_deferred_depth,
    record_fault,
    record_state_bytes,
    record_states_synced,
    state_nbytes,
)
from metrics_tpu.observability.devtime import DEVTIME as _DEVTIME, fence as _fence
from metrics_tpu.observability.trace import TRACE, span as _span
from metrics_tpu.parallel.buffer import PaddedBuffer, buffer_append, buffer_init
from metrics_tpu.parallel.cms import CMSSpec, cms_init
from metrics_tpu.parallel.qsketch import QSketchSpec, qsketch_init
from metrics_tpu.parallel.sketch import SketchSpec, is_sketch, sketch_init
from metrics_tpu.parallel.slab import SlabSpec, slab_init, slab_sync_reduce
from metrics_tpu.utils import compat, debug
from metrics_tpu.utils.data import is_concrete
from metrics_tpu.utils.exceptions import StateCorruptionError, TracingUnsupportedError
from metrics_tpu.utils.prints import rank_zero_warn
from metrics_tpu.parallel.sync import (
    ReduceFx,
    canonicalize_group,
    canonicalize_reduce_fx,
    coalesced_sync_state,
    gather_all_arrays,
    host_gather,
    is_mergeable,
    is_stack_mergeable,
    merge_values,
    merge_values_stacked,
)

State = Dict[str, Any]

# Session-wide default for Metric(jit=None): None = auto (jit the fused step
# when all states are fixed-shape). Test harnesses that build thousands of
# short-lived metric instances can set this to False to avoid paying an XLA
# compile per instance; explicit per-metric `jit=` always wins.
_DEFAULT_JIT: Optional[bool] = None


def set_default_jit(value: Optional[bool]) -> Optional[bool]:
    """Set the process-wide default for ``Metric(jit=None)``; returns the old value."""
    global _DEFAULT_JIT
    old = _DEFAULT_JIT
    _DEFAULT_JIT = value
    return old


# The first-class state-spec registry of record: every mergeable state
# declaration kind (sketch histogram / count-min tail / quantile sketch /
# keyed slab) maps to its materializer HERE, so add_state, both materialize
# paths, and the checkpoint-restore fallback branch on one table instead of
# each growing a per-kind isinstance chain with every new state kind.
_SPEC_MATERIALIZERS = {
    SketchSpec: sketch_init,
    CMSSpec: cms_init,
    QSketchSpec: qsketch_init,
    SlabSpec: slab_init,
}

# the spec kinds whose states are sum-mergeable BY CONSTRUCTION (merge =
# elementwise add, sync = the existing psum buckets): add_state requires
# dist_reduce_fx='sum' for these. Slabs are excluded — their reduction is
# the spec's own slab_sync_reduce.
_SUM_MERGEABLE_SPECS = (SketchSpec, CMSSpec, QSketchSpec)


def materialize_state_spec(spec: Any) -> Any:
    """Materialize a registered first-class state spec, or ``None`` when
    ``spec`` is not one (callers fall through to their array/list arms)."""
    init = _SPEC_MATERIALIZERS.get(type(spec))
    return None if init is None else init(spec)


# -------------------------------------------------- state-integrity scanning
# Jittable pure scans over a state pytree: usable inside jit/shard_map (the
# pure API / in-jit sync plane) AND by the stateful check_finite policies
# below (which read the scalars back host-side at eager call boundaries).
CHECK_FINITE_POLICIES = (None, "warn", "raise", "quarantine")


def nonfinite_count(state: "State") -> Array:
    """Number of non-finite (NaN/Inf) elements across all float leaves of a
    state pytree (int32 scalar; jittable — NaN poisoning propagates through
    psum/all_gather identically on the flat and hierarchical sync planes, so
    this scan works before or after either)."""
    total = jnp.zeros((), dtype=jnp.int32)
    for leaf in jax.tree_util.tree_leaves(state):
        if jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.inexact):
            total = total + jnp.sum(~jnp.isfinite(leaf)).astype(jnp.int32)
    return total


def saturated_count(state: "State") -> Array:
    """Number of integer elements within a safety margin of their dtype's
    range (int32 scalar; jittable).

    A saturated count state is pre-wraparound corruption: one more epoch of
    updates flips it negative with no error anywhere. The margin is
    ``iinfo.max // 2048`` (for int32: ~2^20 — several large batches of
    headroom, far above any legitimate stat count that close to 2^31).
    """
    total = jnp.zeros((), dtype=jnp.int32)
    for leaf in jax.tree_util.tree_leaves(state):
        arr = jnp.asarray(leaf)
        if jnp.issubdtype(arr.dtype, jnp.integer):
            info = jnp.iinfo(arr.dtype)
            margin = max(info.max // 2048, 1)
            hit = (arr >= info.max - margin) | (arr <= info.min + margin)
            total = total + jnp.sum(hit).astype(jnp.int32)
    return total


def state_integrity_counts(state: "State") -> tuple:
    """(nonfinite, saturated) element counts — the jittable integrity scan
    behind the ``check_finite`` policies."""
    return nonfinite_count(state), saturated_count(state)


# ------------------------------------------------------- jitted-step sharing
# Two config-identical instances trace to the same XLA program, so compiled
# steps are shared process-wide: workloads that construct metrics repeatedly
# (fresh metric per eval epoch, per-fold loops) pay the trace once. Keys pin
# the first instance so id()-based parts stay allocated (each entry pins its
# own referents, so evicting one entry cannot invalidate another's key).
# Instances whose config cannot be fingerprinted exactly get a private step
# (never a wrong cache hit). Both caches are FIFO-bounded so a process
# sweeping many distinct configs cannot grow memory without bound.
_JITTED_STEP_CACHE: Dict[Any, tuple] = {}
_JITTED_STEP_CACHE_MAX = 256
_JITTED_STEP_CACHE_LOCK = threading.Lock()

# default-state device constants shared across instances (immutable arrays)
_DEFAULT_CONSTANT_CACHE: Dict[Any, Any] = {}
_DEFAULT_CONSTANT_CACHE_MAX = 1024


_CACHE_LOCK = threading.Lock()


def _bounded_insert(cache: Dict[Any, Any], key: Any, value: Any, max_size: int) -> None:
    with _CACHE_LOCK:
        if len(cache) >= max_size:
            cache.pop(next(iter(cache)), None)  # insertion order: FIFO
        cache[key] = value

# attrs that do not influence the traced computation (or are per-instance
# caches); state attrs are excluded by name via self._defaults
_NON_TRACE_ATTRS = frozenset({
    "update", "compute", "_update_signature", "_update_impl", "_compute_impl",
    "_computed", "_forward_cache", "_jitted_step", "_jitted_step_fc",
    "_jitted_scan", "_scan_failed",
    "_jit_failed", "_fc_failed", "_compute_jit_failed", "_count_bound", "_overflow_warned",
    "_metric_label",
    "_epoch_watermark", "check_finite",
    "_default_keys",
    "_to_sync", "_in_forward", "_sync_count", "dist_sync_fn",
    "_placement", "_state_dtype", "compute_on_step", "dist_sync_on_step",
    "process_group", "sync_lag", "_handle_ring", "_lag_controller",
})


class _Unfingerprintable(Exception):
    pass


@functools.lru_cache(maxsize=None)
def _traced_attr_writes(cls: type) -> Optional[frozenset]:
    """Names the traced step may assign on ``self``, or None when undeterminable.

    Sharing a compiled step across instances is only sound when tracing it
    writes registered states exclusively — side writes (e.g. a curve metric
    caching ``self.mode`` on first update) would land on the instance that
    traced the step, not the one calling it. The scan covers ``update`` and
    ``compute`` (both run during a with-compute trace) and recurses into
    ``self.<method>()`` calls they make; dynamic ``setattr`` or unreadable
    source makes the class unshareable (fail safe).
    """
    import ast
    import textwrap

    writes: set = set()
    scanned: set = set()

    def scan(method_name: str) -> bool:
        if method_name in scanned:
            return True
        scanned.add(method_name)
        fn = None
        for klass in cls.__mro__:
            fn = vars(klass).get(method_name)
            if fn is not None:
                break
        if fn is None or not callable(fn):
            return False  # unresolvable self-call -> unshareable
        try:
            tree = ast.parse(textwrap.dedent(inspect.getsource(fn)))
        except (OSError, TypeError, SyntaxError):
            return False
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Attribute)
                and isinstance(node.ctx, ast.Store)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"
            ):
                writes.add(node.attr)
            if isinstance(node, ast.Call):
                if isinstance(node.func, ast.Name) and node.func.id == "setattr":
                    return False
                if (
                    isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "self"
                ):
                    # self._append("name", v) writes exactly the named state
                    # (its internal setattr would otherwise fail the scan) —
                    # trusted only for the base implementation; an override
                    # could side-write, so it goes through the normal scan
                    if (
                        node.func.attr == "_append"
                        and node.args
                        and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)
                        and getattr(cls, "_append", None) is Metric._append
                    ):
                        writes.add(node.args[0].value)
                        continue
                    if not scan(node.func.attr):
                        return False
        return True

    if not (scan("update") and scan("compute")):
        return None
    return frozenset(writes)


def _fingerprint_value(v: Any, pins: list) -> Any:
    if v is None or isinstance(v, (bool, int, float, str, bytes)):
        return v
    if isinstance(v, (np.ndarray, jnp.ndarray, Array)):
        arr = np.asarray(v)
        return ("arr", arr.shape, str(arr.dtype), arr.tobytes())
    if isinstance(v, CMSSpec):
        # before the generic tuple arm: the seed is first-class fingerprint
        # material (it parameterizes the bucket family, so two CMS states
        # merge soundly only on equal seeds) and the stable "cmsspec" tag
        # keeps the key independent of the NamedTuple's field order
        return (
            "cmsspec", v.depth, v.width, v.item_shape, str(jnp.dtype(v.dtype)), v.seed,
        )
    if isinstance(v, QSketchSpec):
        # before the generic tuple arm, like CMSSpec: the grid parameters
        # are first-class fingerprint material (two qsketch states merge
        # soundly only on the identical (alpha, min_value, max_value)
        # bucket map) and the stable tag keeps the key independent of the
        # NamedTuple's field order
        return (
            "qsketchspec", v.kind, v.shape, str(jnp.dtype(v.dtype)),
            v.alpha, v.min_value, v.max_value,
        )
    if isinstance(v, (list, tuple)):
        return (type(v).__name__, tuple(_fingerprint_value(x, pins) for x in v))
    if isinstance(v, dict):
        return ("dict", tuple((k, _fingerprint_value(x, pins)) for k, x in sorted(v.items())))
    if isinstance(v, _BufferSpec):
        return ("bufspec", v.capacity, v.item_shape, str(v.dtype))
    if isinstance(v, SketchSpec):
        return ("sketchspec", v.kind, v.shape, str(jnp.dtype(v.dtype)), v.lo, v.hi)
    if isinstance(v, SlabSpec):
        # slab shapes are first-class fingerprint material: two slab metrics
        # share a compiled step / compute-group key only on equal (kind, K,
        # row schema, reduce, fill template)
        return (
            "slabspec", v.kind, v.num_slots, v.item_shape, str(jnp.dtype(v.dtype)),
            v.reduce, v.fill,
        )
    if callable(v) or isinstance(v, type):
        pins.append(v)  # the cache entry pins this object -> id stays live
        return ("fn", id(v))
    try:
        hash(v)
    except TypeError:
        raise _Unfingerprintable(type(v).__name__)
    return ("obj", type(v).__name__, v)


def _validate_sync_lag(value: Any, dist_sync_on_step: bool) -> Any:
    """Canonicalize a ``sync_lag`` setting: an int in ``[0, MAX_SYNC_LAG]``
    or the literal ``"auto"``. Raises on anything else, loudly — a silently
    clamped lag would change the documented staleness contract."""
    from metrics_tpu.parallel.deferred import MAX_SYNC_LAG

    if value == "auto":
        lag: Any = "auto"
    else:
        if isinstance(value, bool) or not isinstance(value, int):
            raise ValueError(
                f"`sync_lag` must be an int in [0, {MAX_SYNC_LAG}] or 'auto', got {value!r}"
            )
        if not 0 <= value <= MAX_SYNC_LAG:
            raise ValueError(
                f"`sync_lag` must be in [0, {MAX_SYNC_LAG}] (the handle-ring depth is"
                f" bounded so the rendezvous pool and the background host plane never"
                f" wedge) or 'auto', got {value!r}"
            )
        lag = int(value)
    if lag and not dist_sync_on_step:
        raise ValueError(
            f"`sync_lag={lag!r}` defers the per-step sync inside `forward`; it"
            " requires `dist_sync_on_step=True`"
        )
    return lag


class _BufferSpec(NamedTuple):
    capacity: int
    item_shape: tuple
    dtype: Any


class PureMetric(NamedTuple):
    """Bound pure-functional view of a metric, for use inside jit/pjit/shard_map."""

    init: Callable[[], State]
    update: Callable[..., State]  # (state, *args, **kwargs) -> state
    compute: Callable[[State], Any]
    merge: Callable[[State, State], State]
    sync: Callable[[State, str], State]  # (state, axis_name) -> state


class Metric(ABC):
    """Base class of all metrics: stateful accumulation + device-mesh sync.

    Args:
        compute_on_step: ``forward`` returns the batch-local value if True.
        dist_sync_on_step: sync state across processes inside every ``forward``.
        process_group: iterable of process indices to scope the host-plane
            sync to (must include the local process; reference
            metric.py:66,185 semantics). Every process still enters one
            world collective, but each reduces over its group only.
            Construct metrics after ``jax.distributed.initialize`` so the
            group validates against the real world size. For the in-jit
            plane, scope by the mesh axis passed to ``sync_state`` instead.
        dist_sync_fn: custom host-plane gather, ``fn(array) -> List[array]``
            (one entry per process). Defaults to ``process_allgather`` when
            running multi-host.
        capacity: optional fixed capacity for list ("cat") states; when set,
            states declared with an ``item_shape`` become jit-safe
            :class:`PaddedBuffer` s instead of Python lists.
        jit: compile the fused per-step update. ``None`` (default) auto-enables
            when all states are fixed-shape arrays/buffers and falls back to
            eager on metrics that need data-dependent Python (e.g. class-count
            inference from values).
        check_finite: opt-in state-integrity guard (``None`` = off). After
            every eager update/forward and after each host-plane sync the
            state pytree is scanned for non-finite floats and saturated
            integer counts (:func:`state_integrity_counts` — the scan itself
            is jittable; the policy check reads one scalar back). Policies:
            ``'warn'`` warns, ``'raise'`` throws a typed
            ``StateCorruptionError``, ``'quarantine'`` discards the poisoned
            batch delta (the accumulator reverts to its pre-update value and
            ``quarantined_updates`` bumps) or, on sync, keeps the local state
            instead of a poisoned gathered one. Subclasses don't forward the
            kwarg — set the ``metric.check_finite`` attribute after
            construction for library metrics.
        sync_lag: opt-in DEFERRED per-step sync for ``dist_sync_on_step``
            consumers (``0`` = synchronous, the default; ``k`` in
            ``[1, MAX_SYNC_LAG]`` = a ring of k in-flight deferred gathers;
            ``"auto"`` = adaptive). With ``sync_lag=k`` every ``forward``
            snapshots its batch delta (the double buffer — jax arrays are
            immutable, so the snapshot is free), dispatches its host gather
            on the BACKGROUND host plane (``parallel/deferred.py``), and
            pushes the handle onto a bounded ring; once the ring holds more
            than k handles the OLDEST resolves and the step's returned value
            is computed from ITS merged view — which finished gathering
            while the last k steps' updates ran. Values are bit-exact vs the
            synchronous plane modulo the documented k-step lag: step ``i``
            (``i >= k``) returns exactly what the synchronous plane returned
            at step ``i - k``; steps ``0..k-1`` return the local (unsynced)
            batch value as warm-up. Epoch-level ``compute()`` stays
            synchronous — it first drains the whole ring in entry order so
            gather pairing is preserved across ranks, then syncs the
            accumulator fresh (the accumulated state never lags, only the
            per-step read). ``reset``/``clone``/``state_dict`` never carry
            handles. ``sync_lag="auto"`` wires in a
            :class:`~metrics_tpu.parallel.deferred.LagController`: lag 0
            (fully synchronous, zero staleness) while the measured blocking
            wait says the collective is effectively free, deeper toward the
            cap when the (DCN) gather is slow. Subclasses don't forward the
            kwarg — set the ``metric.sync_lag`` attribute after construction
            for library metrics (same convention as ``check_finite``).
    """

    def __init__(
        self,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
        capacity: Optional[int] = None,
        jit: Optional[bool] = None,
        check_finite: Optional[str] = None,
        sync_lag: int = 0,
    ):
        self.dist_sync_on_step = dist_sync_on_step
        self.compute_on_step = compute_on_step
        # loud validation, never a silent no-op; store the canonical tuple so
        # one-shot iterables cannot pass validation exhausted
        self.process_group = canonicalize_group(process_group)
        self.dist_sync_fn = dist_sync_fn
        self.capacity = capacity
        self._jit = jit if jit is not None else _DEFAULT_JIT
        if check_finite not in CHECK_FINITE_POLICIES:
            raise ValueError(
                f"`check_finite` must be one of {CHECK_FINITE_POLICIES}, got {check_finite!r}"
            )
        self.check_finite = check_finite
        self.sync_lag = _validate_sync_lag(sync_lag, dist_sync_on_step)
        # the lag-k ring: in-flight SyncHandles, oldest first (sync_lag >= 1)
        self._handle_ring: deque = deque()
        self._lag_controller = None  # LagController, built lazily (sync_lag="auto")
        self._to_sync = True
        self._in_forward = False
        self._sync_count = 0
        # epoch watermark: batches folded into the accumulator this epoch.
        # Persisted by state_dict so a preempted-and-restored loop can replay
        # its last step idempotently (guarded_update).
        self._epoch_watermark = 0

        self._update_signature = inspect.signature(self.update)
        self._update_impl = self.update  # unwrapped bound method (pure w.r.t. registered states)
        self._compute_impl = self.compute
        self.update = self._wrap_update(self.update)
        self.compute = self._wrap_compute(self.compute)
        self._computed = None
        self._forward_cache = None

        self._defaults: Dict[str, Any] = {}  # numpy templates / [] / _BufferSpec
        self._default_keys: Dict[str, Any] = {}  # precomputed constant-cache keys
        self._persistent: Dict[str, bool] = {}
        self._reductions: Dict[str, ReduceFx] = {}
        self._jitted_step = None
        self._jitted_step_fc = None  # step that also computes the batch value
        self._jitted_scan = None  # multi-batch scan step (forward_batched)
        self._jit_failed = False
        self._fc_failed = False  # compute cannot trace -> keep compute eager
        self._scan_failed = False  # scan step cannot trace -> per-step fallback
        self._count_bound = 0  # host-side elements-processed bound (overflow warning)
        self._overflow_warned = False
        self._placement = None  # last device/sharding passed to device_put; re-applied on reset
        self._state_dtype = None  # last float dtype passed to astype; re-applied on reset

    # ------------------------------------------------------------------ state
    def add_state(
        self,
        name: str,
        default: Any,
        dist_reduce_fx: Optional[ReduceFx] = None,
        persistent: bool = False,
        item_shape: Optional[tuple] = None,
        item_dtype: Any = None,
    ) -> None:
        """Register a state variable (reference ``add_state``, metric.py:88-148).

        ``default`` is an array (fixed-shape state) or an empty list (cat
        state). Extension over the reference: ``dist_reduce_fx`` additionally
        accepts ``'min'``/``'max'`` (the reference passes ``torch.min/max``
        callables for PSNR), and list states may declare ``item_shape`` /
        ``item_dtype`` so that, when the metric was built with a ``capacity``,
        they become jit-safe PaddedBuffers.

        ``default`` may also be a :class:`~metrics_tpu.parallel.sketch.
        SketchSpec` — the MERGEABLE SKETCH state kind (fixed-grid
        histogram/rank sketches): the state materializes as a zero-count
        ``HistogramSketch``/``RankSketch``, its shape is traffic-independent,
        merge is bit-exact integer addition, and sync rides the existing
        per-dtype sum-psum buckets (``dist_reduce_fx`` must be ``"sum"``).

        Or a :class:`~metrics_tpu.parallel.cms.CMSSpec` — the COUNT-MIN TAIL
        state kind (``wrappers/heavy_hitters.py``): a ``(depth, width,
        *item_shape)`` accumulator that folds an UNBOUNDED key space into
        constant memory with a certified overcount read. Sum-mergeable by
        construction like sketches (``dist_reduce_fx`` must be ``"sum"``),
        so sync rides the existing per-dtype sum-psum buckets.

        Or a :class:`~metrics_tpu.parallel.qsketch.QSketchSpec` — the
        MERGEABLE QUANTILE SKETCH state kind (log-bucketed, relative-
        accuracy ``alpha`` DDSketch-style grid with a zero bucket and
        signed overflow end buckets): the state materializes as a
        zero-count ``QuantileSketch``, its shape is traffic-independent,
        and it follows the same sum-mergeable contract as sketches
        (``dist_reduce_fx`` must be ``"sum"``).

        Or a :class:`~metrics_tpu.parallel.slab.SlabSpec` — the KEYED SLAB
        state kind (one row per segment slot, see ``wrappers/keyed.py``):
        the state materializes as a ``(K, *item_shape)`` array (or a sketch
        whose counts grow the leading K axis), and ``dist_reduce_fx`` must be
        the spec's sync reduction (``slab_sync_reduce``: ``sum`` for
        sum/mean/sketch slabs, ``min``/``max`` pass through) so merge and
        sync ride the existing reduce buckets — one psum moves all K
        segments.
        """
        if isinstance(default, SlabSpec):
            expected = slab_sync_reduce(default.reduce)
            if dist_reduce_fx != expected:
                raise ValueError(
                    f"a {default.reduce!r}-kind slab state syncs through the"
                    f" {expected!r} bucket plane; declare it with"
                    f" dist_reduce_fx={expected!r} (got {dist_reduce_fx!r})"
                )
            self._defaults[name] = default
            self._persistent[name] = persistent
            self._reductions[name] = expected
            setattr(self, name, slab_init(default))
            return
        if isinstance(default, _SUM_MERGEABLE_SPECS):
            # the sketch-family state kinds (fixed-grid histogram/rank
            # sketches, count-min tails, log-bucketed quantile sketches):
            # one registry arm — merge is elementwise add, sync rides the
            # existing per-dtype sum-psum buckets.
            if dist_reduce_fx != "sum":
                raise ValueError(
                    f"{type(default).__name__} states are sum-mergeable by construction;"
                    f" declare them with dist_reduce_fx='sum' (got {dist_reduce_fx!r})"
                )
            self._defaults[name] = default
            self._persistent[name] = persistent
            self._reductions[name] = "sum"
            setattr(self, name, materialize_state_spec(default))
            return
        is_list = isinstance(default, list) and len(default) == 0
        is_arraylike = isinstance(default, (int, float, np.ndarray, jnp.ndarray, Array)) and not isinstance(
            default, bool
        )
        if not (is_list or is_arraylike):
            raise ValueError("state variable must be a tensor or any empty list (where you can append tensors)")
        dist_reduce_fx = canonicalize_reduce_fx(dist_reduce_fx)

        if is_list and self.capacity is not None and item_shape is not None:
            default_spec: Any = _BufferSpec(self.capacity, tuple(item_shape), item_dtype or jnp.float32)
        elif is_list:
            default_spec = []
        else:
            default_spec = np.asarray(default)  # host-side template; materialized per reset

        self._defaults[name] = default_spec
        self._persistent[name] = persistent
        self._reductions[name] = dist_reduce_fx
        if isinstance(default_spec, np.ndarray):
            self._default_keys[name] = (default_spec.shape, str(default_spec.dtype), default_spec.tobytes())
        setattr(self, name, self._materialize_default(default_spec, self._default_keys.get(name)))

    @staticmethod
    def _materialize_default(spec: Any, key: Any = None) -> Any:
        if isinstance(spec, _BufferSpec):
            return buffer_init(spec.capacity, spec.item_shape, spec.dtype)
        materialized = materialize_state_spec(spec)
        if materialized is not None:
            return materialized
        if isinstance(spec, list):
            return []
        # identical templates share one transferred device constant, and each
        # instance gets a device-side copy of it: construction/reset cost no
        # host->device transfer after the first, and the private copy keeps
        # the cached buffer safe from the fused step's donation (TPU path
        # donates the accumulator argument). ``key`` is precomputed in
        # add_state so big templates are not re-hashed per reset.
        if key is None:
            key = (spec.shape, str(spec.dtype), spec.tobytes())
        cached = _DEFAULT_CONSTANT_CACHE.get(key)
        if cached is None:
            cached = jnp.asarray(spec)
            _bounded_insert(_DEFAULT_CONSTANT_CACHE, key, cached, _DEFAULT_CONSTANT_CACHE_MAX)
        return jnp.array(cached, copy=True)

    def _append(self, name: str, value: Array) -> None:
        """Append to a cat state — list (eager) or PaddedBuffer (jit-safe).

        When the metric was built with a ``capacity`` but the cat state has
        no declared ``item_shape`` (curve/retrieval metrics infer their item
        layout from the data mode at the first update), the FIRST eager
        append promotes the state to a PaddedBuffer with the observed item
        shape/dtype. From then on the metric is buffer-backed: jit-safe
        fused steps, in-jit sync, and mesh placement (``device_put`` targets
        recorded before promotion are applied to the new buffer).
        """
        current = getattr(self, name)
        if isinstance(current, PaddedBuffer):
            setattr(self, name, buffer_append(current, value))
            return
        if (
            self.capacity is not None
            and isinstance(self._defaults.get(name), list)
            and not current
        ):
            if self._under_trace():
                # a tracer must not leak into the eager list state — fail
                # loudly (caught by the fused-step fallback machinery; a
                # user-level jit surfaces this at the update call, not as an
                # opaque UnexpectedTracerError at compute)
                raise TracingUnsupportedError(
                    f"{type(self).__name__} with `capacity` infers its buffer layout from"
                    " the first update, which cannot happen under jit tracing. Run one"
                    " eager update first, or declare the state with `item_shape`."
                )
            value = jnp.atleast_1d(jnp.asarray(value))
            spec = _BufferSpec(self.capacity, tuple(value.shape[1:]), value.dtype)
            buf = buffer_init(spec.capacity, spec.item_shape, spec.dtype)
            if self._placement is not None:
                # placement may reject the buffer (e.g. row_sharded
                # divisibility) — it must raise BEFORE the spec is committed,
                # or a retried update would half-promote the cat states
                resolve = (
                    self._placement if callable(self._placement) else (lambda _n, _v: self._placement)
                )
                buf = jax.device_put(buf, resolve(name, buf))
            self._defaults[name] = spec
            setattr(self, name, buffer_append(buf, value))
            return
        current.append(value)

    # ------------------------------------------------------------- pure core
    @staticmethod
    def _under_trace() -> bool:
        return compat.under_trace()

    def init_state(self) -> State:
        """Fresh default state pytree.

        Under tracing (inside jit/vmap — the step builders and the pure API
        call this from traced code) array defaults come from the HOST numpy
        specs, NOT the eager device-constant cache: a traced-over device
        array must be read back to the host at lowering time to be embedded
        as a compile-time constant, and through a remote-device tunnel a
        single device-to-host readback permanently degrades every subsequent
        dispatch in the process (~100 ms per block). Host-backed specs embed
        for free. Eager callers keep the shared-transfer + private-copy path.
        """
        if self._under_trace():
            return {
                name: self._materialize_default_traced(spec) for name, spec in self._defaults.items()
            }
        return {
            name: self._materialize_default(spec, self._default_keys.get(name))
            for name, spec in self._defaults.items()
        }

    @staticmethod
    def _materialize_default_traced(spec: Any) -> Any:
        if isinstance(spec, _BufferSpec):
            return buffer_init(spec.capacity, spec.item_shape, spec.dtype)
        # registry kinds materialize zeros / host-template broadcasts, which
        # stage as compile-time constants under tracing
        materialized = materialize_state_spec(spec)
        if materialized is not None:
            return materialized
        if isinstance(spec, list):
            return []
        return jnp.asarray(spec)  # numpy spec -> host-backed staged constant

    def _current_state(self) -> State:
        return {name: getattr(self, name) for name in self._defaults}

    def _set_state(self, state: State) -> None:
        for name, value in state.items():
            setattr(self, name, value)

    def _run_update_on_state(self, state: State, *args: Any, **kwargs: Any) -> State:
        """Run the subclass ``update`` as a pure function of ``state``."""
        saved = self._current_state()
        self._set_state(state)
        try:
            self._update_impl(*args, **kwargs)
            return self._current_state()
        finally:
            self._set_state(saved)

    def update_state(self, state: State, *args: Any, **kwargs: Any) -> State:
        """Pure update: returns the new state. Jit-safe for array/buffer states."""
        return self._run_update_on_state(state, *args, **kwargs)

    def compute_from_state(self, state: State) -> Any:
        """Pure compute on an explicit state pytree."""
        saved = self._current_state()
        self._set_state(state)
        try:
            return self._compute_impl()
        finally:
            self._set_state(saved)

    def merge_states(self, a: State, b: State) -> State:
        """Pairwise-associative merge (powers fused forward, tree-reduction, shard merging)."""
        return {name: merge_values(self._reductions[name], a[name], b[name]) for name in self._defaults}

    def sync_state(
        self, state: State, axis_name: Any, deferred: bool = False, mesh: Any = None
    ) -> State:
        """In-jit cross-device sync over a named mesh axis (use inside shard_map/pmap).

        Leaves of a common dtype sync through bucketed collectives
        (``parallel.sync.coalesced_sync_state``): sum/min/max leaves share
        one ``psum``/``pmin``/``pmax`` per bucket (``mean`` folds into the
        sum bucket as psum-then-divide), gather-semantics array leaves share
        one ``all_gather``, and same-dtype PaddedBuffer cat-states share ONE
        ``all_gather`` per bucket (the counts vector rides inside the data
        payload for 4-byte dtypes) — a multi-state metric like StatScores
        pays one ``psum``, not four, and a two-buffer curve metric pays 1
        gather, not 4.

        ``axis_name`` may also be a tuple of axes (the flat world span of a
        2-level mesh) or a ``parallel.placement.MeshHierarchy`` — buckets
        then stage HIERARCHICALLY, ici-first reduce / dcn-first gather, so
        only per-slice payloads cross the slow interconnect.

        ``deferred=True`` is the FUTURE-RETURNING form (eager callers only):
        the state pytree — leaves stacked over the mesh axis on their leading
        dimension, i.e. the output of a ``shard_map(update,
        out_specs=P(axis))`` delta program — is snapshotted into the double
        buffer and the compiled sync program (the IDENTICAL staged
        collectives) is dispatched WITHOUT fencing; the returned
        :class:`~metrics_tpu.parallel.deferred.SyncHandle` fences on
        ``result()``, so XLA overlaps the collective with whatever the host
        dispatches next. ``mesh`` defaults to the leaves' sharding mesh.
        Raises ``TracingUnsupportedError`` under a trace (a host-side future
        cannot exist inside jit — use the synchronous plane there)."""
        if deferred:
            if self._under_trace():
                raise TracingUnsupportedError(
                    f"{type(self).__name__}.sync_state(deferred=True) dispatches a"
                    " compiled sync program and returns a host-side SyncHandle,"
                    " which cannot exist under tracing; inside jit use the"
                    " synchronous plane (deferred=False)"
                )
            from metrics_tpu.parallel.deferred import deferred_sync_state

            return deferred_sync_state(
                state, self._reductions, axis_name, mesh=mesh,
                watermark=self._epoch_watermark,
            )
        return coalesced_sync_state(state, self._reductions, axis_name)

    def pure(self) -> PureMetric:
        """The pure-functional view: use inside jit/pjit-ed training steps."""
        return PureMetric(
            init=self.init_state,
            update=self.update_state,
            compute=self.compute_from_state,
            merge=self.merge_states,
            sync=self.sync_state,
        )

    # --------------------------------------------------------------- forward
    @property
    def _fusable(self) -> bool:
        return all(
            is_mergeable(self._reductions[name], getattr(self, name, self._defaults[name]))
            for name in self._defaults
        )

    @property
    def _jittable(self) -> bool:
        if self._jit is False or self._jit_failed:
            return False
        # eager python-list states change pytree structure every step -> no jit
        return not any(isinstance(self._defaults[n], list) for n in self._defaults)

    def _build_jitted_step(self, with_compute: bool = False, isolate: bool = False) -> Callable:
        donate = (0,) if jax.default_backend() == "tpu" else ()
        # Retraces run update/compute against the carrier's attrs
        # (saved/restored); the lock serializes concurrent retraces.
        # Compiled-call replays never enter the traced body, so steady state
        # is lock-free. Shared steps (isolate=True) close over a detached
        # reset copy instead of a live instance: a retrace can never plant
        # tracers on (or read accumulated state of) any user-visible metric,
        # and the cache pins only default-sized state buffers.
        carrier = self
        if isolate:
            carrier = deepcopy(self)
            carrier.reset()
        lock = threading.Lock()

        def step(acc: State, *args: Any, **kwargs: Any):
            with lock:
                delta = carrier._run_update_on_state(carrier.init_state(), *args, **kwargs)
            merged = carrier.merge_states(acc, delta)
            if with_compute:
                with lock:
                    value = carrier.compute_from_state(delta)
                return merged, delta, value
            return merged, delta

        return jax.jit(step, donate_argnums=donate)

    def _config_fingerprint(self) -> Optional[tuple]:
        """(key, pinned-referents) for the trace-relevant config, or None.

        ``pins`` are the objects whose ``id()`` appears in the key; the cache
        entry keeps them alive (via these pins and the detached carrier the
        step closes over) so ids are never reused while the entry lives.
        """
        writes = _traced_attr_writes(type(self))
        if writes is None or not writes <= set(self._defaults):
            return None  # update has side writes -> step must stay private
        pins: list = [type(self)]
        try:
            items = tuple(
                (k, _fingerprint_value(v, pins))
                for k, v in sorted(vars(self).items())
                if k not in _NON_TRACE_ATTRS and k not in self._defaults
            )
        except _Unfingerprintable:
            return None
        return ((type(self), items), pins)

    def _unfusable_reason(self) -> Optional[str]:
        """Why this metric cannot join a collection-level fused step, or None.

        Mirrors ``MetricCollection``'s fusability predicate and, when the
        config fingerprint is what failed, retries it attribute by attribute
        to NAME the offending attr — so the fallback warning tells users what
        to fix instead of silently eating the per-group path.
        """
        if not self._fusable:
            return "a state reduction that is not in-jit mergeable"
        if not self._jittable:
            return "jit disabled (`jit=False`, a failed trace, or eager list state)"
        if not self.compute_on_step:
            return "compute_on_step=False"
        if self.dist_sync_on_step:
            return "dist_sync_on_step=True"
        if self.dist_sync_fn is not None:
            return "a custom `dist_sync_fn`"
        writes = _traced_attr_writes(type(self))
        if writes is None:
            return "update() attribute writes that cannot be statically resolved"
        if not writes <= set(self._defaults):
            extra = ", ".join(sorted(writes - set(self._defaults)))
            return f"update() writing non-state attribute(s) {extra}"
        for k, v in sorted(vars(self).items()):
            if k in _NON_TRACE_ATTRS or k in self._defaults:
                continue
            try:
                _fingerprint_value(v, [])
            except _Unfingerprintable:
                return f"unfingerprintable config attribute {k!r} ({type(v).__name__})"
        return None

    # Attr names (beyond base ``capacity``) that feed ``update``; a subclass
    # declares them to opt its instances into MetricCollection compute groups.
    # None (the default) means "never grouped": without the declaration the
    # library cannot know which config attrs are update-relevant, and a wrong
    # guess would silently share deltas between metrics that update
    # differently. Compute-only config (e.g. FBeta's ``beta``/``average``)
    # must stay OFF this list — that is the whole point of grouping.
    _GROUP_UPDATE_ATTRS: Optional[tuple] = None

    # The EXCLUSION form of the same opt-in: a class (or shared base, e.g.
    # ``RetrievalMetric``) declares the attrs that are COMPUTE-ONLY, and the
    # update-relevant config is derived as every fingerprintable instance
    # attr EXCEPT those, the registered states, and the non-trace bookkeeping
    # attrs. This is the safer default for metric families sharing one base
    # update: a subclass that adds update-relevant config is automatically
    # included in the key (conservatively splitting groups), and only a
    # deliberately-listed compute-only attr (``k``, a policy flag) is
    # excluded — new metrics opt out declaratively instead of re-declaring
    # ``_GROUP_UPDATE_ATTRS = ()`` per class. ``_GROUP_UPDATE_ATTRS`` wins
    # when both are set.
    _GROUP_COMPUTE_ONLY_ATTRS: Optional[tuple] = None

    def _group_fingerprint(self) -> Optional[Any]:
        """Hashable identity of this metric's update+state plane, or None.

        Two metrics with equal group fingerprints run the SAME ``update``
        (the identical function object found on the MRO) over the SAME state
        schema with the SAME update-relevant config — so inside a
        ``MetricCollection`` one shared update delta serves them all, and
        each member only needs its own ``compute``. ``F1``, ``Precision``,
        ``Recall`` and ``Specificity`` with matching config all reduce to
        one ``StatScores`` group this way; the whole retrieval family
        reduces to one flatten-append group via the exclusion declaration.

        The state schema covers every declared kind — array templates,
        buffer specs, sketch specs, and slab specs (``SlabSpec``: slot
        count, row shape, per-slot reduce), so keyed slab states group
        soundly out of the box.
        """
        attrs = type(self)._GROUP_UPDATE_ATTRS
        excluded = type(self)._GROUP_COMPUTE_ONLY_ATTRS
        if attrs is None and excluded is None:
            return None
        update_fn = next(
            (vars(klass)["update"] for klass in type(self).__mro__ if "update" in vars(klass)), None
        )
        if update_fn is None:
            return None
        pins: list = []  # keys are compared between live siblings only; no pinning needed
        try:
            if attrs is not None:
                config = tuple(
                    (a, _fingerprint_value(getattr(self, a, None), pins))
                    for a in (*attrs, "capacity")
                )
            else:
                config = tuple(
                    (k, _fingerprint_value(v, pins))
                    for k, v in sorted(vars(self).items())
                    if k not in _NON_TRACE_ATTRS
                    and k not in self._defaults
                    and k not in excluded
                )
            schema = tuple(
                (name, _fingerprint_value(self._defaults[name], pins),
                 _fingerprint_value(self._reductions[name], pins))
                for name in sorted(self._defaults)
            )
        except _Unfingerprintable:
            return None
        return (update_fn, config, schema)

    def _lookup_or_build_jitted_step(self, with_compute: bool = False) -> Callable:
        fp = self._config_fingerprint()
        if fp is None:
            return self._build_jitted_step(with_compute)
        key_body, pins = fp
        key = (key_body, with_compute)
        with _JITTED_STEP_CACHE_LOCK:
            hit = _JITTED_STEP_CACHE.get(key)
            record_cache("step", hit is not None)
            if hit is None:
                hit = (pins, self._build_jitted_step(with_compute, isolate=True))
                _bounded_insert(_JITTED_STEP_CACHE, key, hit, _JITTED_STEP_CACHE_MAX)
        return hit[1]

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        """Accumulate this batch and (if ``compute_on_step``) return its batch-local value."""
        if TRACE.enabled:
            with _span("metric.forward", {"metric": type(self).__name__}):
                if self._fusable:
                    out = self._forward_fused(*args, **kwargs)
                else:
                    out = self._forward_reference(*args, **kwargs)
                if _DEVTIME.enabled:  # phase fence: charge the device tail here
                    _fence((out, self._current_state()))
                return out
        if self._fusable:
            return self._forward_fused(*args, **kwargs)
        return self._forward_reference(*args, **kwargs)

    _TRACER_ERRORS = (
        jax.errors.TracerArrayConversionError,
        jax.errors.ConcretizationTypeError,
        jax.errors.TracerBoolConversionError,
        TracingUnsupportedError,
    )
    _NO_VALUE = object()  # sentinel: fused step did not produce the batch value

    def _forward_fused(self, *args: Any, **kwargs: Any) -> Any:
        self._computed = None
        self._forward_cache = None
        self._note_rows(args, kwargs)
        revert_to = self._pre_update_snapshot()
        delta = None
        value = self._NO_VALUE
        if self._jittable:
            # fully fused step: update + merge + batch-value compute in ONE
            # dispatch — the hot-loop shape (per-step value, no cross-process
            # sync inside forward)
            if self.compute_on_step and not self.dist_sync_on_step and not self._fc_failed:
                if self._jitted_step_fc is None:
                    self._jitted_step_fc = self._lookup_or_build_jitted_step(with_compute=True)
                try:
                    new_acc, delta, value = self._jitted_step_fc(self._current_state(), *args, **kwargs)
                    self._set_state(new_acc)
                except self._TRACER_ERRORS:
                    # compute (or update) needs concrete values; retry below
                    # with the compute left eager — same results, extra dispatch
                    self._fc_failed = True
                    delta, value = None, self._NO_VALUE
            if delta is None:
                if self._jitted_step is None:
                    self._jitted_step = self._lookup_or_build_jitted_step()
                try:
                    new_acc, delta = self._jitted_step(self._current_state(), *args, **kwargs)
                    self._set_state(new_acc)
                except self._TRACER_ERRORS as err:
                    # update needs concrete values (e.g. class inference) -> permanent eager
                    # fallback. Any other exception (a genuine bug in `update`) propagates.
                    rank_zero_warn(
                        f"{self.__class__.__name__}.update cannot be jit-compiled"
                        f" ({type(err).__name__}); falling back to the eager per-step path."
                        " Pass static args (e.g. num_classes) to enable the fused step.",
                        UserWarning,
                    )
                    self._jit_failed = True
                    delta = None
        if delta is None:
            delta = self._run_update_on_state(self.init_state(), *args, **kwargs)
            self._set_state(self.merge_states(self._current_state(), delta))
        self._guard_state_integrity("forward", revert_to)

        if not self.compute_on_step:
            return None

        if value is not self._NO_VALUE:
            self._forward_cache = value
            self._computed = None
            return value

        self._to_sync = self.dist_sync_on_step
        self._in_forward = True
        acc = self._current_state()
        self._set_state(delta)
        try:
            self._forward_cache = self.compute()
        finally:
            self._set_state(acc)
            self._to_sync = True
            self._in_forward = False
        self._computed = None
        return self._forward_cache

    def _forward_reference(self, *args: Any, **kwargs: Any) -> Any:
        """Reference-exact double-update path (reference metric.py:150-177)."""
        self.update(*args, **kwargs)
        self._forward_cache = None
        if self.compute_on_step:
            self._to_sync = self.dist_sync_on_step
            self._in_forward = True
            cache = self._current_state()
            bound = self._count_bound
            watermark = self._epoch_watermark
            ring = self._handle_ring
            self.reset()
            # the temp reset must not drop the in-flight lag-k ring: the
            # lagged compute below reads (and extends) it
            self._handle_ring = ring
            try:
                self.update(*args, **kwargs)
                self._forward_cache = self.compute()
            finally:
                self._set_state(cache)
                self._count_bound = bound  # the temp reset must not lose the epoch bound
                self._epoch_watermark = watermark  # nor the replay watermark
                self._to_sync = True
                self._in_forward = False
            self._computed = None
            return self._forward_cache
        return None

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        return self.forward(*args, **kwargs)

    # ------------------------------------------------------- batched forward
    @property
    def _stack_mergeable(self) -> bool:
        """All states support the one-op stacked merge (vmap-batched forward)."""
        return all(
            is_stack_mergeable(self._reductions[name], self._defaults[name]) for name in self._defaults
        )

    def _build_scan_step(self, with_compute: bool, isolate: bool = False) -> Callable:
        """One jitted program for a whole STACK of batches.

        When every state supports a stacked merge, the per-batch deltas come
        from a ``vmap``-ed update and the whole stack folds into the
        accumulator with one reduction op per state — a fully parallel XLA
        program (a serial ``lax.scan`` pays ~10 ms *per iteration* through a
        remote-device tunnel, and serializes work the MXU could batch).
        Cat-state metrics (lists/buffers) fall back to ``lax.scan``, which
        preserves append order.
        """
        donate = (0,) if jax.default_backend() == "tpu" else ()
        carrier = self
        if isolate:
            carrier = deepcopy(self)
            carrier.reset()
        lock = threading.Lock()
        parallel = self._stack_mergeable

        def step(acc: State, *stacked: Any):
            if parallel:
                def one(*batch):
                    with lock:
                        return carrier._run_update_on_state(carrier.init_state(), *batch)

                deltas = jax.vmap(one)(*stacked)
                merged = {
                    name: merge_values_stacked(carrier._reductions[name], acc[name], deltas[name])
                    for name in carrier._defaults
                }
                if with_compute:
                    with lock:
                        values = jax.vmap(carrier.compute_from_state)(deltas)
                else:
                    values = jnp.zeros(())
            else:
                def body(carry, batch):
                    with lock:
                        delta = carrier._run_update_on_state(carrier.init_state(), *batch)
                    merged = carrier.merge_states(carry, delta)
                    if with_compute:
                        with lock:
                            value = carrier.compute_from_state(delta)
                        return merged, value
                    return merged, jnp.zeros(())

                merged, values = jax.lax.scan(body, acc, stacked)
            if with_compute:
                with lock:
                    epoch_value = carrier.compute_from_state(merged)
            else:
                epoch_value = jnp.zeros(())
            return merged, values, epoch_value

        return jax.jit(step, donate_argnums=donate)

    def _lookup_or_build_scan_step(self, with_compute: bool) -> Callable:
        fp = self._config_fingerprint()
        if fp is None:
            return self._build_scan_step(with_compute)
        key_body, pins = fp
        key = (key_body, ("scan", with_compute))
        with _JITTED_STEP_CACHE_LOCK:
            hit = _JITTED_STEP_CACHE.get(key)
            record_cache("step", hit is not None)
            if hit is None:
                hit = (pins, self._build_scan_step(with_compute, isolate=True))
                _bounded_insert(_JITTED_STEP_CACHE, key, hit, _JITTED_STEP_CACHE_MAX)
        return hit[1]

    def forward_batched(self, *args: Any, **kwargs: Any) -> Any:
        """Accumulate a whole stack of batches (leading axis = steps) in one
        device dispatch; returns the per-step batch values stacked (or
        ``None`` when ``compute_on_step=False``).

        Semantically identical to calling ``forward`` once per slice —
        including per-batch values computed on the batch alone — but the
        loop, the merges, the per-batch values, AND the epoch value of the
        accumulated state run as a single ``lax.scan`` program. The epoch
        value is cached so a following ``compute()`` returns without another
        dispatch (unless a cross-process sync is configured). Falls back to
        the per-step path for metrics whose update cannot trace, for
        keyword arguments, and for ``dist_sync_on_step``.
        """
        usable = (
            not kwargs
            and not self.dist_sync_on_step
            and not self._scan_failed
            and self._fusable
            and self._jittable
            and args
            # compute cannot trace + per-step values wanted -> the scan
            # cannot honor the contract; use the per-step fallback below
            and not (self.compute_on_step and self._fc_failed)
        )
        if usable:
            with_compute = self.compute_on_step
            # the slot is keyed by mode: toggling compute_on_step between
            # calls must not reuse a scan built for the other mode
            if self._jitted_scan is None or self._jitted_scan[0] != with_compute:
                self._jitted_scan = (with_compute, self._lookup_or_build_scan_step(with_compute))
            try:
                new_acc, values, epoch_value = self._jitted_scan[1](self._current_state(), *args)
            except self._TRACER_ERRORS:
                self._scan_failed = True
                self._jitted_scan = None
            else:
                self._note_rows(args, {})  # advances the watermark by 1 ...
                # ... and the scan folded a whole stack of steps
                self._epoch_watermark += args[0].shape[0] - 1
                self._set_state(new_acc)
                if with_compute:
                    self._forward_cache = jax.tree_util.tree_map(lambda v: v[-1], values)
                    # pre-seed the compute cache only when compute() would not
                    # need a cross-process sync of fresh state
                    if jax.process_count() == 1 and self.dist_sync_fn is None:
                        self._computed = epoch_value
                    else:
                        self._computed = None
                    return values
                self._computed = None
                return None

        # eager fallback: one forward per leading-axis slice
        index = (lambda i: tuple(a[i] for a in args), lambda i: {k: v[i] for k, v in kwargs.items()})
        steps = (args[0] if args else next(iter(kwargs.values()))).shape[0]
        values = [self.forward(*index[0](i), **index[1](i)) for i in range(steps)]
        if not self.compute_on_step:
            return None
        return jax.tree_util.tree_map(lambda *vs: jnp.stack(vs), *values)

    # -------------------------------------------------- preemption-safe resume
    @property
    def epoch_watermark(self) -> int:
        """Number of batches folded into the accumulator this epoch — i.e.
        the next step index this metric expects. Persisted by ``state_dict``
        and restored by ``load_state_dict``, so a loop restarted from a
        mid-epoch checkpoint knows exactly which steps are already in."""
        return self._epoch_watermark

    def guarded_update(
        self, step_index: int, *args: Any, span_end: Optional[int] = None, **kwargs: Any
    ) -> bool:
        """Idempotent update: apply the batch only if ``step_index`` is not
        already folded into the state.

        The preemption-safe accumulation API: drive the epoch with 0-based
        step indices (``guarded_update(i, preds, target)``) and, after a
        kill/restore, simply replay from anywhere at or before the
        checkpoint — steps below the restored :attr:`epoch_watermark` are
        no-ops (returns ``False``), so re-running the step that was in
        flight at preemption cannot double-count. Returns ``True`` when the
        batch was applied.

        ``span_end`` is the coalesced-ingest form: the one ``update`` call
        carries the folded concatenation of sequential steps ``step_index ..
        span_end`` (inclusive), and on success the epoch watermark advances
        past ``span_end`` — replaying the whole span later no-ops exactly
        like replaying a single step. Span replay is ALL-OR-NOTHING: a span
        entirely below the watermark no-ops (returns ``False``), a span
        STRADDLING it (``step_index < epoch_watermark <= span_end``) raises
        ``ValueError`` — the caller must split at the watermark and re-fold
        only the unapplied suffix (the service's coalescer does; the
        partial-span pin in ``tests/serving/test_ingest_coalesce.py`` holds
        it to that).
        """
        if span_end is None:
            if step_index < self._epoch_watermark:
                return False
            self.update(*args, **kwargs)
            return True
        if span_end < step_index:
            raise ValueError(f"span_end {span_end} < step_index {step_index}")
        if span_end < self._epoch_watermark:
            return False  # the whole span is already folded in — no-op replay
        if step_index < self._epoch_watermark:
            raise ValueError(
                f"span [{step_index}, {span_end}] straddles the epoch watermark "
                f"{self._epoch_watermark}: split at the watermark and re-fold "
                "only the unapplied suffix"
            )
        self.update(*args, **kwargs)  # advances the watermark by one step...
        self._epoch_watermark += span_end - step_index  # ...plus the span's rest
        return True

    # ------------------------------------------------------------------ sync
    def _default_gather(self) -> Callable:
        """World gather, scoped to ``process_group`` when one was given
        (reference metric.py:185 passes the group into gather_all_tensors)."""
        if self.process_group is None:
            return gather_all_arrays
        return functools.partial(gather_all_arrays, group=self.process_group)

    def _states_own_sync(self) -> bool:
        """Whether this compute will dispatch to the sharded epoch engine
        (whose collectives combine states across devices AND processes),
        making the host-plane gather redundant. Overridden by the metrics
        that own a sharded dispatch; must mirror the dispatch's own
        applicability test exactly, or a declined dispatch would run the
        gather path with sync silently disabled."""
        return False

    def _sync_dist(
        self, dist_sync_fn: Optional[Callable] = None,
        timer: Optional[Callable[[float], None]] = None,
    ) -> None:
        """Host-plane sync: gather + stack/flatten + per-state reduction
        (reference metric.py:179-197). Runs under the active ``SyncGuard``
        (deadlines/retry/degrade — see ``parallel.sync``); the
        ``check_finite`` policy then vets the gathered state (``quarantine``
        keeps the LOCAL state when the synced one is poisoned). ``timer``
        receives the gather's blocking milliseconds (the adaptive lag
        controller's lag-0 probe — see ``parallel.sync.host_gather``)."""
        gather = dist_sync_fn if dist_sync_fn is not None else self._default_gather()
        record_states_synced(len(self._defaults))
        local = self._current_state() if self.check_finite == "quarantine" else None
        if TRACE.enabled:
            with _span("metric.sync_state", {"metric": type(self).__name__}) as sp:
                synced = host_gather(
                    self._current_state(), self._reductions, gather_fn=gather, timer=timer
                )
                if _DEVTIME.enabled:
                    _fence(synced)
                self._set_state(synced)
                self._guard_state_integrity("sync", local)
                self._note_state_bytes(sp)
        else:
            synced = host_gather(
                self._current_state(), self._reductions, gather_fn=gather, timer=timer
            )
            self._set_state(synced)
            self._guard_state_integrity("sync", local)
            self._note_state_bytes()

    # ------------------------------------------------- the lag-k handle ring
    def _resolve_sync_lag(self) -> int:
        """The effective ring depth this step: the static ``sync_lag``, or
        the adaptive controller's current verdict for ``sync_lag="auto"``
        (the controller is built on first use and fed the measured blocking
        waits — lag-0 steps feed the synchronous gather's wall time, lag-k
        steps the oldest handle's fence wait)."""
        lag = self.sync_lag
        if lag == "auto":
            ctrl = self._lag_controller
            if ctrl is None:
                from metrics_tpu.parallel.deferred import LagController

                self._lag_controller = ctrl = LagController()
            return ctrl.lag
        # attribute-set path (library metrics): validate as loudly as __init__
        return _validate_sync_lag(lag, self.dist_sync_on_step) if lag else 0

    def _drain_handle_ring(self) -> None:
        """Resolve every in-flight deferred handle in entry order and drop
        the views (the accumulated state never lags; the epoch-level sync
        that follows is fresh). Guard-policy ``raise`` exhaustion surfaces
        here — exactly where the synchronous plane would have thrown."""
        ring = self._handle_ring
        while ring:
            ring.popleft().result()

    def _wrap_update(self, update: Callable) -> Callable:
        @functools.wraps(update)
        def wrapped_func(*args: Any, **kwargs: Any) -> Any:
            self._computed = None
            self._note_rows(args, kwargs)
            revert_to = self._pre_update_snapshot()
            if TRACE.enabled:
                with _span("metric.update", {"metric": type(self).__name__}) as sp:
                    out = update(*args, **kwargs)
                    if _DEVTIME.enabled:  # phase fence on the written states
                        _fence(self._current_state())
                    self._guard_state_integrity("update", revert_to)
                    self._note_state_bytes(sp)
                    return out
            out = update(*args, **kwargs)
            self._guard_state_integrity("update", revert_to)
            self._note_state_bytes()
            return out

        return wrapped_func

    def _note_state_bytes(self, span: Any = None) -> None:
        """Record this metric's current state footprint.

        Feeds the per-metric ``state_bytes`` gauge in every counters snapshot
        (how the sketch-vs-buffer memory win becomes a measured number, not a
        claim) and stamps the enclosing update/sync span so
        ``export.summarize()`` can surface a per-phase ``state_bytes``
        column. Disabled observability pays one attribute check.
        """
        if not _COUNTERS.enabled and span is None:
            return
        nbytes = state_nbytes(self._current_state())
        # wrappers override the label to keep gauges attributable (e.g.
        # ``Keyed(AUROC)`` rather than a bare ``Keyed`` for every inner kind)
        record_state_bytes(getattr(self, "_metric_label", type(self).__name__), nbytes)
        if span is not None and getattr(span, "attrs", None) is not None:
            span.attrs["state_bytes"] = nbytes

    # -------------------------------------------------- state-integrity guard
    def _integrity_state(self) -> State:
        """The state view the ``check_finite`` scan runs over.

        Default: the current state verbatim. States whose legitimate resting
        values would false-positive the scan override this — e.g. ``Keyed``
        masks never-touched slab slots, whose min/max identity fills sit at
        the dtype extremes the saturation scan watches for.
        """
        return self._current_state()

    def _pre_update_snapshot(self) -> Optional[State]:
        """Pre-update state refs, captured only under the quarantine policy
        (jax arrays are immutable, so holding the refs is free)."""
        if self.check_finite == "quarantine" and not self._under_trace():
            return self._current_state()
        return None

    def _guard_state_integrity(self, where: str, revert_to: Optional[State] = None) -> None:
        """Apply the ``check_finite`` policy to the CURRENT state.

        Host-side and eager-only: under tracing the scan would need a
        readback that cannot happen (use the pure :func:`nonfinite_count` /
        :func:`saturated_count` inside jit instead). Policies: ``warn``
        warns; ``raise`` throws ``StateCorruptionError``;
        ``quarantine`` restores ``revert_to`` (the pre-update accumulator —
        dropping the poisoned batch) when one was captured, else warns.
        """
        policy = self.check_finite
        if not policy or self._under_trace():
            return
        state = self._integrity_state()
        if any(isinstance(v, list) for v in state.values()):
            # eager list states: scan the concrete elements, not the pytree
            state = {
                k: (
                    jnp.concatenate([jnp.ravel(jnp.asarray(e)) for e in v]) if v else jnp.zeros((0,))
                )
                if isinstance(v, list)
                else v
                for k, v in state.items()
            }
        nonfinite, saturated = state_integrity_counts(state)
        nonfinite, saturated = int(nonfinite), int(saturated)
        if not nonfinite and not saturated:
            return
        detail = (
            f"{self.__class__.__name__} state failed the integrity scan after {where}: "
            f"{nonfinite} non-finite float element(s), {saturated} near-saturated integer "
            "count(s)."
        )
        if policy == "raise":
            raise StateCorruptionError(detail)
        if policy == "quarantine" and revert_to is not None:
            self._set_state(revert_to)
            self._computed = None
            record_fault("quarantined_updates")
            rank_zero_warn(detail + " The batch delta was quarantined (accumulator unchanged).", UserWarning)
            return
        rank_zero_warn(detail, UserWarning)

    # warn at half the int32 range: headroom for a few more epochs of updates
    _OVERFLOW_WARN_THRESHOLD = 2**30

    @property
    def _has_int_states(self) -> bool:
        return any(
            hasattr(d, "dtype") and jnp.issubdtype(d.dtype, jnp.integer) for d in self._defaults.values()
        )

    def note_count(self, amount: int) -> None:
        """Advance the host-side count bound behind the int32-overflow warning.

        The library tracks an upper bound on every int count state WITHOUT
        touching the device: each processed element can contribute at most 1
        to a count, so the bound advances by the largest argument size per
        update. A custom metric whose update adds MORE than one per element
        to an integer state should call this with the amount added, or the
        overflow warning may come late. (Device-side probing is deliberately
        avoided: a single device-to-host readback per step is the dominant
        cost on remote-attached accelerators.)
        """
        self._count_bound += int(amount)

    def _note_rows(self, args: tuple, kwargs: dict) -> None:
        # min over argument sizes ~ the number of labeled samples: for
        # (B, C) preds + (B,) target this is B, for multidim (B, C, X) +
        # (B, X) it is B*X — matching what count states actually accrue
        sizes = [getattr(a, "size", None) for a in (*args, *kwargs.values())]
        sizes = [s for s in sizes if isinstance(s, int)]
        if sizes:
            self._count_bound += min(sizes)
        # every accumulation path notes its rows exactly once per logical
        # step (the reference-path value recomputation runs _in_forward), so
        # this is also where the epoch watermark advances
        if not self._in_forward:
            self._epoch_watermark += 1

    def _after_compute(self, result: Any) -> None:
        """Hook run by the wrapped ``compute`` after the sync cache/restore.

        State written inside ``compute`` itself is discarded when a
        cross-process sync restores the local state; writes from this hook
        persist. Default: nothing.
        """

    def _host_warnings(self) -> None:
        """Host-side health warnings at epoch-compute time (no device work).

        Runs even when the compute cache is pre-seeded (``forward_batched``).
        Subclasses with their own host-bound warnings extend this.
        """
        self._check_accumulator_overflow()

    def _check_accumulator_overflow(self) -> None:
        """Warn loudly when an int32 count accumulator nears wraparound.

        Without x64 enabled, count states accumulate in int32 (see
        ``utils.data.accum_int_dtype``); a pod-scale epoch can silently wrap
        at 2^31. The check compares a host-maintained upper bound (elements
        processed, see ``note_count``) against the threshold — no device
        work, no readback, sync-free.
        """
        if jax.config.jax_enable_x64 or self._overflow_warned:
            return
        if self._count_bound >= self._OVERFLOW_WARN_THRESHOLD and self._has_int_states:
            self._overflow_warned = True
            rank_zero_warn(
                f"{self.__class__.__name__} has processed ~{self._count_bound} elements; its"
                " int32 count states may be nearing 2^31, where they silently wrap. Enable"
                " jax_enable_x64 to accumulate counts in int64.",
                UserWarning,
            )

    def _wrap_compute(self, compute: Callable) -> Callable:
        @functools.wraps(compute)
        def wrapped_func(*args: Any, **kwargs: Any) -> Any:
            if TRACE.enabled:
                with _span("metric.compute", {"metric": type(self).__name__}):
                    out = compute_body(*args, **kwargs)
                    if _DEVTIME.enabled:
                        _fence(out)
                    return out
            return compute_body(*args, **kwargs)

        def compute_body(*args: Any, **kwargs: Any) -> Any:
            if not self._in_forward:  # epoch-level compute, not the per-step batch value
                # before the cache early-return: a forward_batched-seeded
                # cache must not suppress the overflow warning
                self._host_warnings()
            if self._computed is not None:
                return self._computed

            dist_sync_fn = self.dist_sync_fn
            if dist_sync_fn is None and jax.process_count() > 1:
                dist_sync_fn = self._default_gather()
            if dist_sync_fn is not None and self._states_own_sync():
                # mesh-row-sharded global states span processes already; their
                # combination happens via XLA collectives inside the jitted
                # sharded compute — a host gather would re-materialize the
                # epoch the sharded placement exists to avoid
                dist_sync_fn = None

            synced = False
            cache = {}
            if self._to_sync and dist_sync_fn is not None:
                lag = (
                    self._resolve_sync_lag()
                    if self.sync_lag and self._in_forward
                    else 0
                )
                if lag:
                    # the DEFERRED per-step plane (sync_lag=k): snapshot this
                    # step's delta into the double buffer, dispatch its
                    # gather on the background host plane, push the handle
                    # onto the lag-k ring, and — once the ring overflows its
                    # depth — read the OLDEST handle's merged view, which
                    # finished gathering while the last k steps' updates ran.
                    # The debug sync-count probe is skipped here: its own
                    # eager gather would jump the entry-order queue the
                    # background executor preserves.
                    from metrics_tpu.parallel.deferred import deferred_host_gather

                    ring = self._handle_ring
                    attrs = None
                    if TRACE.enabled:
                        attrs = {"lag_controller": lag}
                    ring.append(deferred_host_gather(
                        self._current_state(), self._reductions,
                        gather_fn=dist_sync_fn, watermark=self._epoch_watermark,
                        attrs=attrs,
                    ))
                    self._sync_count += 1
                    view = None
                    # overflow: resolve oldest handles until the ring is back
                    # at its depth (one pop per step in steady state; several
                    # when the lag just shallowed). The NEWEST resolved view
                    # is the step's read — the freshest k-lagged merge.
                    while len(ring) > lag:
                        oldest = ring.popleft()
                        t0 = time.perf_counter()
                        view = oldest.result()
                        if self._lag_controller is not None and self.sync_lag == "auto":
                            self._lag_controller.observe(
                                (time.perf_counter() - t0) * 1e3
                            )
                    record_deferred_depth(
                        getattr(self, "_metric_label", type(self).__name__), len(ring)
                    )
                    if view is not None:
                        cache = self._current_state()
                        local = cache if self.check_finite == "quarantine" else None
                        self._set_state(view)
                        self._guard_state_integrity("sync", local)
                        self._note_state_bytes()
                        synced = True
                    # warm-up (ring not yet at depth): the state stays the
                    # local delta — steps 0..k-1 read the documented unsynced
                    # view
                else:
                    if self._handle_ring:
                        # entry order: a synchronous sync must not overtake
                        # in-flight deferred gathers on any rank — drain the
                        # whole ring, oldest first
                        self._drain_handle_ring()
                    if debug.sync_count_check_enabled():
                        counts = [int(c) for c in dist_sync_fn(jnp.asarray(self._sync_count, dtype=jnp.int32))]
                        if len(set(counts)) > 1:
                            raise RuntimeError(
                                f"{self.__class__.__name__}: processes disagree on the synced-compute"
                                f" sequence number ({counts}). Some rank called a synced compute() a"
                                " different number of times — this pairs collectives wrongly and"
                                " eventually deadlocks."
                            )
                    self._sync_count += 1
                    cache = self._current_state()
                    if self._lag_controller is not None and self.sync_lag == "auto" and self._in_forward:
                        # the controller's lag-0 probe: feed it the blocking
                        # wait this synchronous gather cost the step — the
                        # wait a deeper ring would have hidden
                        self._sync_dist(dist_sync_fn, timer=self._lag_controller.observe)
                    else:
                        self._sync_dist(dist_sync_fn)
                    synced = True

            self._computed = compute(*args, **kwargs)
            if synced:
                self._set_state(cache)
            # post-compute hook AFTER the sync restore: state written here
            # persists (wrappers use it to track computed values as state)
            self._after_compute(self._computed)
            return self._computed

        return wrapped_func

    @abstractmethod
    def update(self) -> None:  # pylint: disable=E0202
        """Override to update registered state from a batch."""

    @abstractmethod
    def compute(self) -> Any:  # pylint: disable=E0202
        """Override to compute the final value from (synced) state."""

    # ------------------------------------------------------------- lifecycle
    def reset(self) -> None:
        """Reset all states to defaults, preserving device placement and dtype
        (the reference re-creates defaults on the *current* device,
        metric.py:256-265; here the last ``device_put``/``astype`` target is
        re-applied so mesh placement survives epoch resets)."""
        self._computed = None
        self._count_bound = 0
        self._overflow_warned = False
        self._epoch_watermark = 0
        # in-flight deferred gathers still complete on the background plane
        # (entry order), but a reset metric never reads their views
        self._handle_ring = deque()
        state = self.init_state()
        self._set_state(state)
        if self._state_dtype is not None:
            self.astype(self._state_dtype)
        if self._placement is not None:
            self.device_put(self._placement)

    def clone(self) -> "Metric":
        return deepcopy(self)

    def __getstate__(self) -> dict:
        # _handle_ring holds live futures (threads, device buffers): they
        # never travel — a copy/restore starts with no in-flight sync. The
        # lag controller's measurements are machine-local, so it stays too.
        skip = ("update", "compute", "_update_impl", "_compute_impl", "_jitted_step", "_jitted_step_fc",
                "_jitted_scan", "_handle_ring", "_lag_controller")
        return {k: v for k, v in self.__dict__.items() if k not in skip}

    def __setstate__(self, state: dict) -> None:
        self.__dict__.update(state)
        self.__dict__.setdefault("_jitted_step_fc", None)
        self.__dict__.setdefault("_default_keys", {})
        self.__dict__.setdefault("_fc_failed", False)
        self.__dict__.setdefault("_scan_failed", False)
        self.__dict__.setdefault("_count_bound", 0)
        self.__dict__.setdefault("_overflow_warned", False)
        self.__dict__.setdefault("_epoch_watermark", 0)
        self.__dict__.setdefault("check_finite", None)
        self.__dict__.setdefault("sync_lag", 0)
        # handles never travel: drop ANY lag-k ring a foreign __dict__ sneaked
        # in (and the legacy single-handle slot from pre-ring pickles) — a
        # restored metric starts with no in-flight sync and a fresh controller
        self.__dict__["_handle_ring"] = deque()
        self.__dict__["_lag_controller"] = None
        self.__dict__.pop("_deferred_handle", None)
        self._update_impl = self.__class__.update.__get__(self)
        self._compute_impl = self.__class__.compute.__get__(self)
        self.update = self._wrap_update(self._update_impl)
        self.compute = self._wrap_compute(self._compute_impl)
        self._jitted_step = None
        self._jitted_step_fc = None
        self._jitted_scan = None

    def __deepcopy__(self, memo: dict) -> "Metric":
        cls = self.__class__
        new = cls.__new__(cls)
        memo[id(self)] = new
        skip = ("update", "compute", "_update_impl", "_compute_impl", "_jitted_step", "_jitted_step_fc",
                "_jitted_scan", "_handle_ring", "_lag_controller")
        for k, v in self.__dict__.items():
            if k in skip:
                continue
            if isinstance(v, (jnp.ndarray, Array)) or isinstance(v, PaddedBuffer) or is_sketch(v):
                if k in self._defaults:
                    # registered states are DONATED by the fused jitted step on
                    # TPU: clone and original must not alias the same buffer,
                    # or the first donated step invalidates the other's state
                    new.__dict__[k] = jax.tree_util.tree_map(lambda a: jnp.array(a, copy=True), v)
                else:
                    new.__dict__[k] = v  # non-state device arrays are never donated
            else:
                new.__dict__[k] = deepcopy(v, memo)
        new._update_impl = cls.update.__get__(new)
        new._compute_impl = cls.compute.__get__(new)
        new.update = new._wrap_update(new._update_impl)
        new.compute = new._wrap_compute(new._compute_impl)
        new._jitted_step = None
        new._jitted_step_fc = None
        new._jitted_scan = None
        new.__dict__["_handle_ring"] = deque()
        new.__dict__["_lag_controller"] = None
        return new

    # ------------------------------------------------------- device / shards
    def device_put(self, device_or_sharding: Any) -> "Metric":
        """Place all states on a device or ``jax.sharding.Sharding`` (the
        TPU-native analogue of the reference's ``_apply`` device movement,
        metric.py:281-298).

        Accepts a callable ``(state_name, value) -> device | Sharding`` for
        per-state placement — e.g. class-axis states sharded over a model
        axis of a 2-D mesh while scalar counters stay replicated (see
        ``metrics_tpu.parallel.placement.class_sharded``).
        """
        self._placement = device_or_sharding
        resolve = device_or_sharding if callable(device_or_sharding) else (lambda _n, _v: device_or_sharding)
        for name in self._defaults:
            value = getattr(self, name)
            if isinstance(value, list):
                setattr(self, name, [jax.device_put(v, resolve(name, v)) for v in value])
            else:
                setattr(self, name, jax.device_put(value, resolve(name, value)))
        return self

    def astype(self, dtype: Any) -> "Metric":
        """Cast floating-point states (analogue of ``.half()/.float()`` movement)."""
        self._state_dtype = dtype
        for name in self._defaults:
            value = getattr(self, name)

            def _cast(v: Array) -> Array:
                return v.astype(dtype) if jnp.issubdtype(v.dtype, jnp.floating) else v

            if isinstance(value, list):
                setattr(self, name, [_cast(v) for v in value])
            elif isinstance(value, PaddedBuffer):
                setattr(self, name, PaddedBuffer(_cast(value.data), value.count))
            elif is_sketch(value):
                # sketch counts are integer by construction; _cast is a no-op
                # unless a float-count sketch was declared explicitly
                setattr(self, name, type(value)(_cast(value.counts)))
            else:
                setattr(self, name, _cast(value))
        return self

    # ------------------------------------------------------------ checkpoint
    def persistent(self, mode: bool = False) -> None:
        for key in self._persistent:
            self._persistent[key] = mode

    def state_dict(self, destination: Optional[dict] = None, prefix: str = "") -> dict:
        """Persistent states as host numpy (orbax/pickle friendly)."""
        destination = {} if destination is None else destination
        for key in self._defaults:
            if self._persistent[key]:
                value = getattr(self, key)
                if isinstance(value, list):
                    destination[prefix + key] = [np.asarray(v) for v in value]
                elif isinstance(value, PaddedBuffer):
                    destination[prefix + key] = {"data": np.asarray(value.data), "count": np.asarray(value.count)}
                elif is_sketch(value):
                    destination[prefix + key] = {"sketch_counts": np.asarray(value.counts)}
                else:
                    destination[prefix + key] = np.asarray(value)
        # the host-side overflow bound must survive checkpoint/resume, or a
        # restored metric would never warn (the bound is host metadata, not
        # a device state)
        destination[prefix + "_count_bound"] = np.asarray(self._count_bound, dtype=np.int64)
        # the epoch watermark rides every checkpoint: restore + replay of the
        # in-flight step must be a no-op (guarded_update)
        destination[prefix + "_epoch_watermark"] = np.asarray(self._epoch_watermark, dtype=np.int64)
        return destination

    def load_state_dict(self, state_dict: dict, prefix: str = "") -> None:
        for key in self._defaults:
            if prefix + key in state_dict:
                value = state_dict[prefix + key]
                if isinstance(value, dict) and set(value) == {"data", "count"}:
                    setattr(self, key, PaddedBuffer(jnp.asarray(value["data"]), jnp.asarray(value["count"])))
                elif isinstance(value, dict) and set(value) == {"sketch_counts"}:
                    # the sketch-kind resolution of record: the live state's
                    # type wins; otherwise the spec registry materializes the
                    # declared kind (histogram/rank/CMS/quantile — and slab
                    # forms thereof) so old checkpoints restore unchanged
                    # without a per-kind fallback chain here.
                    spec = self._defaults[key]
                    kind = type(getattr(self, key)) if is_sketch(getattr(self, key, None)) else None
                    if kind is None:
                        materialized = materialize_state_spec(spec)
                        kind = type(materialized) if is_sketch(materialized) else None
                    if kind is None:
                        raise ValueError(
                            f"checkpoint entry '{key}' holds sketch counts but the state is not a sketch"
                        )
                    setattr(self, key, kind(jnp.asarray(value["sketch_counts"])))
                elif isinstance(value, list):
                    setattr(self, key, [jnp.asarray(v) for v in value])
                else:
                    setattr(self, key, jnp.asarray(value))
        if prefix + "_count_bound" in state_dict:
            self._count_bound = int(state_dict[prefix + "_count_bound"])
        if prefix + "_epoch_watermark" in state_dict:
            self._epoch_watermark = int(state_dict[prefix + "_epoch_watermark"])

    def state_pytree(self) -> State:
        """All current states as a pytree (for orbax checkpointing of the full metric)."""
        return self._current_state()

    # -------------------------------------------------------------- plumbing
    def _filter_kwargs(self, **kwargs: Any) -> Dict[str, Any]:
        """Keep only kwargs accepted by this metric's ``update`` (reference metric.py:321-336)."""
        _params = (inspect.Parameter.VAR_POSITIONAL, inspect.Parameter.VAR_KEYWORD)
        _sign_params = self._update_signature.parameters
        filtered_kwargs = {
            k: v for k, v in kwargs.items() if (k in _sign_params and _sign_params[k].kind not in _params)
        }
        return filtered_kwargs or kwargs

    def __hash__(self) -> int:
        # identity-based like the reference (torch tensors hash by id); the
        # instance id is included because XLA interns equal small constants,
        # so state-array ids alone cannot distinguish two fresh instances
        hash_vals = [self.__class__.__name__, id(self)]
        for key in self._defaults:
            value = getattr(self, key)
            if isinstance(value, list):
                hash_vals.extend(id(v) for v in value)
            else:
                hash_vals.append(id(value))
        return hash(tuple(hash_vals))

    def __repr__(self) -> str:
        return f"{self.__class__.__name__}()"

    # ------------------------------------------------------------- operators
    def __add__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.add, self, other)

    def __and__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.bitwise_and, self, other)

    def __eq__(self, other: Any) -> "CompositionalMetric":  # type: ignore[override]
        return CompositionalMetric(jnp.equal, self, other)

    def __floordiv__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.floor_divide, self, other)

    def __ge__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.greater_equal, self, other)

    def __gt__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.greater, self, other)

    def __le__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.less_equal, self, other)

    def __lt__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.less, self, other)

    def __matmul__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.matmul, self, other)

    def __mod__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.fmod, self, other)

    def __mul__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.multiply, self, other)

    def __ne__(self, other: Any) -> "CompositionalMetric":  # type: ignore[override]
        return CompositionalMetric(jnp.not_equal, self, other)

    def __or__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.bitwise_or, self, other)

    def __pow__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.power, self, other)

    def __radd__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.add, other, self)

    def __rand__(self, other: Any) -> "CompositionalMetric":
        # bitwise_and is commutative
        return CompositionalMetric(jnp.bitwise_and, self, other)

    def __rfloordiv__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.floor_divide, other, self)

    def __rmatmul__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.matmul, other, self)

    def __rmod__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.fmod, other, self)

    def __rmul__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.multiply, other, self)

    def __ror__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.bitwise_or, other, self)

    def __rpow__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.power, other, self)

    def __rsub__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.subtract, other, self)

    def __rtruediv__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.true_divide, other, self)

    def __rxor__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.bitwise_xor, other, self)

    def __sub__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.subtract, self, other)

    def __truediv__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.true_divide, self, other)

    def __xor__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.bitwise_xor, self, other)

    def __abs__(self) -> "CompositionalMetric":
        return CompositionalMetric(jnp.abs, self, None)

    def __inv__(self) -> "CompositionalMetric":
        return CompositionalMetric(jnp.bitwise_not, self, None)

    def __invert__(self) -> "CompositionalMetric":
        return self.__inv__()

    def __neg__(self) -> "CompositionalMetric":
        return CompositionalMetric(_neg, self, None)

    def __pos__(self) -> "CompositionalMetric":
        return CompositionalMetric(jnp.abs, self, None)


def _neg(tensor: Array) -> Array:
    return -jnp.abs(tensor)


class CompositionalMetric(Metric):
    """Lazy composition of two metrics under an operator (reference metric.py:457-536)."""

    def __init__(
        self,
        operator: Callable,
        metric_a: Union[Metric, int, float, Array],
        metric_b: Union[Metric, int, float, Array, None],
    ):
        super().__init__()
        self.op = operator
        self.metric_a = jnp.asarray(metric_a) if isinstance(metric_a, (jnp.ndarray, np.ndarray)) else metric_a
        self.metric_b = jnp.asarray(metric_b) if isinstance(metric_b, (jnp.ndarray, np.ndarray)) else metric_b

    def _sync_dist(
        self, dist_sync_fn: Optional[Callable] = None,
        timer: Optional[Callable[[float], None]] = None,
    ) -> None:
        # syncing is done by the child metrics themselves (reference metric.py:489-491)
        pass

    @property
    def _fusable(self) -> bool:
        # forward() is overridden below; the base dispatch never runs
        return False

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        """Fused composed forward: ONE forward per child per step.

        Each Metric child runs its own (fused, single-dispatch) ``forward``
        — accumulating the batch once and yielding its batch-local value —
        and the composed batch value is the operator over those values. The
        reference instead routes through its double-update forward
        (reference metric.py:150-177), paying two updates per child per
        step; this halves the dispatch count and leaves children's
        accumulated state intact.
        """
        self._computed = None  # children advanced: any cached epoch value is stale

        def _child(child):
            if isinstance(child, Metric):
                return child.forward(*args, **child._filter_kwargs(**kwargs))
            return child

        val_a = _child(self.metric_a)
        val_b = _child(self.metric_b)
        if not self.compute_on_step:
            return None
        # a child with compute_on_step=False yields no batch value to compose
        if val_a is None or (isinstance(self.metric_b, Metric) and val_b is None):
            return None
        self._forward_cache = self.op(val_a) if val_b is None else self.op(val_a, val_b)
        return self._forward_cache

    def update(self, *args: Any, **kwargs: Any) -> None:
        if isinstance(self.metric_a, Metric):
            self.metric_a.update(*args, **self.metric_a._filter_kwargs(**kwargs))
        if isinstance(self.metric_b, Metric):
            self.metric_b.update(*args, **self.metric_b._filter_kwargs(**kwargs))

    def compute(self) -> Any:
        val_a = self.metric_a.compute() if isinstance(self.metric_a, Metric) else self.metric_a
        val_b = self.metric_b.compute() if isinstance(self.metric_b, Metric) else self.metric_b
        if val_b is None:
            return self.op(val_a)
        return self.op(val_a, val_b)

    def reset(self) -> None:
        self._computed = None
        if isinstance(self.metric_a, Metric):
            self.metric_a.reset()
        if isinstance(self.metric_b, Metric):
            self.metric_b.reset()

    def persistent(self, mode: bool = False) -> None:
        if isinstance(self.metric_a, Metric):
            self.metric_a.persistent(mode=mode)
        if isinstance(self.metric_b, Metric):
            self.metric_b.persistent(mode=mode)

    def __repr__(self) -> str:
        _op_metrics = f"(\n  {self.op.__name__}(\n    {repr(self.metric_a)},\n    {repr(self.metric_b)}\n  )\n)"
        return self.__class__.__name__ + _op_metrics
