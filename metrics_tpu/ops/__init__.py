"""TPU Pallas kernels for the hot ops.

Each kernel ships with an XLA fallback (used on non-TPU backends and as the
numerical oracle in tests); dispatch is by ``jax.default_backend()`` with an
explicit ``impl=`` override.
"""
from metrics_tpu.ops.binned import binned_stat_counts

__all__ = ["binned_stat_counts"]
