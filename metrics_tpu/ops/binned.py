"""Pallas TPU kernel for binned-curve threshold counting.

The binned curve family (``functional/classification/binned_curves.py``)
reduces every batch to per-threshold TP/FP counts:

    tp[t] = sum_n pos[n] * (preds[n] >= thr[t])
    fp[t] = sum_n neg[n] * (preds[n] >= thr[t])

This kernel streams N through VMEM in tiles and contracts on the MXU:

    [pos; neg] (8 x TILE_N)  @  (preds_tile >= thr) (TILE_N x T)  ->  (8, T)

accumulated across tiles on-chip, so HBM traffic is just the batch plus the
tiny output.

**Round-3 verdict (v5e sweep, N ∈ {64k..4M} × T ∈ {512, 2048}, recorded in
BASELINE.md): the kernel is RETIRED from the default dispatch.** XLA does
not in fact materialize the ``(T, N)`` comparison in HBM — it fuses the
comparison into the contraction — so the hypothesized bandwidth win never
appears: both paths measure equal within noise (~±30%) at every size, with
identical outputs bit-for-bit. Per SURVEY §2's own rule ("Pallas only where
profiling justifies it"), ``impl="auto"`` now always takes the XLA path;
the kernel remains available via ``impl="pallas"`` (and
``"pallas_interpret"`` for CPU tests) as the packaged example of the
tile/grid/MXU pattern for ops XLA handles less well.

Per-class (multiclass/multilabel) inputs always took the XLA einsum path:
the comparison there is ``(T, N, C)`` with C a batch dimension, which XLA
already handles well.

Counts accumulate in float32: exact up to 2**24 per call, and the callers
accumulate across batches in integer state (same contract as the one-hot
matmul in ``functional/classification/confusion_matrix.py``).
"""
import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import Array

_SUBLANE = 8  # float32 min sublane count
_LANE = 128  # lane width
_TILE_N = 2048  # N elements streamed per grid step (8 KiB of scores)


def _pad_to(x: Array, size: int, axis: int, value: float) -> Array:
    pad = size - x.shape[axis]
    if pad <= 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths, constant_values=value)


def _round_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


def _binary_kernel(preds_ref, w_ref, thr_ref, out_ref):
    """One N-tile: MXU-contract the threshold comparison against the weights."""
    import jax.experimental.pallas as pl

    @pl.when(pl.program_id(0) == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    # (TILE_N, 1) >= (1, T) -> (TILE_N, T), sublane=N tile, lane=T: no relayout
    ge = (preds_ref[...] >= thr_ref[...]).astype(jnp.float32)
    out_ref[...] += jax.lax.dot(w_ref[...], ge, preferred_element_type=jnp.float32)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _binned_counts_pallas_binary(
    preds: Array, pos: Array, neg: Array, thresholds: Array, *, interpret: bool = False
) -> Tuple[Array, Array]:
    """(N,) binary inputs -> ((T,), (T,)) float32 TP/FP counts via Pallas."""
    import jax.experimental.pallas as pl

    n = preds.shape[0]
    t = thresholds.shape[0]
    t_pad = _round_up(t, _LANE)
    tile_n = min(_TILE_N, _round_up(n, _LANE))
    n_pad = _round_up(n, tile_n)

    # padded samples: preds=-inf never reaches any threshold, weights are 0;
    # padded thresholds are +inf so no sample reaches them
    preds_col = _pad_to(preds.astype(jnp.float32), n_pad, 0, -jnp.inf)[:, None]  # (N, 1)
    w = jnp.stack([pos.astype(jnp.float32), neg.astype(jnp.float32)])  # (2, N)
    w = _pad_to(_pad_to(w, n_pad, 1, 0.0), _SUBLANE, 0, 0.0)  # (8, N)
    thr = _pad_to(thresholds.astype(jnp.float32), t_pad, 0, jnp.inf)[None, :]  # (1, T)

    out = pl.pallas_call(
        _binary_kernel,
        grid=(n_pad // tile_n,),
        in_specs=[
            pl.BlockSpec((tile_n, 1), lambda i: (i, 0)),
            pl.BlockSpec((_SUBLANE, tile_n), lambda i: (0, i)),
            pl.BlockSpec((1, t_pad), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((_SUBLANE, t_pad), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((_SUBLANE, t_pad), jnp.float32),
        interpret=interpret,
    )(preds_col, w, thr)
    return out[0, :t], out[1, :t]


def _binned_counts_xla(preds_c: Array, pos: Array, neg: Array, thresholds: Array) -> Tuple[Array, Array]:
    """XLA path: threshold comparison contracted as one matmul.

    Binary case: ``(T, N) @ (N, 2)`` with tp and fp as the two output
    columns — measured 2x faster than the ``tnc,nc->tc`` einsum pair at
    16M-64M rows on v5e (one ``ge`` operand, one MXU pass; see BASELINE.md
    round-4 sweep). Multiclass keeps the einsum (its ``ge`` is per-class, so
    the operand cannot collapse to 2-D, and C output columns already fill
    the MXU better).
    """
    n, c = preds_c.shape
    # BOOL weight columns (the unweighted curve family) route through the
    # int8 MXU path: 2x the bf16/f32 MAC rate, int32 accumulation exact to
    # 2^31 (measured 1.5-1.9x at 16M-64M on v5e, BASELINE.md round-5 int8
    # experiment). The gate is bool-only on purpose: integer weights could
    # exceed int8 range and astype(int8) would silently wrap — numeric
    # (float/int) weights keep the f32 matmul.
    exact01 = jnp.issubdtype(pos.dtype, jnp.bool_) and jnp.issubdtype(neg.dtype, jnp.bool_)
    if c == 1:
        if exact01:
            ge = (preds_c[:, 0][None, :] >= thresholds[:, None]).astype(jnp.int8)
            w = jnp.concatenate([pos, neg], axis=1).astype(jnp.int8)  # (N, 2)
            out = jnp.matmul(ge, w, preferred_element_type=jnp.int32).astype(jnp.float32)
        else:
            ge = (preds_c[:, 0][None, :] >= thresholds[:, None]).astype(preds_c.dtype)  # (T, N)
            w = jnp.concatenate([pos, neg], axis=1)  # (N, 2)
            out = ge @ w  # (T, 2)
        return out[:, :1].T, out[:, 1:].T
    if exact01:
        ge = (preds_c[None, :, :] >= thresholds[:, None, None]).astype(jnp.int8)
        tp = jnp.einsum("tnc,nc->tc", ge, pos.astype(jnp.int8),
                        preferred_element_type=jnp.int32).T.astype(jnp.float32)
        fp = jnp.einsum("tnc,nc->tc", ge, neg.astype(jnp.int8),
                        preferred_element_type=jnp.int32).T.astype(jnp.float32)
        return tp, fp
    ge = (preds_c[None, :, :] >= thresholds[:, None, None]).astype(preds_c.dtype)  # (T, N, C)
    tp = jnp.einsum("tnc,nc->tc", ge, pos).T  # (C, T)
    fp = jnp.einsum("tnc,nc->tc", ge, neg).T
    return tp, fp


def binned_stat_counts(
    preds_c: Array, pos: Array, neg: Array, thresholds: Array, impl: str = "auto"
) -> Tuple[Array, Array]:
    """Per-threshold TP/FP counts: ``tp[c, t] = sum_n pos[n, c] * (preds[n, c] >= thr[t])``.

    Args:
        preds_c: ``(N, C)`` scores (float32).
        pos / neg: ``(N, C)`` weights of positive / negative samples —
            float32 for weighted counts, or BOOL 0/1 masks, which engage
            the exact int8 MXU fast path (see ``_binned_counts_xla``).
        thresholds: ``(T,)`` ascending thresholds.
        impl: ``"auto"`` (the XLA einsum — measured equal to the kernel at
            every size, see module docstring), ``"pallas"``,
            ``"pallas_interpret"`` (for tests on CPU), or ``"xla"``.

    Returns:
        ``(tp, fp)`` of shape ``(C, T)``, same count dtype as ``preds_c``.
    """
    if impl not in ("auto", "xla", "pallas", "pallas_interpret"):
        raise ValueError(f"impl must be 'auto', 'pallas', 'pallas_interpret' or 'xla', got {impl!r}")
    n, c = preds_c.shape
    if impl == "auto":
        # measured equal to the XLA fusion at every size (see module
        # docstring); default to the simpler compiler path
        impl = "xla"
    if impl == "xla" or n == 0 or c > 1:
        # multiclass and empty batches take the XLA path (see module docstring)
        return _binned_counts_xla(preds_c, pos, neg, thresholds)

    tp, fp = _binned_counts_pallas_binary(
        preds_c[:, 0], pos[:, 0], neg[:, 0], thresholds, interpret=(impl == "pallas_interpret")
    )
    return tp[None, :].astype(preds_c.dtype), fp[None, :].astype(preds_c.dtype)
