"""Mergeable sketch states: constant-memory curve/rank metrics.

The curve/rank metric families (AUROC, ROC, PrecisionRecallCurve,
AveragePrecision, Spearman, Kendall) are the library's last O(samples)
states: every prediction lands in a ``PaddedBuffer`` cat-state, so state
memory and sync traffic grow with traffic — the hierarchical gather
collection moves ~49 KB of DCN payload per sync where a few-KB sketch would
do, and at millions-of-users scale an O(samples) state is a non-starter.

This module provides the fix as a first-class *mergeable sketch* state kind
next to :class:`~metrics_tpu.parallel.buffer.PaddedBuffer`, specialized from
the streaming-summary literature (Karnin–Lang–Liberty quantile sketches,
Ben-Haim & Tom-Tov streaming parallel histograms) to FIXED-GRID histograms so
that every operation stays XLA-native:

- :class:`HistogramSketch` — per-class score histograms conditioned on the
  target, counts of shape ``(2, B)`` (binary: row 0 positives, row 1
  negatives) or ``(C, 2, B)``. Thresholded TP/FP/TN/FN on the ``B + 1``
  threshold grid (the ``B`` bin lower edges plus a terminal all-rejecting
  threshold above the top bin) are EXACT for the binned data (a suffix
  cumsum), so ROC / PR / AUROC / AP derive at ``compute()`` with error
  bounded by the in-bin collision mass (see :func:`auroc_error_bound`).
- :class:`RankSketch` — a 2-D joint histogram over per-variable quantile
  grids. Spearman is the binned-rank (midrank) Pearson correlation over the
  joint counts — exactly scipy's tie-averaged Spearman for the binned data —
  and Kendall's tau-b comes from the joint concordance contraction (2-D
  suffix sums) with tie terms from the marginals.

Why fixed-grid instead of adaptive KLL: ``update`` stays a jittable
scatter-add (one fused op inside the training step), ``merge`` is elementwise
integer addition — associative, commutative, and BIT-EXACT, so a ``psum`` of
per-device sketches equals the single-process sketch — and ``sync`` rides
the existing per-dtype sum-psum buckets of
:func:`~metrics_tpu.parallel.sync.coalesced_sync_state` with ZERO new
collective kinds. State size is traffic-independent: a 2048-bin binary curve
sketch is 16 KB forever, reduced (not gathered) across the mesh.

The metric modules expose this via ``approx="sketch"`` / ``num_bins=``
constructor arguments (exact buffers stay the default); see
``docs/collection_performance.md`` for the state-size table and the error
bounds of record.
"""
from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.parallel.cms import CountMinSketch
from metrics_tpu.parallel.qsketch import QuantileSketch

__all__ = [
    "HistogramSketch",
    "RankSketch",
    "SketchSpec",
    "auroc_error_bound",
    "auroc_from_histogram",
    "average_precision_from_histogram",
    "curve_collision_bound",
    "curve_counts_from_histogram",
    "curve_sketch_group_key",
    "curve_sketch_spec",
    "is_sketch",
    "kendall_from_joint",
    "precision_recall_from_histogram",
    "rank_collision_bound",
    "rank_sketch_group_key",
    "rank_sketch_spec",
    "rank_to_bin",
    "roc_from_histogram",
    "score_to_bin",
    "sketch_curve_update",
    "sketch_init",
    "sketch_merge",
    "sketch_nbytes",
    "sketch_rank_update",
    "sketch_thresholds",
    "spearman_from_joint",
]


class HistogramSketch(NamedTuple):
    """Fixed-grid score histogram conditioned on the target.

    ``counts``: ``(2, B)`` integer bin counts for binary input (row 0 =
    positives, row 1 = negatives) or ``(C, 2, B)`` per class. A pytree of one
    integer leaf: jit/scan/donation-safe, ``dist_reduce_fx="sum"`` semantics
    (merge = elementwise add, sync = one psum, both bit-exact).
    """

    counts: Array


class RankSketch(NamedTuple):
    """2-D joint histogram over (preds-bin, target-bin) quantile grids.

    ``counts``: ``(B, B)`` integer counts. Same mergeable-sum contract as
    :class:`HistogramSketch`; Spearman and Kendall derive from it at
    ``compute()`` (midrank Pearson / tau-b concordance).
    """

    counts: Array


# CountMinSketch (parallel/cms.py) and QuantileSketch (parallel/qsketch.py)
# join the family: each is one more counts-backed mergeable-sum state, so
# every counts-based arm — the sync bucket planes, slab scatters, checkpoint
# round-trips, wrapper merges — handles them through the same ``is_sketch``
# branch as the histogram kinds.
_SKETCH_TYPES = (HistogramSketch, RankSketch, CountMinSketch, QuantileSketch)
_KINDS = {"hist": HistogramSketch, "rank": RankSketch, "cms": CountMinSketch,
          "qsketch": QuantileSketch}


def is_sketch(value: Any) -> bool:
    """Whether ``value`` is a sketch state (the kind test the state model,
    sync planes, and checkpoint paths branch on — the sketch analogue of
    ``isinstance(v, PaddedBuffer)``). Count-Min tail sketches
    (``parallel/cms.py``) are members: one integer counts leaf, merge =
    add, sync = the sum bucket."""
    return isinstance(value, _SKETCH_TYPES)


class SketchSpec(NamedTuple):
    """Host-side sketch state declaration (what ``Metric.add_state`` records
    in ``self._defaults``, the sketch analogue of ``_BufferSpec``).

    ``kind``: ``"hist"`` (:class:`HistogramSketch`) or ``"rank"``
    (:class:`RankSketch`). ``shape``/``dtype``: the counts array.
    ``lo``/``hi``: the value range of the linear bin grid; ``None``/``None``
    (rank sketches only) selects the range-free soft-sign squash grid of
    :func:`rank_to_bin`. The spec is pure config — materialization is
    :func:`sketch_init` — and it is fingerprintable, so config-identical
    sketch metrics share compiled steps and compute groups.
    """

    kind: str
    shape: Tuple[int, ...]
    dtype: Any
    lo: Optional[float]
    hi: Optional[float]


def sketch_init(spec: SketchSpec):
    """Fresh zero-count sketch for ``spec`` (jit-safe: zeros stage as
    compile-time constants under tracing)."""
    return _KINDS[spec.kind](jnp.zeros(spec.shape, dtype=spec.dtype))


def sketch_merge(a, b):
    """Pairwise sketch merge: elementwise integer addition — associative,
    commutative, bit-exact (the property the psum-mergeability gate pins)."""
    if type(a) is not type(b):
        raise TypeError(f"cannot merge sketch kinds {type(a).__name__} and {type(b).__name__}")
    return type(a)(a.counts + b.counts)


def sketch_nbytes(value) -> int:
    """State bytes of one sketch (traffic-independent by construction)."""
    return int(value.counts.size) * int(jnp.dtype(value.counts.dtype).itemsize)


def _accum_dtype():
    from metrics_tpu.utils.data import accum_int_dtype

    return accum_int_dtype()


# ------------------------------------------------------------------- binning
def score_to_bin(x: Array, num_bins: int, lo: float, hi: float) -> Array:
    """Linear bin index of ``x`` on the ``[lo, hi)`` grid, clipped into the
    end bins (out-of-range scores — ``±inf`` included — merge into bin 0 /
    bin B-1: part of the documented approximation, not an error).

    ``NaN`` has no defined bin (``astype(int32)`` of NaN is undefined in
    XLA): callers must mask NaN before binning, as the sketch update planes
    do (NaN samples are dropped via a zero scatter increment).
    """
    scaled = (x - lo) * (num_bins / (hi - lo))
    return jnp.clip(jnp.floor(scaled), 0, num_bins - 1).astype(jnp.int32)


def rank_to_bin(x: Array, num_bins: int, lo: Optional[float], hi: Optional[float]) -> Array:
    """Bin index for rank sketches.

    With an explicit ``(lo, hi)`` this is the linear grid. With
    ``lo is None`` the value is first squashed through the strictly
    increasing soft-sign map ``s(x) = 1/2 + x / (2 (1 + |x|))`` into
    ``(0, 1)`` and binned there — a fixed quantile-style grid that needs no
    range configuration. Rank statistics are invariant under any strictly
    increasing transform, and exact ties stay exact ties through it, so the
    squash changes only which values COLLIDE in a bin, never their order.
    ``±inf`` takes the squash's sign limit (bin 0 / bin B-1) rather than the
    undefined ``inf/inf`` path; NaN must be masked by the caller (see
    :func:`score_to_bin`).
    """
    if lo is None:
        t = jnp.where(jnp.isinf(x), jnp.sign(x), x / (1.0 + jnp.abs(x)))
        return score_to_bin(0.5 + 0.5 * t, num_bins, 0.0, 1.0)
    return score_to_bin(x, num_bins, lo, hi)


def sketch_thresholds(num_bins: int, lo: float, hi: float) -> np.ndarray:
    """The ``B + 1`` thresholds curve sketches report: the ``B`` bin lower
    edges plus ``hi``, the virtual terminal threshold above the top bin where
    every sample is rejected — the curve's zero-count (0, 0) anchor.

    Host-side numpy on purpose (threshold grids are metric config; under jit
    they stage as constants), matching
    ``functional.classification.binned_curves.default_thresholds``.
    """
    return (lo + np.arange(num_bins + 1, dtype=np.float64) * ((hi - lo) / num_bins)).astype(np.float32)


# ------------------------------------------------------------------- updates
def sketch_curve_update(
    counts: Array,
    preds: Array,
    target: Array,
    lo: float,
    hi: float,
    pos_label: int,
) -> Array:
    """Scatter one batch into per-class positive/negative score histograms.

    The SHARED update plane of every curve metric's sketch mode — AUROC,
    ROC, PrecisionRecallCurve and AveragePrecision instances with equal
    sketch config all run exactly this function, which is what lets a
    ``MetricCollection`` fuse them into ONE compute group (one scatter-add
    update, one synced state for the whole curve family).

    Layouts (shapes are static, so the branch resolves at trace time):

    - binary: ``preds (N,)``, ``target (N,)`` — ``counts (2, B)``; positives
      are ``target == pos_label``.
    - multiclass: ``preds (N, C)``, ``target (N,)`` int labels — ``counts
      (C, 2, B)``, one-vs-rest per class.
    - multilabel: ``preds (N, C)``, ``target (N, C)`` — ``counts (C, 2, B)``,
      positives are ``target == pos_label`` per column.

    Pure and jittable: one clip-floor binning plus one scatter-add, no
    data-dependent shapes, no host sync. NaN predictions are DROPPED (zero
    scatter increment) rather than scattered into an undefined bin — the
    sketch-mode analogue of buffer mode preserving NaN for the
    ``check_finite`` policies to catch; ``±inf`` clips into the end bins
    like any out-of-range score.
    """
    num_bins = counts.shape[-1]
    if preds.ndim == 1:
        if counts.ndim != 2:
            raise ValueError(
                f"sketch expects per-class input (N, {counts.shape[0]}); got 1-D predictions."
                " Construct the metric without num_classes for binary sketch mode."
            )
        nan = jnp.isnan(preds)
        b = score_to_bin(jnp.where(nan, lo, preds), num_bins, lo, hi)
        row = jnp.where(target == pos_label, 0, 1)
        return counts.at[row, b].add((~nan).astype(counts.dtype))
    if preds.ndim != 2 or counts.ndim != 3 or preds.shape[1] != counts.shape[0]:
        raise ValueError(
            f"sketch/state layout mismatch: preds {preds.shape} vs counts {counts.shape}."
            " Multiclass/multilabel sketch mode needs num_classes at construction."
        )
    num_classes = preds.shape[1]
    nan = jnp.isnan(preds)
    b = score_to_bin(jnp.where(nan, lo, preds), num_bins, lo, hi)  # (N, C)
    if target.ndim == 1:
        pos = target[:, None] == jnp.arange(num_classes)[None, :]
    else:
        pos = target == pos_label
    cls = jnp.broadcast_to(jnp.arange(num_classes)[None, :], b.shape)
    row = jnp.where(pos, 0, 1)
    return counts.at[cls, row, b].add((~nan).astype(counts.dtype))


def sketch_rank_update(
    counts: Array,
    preds: Array,
    target: Array,
    lo: Optional[float],
    hi: Optional[float],
) -> Array:
    """Scatter one batch of (preds, target) pairs into the 2-D joint
    histogram — the shared update plane of Spearman's and Kendall's sketch
    mode (equal-config instances form one compute group). Jittable. Pairs
    with a NaN on either side are dropped (zero scatter increment) instead
    of corrupting an undefined bin; ``±inf`` lands in the end bins."""
    nan = jnp.isnan(preds) | jnp.isnan(target)
    bi = rank_to_bin(jnp.where(nan, 0.0, preds), counts.shape[0], lo, hi)
    bj = rank_to_bin(jnp.where(nan, 0.0, target), counts.shape[1], lo, hi)
    return counts.at[bi, bj].add((~nan).astype(counts.dtype))


# ---------------------------------------------------------------- curve math
def curve_counts_from_histogram(counts: Array) -> Tuple[Array, Array, Array, Array]:
    """Thresholded ``(tp, fp, tn, fn)`` float32 counts on the ``B + 1``
    threshold grid of :func:`sketch_thresholds` — the ``B`` bin lower edges
    plus the virtual terminal threshold above the top bin — from
    ``(..., 2, B)`` histogram counts.

    ``score >= thr[t]`` is EXACTLY ``bin(score) >= t`` for in-range scores
    (the grid's defining property), so these counts are exact for the binned
    data — the suffix cumsum is the whole derivation. The terminal column
    rejects everything (``tp = fp = 0``): it anchors the derived ROC/PR
    curves at (0, 0) so top-bin samples — saturated sigmoids, out-of-range
    scores clipped into bin B-1 — keep their final trapezoid/step segment,
    the half-credit property :func:`auroc_error_bound`'s certificate relies
    on. Shapes: ``(..., B + 1)``.
    """
    h = counts.astype(jnp.float32)
    pos = h[..., 0, :]
    neg = h[..., 1, :]
    # suffix (reverse) cumulative sums: samples at or above each bin edge,
    # plus a trailing zero column for the above-the-top terminal threshold
    zero = jnp.zeros_like(pos[..., :1])
    tp = jnp.concatenate([jnp.flip(jnp.cumsum(jnp.flip(pos, -1), -1), -1), zero], -1)
    fp = jnp.concatenate([jnp.flip(jnp.cumsum(jnp.flip(neg, -1), -1), -1), zero], -1)
    fn = jnp.sum(pos, -1, keepdims=True) - tp
    tn = jnp.sum(neg, -1, keepdims=True) - fp
    return tp, fp, tn, fn


def roc_from_histogram(counts: Array) -> Tuple[Array, Array]:
    """(fpr, tpr) on the ascending ``B + 1`` threshold grid (binned-curve
    conventions, matching ``classification.binned.BinnedROC``), ending at
    the (0, 0) terminal point."""
    tp, fp, tn, fn = curve_counts_from_histogram(counts)
    tpr = tp / jnp.maximum(tp + fn, 1.0)
    fpr = fp / jnp.maximum(fp + tn, 1.0)
    return fpr, tpr


def auroc_from_histogram(counts: Array) -> Array:
    """AUROC via the trapezoidal rule over the sketched ROC.

    The grid points lie exactly ON the empirical ROC curve (the thresholded
    counts are exact for binned data), so the only error is the within-bin
    interpolation — see :func:`auroc_error_bound` for the certificate.
    """
    fpr, tpr = roc_from_histogram(counts)
    return -jnp.trapezoid(tpr, fpr, axis=-1)


def auroc_error_bound(counts: Array) -> Array:
    """Data-dependent certificate: ``|sketch AUROC - exact AUROC| <= bound``.

    The exact AUROC is ``P(s+ > s-) + P(s+ = s-) / 2`` over positive/negative
    score pairs. The sketch resolves every cross pair whose scores fall in
    DIFFERENT bins exactly, and the trapezoid assigns exactly half credit to
    each same-bin cross pair — so the error is at most half the in-bin
    collision mass::

        bound = sum_b pos_b * neg_b / (2 * P * N)

    Computable from the sketch itself (this function), shrinking as the grid
    refines or the score distribution spreads; ties that share a bin with no
    other value contribute ZERO error (half credit is the exact tie value).
    """
    h = counts.astype(jnp.float32)
    pos = h[..., 0, :]
    neg = h[..., 1, :]
    p_total = jnp.maximum(jnp.sum(pos, -1), 1.0)
    n_total = jnp.maximum(jnp.sum(neg, -1), 1.0)
    return jnp.sum(pos * neg, -1) / (2.0 * p_total * n_total)


def curve_collision_bound(counts: Array) -> Array:
    """Data-dependent resolution certificate of a curve histogram: the
    fraction of positive/negative cross pairs COLLIDING in one score bucket
    (``sum_b pos_b * neg_b / (P * N)``) — the mass whose order the grid
    cannot resolve, and exactly twice :func:`auroc_error_bound` (which
    charges half credit per collision). The quantity the AveragePrecision
    sketch modes report as their certificate: the step integral's deviation
    is driven by, and vanishes with, this collision mass. Works on any
    monotone grid — the linear ``sketch_range`` grid and the log-bucketed
    qsketch grid alike."""
    return 2.0 * auroc_error_bound(counts)


def rank_collision_bound(counts: Array) -> Array:
    """Data-dependent resolution certificate of a 2-D joint rank histogram:
    the fraction of sample pairs colliding in one grid bucket on either
    variable (``sum_i p_i (p_i - 1) / (n (n - 1))`` per marginal, summed).
    Colliding pairs are the ONLY pairs whose order the binned-rank
    statistics resolve as ties instead of exactly — true ties contribute
    zero error (tie-averaging is exact for them) — so the sketch
    Spearman/Kendall deviation is driven by, and vanishes with, this mass.
    Grid-agnostic like :func:`curve_collision_bound`."""
    h = counts.astype(jnp.float32)
    n = jnp.sum(h)
    p = jnp.sum(h, axis=1)
    t = jnp.sum(h, axis=0)
    pairs = jnp.maximum(n * (n - 1.0), 1.0)
    return (jnp.sum(p * (p - 1.0)) + jnp.sum(t * (t - 1.0))) / pairs


def precision_recall_from_histogram(counts: Array) -> Tuple[Array, Array]:
    """(precision, recall) on the ascending ``B + 1`` threshold grid
    (``BinnedPrecisionRecallCurve`` conventions: 0 where undefined), except
    the terminal zero-count point takes the exact module's
    ``(precision=1, recall=0)`` endpoint convention — the curve ends at the
    same anchor whether computed from buffers or from the sketch."""
    tp, fp, tn, fn = curve_counts_from_histogram(counts)
    denom_p = tp + fp
    denom_r = tp + fn
    precision = jnp.where(denom_p == 0, 0.0, tp / jnp.where(denom_p == 0, 1.0, denom_p))
    precision = precision.at[..., -1].set(1.0)
    recall = jnp.where(denom_r == 0, 0.0, tp / jnp.where(denom_r == 0, 1.0, denom_r))
    return precision, recall


def average_precision_from_histogram(counts: Array) -> Array:
    """Average precision as the step integral over the sketched PR curve
    (descending recall, ``BinnedAveragePrecision`` conventions). The
    terminal (recall=0) grid point supplies the final recall-drop step, so
    positives saturated into the top bin contribute their
    ``precision * recall`` mass instead of silently vanishing."""
    precision, recall = precision_recall_from_histogram(counts)
    return -jnp.sum((recall[..., 1:] - recall[..., :-1]) * precision[..., :-1], axis=-1)


# ----------------------------------------------------------------- rank math
def _midranks(marginal: Array) -> Array:
    """1-based average (mid) ranks of each bin's occupants from a marginal
    histogram: a bin of ``m`` tied values occupying ranks ``c+1 .. c+m`` gets
    rank ``c + (m + 1) / 2`` — scipy's tie-averaged ranking, per bin."""
    cum = jnp.cumsum(marginal)
    return cum - marginal / 2.0 + 0.5


def spearman_from_joint(counts: Array) -> Array:
    """Spearman rank correlation from the 2-D joint histogram.

    Binned-rank correlation: each variable's bins get tie-averaged midranks
    from its marginal, and the statistic is the ``counts``-weighted Pearson
    correlation of those ranks — EXACTLY scipy's tie-averaged Spearman for
    the binned data (data whose distinct values map 1:1 onto bins loses
    nothing; otherwise the error is the in-bin collision mass). ``nan`` on
    degenerate input (constant ranks, empty sketch) — the scipy convention
    the exact kernel also follows.
    """
    h = counts.astype(jnp.float32)
    n = jnp.sum(h)
    p = jnp.sum(h, axis=1)
    t = jnp.sum(h, axis=0)
    r = _midranks(p) - (n + 1.0) / 2.0  # centered: mean rank is (N+1)/2
    s = _midranks(t) - (n + 1.0) / 2.0
    cov = jnp.sum(h * r[:, None] * s[None, :])
    var_x = jnp.sum(p * r * r)
    var_y = jnp.sum(t * s * s)
    denom = jnp.sqrt(jnp.maximum(var_x, 0.0) * jnp.maximum(var_y, 0.0))
    return jnp.where(denom == 0, jnp.nan, cov / jnp.where(denom == 0, 1.0, denom))


def kendall_from_joint(counts: Array) -> Array:
    """Kendall's tau-b from the 2-D joint histogram.

    Concordant/discordant pair totals come from 2-D suffix contractions over
    the joint counts (pairs in distinct bins resolve exactly; same-bin pairs
    are ties by construction), tie corrections from the marginals — exactly
    ``scipy.stats.kendalltau`` (tau-b) for the binned data. ``nan`` on
    degenerate input, matching the exact kernel.
    """
    h = counts.astype(jnp.float32)
    n = jnp.sum(h)
    # inclusive 2-D suffix sums, then shift by one for the strict quadrant
    suf = jnp.flip(jnp.cumsum(jnp.cumsum(jnp.flip(h, (0, 1)), axis=0), axis=1), (0, 1))
    s_gt = jnp.zeros_like(h).at[:-1, :-1].set(suf[1:, 1:])  # i' > i and j' > j
    # discordant quadrant: i' > i, j' < j (exclusive suffix over rows, then
    # exclusive prefix over columns)
    row_suf = jnp.zeros_like(h).at[:-1, :].set(
        jnp.flip(jnp.cumsum(jnp.flip(h, 0), axis=0), 0)[1:, :]
    )
    s_lt = jnp.zeros_like(h).at[:, 1:].set(jnp.cumsum(row_suf, axis=1)[:, :-1])
    concordant = jnp.sum(h * s_gt)
    discordant = jnp.sum(h * s_lt)
    p = jnp.sum(h, axis=1)
    t = jnp.sum(h, axis=0)
    n0 = n * (n - 1.0) / 2.0
    n1 = jnp.sum(p * (p - 1.0)) / 2.0
    n2 = jnp.sum(t * (t - 1.0)) / 2.0
    denom = jnp.sqrt(jnp.maximum(n0 - n1, 0.0) * jnp.maximum(n0 - n2, 0.0))
    return jnp.where(denom > 0, (concordant - discordant) / jnp.where(denom > 0, denom, 1.0), jnp.nan)


# ----------------------------------------------------- metric-side plumbing
def curve_sketch_spec(
    num_bins: int,
    num_classes: Optional[int],
    lo: float,
    hi: float,
    dtype: Any = None,
) -> SketchSpec:
    """The :class:`SketchSpec` a curve metric registers for ``approx="sketch"``."""
    if not isinstance(num_bins, int) or num_bins < 2:
        raise ValueError(f"`num_bins` must be an int >= 2, got {num_bins!r}")
    if not (hi > lo):
        raise ValueError(f"sketch range must satisfy lo < hi, got ({lo}, {hi})")
    shape = (2, num_bins) if num_classes in (None, 1) else (num_classes, 2, num_bins)
    return SketchSpec("hist", shape, dtype or _accum_dtype(), float(lo), float(hi))


def rank_sketch_spec(
    num_bins: int,
    lo: Optional[float],
    hi: Optional[float],
    dtype: Any = None,
) -> SketchSpec:
    """The :class:`SketchSpec` a rank metric registers for ``approx="sketch"``
    (``lo=None`` selects the range-free soft-sign grid)."""
    if not isinstance(num_bins, int) or num_bins < 2:
        raise ValueError(f"`num_bins` must be an int >= 2, got {num_bins!r}")
    if (lo is None) != (hi is None):
        raise ValueError("sketch_range must be None or a (lo, hi) pair")
    if lo is not None and not (hi > lo):
        raise ValueError(f"sketch range must satisfy lo < hi, got ({lo}, {hi})")
    return SketchSpec(
        "rank", (num_bins, num_bins), dtype or _accum_dtype(),
        None if lo is None else float(lo), None if hi is None else float(hi),
    )


def canonicalize_approx(
    approx: Optional[str], allowed: Tuple[str, ...] = ("sketch",)
) -> Optional[str]:
    """Validate an ``approx=`` constructor argument (None = exact buffers).
    Metrics that also support the log-bucketed quantile-sketch grid pass
    ``allowed=("sketch", "qsketch")``."""
    if approx is not None and approx not in allowed:
        raise ValueError(
            f"`approx` must be None or one of {allowed}, got {approx!r}"
        )
    return approx


def curve_sketch_group_key(metric: Any) -> tuple:
    """Compute-group fingerprint of a curve metric's sketch update plane.

    Any two curve-family instances (across AUROC / ROC /
    PrecisionRecallCurve / AveragePrecision) with equal keys run the
    IDENTICAL :func:`sketch_curve_update` over the identical ``hist`` state
    schema, so inside a ``MetricCollection`` one scatter-add delta serves
    them all; each member keeps its own ``compute``.
    """
    spec = metric._defaults["hist"]
    pos_label = metric.pos_label if getattr(metric, "pos_label", None) is not None else 1
    return ("sketch_curve", spec.shape, str(jnp.dtype(spec.dtype)), spec.lo, spec.hi, int(pos_label))


def rank_sketch_group_key(metric: Any) -> tuple:
    """Compute-group fingerprint of a rank metric's sketch update plane
    (shared across Spearman / Kendall instances with equal config)."""
    spec = metric._defaults["joint"]
    return ("sketch_rank", spec.shape, str(jnp.dtype(spec.dtype)), spec.lo, spec.hi)
