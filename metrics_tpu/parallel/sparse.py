"""Sparse delta sync: collective bytes proportional to TOUCHED rows, not K.

The dense sync planes (``parallel/sync.py``) move every slab's full
``(K, *item)`` payload each round — 2,640,000 B for the bench's K=10,000
keyed AUROC slab — even when a step touched a handful of segment rows.
This module adds the sparse plane that exploits slab mergeability:

1. **Touched bitmap** — each rank derives the set of slab rows its state
   changed since the last round (a bitwise compare against the plane's
   baseline snapshot, or an explicit ``touched=`` hint produced by
   :func:`~metrics_tpu.parallel.slab.slab_touched_mask` from the slot ids
   the batch actually scattered). The (K,) booleans pack into multi-bit
   LANES of a uint32 word vector — ``psum`` ADDS, so a plain 1-bit pack
   would overflow when several ranks touch the same row; lanes are sized so
   a lane holds the world's touch count (``world < 2**lane_bits``) and the
   packed bitmap psums across the mesh in ONE collective (~K/8 bytes at
   world 8). The union is every lane with a nonzero count.
2. **Fixed-capacity row exchange** — when the union fits ``capacity``, the
   ranks exchange ONLY the union's rows: one ``all_gather`` whose payload is
   a slot-id HEADER followed by each rank's per-leaf row payloads (4-byte
   leaves bitcast to uint32 so mixed int/float row slabs still ride a single
   gather). The fold scatters the gathered rows into the plane's merged view
   — ``sum``-kind rows scatter-ADD the (current − baseline) delta, ``min``/
   ``max`` rows scatter-min/max the current rows (idempotent, so re-folding
   is harmless) — which mergeability makes exact for all four state kinds:
   plain arrays, histogram/rank sketches, count-min tails, and quantile
   sketches (the latter three are one integer counts leaf each).
3. **Dense fallback** — a union larger than ``capacity`` falls back to the
   existing dense coalesced plane for that round (bit-exact by definition)
   and counts it (``sparse_fallbacks``), so correctness NEVER depends on the
   sparsity estimate; a persistent overflow trips a one-shot
   ``rank_zero_warn_once`` naming the ``sparse_capacity=`` knob.
4. **Empty skip** — a round whose union is empty skips the row exchange
   entirely (``gather_skips`` plus the ``sparse.skips`` counter): the only
   traffic is the bitmap psum.

Dense RESIDUAL leaves (e.g. ``HeavyHitters``' constant-size count-min tail)
are delta-synced every round with zero extra collectives: their integer
32-bit deltas bitcast to uint32 and ride the bitmap psum payload
(two's-complement addition is bit-identical through the cast); other dtypes
get a psum of their own. Only ``sum``-kind dense leaves are supported — the
wrappers' tails all are; anything else belongs on the dense plane.

The staged collective count is INDEPENDENT of K (flat: 1 psum + 1 gather;
hierarchical: 2 + 2) — the property ``bench.py --check-collectives`` pins —
and both programs stage their collectives through the same
``_resolve_hierarchy``/``_hier_reduce``/``_hier_gather_stack`` plumbing as
the dense planes, so a :class:`~metrics_tpu.parallel.placement.
MeshHierarchy` (or the auto-derived ``("dcn", "ici")`` hierarchy) gives the
sparse plane ici-first/DCN-last staging for free.

EXACTNESS: integer row slabs (sketch counts, sample-count rows — the whole
sketch/CMS/qsketch family) merge bit-exactly with the dense plane; float
``sum`` slabs merge delta-exactly when the deltas are exactly representable
(integers in float32, the common case for count-like floats). ``min``/
``max`` rows are idempotent folds and always exact.

FAULT TOLERANCE: one sparse round is a single fault site (``"sparse_sync"``)
under the active :class:`~metrics_tpu.parallel.sync.SyncGuard` — injected
drops, deadline-expired stalls, and detected payload corruption (the
``check_finite`` vetting, plus a cross-rank slot-id header agreement check)
retry the WHOLE round, which is idempotent by construction: the plane's
merged view and baseline only commit after an attempt is accepted, and
re-running the compiled programs on unchanged inputs is bit-exact.
"""
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.observability.counters import (
    record_fault,
    record_gather_skip,
    record_sparse_fallback,
    record_sparse_round,
    record_sparse_skip,
)
from metrics_tpu.parallel import sync as _sync
from metrics_tpu.parallel.placement import MeshHierarchy
from metrics_tpu.parallel.sketch import is_sketch
from metrics_tpu.parallel.sync import (
    SyncGuard,
    _attempt_with_deadline,
    _DeadlineExceeded,
    _hier_gather_stack,
    _hier_reduce,
    _payload_suspect,
    _rec,
    _resolve_hierarchy,
    coalesced_sync_state,
    current_sync_guard,
)
from metrics_tpu.utils.exceptions import (
    InjectedFaultError,
    StateCorruptionError,
    SyncTimeoutError,
)
from metrics_tpu.utils.prints import rank_zero_warn_once

__all__ = [
    "SparseSyncPlane",
    "pack_touched",
    "touched_lane_bits",
    "unpack_touched_counts",
]

_ROW_REDUCES = ("sum", "min", "max")


def touched_lane_bits(world: int) -> int:
    """Bitmap lane width (bits) for a ``world``-rank mesh.

    ``psum`` ADDS the packed words, so each row's lane must hold the count
    of ranks that touched it — up to ``world`` — without carrying into its
    neighbour: the smallest 32-divisor width with ``world < 2**bits``.
    """
    if not (isinstance(world, int) and world >= 1):
        raise ValueError(f"`world` must be a positive int, got {world!r}")
    for bits in (1, 2, 4, 8, 16):
        if world < (1 << bits):
            return bits
    return 32


def pack_touched(touched: Array, world: int) -> Array:
    """Pack a ``(K,)`` touched mask into lane-counted uint32 words (jit-safe).

    Each word carries ``32 // lane_bits`` rows; the local contribution per
    lane is 0/1, and the cross-rank psum of the words yields each row's
    touch COUNT in its lane (no carry: lanes are sized to the world)."""
    bits = touched_lane_bits(world)
    rpw = 32 // bits
    k = touched.shape[0]
    words = -(-k // rpw)
    t = jnp.pad(touched.astype(jnp.uint32), (0, words * rpw - k))
    shifts = jnp.left_shift(
        jnp.uint32(1), (bits * jnp.arange(rpw, dtype=jnp.uint32))
    )
    return jnp.sum(t.reshape(words, rpw) * shifts[None, :], axis=1, dtype=jnp.uint32)


def unpack_touched_counts(words: Any, num_rows: int, world: int) -> np.ndarray:
    """Host-side inverse of :func:`pack_touched` AFTER the psum: per-row
    touch counts (``> 0`` is the union membership test)."""
    bits = touched_lane_bits(world)
    rpw = 32 // bits
    w = np.asarray(words, dtype=np.uint32)
    lane = np.uint32((1 << bits) - 1)
    shifts = (bits * np.arange(rpw, dtype=np.uint32))[None, :]
    return ((w[:, None] >> shifts) & lane).reshape(-1)[:num_rows]


def _payload_of(value: Any) -> Array:
    """The raw array a state leaf moves (sketch/CMS/qsketch leaves move
    their counts)."""
    return value.counts if is_sketch(value) else value


def _rewrap(template: Any, payload: Array) -> Any:
    return type(template)(payload) if is_sketch(template) else payload


def _fold_identity(dtype: Any, fx: str) -> Any:
    """The reduce identity used to blank invalid gather lanes (``min`` lanes
    fold a dtype-max row into slot 0, a no-op; ``max`` symmetric)."""
    if jnp.issubdtype(dtype, jnp.inexact):
        return jnp.array(jnp.inf if fx == "min" else -jnp.inf, dtype)
    info = jnp.iinfo(dtype)
    return jnp.array(info.max if fx == "min" else info.min, dtype)


def _rides_u32(dtype: Any) -> bool:
    """Whether a leaf payload can bitcast-ride the shared uint32 payload
    (pure reinterpretation: gathers move bits, psums of bitcast ints are
    two's-complement adds — bit-identical either way)."""
    dt = jnp.dtype(dtype)
    return dt.itemsize == 4 and (
        jnp.issubdtype(dt, jnp.integer) or jnp.issubdtype(dt, jnp.floating)
    )


class SparseSyncPlane:
    """Stateful sparse delta-sync plane over a slab-shaped state dict.

    The plane holds two snapshots between rounds:

    - ``merged`` — the replicated cross-rank merged view, the value the
      dense plane would have produced from the ranks' CURRENT states. This
      is what :meth:`sync` returns.
    - ``baseline`` — each call's reference point: the state as of the last
      accepted round (immutable jax arrays, so snapshots are reference
      rebinds, zero copies). ``current − baseline`` is the delta a round
      exchanges.

    Construct it from the metric's RESET state (``sum`` leaves all-zero,
    ``min``/``max`` leaves at their fill template): that is the one state
    where every rank's copy and the dense merged view coincide, which seeds
    the invariant ``merged == dense_sync(current)`` that each round then
    preserves. :meth:`rebase` re-seeds it (epoch reset, checkpoint restore).

    ``state`` leaves are split into ROW leaves (leading dimension
    ``num_rows`` — the slabs the sparse exchange slices) and DENSE residual
    leaves (everything else, e.g. ``HeavyHitters``' count-min tail), which
    delta-sync through the bitmap psum every round. Pass ``row_leaves=`` to
    override the leading-dimension classification.

    Input convention matches the bench/test shard_map convention: leaves
    are REPLICATED over ``mesh`` and each device treats its copy as its
    local shard (``in_specs=P()``). ``stacked=True`` switches to the
    deferred plane's convention — leaves carry the mesh's device axis as
    their leading dimension and each device contributes its own row.
    """

    def __init__(
        self,
        state: Dict[str, Any],
        reductions: Dict[str, Any],
        num_rows: int,
        axis_name: Any,
        mesh: Any = None,
        *,
        capacity: int = 64,
        row_leaves: Optional[Tuple[str, ...]] = None,
        hierarchy: Optional[Union[MeshHierarchy, bool]] = None,
        guard: Optional[SyncGuard] = None,
        stacked: bool = False,
        fallback_warn_fraction: float = 0.5,
        fallback_warn_rounds: int = 8,
    ) -> None:
        if not (isinstance(num_rows, int) and num_rows >= 1):
            raise ValueError(f"`num_rows` must be a positive int, got {num_rows!r}")
        if not (isinstance(capacity, int) and capacity >= 1):
            raise ValueError(f"`sparse_capacity` must be a positive int, got {capacity!r}")
        if not state:
            raise ValueError("SparseSyncPlane needs at least one state leaf")
        if mesh is None:
            for leaf in jax.tree_util.tree_leaves(dict(state)):
                mesh = getattr(getattr(leaf, "sharding", None), "mesh", None)
                if mesh is not None and getattr(mesh, "axis_names", None):
                    break
            if mesh is None or not getattr(mesh, "axis_names", None):
                raise ValueError(
                    "SparseSyncPlane could not infer the mesh from the state's"
                    " sharding; pass mesh= explicitly"
                )
        self._mesh = mesh
        self._axis = axis_name
        self._hierarchy = hierarchy
        self._guard = guard
        self._stacked = bool(stacked)
        self.num_rows = num_rows
        self.capacity = capacity
        self.fallback_warn_fraction = float(fallback_warn_fraction)
        self.fallback_warn_rounds = int(fallback_warn_rounds)

        axes = self._axis_span(axis_name)
        self._world = int(np.prod([mesh.shape[a] for a in axes]))

        def leading(v: Any) -> Optional[int]:
            arr = _payload_of(v)
            shape = getattr(arr, "shape", ())
            if self._stacked:
                shape = shape[1:]  # strip the device axis
            return shape[0] if shape else None

        if row_leaves is None:
            row_leaves = tuple(n for n, v in state.items() if leading(v) == num_rows)
        row_set = set(row_leaves)
        self._row_names: Tuple[str, ...] = tuple(n for n in state if n in row_set)
        self._dense_names: Tuple[str, ...] = tuple(n for n in state if n not in row_set)
        if not self._row_names:
            raise ValueError(
                f"no state leaf has leading dimension num_rows={num_rows}; the"
                " sparse plane needs at least one row slab (pass row_leaves= to"
                " name them explicitly)"
            )
        self._reductions = {}
        self._row_reduce: Dict[str, str] = {}
        self._item_shape: Dict[str, Tuple[int, ...]] = {}
        self._leaf_dtype: Dict[str, Any] = {}
        self._dense_shape: Dict[str, Tuple[int, ...]] = {}
        for n, v in state.items():
            fx = reductions[n]
            self._reductions[n] = fx
            arr = _payload_of(v)
            shape = tuple(arr.shape[1:] if self._stacked else arr.shape)
            self._leaf_dtype[n] = jnp.dtype(arr.dtype)
            if n in row_set:
                if leading(v) != num_rows:
                    raise ValueError(
                        f"row leaf {n!r} has leading dimension {leading(v)},"
                        f" expected num_rows={num_rows}"
                    )
                fx = "sum" if is_sketch(v) else fx
                if fx not in _ROW_REDUCES:
                    raise ValueError(
                        f"row leaf {n!r} has reduction {fx!r}; the sparse plane"
                        f" folds {_ROW_REDUCES} rows (slab reductions) — use the"
                        " dense plane for anything else"
                    )
                self._row_reduce[n] = fx
                self._item_shape[n] = shape[1:]
            else:
                if not (is_sketch(v) or fx == "sum"):
                    raise ValueError(
                        f"dense residual leaf {n!r} has reduction {fx!r}; only"
                        " 'sum'-kind residuals (count-min tails, counts leaves)"
                        " delta-sync through the sparse plane — use the dense"
                        " plane for anything else"
                    )
                self._dense_shape[n] = shape

        self._merged = dict(state)
        self._baseline = dict(state)
        self.rounds = 0
        self.fallbacks = 0
        self.skips = 0
        self._warned_fallbacks = False
        self._progs: Dict[str, Any] = {}
        self._lock = threading.Lock()

    # ------------------------------------------------------------- plumbing
    @staticmethod
    def _axis_span(axis_name: Any) -> Tuple[str, ...]:
        if isinstance(axis_name, MeshHierarchy):
            return (axis_name.dcn_axis, axis_name.ici_axis)
        if isinstance(axis_name, tuple):
            return tuple(axis_name)
        return (axis_name,)

    def _unstack(self, leaves: Dict[str, Any]) -> Dict[str, Any]:
        if not self._stacked:
            return leaves
        return {
            n: _rewrap(v, _payload_of(v)[0]) for n, v in leaves.items()
        }

    def _in_spec(self):
        from jax.sharding import PartitionSpec as P

        return P(self._axis_span(self._axis)) if self._stacked else P()

    def rebase(self, state: Dict[str, Any], merged: Optional[Dict[str, Any]] = None) -> None:
        """Re-seed the plane's baseline (and merged view) — the epoch-reset /
        checkpoint-restore hook. With ``merged=None`` the state itself seeds
        the merged view, which is only valid for a reset-shaped state (see
        the class docstring)."""
        self._baseline = dict(state)
        self._merged = dict(merged if merged is not None else state)

    @property
    def merged(self) -> Dict[str, Any]:
        """The current replicated merged view (what the last round returned)."""
        return dict(self._merged)

    # ------------------------------------------------------------- programs
    def _bitmap_program(self, hinted: bool) -> Callable:
        """Program A: pack + psum the touched bitmap, ride the dense-residual
        deltas on the same payload. Compiled once per (hinted) variant."""
        key = f"bitmap:{hinted}"
        prog = self._progs.get(key)
        if prog is not None:
            return prog
        from jax.sharding import PartitionSpec as P

        from metrics_tpu.utils.compat import shard_map

        axis, hierarchy = self._axis, self._hierarchy
        row_names, dense_names = self._row_names, self._dense_names
        num_rows, world = self.num_rows, self._world

        def body(touched_hint, current, baseline):
            current = self._unstack(current)
            baseline = self._unstack(baseline)
            ax, h, crossing = _resolve_hierarchy(axis, hierarchy)
            if hinted:
                touched = touched_hint
            else:
                touched = jnp.zeros((num_rows,), bool)
                for n in row_names:
                    cur = _payload_of(current[n])
                    base = _payload_of(baseline[n])
                    touched = touched | jnp.any(
                        (cur != base).reshape(num_rows, -1), axis=1
                    )
            words = pack_touched(touched, world)
            parts = [words]
            layout = []  # (name, offset into the u32 payload, size)
            offset = words.shape[0]
            own_psum = []
            for n in dense_names:
                delta = (
                    _payload_of(current[n]) - _payload_of(baseline[n])
                ).ravel()
                if _rides_u32(delta.dtype):
                    parts.append(jax.lax.bitcast_convert_type(delta, jnp.uint32))
                    layout.append((n, offset, delta.size))
                    offset += delta.size
                else:
                    own_psum.append(n)
            flat = jnp.concatenate(parts) if len(parts) > 1 else parts[0]
            if h is not None:
                summed = _hier_reduce("psum", jax.lax.psum, flat, h)
            else:
                _rec("psum", flat, ax, crossing)
                summed = jax.lax.psum(flat, ax)
            dense_out = {}
            for n, o, size in layout:
                dense_out[n] = jax.lax.bitcast_convert_type(
                    summed[o: o + size], self._leaf_dtype[n]
                ).reshape(self._dense_shape[n])
            for n in own_psum:
                delta = _payload_of(current[n]) - _payload_of(baseline[n])
                if h is not None:
                    dense_out[n] = _hier_reduce("psum", jax.lax.psum, delta, h)
                else:
                    _rec("psum", delta, ax, crossing)
                    dense_out[n] = jax.lax.psum(delta, ax)
            return summed[: words.shape[0]], dense_out

        spec = self._in_spec()
        prog = jax.jit(
            shard_map(
                body,
                self._mesh,
                in_specs=(P(), spec, spec),
                out_specs=P(),
                check_vma=False,
            )
        )
        self._progs[key] = prog
        return prog

    def _gather_program(self) -> Callable:
        """Program B: the fixed-capacity union-row exchange + scatter fold.
        Compiled once; the union's CONTENT is a device input, so round-to-
        round id changes never retrace."""
        prog = self._progs.get("gather")
        if prog is not None:
            return prog
        from jax.sharding import PartitionSpec as P

        from metrics_tpu.utils.compat import shard_map

        axis, hierarchy = self._axis, self._hierarchy
        row_names, capacity = self._row_names, self.capacity

        def body(ids, valid, current, baseline, merged):
            current = self._unstack(current)
            baseline = self._unstack(baseline)
            ax, h, crossing = _resolve_hierarchy(axis, hierarchy)
            # XLA clamps out-of-range gather indices under jit; sentinel
            # lanes must read row 0 explicitly and be masked out instead
            ids_safe = jnp.where(valid, ids, 0)
            # the slot-id header: replicated union ids ride ahead of the rows
            # so the fold can PROVE every rank exchanged the same union
            parts = [jax.lax.bitcast_convert_type(ids, jnp.uint32)]
            layout = []  # (name, offset, size)
            offset = capacity
            own_gather = []
            contribs = {}
            for n in row_names:
                fx = self._row_reduce[n]
                rows = _payload_of(current[n])[ids_safe]  # (cap, *item)
                mask = valid.reshape((capacity,) + (1,) * (rows.ndim - 1))
                if fx == "sum":
                    base_rows = _payload_of(baseline[n])[ids_safe]
                    contrib = jnp.where(mask, rows - base_rows, 0)
                else:
                    contrib = jnp.where(
                        mask, rows, _fold_identity(rows.dtype, fx)
                    )
                contribs[n] = contrib
                flat = contrib.ravel()
                if _rides_u32(flat.dtype):
                    parts.append(jax.lax.bitcast_convert_type(flat, jnp.uint32))
                    layout.append((n, offset, flat.size))
                    offset += flat.size
                else:
                    own_gather.append(n)

            def gather(value):
                if h is not None:
                    return _hier_gather_stack(value, h)
                _rec("all_gather", value, ax, crossing)
                return jax.lax.all_gather(value, ax)

            payload = jnp.concatenate(parts) if len(parts) > 1 else parts[0]
            gathered = gather(payload)  # (world, P)
            headers = jax.lax.bitcast_convert_type(
                gathered[:, :capacity], jnp.int32
            )
            header_ok = jnp.all(headers == ids[None, :])

            def fold(n, stack):
                # stack: (world, cap, *item); invalid lanes carry the fold
                # identity (sum: 0, min/max: dtype extreme) so their scatter
                # into row 0 is a no-op
                fx = self._row_reduce[n]
                target = _payload_of(merged[n])
                if fx == "sum":
                    return target.at[ids_safe].add(jnp.sum(stack, axis=0))
                if fx == "min":
                    return target.at[ids_safe].min(jnp.min(stack, axis=0))
                return target.at[ids_safe].max(jnp.max(stack, axis=0))

            out = {}
            for n, o, size in layout:
                stack = jax.lax.bitcast_convert_type(
                    gathered[:, o: o + size], self._leaf_dtype[n]
                ).reshape((gathered.shape[0], capacity) + self._item_shape[n])
                out[n] = fold(n, stack)
            for n in own_gather:
                out[n] = fold(n, gather(contribs[n]))
            return out, header_ok

        spec = self._in_spec()
        prog = jax.jit(
            shard_map(
                body,
                self._mesh,
                in_specs=(P(), P(), spec, spec, P()),
                out_specs=P(),
                check_vma=False,
            )
        )
        self._progs["gather"] = prog
        return prog

    def _dense_program(self) -> Callable:
        """The overflow fallback: the existing dense coalesced plane, whole
        state, one compiled program."""
        prog = self._progs.get("dense")
        if prog is not None:
            return prog
        from jax.sharding import PartitionSpec as P

        from metrics_tpu.utils.compat import shard_map

        axis, hierarchy = self._axis, self._hierarchy
        reductions = dict(self._reductions)

        def body(current):
            return coalesced_sync_state(
                self._unstack(current), reductions, axis, hierarchy
            )

        prog = jax.jit(
            shard_map(
                body,
                self._mesh,
                in_specs=(self._in_spec(),),
                out_specs=P(),
                check_vma=False,
            )
        )
        self._progs["dense"] = prog
        return prog

    # ----------------------------------------------------------- the round
    def _attempt_round(self, current: Dict[str, Any], touched: Optional[Array], box: Dict[str, Any]):
        """One PURE round attempt: no plane state mutates here, so a guard
        retry re-runs it bit-exactly. Returns the candidate leaf payloads in
        ``box['names']`` order (a plain list, the fault hook's corruption
        surface)."""
        hint = (
            jnp.zeros((self.num_rows,), bool) if touched is None else touched
        )
        words, dense_deltas = self._bitmap_program(touched is not None)(
            hint, dict(current), dict(self._baseline)
        )
        counts = unpack_touched_counts(
            jax.device_get(words), self.num_rows, self._world
        )
        union = np.flatnonzero(counts).astype(np.int32)
        box["rows"] = int(union.size)
        if union.size == 0:
            box["mode"] = "skip"
            box["names"] = list(self._dense_names)
            return [dense_deltas[n] for n in self._dense_names]
        if union.size > self.capacity:
            box["mode"] = "fallback"
            box["names"] = list(self._row_names) + list(self._dense_names)
            merged = self._dense_program()(dict(current))
            return [_payload_of(merged[n]) for n in box["names"]]
        box["mode"] = "sparse"
        box["names"] = list(self._row_names) + list(self._dense_names)
        ids = np.zeros((self.capacity,), np.int32)
        ids[: union.size] = union
        valid = np.zeros((self.capacity,), bool)
        valid[: union.size] = True
        merged_rows = {
            n: _payload_of(self._merged[n]) for n in self._row_names
        }
        new_rows, header_ok = self._gather_program()(
            jnp.asarray(ids), jnp.asarray(valid), dict(current),
            dict(self._baseline), merged_rows,
        )
        if not bool(header_ok):
            raise StateCorruptionError(
                "sparse-sync slot-id headers disagree across ranks; the union"
                " exchange folded inconsistent rows (retrying the round)"
            )
        return [new_rows[n] for n in self._row_names] + [
            dense_deltas[n] for n in self._dense_names
        ]

    def _corrupted(self, box: Dict[str, Any], leaves) -> bool:
        """Corruption vetting of one attempt's candidate payloads — the
        sparse analogue of ``sync._payload_corrupted``: a signature (NaN /
        saturated ints) the PRE-ROUND merged view did not carry."""
        for n, leaf in zip(box["names"], leaves):
            prior = np.asarray(_payload_of(self._merged[n]))
            if _payload_suspect(prior):
                continue  # genuinely-saturated state: never retry forever
            if _payload_suspect(np.asarray(leaf)):
                return True
        return False

    def sync(self, current: Dict[str, Any], touched: Optional[Array] = None) -> Dict[str, Any]:
        """Run one sparse sync round; returns the replicated merged view.

        ``current`` must carry the construction-time schema (same leaves,
        shapes, dtypes — the compiled programs are schema-pinned).
        ``touched=`` is an optional ``(num_rows,)`` boolean hint — e.g.
        :func:`~metrics_tpu.parallel.slab.slab_touched_mask` over the slot
        ids the step scattered — that skips the full-slab baseline compare;
        it MUST cover every row that changed since the last round (a missed
        row's delta would never be exchanged).
        """
        with self._lock:
            return self._sync_locked(current, touched)

    def _sync_locked(self, current: Dict[str, Any], touched: Optional[Array]) -> Dict[str, Any]:
        guard = self._guard if self._guard is not None else current_sync_guard()
        hook = _sync._FAULT_HOOK
        site = "sparse_sync"
        idx = hook.note_call(site) if hook is not None else self.rounds
        box: Dict[str, Any] = {}

        def attempt_call(attempt: int):
            if hook is not None:
                hook.before_call(site, idx, attempt)
            leaves = self._attempt_round(current, touched, box)
            if hook is not None:
                leaves = list(hook.after_call(site, idx, attempt, leaves))
            return leaves

        attempt = 0
        while True:
            try:
                if guard.deadline_s is not None:
                    leaves = _attempt_with_deadline(
                        lambda a=attempt: attempt_call(a), guard.deadline_s
                    )
                else:
                    leaves = attempt_call(attempt)
                if guard.check_finite and self._corrupted(box, leaves):
                    raise StateCorruptionError(
                        f"corruption signature in sparse-sync round {idx} payload"
                    )
                break
            except (InjectedFaultError, _DeadlineExceeded, StateCorruptionError) as err:
                attempt += 1
                record_fault("sync_retries")
                if attempt <= guard.max_retries:
                    time.sleep(guard.backoff_s * (2 ** (attempt - 1)))
                    continue
                record_fault("sync_deadline_exceeded")
                if guard.policy == "degrade":
                    # local-only view for this round: merged/baseline stay,
                    # so the next round re-offers the same deltas
                    record_fault("degraded_computes")
                    return dict(current)
                if isinstance(err, StateCorruptionError):
                    raise
                raise SyncTimeoutError(
                    f"sparse-sync round {idx} failed after {guard.max_retries}"
                    f" retries (deadline {guard.deadline_s}s, policy 'raise'): {err}"
                ) from err

        return self._commit(current, box, leaves)

    def _commit(self, current: Dict[str, Any], box: Dict[str, Any], leaves) -> Dict[str, Any]:
        mode = box["mode"]
        self.rounds += 1
        record_sparse_round(box["rows"])
        folded = dict(zip(box["names"], leaves))
        if mode == "skip":
            # no rows to exchange: the row gather is skipped entirely
            self.skips += 1
            record_sparse_skip()
            record_gather_skip()
            for n in self._dense_names:
                self._merged[n] = _rewrap(
                    self._merged[n], _payload_of(self._merged[n]) + folded[n]
                )
        elif mode == "fallback":
            self.fallbacks += 1
            record_sparse_fallback()
            for n in box["names"]:
                self._merged[n] = _rewrap(self._merged[n], folded[n])
            self._maybe_warn_fallbacks()
        else:
            for n in self._row_names:
                self._merged[n] = _rewrap(self._merged[n], folded[n])
            for n in self._dense_names:
                self._merged[n] = _rewrap(
                    self._merged[n], _payload_of(self._merged[n]) + folded[n]
                )
        # immutable leaves: rebinding the refs IS the baseline snapshot
        self._baseline = dict(current)
        return dict(self._merged)

    def _maybe_warn_fallbacks(self) -> None:
        if self._warned_fallbacks or self.rounds < self.fallback_warn_rounds:
            return
        fraction = self.fallbacks / self.rounds
        if fraction <= self.fallback_warn_fraction:
            return
        # the latch keeps the advisory at one per plane: the message carries
        # the live round counts, so the process-wide text dedup alone would
        # re-fire on every later round
        self._warned_fallbacks = True
        rank_zero_warn_once(
            f"SparseSyncPlane fell back to the dense plane on"
            f" {self.fallbacks}/{self.rounds} rounds (union exceeded"
            f" sparse_capacity={self.capacity}); the sparse exchange is not"
            " paying for its bitmap psum at this touch rate — raise"
            " sparse_capacity= (or sync on the dense plane) to fix."
        )

    def sync_deferred(self, current: Dict[str, Any], touched: Optional[Array] = None,
                      watermark: Optional[int] = None):
        """Run one round on the deferred host plane; returns a
        :class:`~metrics_tpu.parallel.deferred.SyncHandle`.

        The round runs VERBATIM — guard, chaos site, counters — on the
        single-worker background executor, so deferred sparse rounds share
        the submission-order domain every other deferred gather pairs by
        (the host readback between the bitmap psum and the row exchange is
        what keeps the round off the pure device-dispatch path). Delegates
        to :func:`~metrics_tpu.parallel.deferred.deferred_sparse_sync`.
        """
        from metrics_tpu.parallel.deferred import deferred_sparse_sync

        return deferred_sparse_sync(self, current, touched, watermark=watermark)

    def __repr__(self) -> str:
        return (
            f"SparseSyncPlane(rows={self.num_rows}, capacity={self.capacity},"
            f" leaves={len(self._row_names)}+{len(self._dense_names)},"
            f" rounds={self.rounds}, fallbacks={self.fallbacks}, skips={self.skips})"
        )
