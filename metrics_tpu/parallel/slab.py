"""Keyed multi-tenant metric slabs: one metric x thousands of segments.

Production serving rarely wants one global number — it wants AUROC per model
version x cohort x language x A/B arm. The wrapper-level answer
(``wrappers/classwise.py``, ``wrappers/multioutput.py``) clones whole
``Metric`` modules per segment, which multiplies compiled steps, state
pytrees, and sync collectives by K. This module provides the state-level
answer: segments become a LEADING STATE AXIS.

A *slab* is a ``(K, *inner_shape)`` state — one row per segment slot — whose
per-slot semantics are the inner metric's ordinary reduce kind:

- ``sum``/``mean``-kind rows accumulate by addition (``mean`` is stored
  sum-backed and divided by the per-slot sample count at compute time);
- ``min``/``max`` rows accumulate by elementwise min/max;
- sketch states (:class:`~metrics_tpu.parallel.sketch.HistogramSketch` /
  ``RankSketch``) keep their own type with a leading ``(K, ...)`` counts
  axis, so PR 7's constant-memory curve/rank metrics become per-segment for
  free.

``update(..., slot=segment_ids)`` is ONE ``segment_sum``-style scatter of the
inner metric's per-sample deltas (:func:`slab_scatter`), ``compute()`` vmaps
the inner finisher over the slab, and — the point of the design — sync rides
the existing per-dtype coalesced buckets of
:func:`~metrics_tpu.parallel.sync.coalesced_sync_state` UNCHANGED: a slab is
a plain array (or sketch) leaf with a ``sum``/``min``/``max`` reduction, so
one bucketed ``psum`` moves all K segments, flat and hierarchical, with zero
new collective kinds. Collective counts are K-independent by construction
(``bench.py --check-collectives`` pins it).

:class:`SlabSpec` is the host-side state declaration ``Metric.add_state``
materializes (the slab analogue of ``_BufferSpec``/``SketchSpec``);
:class:`LRUSlotTable` maps open-ended key spaces (user ids, experiment arms)
onto the fixed K slots with least-recently-used eviction. The user-facing
wrapper is :class:`metrics_tpu.wrappers.keyed.Keyed`.
"""
import threading
from collections import OrderedDict
from typing import Any, Hashable, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.observability.counters import record_cache
from metrics_tpu.parallel.cms import CountMinSketch
from metrics_tpu.parallel.qsketch import QuantileSketch
from metrics_tpu.parallel.sketch import HistogramSketch, RankSketch, is_sketch

__all__ = [
    "LRUSlotTable",
    "PARTIAL_SCHEMA_VERSION",
    "SLAB_REDUCES",
    "SLAB_SKETCH_KINDS",
    "SlabProgramCache",
    "SlabSpec",
    "bucket_size",
    "check_partial_version",
    "dropped_slot_count",
    "is_slab_spec",
    "make_slab_spec",
    "pad_samples",
    "pad_slot_ids",
    "shared_ingest_program",
    "slab_init",
    "slab_merge",
    "slab_rows_spec",
    "slab_scatter",
    "slab_sync_reduce",
    "slab_take_rows",
    "slab_touched_mask",
]

# The mergeable-partial WIRE FORMAT version. Stamped into every partial the
# wrappers emit (``Windowed.window_partial``, ``Keyed.mergeable_partial``)
# and VALIDATED at every ingest point (``merge_partials``,
# ``value_from_partials``, the retention store's bank) — partials outlive
# the process that produced them (fleet queues, retention tiers), so a
# silent format drift must fail loudly, not merge garbage. Bump it whenever
# a partial's keys or leaf layout change meaning.
PARTIAL_SCHEMA_VERSION = 1


def check_partial_version(partial: Any) -> Any:
    """Validate one mergeable partial's wire-format version, loudly.

    Every ingest point that banks or merges partials produced elsewhere (the
    fleet merge tier, the retention store, ``merge_partials``/
    ``value_from_partials``) runs this first: a partial without a ``version``
    stamp, or with a stamp from another schema generation, must fail HERE —
    silently merging a drifted layout would corrupt every downstream
    roll-up. Returns the partial unchanged so call sites can chain it.
    """
    if not isinstance(partial, dict) or "state" not in partial or "rows" not in partial:
        raise ValueError(
            "not a mergeable partial (expected a dict with 'version', 'rows'"
            f" and 'state' keys): {type(partial).__name__}"
        )
    version = partial.get("version")
    if version != PARTIAL_SCHEMA_VERSION:
        raise ValueError(
            f"mergeable-partial schema version mismatch: got {version!r},"
            f" this library speaks version {PARTIAL_SCHEMA_VERSION} —"
            " refusing to merge a drifted wire format"
        )
    return partial

# per-slot reduce kinds a slab row supports. "mean" is SUM-BACKED: the slab
# stores the running sum of per-sample deltas and the finisher divides by the
# per-slot row count — which is what lets a mean-kind slab merge by addition
# and sync through the same bucketed psum as every sum leaf.
SLAB_REDUCES = ("sum", "mean", "min", "max")

# sketch slab kinds: the slab keeps the sketch TYPE with a leading (K, ...)
# counts axis. "qsketch" rows are log-bucketed quantile sketches — what
# Keyed(Quantile(q=0.99)) turns per-tenant latency into. "cms" rows are
# count-min grids (one (depth, width, *item) counts leaf per slot) — the
# windowed form of the constant-memory tail, merge = elementwise add like
# every other sketch kind.
_SKETCH_KINDS = {
    "hist": HistogramSketch,
    "rank": RankSketch,
    "qsketch": QuantileSketch,
    "cms": CountMinSketch,
}
SLAB_SKETCH_KINDS = tuple(_SKETCH_KINDS)


class SlabSpec(NamedTuple):
    """Host-side slab state declaration (recorded in ``Metric._defaults``).

    ``kind``: ``"array"`` for a plain ``(K, *item_shape)`` slab, or
    ``"hist"``/``"rank"`` for a sketch slab (counts grow the leading K axis).
    ``reduce`` is the PER-SLOT reduce kind (one of :data:`SLAB_REDUCES`;
    sketches are always ``"sum"``). ``fill`` is the inner metric's per-slot
    default template (host numpy), broadcast to every row at init — for
    ``min``/``max`` rows this preserves the inner default's clamping
    semantics exactly (min/max are idempotent, so re-including the default
    per batch changes nothing); ``sum``/``mean`` rows require a zero
    template (a nonzero additive default would be re-added once per SAMPLE
    instead of once per batch). Pure config: materialization is
    :func:`slab_init`, and the spec is fingerprintable so slab metrics can
    share compiled steps and compute-group keys.
    """

    kind: str
    num_slots: int
    item_shape: Tuple[int, ...]
    dtype: Any
    reduce: str
    fill: Optional[bytes] = None  # raveled template bytes (hashable; None = zeros)

    @property
    def row_shape(self) -> Tuple[int, ...]:
        return (self.num_slots, *self.item_shape)

    def fill_template(self) -> np.ndarray:
        """The per-slot init template as host numpy."""
        if self.fill is None:
            return np.zeros(self.item_shape, dtype=np.dtype(self.dtype))
        return np.frombuffer(self.fill, dtype=np.dtype(self.dtype)).reshape(self.item_shape)


def is_slab_spec(value: Any) -> bool:
    return isinstance(value, SlabSpec)


def make_slab_spec(
    num_slots: int,
    template: np.ndarray,
    reduce: str,
    kind: str = "array",
) -> SlabSpec:
    """Validate and build one :class:`SlabSpec` from the inner state's host
    template. Sum/mean templates must be zero (see the class docstring)."""
    if kind != "array" and kind not in _SKETCH_KINDS:
        raise ValueError(
            f"slab kind must be 'array' or one of {SLAB_SKETCH_KINDS}, got {kind!r}"
        )
    if reduce not in SLAB_REDUCES:
        raise ValueError(f"slab reduce must be one of {SLAB_REDUCES}, got {reduce!r}")
    if not isinstance(num_slots, int) or num_slots < 1:
        raise ValueError(f"`num_slots` must be a positive int, got {num_slots!r}")
    template = np.asarray(template)
    fill: Optional[bytes] = None
    if reduce in ("sum", "mean") or kind in _SKETCH_KINDS:
        if np.any(template != 0):
            raise ValueError(
                f"a {reduce!r}-kind slab needs a zero default template (the per-sample"
                " scatter would re-add a nonzero default once per sample); got a"
                " nonzero template"
            )
    elif np.any(template != 0):
        fill = template.tobytes()
    return SlabSpec(kind, num_slots, tuple(template.shape), template.dtype, reduce, fill)


def slab_rows_spec(num_slots: int, dtype: Any = None) -> SlabSpec:
    """The per-slot sample-count slab every ``Keyed`` wrapper carries: a
    ``(K,)`` sum slab backing occupancy masks (empty-slot policy) and the
    sum-backed mean division."""
    if dtype is None:
        from metrics_tpu.utils.data import accum_int_dtype

        dtype = accum_int_dtype()
    return SlabSpec("array", num_slots, (), np.dtype(dtype), "sum", None)


def slab_init(spec: SlabSpec):
    """Fresh slab for ``spec`` (jit-safe: zeros and host-template broadcasts
    stage as compile-time constants under tracing)."""
    if spec.kind in _SKETCH_KINDS:
        return _SKETCH_KINDS[spec.kind](jnp.zeros(spec.row_shape, dtype=spec.dtype))
    if spec.fill is None:
        return jnp.zeros(spec.row_shape, dtype=spec.dtype)
    template = jnp.asarray(spec.fill_template())
    return jnp.broadcast_to(template[None], spec.row_shape) + jnp.zeros((), dtype=spec.dtype)


def slab_scatter(reduce: str, deltas: Array, slot_ids: Array, num_slots: int) -> Array:
    """``(N, *s)`` per-sample deltas -> ``(K, *s)`` per-slot reduction: the
    one-scatter update plane of every slab state.

    ``sum``/``mean`` rows scatter-add (``jax.ops.segment_sum``); ``min``/
    ``max`` rows scatter-min/max, whose empty segments come back as the
    reduce identity (+-inf / iinfo extremes) and therefore vanish in the
    merge with the accumulator. Out-of-range slot ids (negative or >= K) are
    DROPPED — XLA scatter out-of-bounds semantics, documented and tested, so
    a bad segment id can never corrupt another segment's row.
    """
    if reduce in ("sum", "mean"):
        return jax.ops.segment_sum(deltas, slot_ids, num_segments=num_slots)
    if reduce == "min":
        return jax.ops.segment_min(deltas, slot_ids, num_segments=num_slots)
    if reduce == "max":
        return jax.ops.segment_max(deltas, slot_ids, num_segments=num_slots)
    raise ValueError(f"slab reduce must be one of {SLAB_REDUCES}, got {reduce!r}")


def slab_touched_mask(slot_ids: Array, num_slots: int) -> Array:
    """``(K,)`` bool mask of the slab rows a batch's scatter touched.

    The per-step touched-row bitmap of the sparse delta-sync plane
    (:class:`~metrics_tpu.parallel.sparse.SparseSyncPlane`): the rows slab
    already knows which slot ids a batch wrote, so the mask is one more
    ``segment_sum`` over the same ids. Out-of-range ids are dropped by the
    same XLA scatter semantics as :func:`slab_scatter` — a dropped sample
    never marks a row touched, matching the row it never wrote. Jit-safe;
    masks from several updates in a round combine with ``|``.
    """
    ids = jnp.ravel(slot_ids)
    ones = jnp.ones(ids.shape, dtype=jnp.int32)
    return jax.ops.segment_sum(ones, ids, num_segments=num_slots) > 0


def dropped_slot_count(slot_ids: Any, num_slots: int) -> int:
    """How many of ``slot_ids`` fall outside ``[0, num_slots)`` — the samples
    :func:`slab_scatter` silently DROPS by XLA out-of-bounds semantics.

    Host-side by design (one readback of the small id vector on the eager
    path; never call under tracing): the drop itself is a device-side
    non-event, so the evidence has to come from the ids. Call sites feed
    ``observability.counters.record_slab_dropped`` — which, like the fault
    counters, records even with observability off — so a vanished sample
    always leaves a trail. The windowed plane's too-late events reuse this
    path deliberately (slot ``-1`` = drop-and-count, never misroute).
    """
    ids = np.asarray(slot_ids).reshape(-1)
    if ids.size == 0:
        return 0
    return int(((ids < 0) | (ids >= num_slots)).sum())


def slab_take_rows(value: Any, slots: Any) -> Any:
    """The stacked ``(len(slots), *item)`` row payloads of the given slots —
    sketch-aware (sketch slabs return their raw counts rows).

    This is the DEMOTION FOLD's read: ``HeavyHitters`` extracts a demoted
    key's exact slab rows with it and scatters them into the count-min tail
    BEFORE the slot is reset, so eviction conserves mass instead of
    destroying history (contrast ``Keyed``'s LRU eviction, which zeroes the
    recycled row and can only count what it lost).
    """
    idx = jnp.asarray(np.asarray(slots, dtype=np.int32))
    if is_sketch(value):
        return value.counts[idx]
    return value[idx]


def slab_merge(reduce: str, acc: Array, delta: Array) -> Array:
    """Pairwise slab merge under the per-slot reduce kind (mean is
    sum-backed, so it adds). Identity rows from :func:`slab_scatter`'s empty
    segments are absorbed: ``min(acc, +inf) == acc``."""
    if reduce in ("sum", "mean"):
        return acc + delta
    if reduce == "min":
        return jnp.minimum(acc, delta)
    if reduce == "max":
        return jnp.maximum(acc, delta)
    raise ValueError(f"slab reduce must be one of {SLAB_REDUCES}, got {reduce!r}")


def slab_sync_reduce(reduce: str) -> str:
    """The ``dist_reduce_fx`` a slab state registers: mean folds into sum
    (sum-backed), everything else passes through — which is exactly why slab
    leaves ride the existing psum/pmin/pmax buckets with zero new collective
    kinds."""
    return "sum" if reduce in ("sum", "mean") else reduce


class LRUSlotTable:
    """Host-side key -> slot map for open-ended segment spaces.

    Maps arbitrary hashable segment keys (user cohorts, experiment arms,
    model-version strings) onto the fixed ``num_slots`` slab rows. When the
    table is full, the least-recently-used key is evicted and its slot is
    recycled; the caller must reset the recycled rows (``Keyed`` does) and
    the lifetime ``evictions`` counter feeds the observability gauge.
    Resolution is eager host work by construction — the whole point of the
    table is data-dependent key management jit cannot express; the scatter
    that CONSUMES the resolved int ids stays jittable.
    """

    def __init__(self, num_slots: int):
        if not isinstance(num_slots, int) or num_slots < 1:
            raise ValueError(f"`num_slots` must be a positive int, got {num_slots!r}")
        self.num_slots = num_slots
        self._map: "OrderedDict[Hashable, int]" = OrderedDict()  # LRU -> MRU
        self._free: List[int] = list(range(num_slots - 1, -1, -1))  # pop() ascends
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._map)

    def __contains__(self, key: Hashable) -> bool:
        return key in self._map

    def keys(self) -> Tuple[Hashable, ...]:
        """Current keys, least- to most-recently-used."""
        return tuple(self._map)

    def slot_of(self, key: Hashable) -> int:
        """Resolve one key WITHOUT touching recency (reads are not uses)."""
        if key not in self._map:
            raise KeyError(
                f"segment key {key!r} is not resident (evicted or never seen); "
                f"{len(self._map)}/{self.num_slots} slots occupied"
            )
        return self._map[key]

    def resolve(self, keys: Sequence[Hashable]) -> Tuple[np.ndarray, List[int]]:
        """Map a batch of keys to slot ids, evicting LRU keys as needed.

        Returns ``(slot_ids int32 (N,), evicted_slots)`` — the caller resets
        the evicted slots' slab rows BEFORE scattering. A batch that needs
        more distinct slots than the table holds would have to recycle a slot
        already written by this same batch (silent cross-segment corruption),
        so it raises instead.
        """
        slots = np.empty(len(keys), dtype=np.int32)
        assigned_this_batch: set = set()
        evicted: List[int] = []
        for i, key in enumerate(keys):
            slot = self._map.pop(key, None)  # pop + reinsert = touch (MRU)
            if slot is None:
                if self._free:
                    slot = self._free.pop()
                else:
                    old_key, slot = next(iter(self._map.items()))
                    if old_key in assigned_this_batch:
                        raise ValueError(
                            f"one batch touches more than num_slots={self.num_slots}"
                            " distinct segment keys; evicting a key written by this"
                            " same batch would corrupt its rows. Raise num_slots or"
                            " split the batch."
                        )
                    del self._map[old_key]
                    evicted.append(slot)
                    self.evictions += 1
            self._map[key] = slot
            assigned_this_batch.add(key)
            slots[i] = slot
        return slots, evicted

    def state(self) -> dict:
        """Checkpointable view: keys in LRU order + their slots + evictions."""
        return {
            "keys": list(self._map.keys()),
            "slots": np.asarray(list(self._map.values()), dtype=np.int64),
            "evictions": np.asarray(self.evictions, dtype=np.int64),
        }

    def load_state(self, state: dict) -> None:
        self._map = OrderedDict(
            (key, int(slot)) for key, slot in zip(state["keys"], np.asarray(state["slots"]))
        )
        used = set(self._map.values())
        self._free = [s for s in range(self.num_slots - 1, -1, -1) if s not in used]
        self.evictions = int(state["evictions"])

    def reset(self) -> None:
        """Forget every key (the epoch-reset path). The lifetime eviction
        count is deliberately kept — it is a process gauge, not epoch state."""
        self._map.clear()
        self._free = list(range(self.num_slots - 1, -1, -1))


# ---------------------------------------------------------------------------
# Bucketed compiled routing: the ingest fast path's shape-stability plane.
#
# Queue-drain coalescing (``serving/service.py``) produces VARIABLE sample
# counts — one drain might fold 3 batches of 32, the next 7 of 64 — and a
# jitted scatter program keyed on the exact sample count would retrace on
# every new size. The fix is the classic bucketing trick: pad the sample axis
# up to the next power of two and compile ONE program per (bucket, tree
# structure). Padded rows carry slot id ``-1``, which XLA scatter DROPS by
# out-of-bounds semantics (`slab_scatter`), so padding is arithmetic-free:
# the dropped rows never touch a slab row and the per-slot sums are
# bit-identical to the unpadded eager scatter.
# ---------------------------------------------------------------------------


def bucket_size(n: int, minimum: int = 8) -> int:
    """The padded sample count for a batch of ``n``: the next power of two,
    floored at ``minimum`` so tiny drains share one program instead of
    compiling 1/2/4-sample variants."""
    if n < 1:
        raise ValueError(f"bucket_size needs a positive sample count, got {n}")
    size = minimum
    while size < n:
        size *= 2
    return size


def pad_samples(arr: Any, bucket: int) -> np.ndarray:
    """Zero-pad ``arr``'s leading (sample) axis up to ``bucket`` rows.

    The pad VALUE is irrelevant by construction — padded rows scatter to
    slot ``-1`` and are dropped before they meet a slab row — zeros merely
    keep the pad cheap and dtype-exact. The pad runs in HOST numpy on
    purpose: eager ``jnp`` pads would compile a tiny XLA program per
    DISTINCT unpadded ``n`` (exactly the shape churn bucketing exists to
    kill); a numpy operand crosses to the device once, at the compiled
    program's boundary, where only the bucket shape is visible.
    """
    a = np.asarray(arr)
    n = a.shape[0]
    if n == bucket:
        return a
    out = np.zeros((bucket,) + a.shape[1:], dtype=a.dtype)
    out[:n] = a
    return out


def pad_slot_ids(slot_ids: Any, bucket: int) -> np.ndarray:
    """Pad a host-side ``(n,)`` slot-id vector to ``(bucket,)`` with the
    dropped sentinel ``-1`` — the rows XLA scatter ignores."""
    ids = np.asarray(slot_ids, dtype=np.int32).reshape(-1)
    if ids.shape[0] == bucket:
        return ids
    out = np.full(bucket, -1, dtype=np.int32)
    out[: ids.shape[0]] = ids
    return out


# Process-wide jit-callable sharing for config-identical wrappers (the
# collection analogue is ``_COL_STEP_CACHE``): an 8-shard fleet builds 8
# config-identical Windowed metrics, and without sharing each shard worker
# re-traces and re-compiles the same routed-scatter program INSIDE its
# ingest loop — the XLA compile lock then serializes the shards (the exact
# "something global serializes the shard workers" the fleet scaling gate
# watches for). The registry shares the jit CALLABLE, so jax's own
# signature cache makes every (bucket, dtypes) compile happen once per
# process; per-instance ``SlabProgramCache`` hit/miss accounting is
# unchanged. Entries keep their key's ``pins`` alive so id()-based key
# material is never recycled while the entry lives.
_SHARED_INGEST_PROGRAMS: dict = {}
_SHARED_INGEST_PROGRAMS_MAX = 128
_SHARED_INGEST_PROGRAMS_LOCK = threading.Lock()


def shared_ingest_program(key: Hashable, pins: list, build) -> Any:
    """The process-wide jit callable for ``key``, building on first touch.

    ``pins`` are the objects whose ``id()`` appears in ``key`` (the inner
    metric's config fingerprint pins); the entry holds them so the key stays
    valid. Insertion is bounded: oldest entries fall off at the cap."""
    with _SHARED_INGEST_PROGRAMS_LOCK:
        entry = _SHARED_INGEST_PROGRAMS.get(key)
        if entry is None:
            entry = (pins, build())
            while len(_SHARED_INGEST_PROGRAMS) >= _SHARED_INGEST_PROGRAMS_MAX:
                _SHARED_INGEST_PROGRAMS.pop(next(iter(_SHARED_INGEST_PROGRAMS)))
            _SHARED_INGEST_PROGRAMS[key] = entry
        return entry[1]


class SlabProgramCache:
    """Per-wrapper cache of compiled routed-scatter programs, keyed on
    (bucket, tree structure).

    Steady state is a handful of entries — one per occupied sample bucket —
    and the pinned invariant (``bench.py --check-ingest``) is that misses
    stop growing once the buckets are warm. Hits and misses feed the
    ``ingest_program_cache`` counter block via
    :func:`~metrics_tpu.observability.counters.record_cache`.

    Compiled programs hold donated device buffers and jit callables, which
    are neither deep-copyable nor picklable — and wrapper metrics DO get
    deep-copied (``MetricCollection``, checkpoint round-trips). The cache
    therefore deliberately copies/pickles as EMPTY: a restored metric simply
    recompiles on first touch, which is correct (the programs are pure
    derived state) and cheap (one trace per bucket).
    """

    def __init__(self) -> None:
        self._programs: dict = {}

    def __len__(self) -> int:
        return len(self._programs)

    def get(self, key: Hashable, build) -> Any:
        """The cached program for ``key``, building (and counting a miss)
        on first touch."""
        program = self._programs.get(key)
        if program is not None:
            record_cache("ingest_program", hit=True)
            return program
        record_cache("ingest_program", hit=False)
        program = build()
        self._programs[key] = program
        return program

    def clear(self) -> None:
        self._programs.clear()

    def __deepcopy__(self, memo: dict) -> "SlabProgramCache":
        return SlabProgramCache()

    def __reduce__(self):
        return (SlabProgramCache, ())
