"""Exact epoch compute over *sharded* epoch data — O(N/n) per-device memory.

The reference computes epoch metrics on gathered data: every rank materializes
the full epoch before compute (reference torchmetrics/metric.py:188-197), and
round-2's in-jit plane kept that shape (``buffer_all_gather`` replicates the
union). At pod scale that is O(dataset) per device. This module keeps the
epoch sharded *through* compute:

- **Curve scalars (AUROC / average precision)**: a ring pass. Each device
  sorts its local shard once, then the sorted pack circulates over the mesh
  axis via ``lax.ppermute`` (n-1 hops riding ICI, ring-attention style). At
  each hop a device accumulates, for every local element, the visiting
  shard's weight-below / tie / weight-≥ statistics via ``searchsorted`` on
  the sorted pack. After the ring:

  * AUROC is the Mann-Whitney U statistic — per positive item, the global
    negative weight strictly below its score plus half the tied weight;
    ``U / (P·N)`` equals sklearn's trapezoidal ROC area exactly (a tie-run's
    diagonal segment is exactly half credit).
  * AP is the per-item form of the step integral: each positive contributes
    ``w · TP≥/(TP≥+FP≥)`` at its score's tie-run end; summed and divided by
    total positive weight this is exactly ``Σ (R_n−R_{n−1})·P_n`` (reference
    functional/classification/average_precision.py:46-52), because every
    positive in a tie-run sees the run-final cumulative counts — the same
    run-end snapping as ``curve_static.py``, distributed.

  Per-device memory stays O(N/n); compute is O((N/n)·log(N/n)·n).

- **Retrieval (grouped per-query) metrics**: an ``all_to_all`` regroup. Rows
  route to shard ``query_id mod n`` through static-capacity buckets (overflow
  is counted, never silent), so each query lands wholly on one shard; each
  shard then runs the SAME vectorized grouped engine the single-device path
  uses (``RetrievalMetric._device_sums``) on its local queries, and one
  ``psum`` of (score-total, query-count) yields the exact global mean.

Use inside ``shard_map`` over the data axis. All functions are jit-safe,
static-shape, and collective-only (no host round trips).

Every engine's ``axis_name`` may also be a
:class:`~metrics_tpu.parallel.placement.MeshHierarchy` over a 2-level
(ici x dcn) mesh. The rings then stay ICI-LOCAL with a single DCN exchange:
each device's sorted pack (or raw rows, for Kendall) crosses DCN exactly
once via one ``all_gather`` over the dcn axis, and the ring circulates the
cross-slice stack over the ici axis only — per-payload DCN traffic drops
from W-1 ring hops to S-1. The retrieval regroup becomes two staged
``all_to_all``s (slice routing over dcn, then device routing over ici), and
scalar reductions psum ici-first.
"""
from typing import Any, NamedTuple, Optional, Tuple, Union

import jax
import jax.numpy as jnp
from jax import Array

from metrics_tpu.observability.counters import record_collective
from metrics_tpu.observability.jaxprof import annotate
from metrics_tpu.parallel.placement import MeshHierarchy
from metrics_tpu.utils.compat import axis_size, ensure_varying

# pad query id for regroup ghost rows; real query ids must not use it
PAD_QUERY_ID = jnp.iinfo(jnp.int32).max

# an engine axis: one named mesh axis, or the 2-level hierarchy
EngineAxis = Union[str, MeshHierarchy]


# varying-manual-axes marking is jax-version dependent; see utils/compat.py
_ensure_varying = ensure_varying


def _varying(x: Any, axis: EngineAxis) -> Any:
    """``ensure_varying`` over one axis or both levels of a hierarchy."""
    if isinstance(axis, MeshHierarchy):
        return ensure_varying(ensure_varying(x, axis.ici_axis), axis.dcn_axis)
    return ensure_varying(x, axis)


def _axis_world(axis: EngineAxis) -> int:
    """World size the engine spans (trace-time)."""
    if isinstance(axis, MeshHierarchy):
        return axis_size(axis.ici_axis) * axis_size(axis.dcn_axis)
    return axis_size(axis)


def _engine_psum(x: Array, axis: EngineAxis) -> Array:
    """``psum`` over the engine axis — ici-first under a hierarchy, so only
    the per-slice partial sums cross DCN."""
    if isinstance(axis, MeshHierarchy):
        record_collective("psum", x, crossing="ici", fanout=axis_size(axis.ici_axis))
        x = jax.lax.psum(x, axis.ici_axis)
        record_collective("psum", x, crossing="dcn", fanout=axis_size(axis.dcn_axis))
        return jax.lax.psum(x, axis.dcn_axis)
    record_collective("psum", x, fanout=_axis_world(axis))
    return jax.lax.psum(x, axis)


class _SortedPack(NamedTuple):
    """One shard's sorted scores + cumulative class weights (the ring payload)."""

    scores: Array  # (m,) ascending
    cum_wp: Array  # (m,) cumulative positive weight
    cum_wn: Array  # (m,) cumulative negative weight


def _pack(preds: Array, target: Array, weights: Array) -> _SortedPack:
    order = jnp.argsort(preds)
    s = preds[order]
    y = target[order].astype(jnp.float32)
    w = weights[order].astype(jnp.float32)
    return _SortedPack(s, jnp.cumsum(w * y), jnp.cumsum(w * (1.0 - y)))


def _below_tie_ge(pack: _SortedPack, q: Array) -> Tuple[Array, Array, Array, Array]:
    """Per query score: visiting-shard weight sums (neg-below, neg-tied,
    pos-≥, neg-≥) — the four statistics AUROC/AP need."""
    left = jnp.searchsorted(pack.scores, q, side="left")
    right = jnp.searchsorted(pack.scores, q, side="right")

    def at(cum: Array, i: Array) -> Array:
        return jnp.where(i > 0, cum[jnp.maximum(i - 1, 0)], 0.0)

    wn_below = at(pack.cum_wn, left)
    wn_tie = at(pack.cum_wn, right) - wn_below
    wp_ge = pack.cum_wp[-1] - at(pack.cum_wp, left)
    wn_ge = pack.cum_wn[-1] - wn_below
    return wn_below, wn_tie, wp_ge, wn_ge


def _ring_stats_cols(
    preds_cm: Array, target_cm: Array, weights_cm: Array, axis_name: EngineAxis
) -> Tuple[Array, Array, Array, Array]:
    """Per-class ring statistics for ``(C, m)`` column-major shards.

    One ``ppermute`` of the STACKED pack per hop (a single (C, m)-sized ICI
    transfer, not C small ones); the searchsorted accumulation vmaps over the
    class axis. Returns four ``(C, m)`` arrays. A :class:`MeshHierarchy`
    axis runs the hierarchical variant below instead.
    """
    if isinstance(axis_name, MeshHierarchy):
        return _ring_stats_cols_hier(preds_cm, target_cm, weights_cm, axis_name)
    n = axis_size(axis_name)
    pack = jax.vmap(_pack)(preds_cm, target_cm, weights_cm)
    perm = [(j, (j + 1) % n) for j in range(n)]

    def body(_, carry):
        acc, visiting = carry
        visiting = jax.lax.ppermute(visiting, axis_name, perm)
        acc = tuple(a + b for a, b in zip(acc, jax.vmap(_below_tie_ge)(visiting, preds_cm)))
        return acc, visiting

    # one ppermute of the 3-leaf pack staged per loop body (n-1 executed hops)
    for leaf in pack:
        record_collective("ppermute", leaf, fanout=n)
    # local contribution first, then n-1 ring hops (no dead final collective);
    # the named scope labels the ring's ops on the device timeline so a
    # profiler session attributes the hop kernels to the engine phase
    with annotate("sharded.engine.ring"):
        acc = jax.vmap(_below_tie_ge)(pack, preds_cm)
        (acc, _) = jax.lax.fori_loop(0, n - 1, body, (acc, pack))
    return acc


def _ring_stats_cols_hier(
    preds_cm: Array, target_cm: Array, weights_cm: Array, h: MeshHierarchy
) -> Tuple[Array, Array, Array, Array]:
    """The ICI-local ring with a single DCN exchange.

    Each device's sorted pack crosses DCN exactly ONCE: one ``all_gather``
    over the dcn axis stacks the same-ici-position packs of every slice,
    and the ring then circulates that ``(S, C, m)`` stack over the ici axis
    only (L-1 hops). Every device still accumulates against all S*L packs
    exactly once, so results are bit-identical to the flat ring — only the
    crossing structure changes (DCN: one p-sized exchange instead of
    carrying the pack across slice boundaries on W-1 hops).
    """
    L = axis_size(h.ici_axis)
    S = axis_size(h.dcn_axis)
    pack = jax.vmap(_pack)(preds_cm, target_cm, weights_cm)
    for leaf in pack:
        record_collective("all_gather", leaf, crossing="dcn", fanout=S)
    packs = _SortedPack(*(jax.lax.all_gather(leaf, h.dcn_axis) for leaf in pack))  # (S, C, m)

    def acc_stack(stack: _SortedPack) -> Tuple[Array, ...]:
        # sum the per-slice-pack statistics: vmap over the S stacked packs,
        # the inner per-class vmap matching the flat ring's accumulation
        outs = jax.vmap(lambda pk: jax.vmap(_below_tie_ge)(pk, preds_cm))(stack)
        return tuple(jnp.sum(o, axis=0) for o in outs)

    perm = [(j, (j + 1) % L) for j in range(L)]

    def body(_, carry):
        acc, visiting = carry
        visiting = jax.lax.ppermute(visiting, h.ici_axis, perm)
        acc = tuple(a + b for a, b in zip(acc, acc_stack(visiting)))
        return acc, visiting

    for leaf in packs:
        record_collective("ppermute", leaf, crossing="ici", fanout=L)
    with annotate("sharded.engine.ring"):
        acc = acc_stack(packs)
        (acc, _) = jax.lax.fori_loop(0, L - 1, body, (acc, packs))
    return acc


def _cols(preds: Array, target: Array, weights: Optional[Array]) -> Tuple[Array, Array, Array]:
    """Broadcast ``(m, C)`` inputs (+ per-row or per-row-per-class weights)
    to the ``(C, m)`` column-major layout the ring engine runs on."""
    preds_cm = preds.T
    target_cm = target.T.astype(jnp.float32)
    if weights is None:
        w_cm = jnp.ones_like(preds_cm)
    else:
        w = weights.astype(jnp.float32)
        w_cm = jnp.broadcast_to(w[:, None], preds.shape).T if w.ndim == 1 else w.T
    return preds_cm, target_cm, w_cm


def sharded_auroc_matrix(
    preds: Array, target: Array, axis_name: str, sample_weights: Optional[Array] = None,
    with_support: bool = False,
) -> Array:
    """Exact per-class AUROCs over epoch data sharded along ``axis_name``.

    ``preds``/``target`` are the LOCAL ``(m, C)`` shard (one-vs-rest binary
    targets per column); returns the ``(C,)`` class scores, each matching
    ``sklearn.metrics.roc_auc_score`` on that column of the concatenated
    epoch — cross-shard ties included. ``nan`` where a column is
    single-class globally. ``sample_weights`` is per-row ``(m,)`` or
    per-row-per-class ``(m, C)``; zero weight neutralizes a row (padding).
    ``with_support=True`` additionally returns the ``(C,)`` global positive
    weight — it rides the engine's own coalesced collective for free.
    """
    preds_cm, y, w = _cols(preds, target, sample_weights)
    wn_below, wn_tie, _, _ = _ring_stats_cols(preds_cm, y, w, axis_name)
    wp = w * y
    u_local = jnp.sum(wp * (wn_below + 0.5 * wn_tie), axis=-1)
    # one coalesced collective for all three reductions (collectives are
    # latency-bound at these sizes; see parallel.sync.coalesced_sync_state)
    stacked = jnp.stack([u_local, jnp.sum(wp, axis=-1), jnp.sum(w * (1.0 - y), axis=-1)])
    u, pos, neg = _engine_psum(stacked, axis_name)
    denom = pos * neg
    scores = jnp.where(denom == 0, jnp.nan, u / jnp.where(denom == 0, 1.0, denom))
    return (scores, pos) if with_support else scores


def sharded_average_precision_matrix(
    preds: Array, target: Array, axis_name: str, sample_weights: Optional[Array] = None,
    with_support: bool = False,
) -> Array:
    """Exact per-class average precision over sharded ``(m, C)`` epoch data
    (see module docstring for the per-item identity). ``(C,)`` scores; ``nan``
    where a column has zero positive weight globally. ``with_support=True``
    additionally returns the ``(C,)`` global positive weight from the same
    coalesced collective."""
    preds_cm, y, w = _cols(preds, target, sample_weights)
    _, _, wp_ge, wn_ge = _ring_stats_cols(preds_cm, y, w, axis_name)
    wp = w * y
    contrib = jnp.sum(wp * wp_ge / jnp.maximum(wp_ge + wn_ge, 1e-38), axis=-1)
    stacked = jnp.stack([contrib, jnp.sum(wp, axis=-1)])
    total, pos = _engine_psum(stacked, axis_name)
    scores = jnp.where(pos == 0, jnp.nan, total / jnp.where(pos == 0, 1.0, pos))
    return (scores, pos) if with_support else scores


def sharded_auroc(
    preds: Array, target: Array, axis_name: str, sample_weights: Optional[Array] = None
) -> Array:
    """Exact binary AUROC over epoch data sharded along ``axis_name``.

    Call inside ``shard_map``; ``preds``/``target`` are the LOCAL shard.
    Matches ``sklearn.metrics.roc_auc_score`` on the concatenated epoch,
    including cross-shard score ties. ``nan`` when a class is absent
    globally. Rows can be neutralized with ``sample_weights=0`` (padding).
    """
    w = None if sample_weights is None else sample_weights[:, None]
    return sharded_auroc_matrix(preds[:, None], target[:, None], axis_name, w)[0]


def sharded_average_precision(
    preds: Array, target: Array, axis_name: str, sample_weights: Optional[Array] = None
) -> Array:
    """Exact binary average precision over epoch data sharded along
    ``axis_name`` (see module docstring for the per-item identity).

    Matches the reference step integral / ``sklearn.average_precision_score``
    on the concatenated epoch. ``nan`` with zero positive weight.
    """
    w = None if sample_weights is None else sample_weights[:, None]
    return sharded_average_precision_matrix(preds[:, None], target[:, None], axis_name, w)[0]


def sharded_clf_curve_matrix(
    preds_cm: Array, target_cm: Array, weights_cm: Array, axis_name: str
) -> Tuple[Array, Array, Array, Array]:
    """Replicated compacted global clf-curves from ``(C, m)`` column-major
    sharded epoch rows — the distributed route to curve VECTORS.

    The counting stays sharded: the ring computes, for every LOCAL row, the
    GLOBAL positive/negative weight at-or-above its score (tie-run-end
    semantics built in — every member of a cross-shard tie sees the full
    tied weight). Only the finished per-row curve points ``(score, tps,
    fps)`` are then ``all_gather``-ed and key-sorted — O(N) per device for
    the OUTPUT itself, which any replicated capacity-length curve costs by
    definition; the epoch never materializes for counting, and per-device
    transient compute stays O((N/n)·log + N·log N) with the heavy
    ``searchsorted`` accumulation distributed.

    Targets are already 0/1 per class; zero weight marks ghost rows (they
    sort last at ``-inf`` and are never run-final — real rows must not
    score ``-inf``, the ``curve_static`` contract). Returns
    ``(fps, tps, thresholds, counts)``: ``(C, N)`` replicated arrays with
    each class's distinct-threshold points (descending score) compacted to
    the front, tails repeating the final point, plus ``(C,)`` counts —
    exactly the ``binary_clf_curve_padded`` contract, per class.
    """
    from metrics_tpu.functional.classification.curve_static import _compact

    w = _varying(weights_cm, axis_name)
    p = jnp.where(w > 0, preds_cm, -jnp.inf)
    _, _, wp_ge, wn_ge = _ring_stats_cols(p, target_cm, w, axis_name)

    # the four (C, m) sort operands ride ONE coalesced all_gather: stacked to
    # (4, C, m) and gathered tiled along the row axis — same payload bytes,
    # one collective instead of four (small gathers are latency-bound). Under
    # a hierarchy the gather is two-staged (dcn exchange of the local rows,
    # ici replication of the cross-slice tile); the key-sort below makes the
    # concatenation order immaterial.
    stacked = jnp.stack([-p, wp_ge, wn_ge, w])
    if isinstance(axis_name, MeshHierarchy):
        record_collective(
            "coalesced_gather", stacked, crossing="dcn",
            fanout=axis_size(axis_name.dcn_axis),
        )
        gathered = jax.lax.all_gather(
            stacked, axis_name=axis_name.dcn_axis, axis=2, tiled=True
        )
        record_collective(
            "coalesced_gather", gathered, crossing="ici",
            fanout=axis_size(axis_name.ici_axis),
        )
        gathered = jax.lax.all_gather(
            gathered, axis_name=axis_name.ici_axis, axis=2, tiled=True
        )
    else:
        record_collective("coalesced_gather", stacked, fanout=_axis_world(axis_name))
        gathered = jax.lax.all_gather(stacked, axis_name=axis_name, axis=2, tiled=True)
    neg_s, tps, fps, wv = jax.lax.sort(
        (gathered[0], gathered[1], gathered[2], gathered[3]), num_keys=1
    )
    scores = -neg_s
    run_end = jnp.concatenate(
        [scores[:, 1:] != scores[:, :-1], jnp.ones((scores.shape[0], 1), bool)], axis=1
    ) & (wv > 0)
    counts = jnp.sum(run_end.astype(jnp.int32), axis=1)
    compact = jax.vmap(_compact)
    return (
        compact(fps, run_end, counts),
        compact(tps, run_end, counts),
        compact(scores, run_end, counts),
        counts,
    )


def sharded_rank(
    scores: Array, axis_name: str, sample_weights: Optional[Array] = None
) -> Array:
    """Global 1-based midranks (ties → average rank, scipy ``rankdata``
    semantics) of epoch rows sharded along ``axis_name``.

    Rank of a row = global weight strictly below its score plus half the
    global tied weight (self included) plus one half — for unit weights this
    is exactly ``below + (ties + 1) / 2``. ``sample_weights`` is a 0/1
    validity mask (ghost capacity rows get garbage ranks and must be masked
    by the caller); the same sorted-pack ring as AUROC, one extra use.
    """
    w = jnp.ones_like(scores, jnp.float32) if sample_weights is None else sample_weights
    w = _varying(w, axis_name)
    y = _varying(jnp.zeros_like(scores, jnp.float32), axis_name)
    below, tie, _, _ = _ring_stats_cols(scores[None, :], y[None, :], w[None, :], axis_name)
    return _midrank(below[0], tie[0])


def _midrank(below: Array, tie: Array) -> Array:
    """1-based average-of-ties rank from (weight strictly below, tied weight
    incl. self) — shared by ``sharded_rank`` and the stacked Spearman ring."""
    return below + (tie + 1.0) / 2.0


def sharded_spearman(
    preds: Array, target: Array, axis_name: str, sample_weights: Optional[Array] = None
) -> Array:
    """Exact Spearman rho over epoch rows sharded along ``axis_name``.

    Global midranks of both arrays via the sorted-pack ring, then one
    psum-reduced Pearson over the ranks — matches
    ``scipy.stats.spearmanr`` (Pearson of midranks, tie-corrected) on the
    concatenated epoch, cross-shard ties included. ``sample_weights`` is a
    0/1 validity mask. ``nan`` on zero rank variance (constant input) or an
    empty epoch, the scipy convention.
    """
    w = jnp.ones_like(preds, jnp.float32) if sample_weights is None else sample_weights
    w = _varying(w, axis_name)
    # one stacked (2, m) ring for both arrays: a single ppermute payload per
    # hop instead of two back-to-back rings (ring latency dominates at scale)
    stacked = jnp.stack([preds.astype(jnp.float32), target.astype(jnp.float32)])
    y2 = _varying(jnp.zeros_like(stacked), axis_name)
    w2 = jnp.broadcast_to(w, stacked.shape)
    below, tie, _, _ = _ring_stats_cols(stacked, y2, w2, axis_name)
    ranks = _midrank(below, tie)
    rx, ry = ranks[0], ranks[1]
    w_sum = jnp.sum(w)
    total = _engine_psum(w_sum, axis_name)
    # scale ranks to O(1) before the moment sums: correlation is affine-
    # invariant and raw ranks would push f32 accumulations to O(N^3)
    scale = 1.0 / jnp.maximum(total, 1.0)
    rx, ry = rx * scale, ry * scale
    # all five moment reductions ride ONE coalesced collective
    moments = jnp.stack([
        jnp.sum(w * rx), jnp.sum(w * ry),
        jnp.sum(w * rx * rx), jnp.sum(w * ry * ry), jnp.sum(w * rx * ry),
    ])
    sx, sy, sxx, syy, sxy = _engine_psum(moments, axis_name)
    cov = total * sxy - sx * sy
    var_x = total * sxx - sx * sx
    var_y = total * syy - sy * sy
    denom = jnp.sqrt(jnp.maximum(var_x, 0.0) * jnp.maximum(var_y, 0.0))
    bad = (denom == 0) | (total == 0)
    return jnp.where(bad, jnp.nan, cov / jnp.where(bad, 1.0, denom))


def sharded_kendall(
    preds: Array,
    target: Array,
    axis_name: str,
    sample_weights: Optional[Array] = None,
    chunk: int = 1024,
) -> Array:
    """Exact global Kendall tau-b over epoch rows sharded along ``axis_name``.

    The O(N^2) pairwise sign contraction distributed ring-attention style:
    raw ``(x, y, w)`` rows circulate over the mesh axis; at each hop every
    device contracts its local queries against the visiting shard in
    ``chunk``-row blocks (peak intermediate ``chunk x m``, never m x N).
    Per-device compute is O(N^2 / n) — the quadratic total cost split evenly.
    Matches ``scipy.stats.kendalltau`` (tau-b, tie-corrected) on the
    concatenated epoch. ``sample_weights`` is a 0/1 validity mask. ``nan``
    when either array is globally constant or the epoch is empty.
    """
    hier = isinstance(axis_name, MeshHierarchy)
    ring_axis = axis_name.ici_axis if hier else axis_name
    n = axis_size(ring_axis)
    m = preds.shape[0]
    x = preds.astype(jnp.float32)
    y = target.astype(jnp.float32)
    w = jnp.ones((m,), jnp.float32) if sample_weights is None else sample_weights.astype(jnp.float32)
    w = _varying(w, axis_name)

    chunk = min(chunk, m)
    n_chunks = -(-m // chunk)
    padded = n_chunks * chunk
    # pad queries to a chunk multiple so blocks are disjoint (ghost queries
    # compute garbage sums that the w-mask drops at the end)
    xq = jnp.pad(x, (0, padded - m))
    yq = jnp.pad(y, (0, padded - m))

    def contract(visiting, acc):
        xv, yv, wv = visiting

        def block(c, acc):
            s, tx, ty = acc
            start = c * chunk
            xc = jax.lax.dynamic_slice(xq, (start,), (chunk,))
            yc = jax.lax.dynamic_slice(yq, (start,), (chunk,))
            dx = jnp.sign(xc[:, None] - xv[None, :])
            dy = jnp.sign(yc[:, None] - yv[None, :])
            s_b = jnp.sum(dx * dy * wv, axis=-1)
            tx_b = jnp.sum((dx == 0) * wv, axis=-1)
            ty_b = jnp.sum((dy == 0) * wv, axis=-1)
            upd = lambda a, b: jax.lax.dynamic_update_slice(a, jax.lax.dynamic_slice(a, (start,), (chunk,)) + b, (start,))
            return upd(s, s_b), upd(tx, tx_b), upd(ty, ty_b)

        return jax.lax.fori_loop(0, n_chunks, block, acc)

    zeros = jnp.zeros_like(xq)  # derived from the shard: varying-axis typed
    if hier:
        # the single DCN exchange: each device's raw rows cross DCN once,
        # and the quadratic contraction rides the ICI-only ring with the
        # (S*m,)-row cross-slice stack as the visiting payload
        S = axis_size(axis_name.dcn_axis)

        def dgather(v: Array) -> Array:
            record_collective("all_gather", v, crossing="dcn", fanout=S)
            return jax.lax.all_gather(v, axis_name.dcn_axis).reshape(-1)

        visiting0 = (dgather(x), dgather(y), dgather(w))
    else:
        visiting0 = (x, y, w)
    for leaf in visiting0:
        record_collective("ppermute", leaf, crossing="ici" if hier else "world", fanout=n)
    acc = contract(visiting0, (zeros, zeros, zeros))
    perm = [(j, (j + 1) % n) for j in range(n)]

    def hop(_, carry):
        acc, visiting = carry
        visiting = jax.lax.ppermute(visiting, ring_axis, perm)
        return contract(visiting, acc), visiting

    (s_all, tx_all, ty_all), _ = jax.lax.fori_loop(0, n - 1, hop, (acc, visiting0))
    s_all, tx_all, ty_all = s_all[:m], tx_all[:m], ty_all[:m]

    # one coalesced collective for all five epoch sums
    sums = jnp.stack([
        jnp.sum(w * s_all), jnp.sum(w * tx_all), jnp.sum(w * ty_all),
        jnp.sum(w), jnp.sum(w * w),
    ])
    s, t_x, t_y, w_tot, w_sq = _engine_psum(sums, axis_name)
    s = s / 2.0
    n1 = (t_x - w_sq) / 2.0  # pairs tied in x (diagonal removed)
    n2 = (t_y - w_sq) / 2.0
    n0 = (w_tot * w_tot - w_sq) / 2.0
    denom = jnp.sqrt(jnp.maximum(n0 - n1, 0.0) * jnp.maximum(n0 - n2, 0.0))
    return jnp.where(denom > 0, s / jnp.where(denom > 0, denom, 1.0), jnp.nan)


def _route_rows(
    dest: Array,
    payload: Tuple[Tuple[Array, Any], ...],
    n: int,
    capacity: int,
    axis_name: str,
    crossing: str = "world",
) -> Tuple[Tuple[Array, ...], Array, Array]:
    """One static-shape routing stage: bucket rows by ``dest`` in ``[0, n)``
    (``>= n`` marks ghost rows that take no slot) and exchange the buckets
    over ``axis_name`` with one ``all_to_all`` per payload array.

    ``payload`` is ``((values, fill), ...)``; returns the routed arrays
    (each ``(n * capacity,)``), the routed real-row mask, and the LOCAL
    overflow count (rows past their destination bucket's capacity).
    """
    rows = dest.shape[0]
    order = jnp.argsort(dest, stable=True)
    sorted_dest = dest[order]
    counts = jax.ops.segment_sum(jnp.ones((rows,), jnp.int32), sorted_dest, n + 1)[:n]
    starts = jnp.concatenate([jnp.zeros((1,), jnp.int32), jnp.cumsum(counts)[:-1]])
    slot = jnp.arange(rows, dtype=jnp.int32) - starts[jnp.minimum(sorted_dest, n - 1)]

    in_range = (slot < capacity) & (sorted_dest < n)
    flat = jnp.where(in_range, sorted_dest * capacity + slot, n * capacity)  # OOB -> drop

    def scatter(values: Array, fill) -> Array:
        out = jnp.full((n * capacity,), fill, dtype=values.dtype)
        return out.at[flat].set(values[order], mode="drop")

    def ex(x):
        record_collective("all_to_all", x, crossing=crossing, fanout=n)
        return jax.lax.all_to_all(x, axis_name=axis_name, split_axis=0, concat_axis=0, tiled=True)

    # regroup exchange labeled for the device timeline (profiler sessions
    # attribute the all_to_all kernels to the engine phase by this scope)
    with annotate("sharded.engine.regroup"):
        routed = tuple(
            ex(scatter(values, fill).reshape(n, capacity)).reshape(-1)
            for values, fill in payload
        )
        real = ex(scatter(jnp.ones((rows,), jnp.bool_), False).reshape(n, capacity)).reshape(-1)
    overflow = jnp.sum(jnp.maximum(counts - capacity, 0))
    return routed, real, overflow


def regroup_by_query(
    idx: Array,
    preds: Array,
    target: Array,
    axis_name: EngineAxis,
    capacity: Optional[int] = None,
    valid: Optional[Array] = None,
) -> Tuple[Array, Array, Array, Array, Array]:
    """Route rows to shard ``query_id mod n`` so each query lands wholly on
    one shard (static-shape ``all_to_all`` through per-destination buckets).

    Returns ``(idx, preds, target, pad, dropped)`` where the first four have
    shape ``(n * capacity,)``, ``pad`` marks ghost rows, and ``dropped`` is
    the GLOBAL count of rows that overflowed their destination bucket —
    assert it is zero outside jit (never silently wrong). ``capacity``
    defaults to ``2 * ceil(local_rows / n)``; raise it for skewed query-id
    distributions. ``valid`` (bool, per row) excludes rows entirely: they
    take no bucket slot, never count as dropped, and arrive as pad rows
    (the padded-buffer epoch-state story, ``parallel/sharded_dispatch.py``).

    A :class:`MeshHierarchy` axis routes in TWO stages — to the destination
    slice over dcn, then to the destination device over ici — so each row
    crosses DCN at most once and the second exchange stays intra-slice
    (``_regroup_by_query_hier``).
    """
    if isinstance(axis_name, MeshHierarchy):
        return _regroup_by_query_hier(idx, preds, target, axis_name, capacity, valid)
    n = axis_size(axis_name)
    rows = idx.shape[0]
    if capacity is None:
        capacity = max(2 * -(-rows // n), 1)

    dest = idx % n  # floor-mod: negative ids still land in [0, n)
    if valid is not None:
        dest = jnp.where(valid, dest, n)  # ghost bucket: sorts last, never scatters
    (my_idx, my_preds, my_target), my_real, overflow = _route_rows(
        dest,
        (
            (idx, PAD_QUERY_ID),
            (preds, jnp.float32(-jnp.inf)),
            (target, jnp.zeros((), target.dtype)),
        ),
        n,
        capacity,
        axis_name,
    )
    dropped = _engine_psum(overflow, axis_name)
    return my_idx, my_preds, my_target, ~my_real, dropped


def _regroup_by_query_hier(
    idx: Array,
    preds: Array,
    target: Array,
    h: MeshHierarchy,
    capacity: Optional[int],
    valid: Optional[Array],
) -> Tuple[Array, Array, Array, Array, Array]:
    """The two-stage regroup: rows cross DCN once, then settle intra-slice.

    Query ``q``'s home is world device ``q mod W`` = (slice ``(q mod W) //
    L``, device ``(q mod W) mod L``) — the SAME assignment the flat regroup
    makes over slice-major device order. Stage 1 ``all_to_all``s rows to
    their home slice over dcn; stage 2 settles them on the home device over
    ici. Overflow is counted at BOTH stages and summed into ``dropped``.
    With ``capacity`` given, stage-1 buckets get ``capacity * L`` slots (a
    slice absorbs L devices' quota) and stage-2 headroom scales to match;
    defaults give 2x-average headroom per stage.
    """
    L = axis_size(h.ici_axis)
    S = axis_size(h.dcn_axis)
    W = S * L
    rows = idx.shape[0]

    cap1 = max(2 * -(-rows // S), 1) if capacity is None else max(capacity * L, 1)
    w_dest = idx % W  # floor-mod: world home, slice-major device order
    s_dest = w_dest // L
    if valid is not None:
        s_dest = jnp.where(valid, s_dest, S)
    (i1, p1, t1), real1, over1 = _route_rows(
        s_dest,
        (
            (idx, PAD_QUERY_ID),
            (preds, jnp.float32(-jnp.inf)),
            (target, jnp.zeros((), target.dtype)),
        ),
        S,
        cap1,
        h.dcn_axis,
        crossing="dcn",
    )

    m2 = i1.shape[0]  # S * cap1 rows, now on the home slice
    cap2 = max(2 * -(-m2 // L), 1) if capacity is None else max(S * capacity, 1)
    d_dest = jnp.where(real1, (i1 % W) % L, L)
    (i2, p2, t2), real2, over2 = _route_rows(
        d_dest,
        (
            (i1, PAD_QUERY_ID),
            (p1, jnp.float32(-jnp.inf)),
            (t1, jnp.zeros((), t1.dtype)),
        ),
        L,
        cap2,
        h.ici_axis,
        crossing="ici",
    )
    dropped = _engine_psum(over1 + over2, h)
    return i2, p2, t2, ~real2, dropped


def sharded_retrieval_sums(
    metric,
    idx: Array,
    preds: Array,
    target: Array,
    axis_name: str,
    capacity: Optional[int] = None,
    valid: Optional[Array] = None,
) -> Tuple[Array, Array, Array]:
    """Exact global (mean, empty-query flag, dropped-row count) for a
    ``RetrievalMetric`` over epoch rows sharded along ``axis_name``.

    ``metric`` provides config (grouped kernel, policy, ``exclude``); its
    accumulated state is NOT read. Each shard scores only the queries routed
    to it, then one psum combines the partial sums — per-device memory is
    O(local rows), never O(dataset). ``valid`` excludes rows before routing
    (padded-buffer ghost rows).
    """
    g_idx, g_preds, g_target, pad, dropped = regroup_by_query(
        idx, preds, target, axis_name, capacity, valid=valid
    )
    total, count, flag = metric._device_sums(g_idx, g_preds, g_target, pad=pad)
    total = _engine_psum(total, axis_name)
    # count/flag coalesce into one integer collective (total keeps its own
    # float plane: folding counts into f32 would lose exactness past 2^24)
    int_plane = jnp.stack([jnp.asarray(count, jnp.int32), flag.astype(jnp.int32)])
    count, flag_sum = _engine_psum(int_plane, axis_name)
    flag = flag_sum > 0
    mean = jnp.where(count == 0, 0.0, total / jnp.maximum(count, 1))
    return mean, flag, dropped
