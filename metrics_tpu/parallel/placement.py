"""Per-state mesh placement policies for 2-D (data x model) deployments,
plus the 2-LEVEL (ICI x DCN) topology descriptors the hierarchical sync
plane runs on.

The deployment story the north star asks for: per-class metric states live
*sharded* over a model axis of the device mesh while every step's update syncs
data-parallel shards over the data axis — all inside one jitted program. With
``NamedSharding``-annotated states and data, XLA's SPMD partitioner splits the
per-class compute over the model axis and inserts the cross-``dp`` reduction
automatically (the scaling-book recipe: annotate shardings, let XLA place the
collectives; no reference counterpart — reference sync is a flat NCCL
all-gather per state, torchmetrics/utilities/distributed.py:91-118).

Multi-slice topologies add a second level: devices within a slice talk over
ICI (fast), slices talk over DCN (slow). :class:`MeshHierarchy` names the two
mesh axes so the sync planes (``parallel/sync.py``) and the sharded engines
(``parallel/sharded_epoch.py``) can stage collectives hierarchically — reduce
over ICI first, cross DCN only with the per-slice result (Horovod's
hierarchical allreduce, Sergeev & Del Balso 2018; GSPMD nested meshes, Xu et
al. 2021). :class:`HostHierarchy` is the host-plane analogue: which process
belongs to which slice, and who the slice leader is.
"""
from typing import Any, Callable, Collection, NamedTuple, Optional, Sequence, Tuple, Union

import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from metrics_tpu.parallel.buffer import PaddedBuffer


class MeshHierarchy(NamedTuple):
    """Names of the two levels of a 2-level device mesh.

    ``ici_axis`` is the intra-slice (fast interconnect) mesh axis;
    ``dcn_axis`` the cross-slice (slow interconnect) axis. The convention
    everywhere in this library: the DCN axis is the OUTER mesh dimension
    (``Mesh`` shape ``(n_slices, devices_per_slice)``, axes ``(dcn, ici)``),
    so world order is slice-major and a ``PartitionSpec`` row-sharding over
    ``(dcn_axis, ici_axis)`` lays rows out in the same order a flat
    world-axis sharding over the identically-ordered device list would.
    """

    ici_axis: str = "ici"
    dcn_axis: str = "dcn"

    @property
    def axes(self) -> Tuple[str, str]:
        """Mesh axes in partition-spec (outer-first) order: ``(dcn, ici)``."""
        return (self.dcn_axis, self.ici_axis)


def mesh_hierarchy(mesh: Mesh, ici_axis: str = "ici", dcn_axis: str = "dcn") -> MeshHierarchy:
    """An explicitly-constructed :class:`MeshHierarchy` over an existing mesh
    (the route the (4,2)-virtual-CPU test mesh takes). Validates both axes."""
    for axis in (ici_axis, dcn_axis):
        if axis not in mesh.shape:
            raise ValueError(
                f"mesh_hierarchy: axis {axis!r} is not an axis of the mesh {dict(mesh.shape)}"
            )
    if ici_axis == dcn_axis:
        raise ValueError("mesh_hierarchy: ici_axis and dcn_axis must name distinct mesh axes")
    return MeshHierarchy(ici_axis=ici_axis, dcn_axis=dcn_axis)


def _slice_id_of(device: Any) -> int:
    """The slice a device belongs to: TPU slices report ``slice_index``;
    single-slice backends (CPU/GPU, single-host TPU) group by process."""
    sid = getattr(device, "slice_index", None)
    if sid is not None:
        return int(sid)
    return int(getattr(device, "process_index", 0))


def hierarchical_mesh(
    devices: Optional[Sequence[Any]] = None,
    slices: Optional[int] = None,
    ici_axis: str = "ici",
    dcn_axis: str = "dcn",
) -> Tuple[Mesh, MeshHierarchy]:
    """Build the 2-level ``(dcn, ici)`` mesh for the running topology.

    On multi-slice TPU the grouping comes from ``device.slice_index``;
    elsewhere devices group by process (each host = one "slice" of the DCN
    level). ``slices`` overrides the grouping with an explicit count — the
    route the virtual-CPU test mesh takes (e.g. 8 devices, ``slices=2`` ->
    a (2, 4) mesh: 2 slices x 4 "ICI" devices). Slices must be equal-sized
    (loud error otherwise: a ragged mesh cannot host uniform collectives).
    """
    import jax

    devices = list(jax.devices()) if devices is None else list(devices)
    if slices is None:
        ids = [_slice_id_of(d) for d in devices]
        order = sorted(set(ids))
        groups = [[d for d, i in zip(devices, ids) if i == sid] for sid in order]
    else:
        if slices <= 0 or len(devices) % slices:
            raise ValueError(
                f"hierarchical_mesh: {len(devices)} devices do not split into {slices} equal slices"
            )
        per = len(devices) // slices
        groups = [devices[s * per: (s + 1) * per] for s in range(slices)]
    per_slice = len(groups[0])
    if any(len(g) != per_slice for g in groups):
        raise ValueError(
            f"hierarchical_mesh: ragged slices {[len(g) for g in groups]}; the 2-level mesh"
            " needs every slice to hold the same device count"
        )
    grid = np.empty((len(groups), per_slice), dtype=object)
    for i, group in enumerate(groups):
        for j, device in enumerate(group):
            grid[i, j] = device
    return Mesh(grid, (dcn_axis, ici_axis)), MeshHierarchy(ici_axis=ici_axis, dcn_axis=dcn_axis)


class HostHierarchy(NamedTuple):
    """Host-plane slice membership: ``slice_of_process[p]`` is the slice id
    of process ``p``. The slice LEADER is the lowest process index in each
    slice — the one process per slice that (logically) joins the packed
    cross-slice ``process_allgather`` in slice-leader gathers."""

    slice_of_process: Tuple[int, ...]

    @property
    def n_slices(self) -> int:
        return len(set(self.slice_of_process))

    @property
    def leaders(self) -> Tuple[int, ...]:
        """One process per slice (the lowest index), in slice order."""
        first: dict = {}
        for p, s in enumerate(self.slice_of_process):
            first.setdefault(s, p)
        return tuple(first[s] for s in sorted(first))

    def is_leader(self, process_index: int) -> bool:
        return process_index in self.leaders


def host_hierarchy(slices: Optional[Sequence[int]] = None) -> HostHierarchy:
    """The running job's :class:`HostHierarchy`.

    Derived from each process's devices (``slice_index`` on multi-slice TPU,
    one slice per process elsewhere — the degenerate single-slice shape on a
    single host). ``slices`` constructs it explicitly: a sequence mapping
    process index -> slice id (the test route).
    """
    import jax

    if slices is not None:
        mapping = tuple(int(s) for s in slices)
        if len(mapping) != jax.process_count():
            raise ValueError(
                f"host_hierarchy: got {len(mapping)} slice ids for {jax.process_count()} processes"
            )
        return HostHierarchy(mapping)
    of_process = {}
    for d in jax.devices():
        of_process.setdefault(int(getattr(d, "process_index", 0)), _slice_id_of(d))
    return HostHierarchy(tuple(of_process[p] for p in sorted(of_process)))


def class_sharded(
    mesh: Mesh, axis: str = "mp", names: Optional[Collection[str]] = None
) -> Callable[[str, Any], NamedSharding]:
    """Placement callable for ``Metric.device_put``: shard the leading
    (class) axis of array states over mesh axis ``axis``; replicate
    everything else.

    A state is sharded only when its leading dimension is divisible by the
    ``axis`` size (``NamedSharding`` does not pad); scalars, non-array states
    (PaddedBuffers, lists), and non-divisible states stay replicated, so one
    policy can cover a whole heterogeneous collection. Pass ``names`` to
    restrict sharding to specific state names (e.g. ``{"tp", "fp", "fn",
    "tn", "confmat"}``) when a metric carries a rank>=1 state whose leading
    axis is *not* the class axis.

    Example — states sharded over ``mp`` while updates arrive sharded over
    ``dp``::

        mesh = Mesh(np.array(jax.devices()).reshape(4, 2), ("dp", "mp"))
        collection.device_put(class_sharded(mesh, "mp"))
    """
    axis_size = mesh.shape[axis]

    def resolve(name: str, value: Any) -> NamedSharding:
        ndim = getattr(value, "ndim", None)
        if not ndim:  # scalars, PaddedBuffers, lists: replicate
            return NamedSharding(mesh, P())
        if names is not None and name not in names:
            return NamedSharding(mesh, P())
        if value.shape[0] % axis_size:
            return NamedSharding(mesh, P())
        return NamedSharding(mesh, P(axis, *([None] * (ndim - 1))))

    return resolve


def row_sharded(
    mesh: Mesh,
    axis: Union[str, Tuple[str, ...], MeshHierarchy] = "dp",
    names: Optional[Collection[str]] = None,
) -> Callable[[str, Any], Any]:
    """Placement callable for ``Metric.device_put``: keep cat-state
    (PaddedBuffer) epoch rows SHARDED over mesh axis ``axis`` — the front
    door to sharded epoch compute.

    A curve/retrieval metric built with a ``capacity`` stores its epoch rows
    in fixed-shape PaddedBuffers; placing them with this policy spreads the
    rows over the data axis (O(capacity / axis_size) per device), appends
    land on the device owning the destination rows, and ``compute()``
    detects the sharded placement and dispatches the exact ring /
    ``all_to_all`` engine (``parallel/sharded_epoch.py``) instead of
    gathering the epoch — no reference counterpart (the reference always
    materializes the full epoch per rank, torchmetrics/metric.py:188-197).

    ``capacity`` must be divisible by the ``axis`` size (loud error, never a
    silent replicate — the caller explicitly asked for sharded rows).
    Non-buffer states (scalars, counters) replicate. Pass ``names`` to
    restrict which cat states shard.

    ``axis`` may also be a :class:`MeshHierarchy` (or the equivalent
    ``(dcn_axis, ici_axis)`` tuple) over a 2-level mesh: rows shard over
    BOTH levels in slice-major order, and ``compute()`` dispatches the
    HIERARCHICAL sharded engines (ICI-local rings, one DCN exchange).

    Example::

        mesh = Mesh(np.array(jax.devices()), ("dp",))
        auroc = AUROC(pos_label=1, capacity=1_000_000)
        auroc.device_put(row_sharded(mesh, "dp"))
        for preds, target in loader:
            auroc.update(preds, target)   # rows appended sharded
        auroc.compute()                   # exact ring, O(capacity/n)/device
    """
    if isinstance(axis, MeshHierarchy):
        axis = axis.axes
    if isinstance(axis, (tuple, list)):
        axis = tuple(axis)
        axis_size = 1
        for a in axis:
            axis_size *= mesh.shape[a]
    else:
        axis_size = mesh.shape[axis]

    def resolve(name: str, value: Any) -> Any:
        if isinstance(value, PaddedBuffer) and (names is None or name in names):
            if value.data.shape[0] % axis_size:
                raise ValueError(
                    f"row_sharded: state '{name}' capacity {value.data.shape[0]} is not"
                    f" divisible by mesh axis '{axis}' size {axis_size}; pick a divisible"
                    " `capacity` so every device holds an equal row block."
                )
            spec = P(axis, *([None] * (value.data.ndim - 1)))
            return PaddedBuffer(
                data=NamedSharding(mesh, spec), count=NamedSharding(mesh, P())
            )
        return NamedSharding(mesh, P())

    return resolve


def batch_sharded(mesh: Mesh, axis: str = "dp") -> Callable[[Any], Any]:
    """Shard a batch pytree's leading axis over mesh axis ``axis`` (helper for
    placing input data on the same mesh as the states)."""
    import jax

    def place(batch: Any) -> Any:
        def leaf(x):
            nd = getattr(x, "ndim", 0)
            spec = P(axis, *([None] * (nd - 1))) if nd else P()
            return jax.device_put(x, NamedSharding(mesh, spec))

        return jax.tree_util.tree_map(leaf, batch)

    return place
