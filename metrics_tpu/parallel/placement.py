"""Per-state mesh placement policies for 2-D (data x model) deployments.

The deployment story the north star asks for: per-class metric states live
*sharded* over a model axis of the device mesh while every step's update syncs
data-parallel shards over the data axis — all inside one jitted program. With
``NamedSharding``-annotated states and data, XLA's SPMD partitioner splits the
per-class compute over the model axis and inserts the cross-``dp`` reduction
automatically (the scaling-book recipe: annotate shardings, let XLA place the
collectives; no reference counterpart — reference sync is a flat NCCL
all-gather per state, torchmetrics/utilities/distributed.py:91-118).
"""
from typing import Any, Callable, Collection, Optional

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from metrics_tpu.parallel.buffer import PaddedBuffer


def class_sharded(
    mesh: Mesh, axis: str = "mp", names: Optional[Collection[str]] = None
) -> Callable[[str, Any], NamedSharding]:
    """Placement callable for ``Metric.device_put``: shard the leading
    (class) axis of array states over mesh axis ``axis``; replicate
    everything else.

    A state is sharded only when its leading dimension is divisible by the
    ``axis`` size (``NamedSharding`` does not pad); scalars, non-array states
    (PaddedBuffers, lists), and non-divisible states stay replicated, so one
    policy can cover a whole heterogeneous collection. Pass ``names`` to
    restrict sharding to specific state names (e.g. ``{"tp", "fp", "fn",
    "tn", "confmat"}``) when a metric carries a rank>=1 state whose leading
    axis is *not* the class axis.

    Example — states sharded over ``mp`` while updates arrive sharded over
    ``dp``::

        mesh = Mesh(np.array(jax.devices()).reshape(4, 2), ("dp", "mp"))
        collection.device_put(class_sharded(mesh, "mp"))
    """
    axis_size = mesh.shape[axis]

    def resolve(name: str, value: Any) -> NamedSharding:
        ndim = getattr(value, "ndim", None)
        if not ndim:  # scalars, PaddedBuffers, lists: replicate
            return NamedSharding(mesh, P())
        if names is not None and name not in names:
            return NamedSharding(mesh, P())
        if value.shape[0] % axis_size:
            return NamedSharding(mesh, P())
        return NamedSharding(mesh, P(axis, *([None] * (ndim - 1))))

    return resolve


def row_sharded(
    mesh: Mesh, axis: str = "dp", names: Optional[Collection[str]] = None
) -> Callable[[str, Any], Any]:
    """Placement callable for ``Metric.device_put``: keep cat-state
    (PaddedBuffer) epoch rows SHARDED over mesh axis ``axis`` — the front
    door to sharded epoch compute.

    A curve/retrieval metric built with a ``capacity`` stores its epoch rows
    in fixed-shape PaddedBuffers; placing them with this policy spreads the
    rows over the data axis (O(capacity / axis_size) per device), appends
    land on the device owning the destination rows, and ``compute()``
    detects the sharded placement and dispatches the exact ring /
    ``all_to_all`` engine (``parallel/sharded_epoch.py``) instead of
    gathering the epoch — no reference counterpart (the reference always
    materializes the full epoch per rank, torchmetrics/metric.py:188-197).

    ``capacity`` must be divisible by the ``axis`` size (loud error, never a
    silent replicate — the caller explicitly asked for sharded rows).
    Non-buffer states (scalars, counters) replicate. Pass ``names`` to
    restrict which cat states shard.

    Example::

        mesh = Mesh(np.array(jax.devices()), ("dp",))
        auroc = AUROC(pos_label=1, capacity=1_000_000)
        auroc.device_put(row_sharded(mesh, "dp"))
        for preds, target in loader:
            auroc.update(preds, target)   # rows appended sharded
        auroc.compute()                   # exact ring, O(capacity/n)/device
    """
    axis_size = mesh.shape[axis]

    def resolve(name: str, value: Any) -> Any:
        if isinstance(value, PaddedBuffer) and (names is None or name in names):
            if value.data.shape[0] % axis_size:
                raise ValueError(
                    f"row_sharded: state '{name}' capacity {value.data.shape[0]} is not"
                    f" divisible by mesh axis '{axis}' size {axis_size}; pick a divisible"
                    " `capacity` so every device holds an equal row block."
                )
            spec = P(axis, *([None] * (value.data.ndim - 1)))
            return PaddedBuffer(
                data=NamedSharding(mesh, spec), count=NamedSharding(mesh, P())
            )
        return NamedSharding(mesh, P())

    return resolve


def batch_sharded(mesh: Mesh, axis: str = "dp") -> Callable[[Any], Any]:
    """Shard a batch pytree's leading axis over mesh axis ``axis`` (helper for
    placing input data on the same mesh as the states)."""
    import jax

    def place(batch: Any) -> Any:
        def leaf(x):
            nd = getattr(x, "ndim", 0)
            spec = P(axis, *([None] * (nd - 1))) if nd else P()
            return jax.device_put(x, NamedSharding(mesh, spec))

        return jax.tree_util.tree_map(leaf, batch)

    return place
