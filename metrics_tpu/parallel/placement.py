"""Per-state mesh placement policies for 2-D (data x model) deployments.

The deployment story the north star asks for: per-class metric states live
*sharded* over a model axis of the device mesh while every step's update syncs
data-parallel shards over the data axis — all inside one jitted program. With
``NamedSharding``-annotated states and data, XLA's SPMD partitioner splits the
per-class compute over the model axis and inserts the cross-``dp`` reduction
automatically (the scaling-book recipe: annotate shardings, let XLA place the
collectives; no reference counterpart — reference sync is a flat NCCL
all-gather per state, torchmetrics/utilities/distributed.py:91-118).
"""
from typing import Any, Callable, Collection, Optional

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def class_sharded(
    mesh: Mesh, axis: str = "mp", names: Optional[Collection[str]] = None
) -> Callable[[str, Any], NamedSharding]:
    """Placement callable for ``Metric.device_put``: shard the leading
    (class) axis of array states over mesh axis ``axis``; replicate
    everything else.

    A state is sharded only when its leading dimension is divisible by the
    ``axis`` size (``NamedSharding`` does not pad); scalars, non-array states
    (PaddedBuffers, lists), and non-divisible states stay replicated, so one
    policy can cover a whole heterogeneous collection. Pass ``names`` to
    restrict sharding to specific state names (e.g. ``{"tp", "fp", "fn",
    "tn", "confmat"}``) when a metric carries a rank>=1 state whose leading
    axis is *not* the class axis.

    Example — states sharded over ``mp`` while updates arrive sharded over
    ``dp``::

        mesh = Mesh(np.array(jax.devices()).reshape(4, 2), ("dp", "mp"))
        collection.device_put(class_sharded(mesh, "mp"))
    """
    axis_size = mesh.shape[axis]

    def resolve(name: str, value: Any) -> NamedSharding:
        ndim = getattr(value, "ndim", None)
        if not ndim:  # scalars, PaddedBuffers, lists: replicate
            return NamedSharding(mesh, P())
        if names is not None and name not in names:
            return NamedSharding(mesh, P())
        if value.shape[0] % axis_size:
            return NamedSharding(mesh, P())
        return NamedSharding(mesh, P(axis, *([None] * (ndim - 1))))

    return resolve


def batch_sharded(mesh: Mesh, axis: str = "dp") -> Callable[[Any], Any]:
    """Shard a batch pytree's leading axis over mesh axis ``axis`` (helper for
    placing input data on the same mesh as the states)."""
    import jax

    def place(batch: Any) -> Any:
        def leaf(x):
            nd = getattr(x, "ndim", 0)
            spec = P(axis, *([None] * (nd - 1))) if nd else P()
            return jax.device_put(x, NamedSharding(mesh, spec))

        return jax.tree_util.tree_map(leaf, batch)

    return place
