"""Fixed-capacity padded buffers: the jit-safe representation of "cat" states.

The reference stores curve/retrieval metric states as unbounded Python lists of
tensors (e.g. AUROC cat-states, reference torchmetrics/classification/auroc.py:142-143)
that are gathered with ``all_gather`` and flattened at compute
(reference torchmetrics/metric.py:188-197). XLA requires static shapes, so the
TPU-native equivalent is a pre-allocated ``(capacity, *item)`` buffer plus a
scalar ``count`` — a pytree that can live inside ``jit``/``scan``/``shard_map``,
be donated, and be all-gathered over a mesh axis with one collective.

Overflow policy: ``count`` keeps the true number of appended rows; rows beyond
``capacity`` are dropped on device. What happens when a host-side consumer
observes ``count > capacity`` is an EXPLICIT policy (:func:`handle_overflow`):

- ``"error"`` (default): raise a typed
  :class:`~metrics_tpu.utils.exceptions.BufferOverflowError` — silent
  truncation can't corrupt a metric.
- ``"warn_drop"``: warn once (per message, process lifetime) and keep the
  capacity-truncated rows — the degraded-but-alive mode for serving loops
  where a partial curve beats a crashed epoch.

The process-wide default is set with :func:`set_overflow_policy`; call sites
(``buffer_values``, the host sync plane in ``parallel/sync.py``) accept a
per-call override.
"""
from typing import NamedTuple, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import Array

from metrics_tpu.utils.exceptions import BufferOverflowError

OVERFLOW_POLICIES = ("error", "warn_drop")

_OVERFLOW_POLICY = "error"


def set_overflow_policy(policy: str) -> str:
    """Set the process-wide PaddedBuffer overflow policy; returns the old one."""
    global _OVERFLOW_POLICY
    if policy not in OVERFLOW_POLICIES:
        raise ValueError(f"overflow policy must be one of {OVERFLOW_POLICIES}, got {policy!r}")
    old = _OVERFLOW_POLICY
    _OVERFLOW_POLICY = policy
    return old


def overflow_policy() -> str:
    return _OVERFLOW_POLICY


def handle_overflow(name: str, count: int, capacity: int, policy: Optional[str] = None) -> None:
    """Apply the overflow policy to one observed ``(count, capacity)`` pair.

    No-op when ``count <= capacity``. ``policy=None`` uses the process-wide
    default. ``name`` labels the offending state in the error/warning.
    """
    if count <= capacity:
        return
    policy = policy if policy is not None else _OVERFLOW_POLICY
    if policy not in OVERFLOW_POLICIES:
        raise ValueError(f"overflow policy must be one of {OVERFLOW_POLICIES}, got {policy!r}")
    message = (
        f"PaddedBuffer state '{name}' overflowed: {count} rows appended into capacity "
        f"{capacity}; rows beyond capacity were dropped on device. Increase the metric's "
        "`capacity` argument."
    )
    if policy == "error":
        raise BufferOverflowError(message)
    from metrics_tpu.utils.prints import rank_zero_warn_once

    rank_zero_warn_once(message, UserWarning)


class PaddedBuffer(NamedTuple):
    """A fixed-capacity append buffer. ``data``: (capacity, *item), ``count``: int32 scalar."""

    data: Array
    count: Array

    @property
    def capacity(self) -> int:
        return self.data.shape[0]


def buffer_init(capacity: int, item_shape: Sequence[int] = (), dtype=jnp.float32) -> PaddedBuffer:
    """Create an empty buffer with room for ``capacity`` rows of ``item_shape``."""
    return PaddedBuffer(
        data=jnp.zeros((capacity, *item_shape), dtype=dtype),
        count=jnp.zeros((), dtype=jnp.int32),
    )


def buffer_append(buf: PaddedBuffer, batch: Array) -> PaddedBuffer:
    """Append a ``(B, *item)`` batch. Jit-safe: B is static, offset is dynamic.

    Rows that would land past ``capacity`` are dropped (scatter mode='drop');
    ``count`` still advances so overflow is detectable at compute time.
    """
    batch = jnp.atleast_1d(batch)
    n = batch.shape[0]
    idx = buf.count + jnp.arange(n)
    data = buf.data.at[idx].set(batch.astype(buf.data.dtype), mode="drop")
    return PaddedBuffer(data=data, count=buf.count + n)


def buffer_merge(a: PaddedBuffer, b: PaddedBuffer) -> PaddedBuffer:
    """Concatenate ``b``'s valid rows after ``a``'s. Both keep ``a``'s capacity."""
    arange = jnp.arange(b.data.shape[0])
    valid = arange < b.count
    # invalid rows are routed out-of-bounds and dropped by the scatter
    idx = jnp.where(valid, a.count + arange, a.data.shape[0])
    data = a.data.at[idx].set(b.data, mode="drop")
    return PaddedBuffer(data=data, count=a.count + b.count)


def buffer_compact_gathered(data: Array, counts: Array) -> PaddedBuffer:
    """Compact an already-gathered ``(W, cap, *item)`` stack into one buffer.

    The pure (collective-free) half of :func:`buffer_all_gather`: valid rows
    of every device block are scattered to the front in axis order via an
    exclusive prefix sum over the (capacity-clamped) counts. The coalesced
    gather plane (``parallel.sync.coalesced_sync_state``) runs this on views
    sliced out of ONE bucketed ``all_gather`` payload, so compaction stays
    per-buffer while the collective is shared.
    """
    world, cap = data.shape[0], data.shape[1]
    clamped = jnp.minimum(counts, cap)
    offsets = jnp.cumsum(clamped) - clamped  # exclusive prefix sum
    row = jnp.arange(cap)
    valid = row[None, :] < clamped[:, None]  # (W, cap)
    dest = jnp.where(valid, offsets[:, None] + row[None, :], world * cap)
    out = jnp.zeros((world * cap, *data.shape[2:]), dtype=data.dtype)
    out = out.at[dest.reshape(-1)].set(data.reshape(world * cap, *data.shape[2:]), mode="drop")
    # count stays the UNclamped sum so overflow is still detectable host-side
    return PaddedBuffer(data=out, count=jnp.sum(counts))


def buffer_all_gather(buf: PaddedBuffer, axis_name: str) -> PaddedBuffer:
    """Gather per-device buffers over a mesh axis into one compacted buffer.

    Jit-safe equivalent of the reference's gather+flatten of list states
    (reference torchmetrics/metric.py:188-193). Result capacity = W * capacity;
    valid rows of every device are compacted to the front in axis order.
    """
    data = jax.lax.all_gather(buf.data, axis_name)  # (W, cap, *item)
    counts = jax.lax.all_gather(buf.count, axis_name)  # (W,)
    return buffer_compact_gathered(data, counts)


def buffer_values(buf: PaddedBuffer, overflow: Optional[str] = None) -> Array:
    """Host-side: the valid rows as a dense array.

    Overflow (``count > capacity``) goes through :func:`handle_overflow`:
    policy ``error`` raises ``BufferOverflowError``, ``warn_drop`` warns once
    and returns the capacity-truncated rows.
    """
    count = int(buf.count)
    handle_overflow("<buffer>", count, buf.capacity, policy=overflow)
    return buf.data[: min(count, buf.capacity)]


def buffer_mask(buf: PaddedBuffer) -> Array:
    """Jit-safe validity mask of shape ``(capacity,)``."""
    return jnp.arange(buf.data.shape[0]) < buf.count


BufferOrList = Union[PaddedBuffer, list]


def as_values(state_value: BufferOrList) -> Array:
    """Dense values from either a PaddedBuffer or an eager list of arrays (host-side)."""
    if isinstance(state_value, PaddedBuffer):
        return buffer_values(state_value)
    if isinstance(state_value, (list, tuple)):
        from metrics_tpu.utils.data import dim_zero_cat

        return dim_zero_cat(list(state_value))
    return state_value


def masked_values(state_value: BufferOrList) -> Tuple[Array, Array]:
    """Jit-safe (data, mask) from a PaddedBuffer; eager lists become fully-valid."""
    if isinstance(state_value, PaddedBuffer):
        return state_value.data, buffer_mask(state_value)
    vals = as_values(state_value)
    return vals, jnp.ones(vals.shape[0], dtype=bool)
