"""Count-Min sketch states: the constant-memory TAIL of an open-world key
space.

``Keyed`` (PR 8) made segments a leading state axis — but a slab still has a
fixed ``num_slots``, and "millions of users" means millions of keys: sizing
K for the worst case wastes slab memory and scatter width on the 99% of keys
that are cold, and LRU eviction silently destroys an evicted tenant's
history. The classical answer (Cormode & Muthukrishnan, "An Improved Data
Stream Summary: The Count-Min Sketch and its Applications") is a
``(depth, width)`` counter array updated through ``depth`` pairwise-
independent hash rows: every key folds into ``depth`` cells, a query reads
the MIN over its rows, and the estimate is always an OVERCOUNT bounded by
``(e / width) * N`` with probability ``1 - e^-depth`` — constant memory in
the live-key count, with a data-dependent certificate in the spirit of
``sketch.auroc_error_bound``.

This module provides the CMS as a first-class mergeable state kind next to
:class:`~metrics_tpu.parallel.sketch.HistogramSketch`:

- :class:`CountMinSketch` — one integer (or float, for sum-backed means)
  leaf of shape ``(depth, width, *item_shape)``. ``item_shape = ()`` is the
  classical counter sketch; a non-empty item shape makes every cell a full
  per-key STATE accumulator (e.g. a ``(2, B)`` histogram per cell), so a
  whole metric state folds into the tail, not just a count.
- ``merge`` is elementwise addition — associative, commutative, BIT-exact —
  so a ``psum`` of per-device sketches equals the single-process sketch and
  sync rides the existing per-dtype sum buckets of
  ``parallel.sync.coalesced_sync_state`` with ZERO new collective kinds.
- Row buckets derive from :func:`stable_key_hash` (the fleet's documented
  64-bit FNV-1a, which lives here so the sketch and the router share one
  hash of record) through a seeded multiply-shift family
  (:func:`cms_buckets`): deterministic across processes and restarts, so
  two shards' sketches describe the same cells and merge soundly.

The soundness contract every consumer relies on: per-sample deltas folded
into the tail must be NON-NEGATIVE (sample counts, histogram increments,
non-negative sums), so every cell is ``true + collisions >= true`` and the
min-row read is a certified overcount. The user-facing wrapper is
:class:`metrics_tpu.wrappers.heavy_hitters.HeavyHitters`.
"""
import math
from typing import Any, NamedTuple, Tuple, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

__all__ = [
    "CMSSpec",
    "CMSTail",
    "CountMinSketch",
    "cms_buckets",
    "cms_error_bound",
    "cms_init",
    "cms_merge",
    "cms_nbytes",
    "cms_row_state",
    "cms_scatter",
    "cms_total",
    "is_cms",
    "is_cms_spec",
    "make_cms_spec",
    "stable_key_hash",
    "stable_key_hash_array",
    "stable_key_hashes",
]

# 64-bit FNV-1a: the key hash of record, shared by the fleet router
# (serving/fleet.py re-exports it) and the CMS bucket family below. Chosen
# because it is trivially re-implementable in any producer language (offset
# basis + xor/multiply per byte), has no process-lifetime salt (unlike
# Python's str hash), and its low bits are well-mixed enough for
# `% num_shards` partitioning.
_FNV64_OFFSET = 0xCBF29CE484222325
_FNV64_PRIME = 0x100000001B3
_FNV64_MASK = 0xFFFFFFFFFFFFFFFF


def stable_key_hash(key: Any) -> int:
    """The stable 64-bit key hash of record: FNV-1a over the key's canonical
    bytes.

    Canonical form (type-tagged so ``1`` and ``"1"`` cannot collide by
    construction): ``b"s:" + utf-8`` for str, ``b"b:" + bytes`` for bytes,
    ``b"i:" + decimal`` for ints (numpy integers included). Any other key
    type is rejected loudly — a repr-based fallback would silently change
    routing across library versions, and both consumers (the fleet's
    ``shard_for_key`` partition contract and the CMS row buckets) MUST
    survive restarts.
    """
    if isinstance(key, bytes):
        data = b"b:" + key
    elif isinstance(key, str):
        data = b"s:" + key.encode("utf-8")
    elif isinstance(key, (int, np.integer)) and not isinstance(key, bool):
        data = b"i:" + str(int(key)).encode("ascii")
    else:
        raise TypeError(
            f"keys must be str, bytes or int (stable canonical bytes);"
            f" got {type(key).__name__}"
        )
    h = _FNV64_OFFSET
    for byte in data:
        h = ((h ^ byte) * _FNV64_PRIME) & _FNV64_MASK
    return h


def stable_key_hashes(keys) -> np.ndarray:
    """Vectorized :func:`stable_key_hash`: one ``uint64`` per key."""
    return np.array([stable_key_hash(k) for k in keys], dtype=np.uint64)


def stable_key_hash_array(keys: Any) -> np.ndarray:
    """:func:`stable_key_hash` over a whole numpy key array in one
    vectorized pass — BIT-EQUAL to the scalar hash of every element
    (``tests/parallel/test_cms.py`` pins the equality on a fixed corpus).

    The trick: prepend the canonical type tag with ``np.char`` ops (so
    ``1`` and ``"1"`` still cannot collide), view the tagged fixed-width
    ``'S'`` array as an ``(N, itemsize)`` byte matrix, and fold FNV-1a one
    BYTE POSITION at a time across all N keys — ``itemsize`` numpy passes
    instead of N Python loops, with ``uint64`` arithmetic wrapping mod
    2**64 exactly like the scalar hash's explicit mask (the same wrap
    contract :func:`cms_buckets` documents). Rows shorter than the widest
    key stop folding at their own length, so padding bytes never enter the
    hash; interior NUL bytes DO fold (they are real key bytes — ``'S'``
    storage only strips trailing NULs, which the scalar hash of the same
    array element never sees either).

    Integer (signed/unsigned), bytes (``'S'``) and str (``'U'``) dtypes
    vectorize; object arrays and lists fall back to the scalar loop so
    mixed-type key batches keep working. Bool and float keys are rejected
    exactly like the scalar hash.
    """
    arr = np.asarray(keys)
    if arr.ndim != 1:
        arr = arr.reshape(-1)
    if arr.size == 0:
        return np.empty((0,), dtype=np.uint64)
    kind = arr.dtype.kind
    if kind == "O":
        return stable_key_hashes(arr)
    if kind in "iu":
        tagged = np.char.add(b"i:", arr.astype("S"))
    elif kind == "S":
        tagged = np.char.add(b"b:", arr)
    elif kind == "U":
        tagged = np.char.add(b"s:", np.char.encode(arr, "utf-8"))
    else:
        raise TypeError(
            f"keys must be str, bytes or int (stable canonical bytes);"
            f" got array dtype {arr.dtype}"
        )
    tagged = np.ascontiguousarray(tagged)
    width = tagged.dtype.itemsize
    flat = tagged.view(np.uint8).reshape(tagged.size, width)
    nonzero = flat != 0
    # per-row byte length: index of the last nonzero byte + 1 (the 2-byte
    # type tag is always nonzero, so every row has at least length 2)
    lengths = width - np.argmax(nonzero[:, ::-1], axis=1)
    h = np.full(tagged.size, _FNV64_OFFSET, dtype=np.uint64)
    prime = np.uint64(_FNV64_PRIME)
    for pos in range(width):
        live = pos < lengths
        if not live.any():
            break
        h = np.where(live, (h ^ flat[:, pos].astype(np.uint64)) * prime, h)
    return h


class CountMinSketch(NamedTuple):
    """Count-Min sketch state: one ``(depth, width, *item_shape)`` leaf.

    ``counts[d, w]`` accumulates the state deltas of every key whose row-``d``
    bucket is ``w``. A pytree of one array leaf: jit/scan/donation-safe,
    ``dist_reduce_fx="sum"`` semantics (merge = elementwise add, sync = one
    psum, both bit-exact). Registered in the sketch state family
    (``sketch.is_sketch``), so the sync planes, slab scatters, checkpoint
    paths and wrappers handle it through the counts-based arms they already
    have.
    """

    counts: Array


def is_cms(value: Any) -> bool:
    return isinstance(value, CountMinSketch)


class CMSSpec(NamedTuple):
    """Host-side CMS state declaration (what ``Metric.add_state`` records in
    ``self._defaults`` — the CMS analogue of ``SketchSpec``).

    ``depth``/``width``: the hash-row grid. ``item_shape``/``dtype``: the
    per-cell accumulator. ``seed`` parameterizes the multiply-shift bucket
    family (:func:`cms_buckets`) and is part of the spec so two
    config-identical metrics hash keys to the SAME cells (merge soundness)
    and share compiled steps / compute-group keys (the spec is
    fingerprintable).
    """

    depth: int
    width: int
    item_shape: Tuple[int, ...]
    dtype: Any
    seed: int

    @property
    def shape(self) -> Tuple[int, ...]:
        return (self.depth, self.width, *self.item_shape)


def is_cms_spec(value: Any) -> bool:
    return isinstance(value, CMSSpec)


class CMSTail(NamedTuple):
    """User-facing tail configuration for ``HeavyHitters(..., tail=...)``:
    the ``(depth, width)`` grid plus the bucket-family seed. The defaults
    (4 rows x 4096 buckets) certify overcounts at ``e/4096 ~ 0.07%`` of the
    tail mass with probability ``1 - e^-4 ~ 0.98`` per query."""

    depth: int = 4
    width: int = 4096
    seed: int = 29

    def validate(self) -> "CMSTail":
        if not (isinstance(self.depth, int) and self.depth >= 1):
            raise ValueError(f"CMS depth must be a positive int, got {self.depth!r}")
        if not (isinstance(self.width, int) and self.width >= 2):
            raise ValueError(f"CMS width must be an int >= 2, got {self.width!r}")
        if not isinstance(self.seed, int):
            raise ValueError(f"CMS seed must be an int, got {self.seed!r}")
        return self


def make_cms_spec(tail: Union["CMSTail", Tuple[int, int], int],
                  item_shape: Tuple[int, ...], dtype: Any) -> CMSSpec:
    """Normalize a ``tail=`` argument (a :class:`CMSTail`, a ``(depth,
    width)`` pair, or a bare width) into one :class:`CMSSpec`."""
    if isinstance(tail, CMSTail):
        cfg = tail
    elif isinstance(tail, int):
        cfg = CMSTail(width=tail)
    elif isinstance(tail, tuple) and len(tail) == 2:
        cfg = CMSTail(depth=tail[0], width=tail[1])
    else:
        raise ValueError(
            f"`tail` must be a CMSTail, a (depth, width) pair, or a width int;"
            f" got {tail!r}"
        )
    cfg.validate()
    return CMSSpec(cfg.depth, cfg.width, tuple(item_shape), dtype, cfg.seed)


def cms_init(spec: CMSSpec) -> CountMinSketch:
    """Fresh zero-count CMS for ``spec`` (jit-safe: zeros stage as
    compile-time constants under tracing)."""
    return CountMinSketch(jnp.zeros(spec.shape, dtype=spec.dtype))


def cms_merge(a: CountMinSketch, b: CountMinSketch) -> CountMinSketch:
    """Pairwise CMS merge: elementwise addition — associative, commutative,
    bit-exact (the psum-mergeability property)."""
    return CountMinSketch(a.counts + b.counts)


def cms_nbytes(value: CountMinSketch) -> int:
    """State bytes of one CMS (constant in the live-key count — the point)."""
    return int(value.counts.size) * int(jnp.dtype(value.counts.dtype).itemsize)


def _bucket_params(depth: int, seed: int) -> Tuple[np.ndarray, np.ndarray]:
    """The seeded multiply-shift family's per-row ``(a, b)`` parameters:
    ``depth`` odd 64-bit multipliers plus additive offsets. Deterministic in
    ``seed`` — two processes with the same spec hash keys identically."""
    rng = np.random.RandomState(seed)
    halves = rng.randint(0, 2**32, size=(2, depth, 2)).astype(np.uint64)
    a = (halves[0, :, 0] << np.uint64(32)) | halves[0, :, 1] | np.uint64(1)  # odd
    b = (halves[1, :, 0] << np.uint64(32)) | halves[1, :, 1]
    return a, b


def cms_buckets(hashes: np.ndarray, depth: int, width: int, seed: int) -> np.ndarray:
    """``(N, depth)`` int32 row buckets for ``(N,)`` uint64 key hashes.

    Per row ``d``: ``((a_d * h + b_d) mod 2^64) >> 32 mod width`` — the
    multiply-shift universal family over the :func:`stable_key_hash` values,
    seeded per spec. Host numpy by design (bucket resolution happens on the
    eager, host-routed update path next to the key table); uint64 arithmetic
    wraps mod 2^64, which is exactly the family's definition. Uniformity of
    both the router and this family is pinned by a seeded chi-square test
    (``tests/parallel/test_cms.py``).
    """
    a, b = _bucket_params(depth, seed)
    h = np.asarray(hashes, dtype=np.uint64).reshape(-1, 1)  # (N, 1)
    mixed = (a[None, :] * h + b[None, :]) >> np.uint64(32)
    return (mixed % np.uint64(width)).astype(np.int32)


def cms_scatter(counts: Array, buckets: Array, deltas: Array) -> Array:
    """Fold ``(N, *item)`` per-sample deltas into ``(depth, width, *item)``
    counts at each sample's per-row buckets — the one-scatter update plane
    of every CMS state (each sample lands in ALL ``depth`` rows).

    ``buckets`` is ``(N, depth)`` int32; out-of-range buckets (the hot-tier
    sentinel ``width``) are DROPPED by scatter semantics, never misrouted —
    the same contract as ``slab_scatter``. Pure and jittable.
    """
    depth = counts.shape[0]
    n = deltas.shape[0]
    rows = jnp.broadcast_to(jnp.arange(depth, dtype=jnp.int32)[None, :], (n, depth))
    vals = jnp.broadcast_to(
        jnp.expand_dims(deltas, 1), (n, depth, *deltas.shape[1:])
    ).astype(counts.dtype)
    return counts.at[rows, buckets].add(vals, mode="drop")


def cms_total(row_counts: Array) -> Array:
    """Total mass inserted into a counter CMS (``item_shape = ()``): every
    sample increments every row once, so any single row's sum IS the total —
    exact integer arithmetic, no division."""
    return jnp.sum(row_counts[0])


def cms_row_state(counts: Array, buckets_one: Array) -> Array:
    """One key's ``(depth, *item)`` per-row cell contents (``buckets_one`` is
    its ``(depth,)`` bucket vector). The min/argmin over the leading row axis
    is the caller's query policy: the classical count query takes the min;
    a multi-leaf STATE query picks one argmin row (by the count sketch) so
    every leaf reads the SAME row and stays internally consistent."""
    rows = jnp.arange(counts.shape[0])
    return counts[rows, buckets_one]


def cms_error_bound(row_counts: Array) -> Array:
    """Data-dependent overcount certificate of a counter CMS.

    Any query's estimate is ``true + collisions`` with ``collisions >= 0``
    (non-negative deltas), and ``collisions <= (e / width) * N`` with
    probability ``>= 1 - e^-depth`` per query (Markov over each row, min
    over independent rows) — the classical Count-Min guarantee, surfaced
    from the sketch itself like ``sketch.auroc_error_bound``: ``N`` is the
    current total tail mass, so the bound tightens when traffic concentrates
    in the exact hot tier and is computable at serving time with no oracle.
    """
    width = row_counts.shape[1]
    # weak-typed float multiply: promotes to the default float dtype without
    # requesting x64 (the bound is a certificate, not an accumulator)
    return cms_total(row_counts) * (math.e / width)
