"""Chaos harness: seeded, scenario-addressable fault injection for the sync
planes.

The fault-tolerance layer (deadlines/retry/degrade in ``parallel.sync``,
integrity guards in ``core.metric``) is only trustworthy if its behavior under
faults is *tested*, and real faults — preempted hosts, stalled DCN exchanges,
NaN-poisoned batches — don't reproduce on demand. This module makes them
reproduce: a :class:`ChaosInjector` holds a seeded schedule of
:class:`FaultSpec` s and installs itself as the host-plane fault hook in
``parallel.sync``; every guarded gather call then consults it. Four fault
kinds:

- ``stall``: the gather call sleeps ``duration_s`` before proceeding — the
  deadline machinery must detect it (the stall burns one attempt; a retry
  after the stall is consumed succeeds).
- ``drop``: the gather raises :class:`~metrics_tpu.utils.exceptions.
  InjectedFaultError` (a rank dropped out of / never reached the collective).
  Retryable; ``times`` controls how many consecutive attempts fail.
- ``corrupt``: the gathered payload comes back NaN-poisoned — detectable by
  the guard's ``check_finite`` scan (which retries) or by a metric's
  ``check_finite`` policy downstream.
- ``preempt``: raises :class:`~metrics_tpu.utils.exceptions.PreemptionError`
  — the SIGTERM-mid-epoch analogue. Never retried; the caller is expected to
  checkpoint/restore and replay through the epoch watermark
  (``Metric.guarded_update``). Addressed at a ``site="service.ingest"`` it is
  the MID-WINDOW preempt of the serving runtime: the ``MetricService`` worker
  dies between two batches of an open window and must resume from its last
  snapshot with idempotent replay.

Three further kinds target the SERVING PLANE (``serving/service.py``). They
are consumed through :meth:`ChaosInjector.ingest_faults` — the service asks
the injector what fires on each ingest call and applies the semantics itself
(the injector never touches event payloads it has not been handed):

- ``ingest_stall``: the service's ingest path sleeps ``duration_s`` before
  processing the batch — the lever that backs up the bounded ingress queue
  into the shed policy (``drop_oldest`` counts ``shed_events``, ``block``
  exerts backpressure on the producer).
- ``clock_skew``: the batch's event times shift by ``skew_s`` seconds (a
  producer with a skewed clock; positive skew jumps the watermark forward,
  making honest followers late).
- ``late_burst``: the batch's event times shift by ``-skew_s`` — a delivery
  burst of OLD events, exercising the late-routing and (beyond the allowed
  lateness) the drop-and-count path (``slab_dropped_samples``).

The serving FLEET (``serving/fleet.py``) consults the same ingest hook at the
``"fleet.shard"`` site: every shard of a :class:`~metrics_tpu.serving.fleet.
MetricFleet` reports its shard index alongside its per-shard ingest call
index, and a spec's ``shard=`` field addresses one specific shard (``None``
matches every shard). ``FaultSpec(kind="preempt", site="fleet.shard",
shard=2, call=5)`` therefore kills exactly shard 2's ingest worker on ITS
fifth call — the seeded mid-stream shard kill the fleet failover soak
(``bench.py --check-fleet``) recovers from — and ``kind="ingest_stall"``
with ``rate=1.0`` stalls every shard's worker per batch (the fleet scaling
scenario's simulated per-batch serving work).

MULTI-RANK streams address the same way through ``rank=``: a caller driving
one rank of a virtual mesh (a ``MetricService`` built with ``fault_rank=i``,
or any consumer passing ``rank=`` to :meth:`ChaosInjector.ingest_faults`)
reports its rank index, and a spec with ``rank=`` set fires only on that
rank — ``FaultSpec(kind="clock_skew", rank=1, rate=1.0, skew_s=30.0)`` skews
exactly rank 1's producer clock, ``kind="ingest_stall"`` with ``rank=3``
stalls exactly rank 3's ingest. This is the lever the watermark-agreement
gate (``bench.py --check-watermark``) uses to skew or stall ONE rank of the
(4,2) virtual mesh while its peers stay honest. ``rank=`` and ``shard=``
compose (both must match when both are set); rate verdicts are cached per
(spec, site, call, shard, rank), so two ranks at the same call index draw
independent — but each seed-stable — verdicts.

Faults are *scenario-addressable*: a spec pins the exact gather call index it
fires on (``call=``, counted per site from injector install), or fires
probabilistically (``rate=``) from the injector's seeded RNG — both
deterministic for a given (schedule, seed), which is what lets
``bench.py --check-faults`` assert bit-exact recovery.

The in-jit plane stages XLA collectives at trace time, so runtime injection
is impossible there; :func:`corrupt_pytree` poisons a state pytree *before*
it enters ``sync_state``/``coalesced_sync_state`` instead — NaN propagates
through psum/all_gather identically on the flat and hierarchical planes, and
the jittable ``core.metric.nonfinite_count`` scan detects it after.

Usage (tests, bench)::

    from metrics_tpu.parallel import faults

    schedule = [
        faults.FaultSpec(kind="drop", call=1, times=2),
        faults.FaultSpec(kind="stall", call=3, duration_s=0.5),
    ]
    with faults.ChaosInjector(schedule, seed=0) as inj:
        ...  # drive the eval loop; host gathers 1 and 3 get faulted
    assert inj.injected["drop"] == 2
"""
import random
import threading
import time
from typing import Any, Dict, List, NamedTuple, Optional, Sequence

import numpy as np

from metrics_tpu.utils.exceptions import InjectedFaultError, PreemptionError

__all__ = [
    "ChaosInjector",
    "FaultSpec",
    "chaos",
    "corrupt_pytree",
    "current_injector",
]

FAULT_KINDS = ("stall", "drop", "corrupt", "preempt",
               "ingest_stall", "clock_skew", "late_burst")

# the kinds ingest_faults() surfaces to the serving loop (preempt doubles as
# the mid-window kill when addressed at a service site)
SERVICE_FAULT_KINDS = ("ingest_stall", "clock_skew", "late_burst", "preempt")


class FaultSpec(NamedTuple):
    """One addressable fault in a chaos schedule.

    ``call`` pins the site-relative gather-call index the fault fires on
    (``None`` = fire probabilistically at ``rate`` per call, from the
    injector's seeded RNG). ``times`` is how many consecutive *attempts* of
    that call are affected — the lever that distinguishes a transient fault
    (``times <= max_retries``, recovered) from a persistent one
    (``times`` large, exhausting the budget into raise/degrade).
    """

    kind: str  # one of FAULT_KINDS
    call: Optional[int] = None
    times: int = 1
    duration_s: float = 0.0  # stall / ingest_stall length
    rate: float = 0.0  # per-call probability when call is None
    site: str = "host_gather"
    skew_s: float = 0.0  # clock_skew shift (late_burst shifts by -skew_s)
    shard: Optional[int] = None  # fleet shard index (None = every shard)
    rank: Optional[int] = None  # mesh/stream rank index (None = every rank)


class ChaosInjector:
    """Seeded fault injector; install as the sync-plane hook via ``with`` (or
    ``install()``/``uninstall()``).

    Thread-safe: guarded gather attempts may run on deadline worker threads.
    ``calls`` counts gather calls seen per site; ``injected`` counts fired
    faults per kind — both are the assertion surface for chaos tests.
    """

    def __init__(self, schedule: Sequence[FaultSpec], seed: int = 0):
        for spec in schedule:
            if spec.kind not in FAULT_KINDS:
                raise ValueError(f"unknown fault kind {spec.kind!r}; expected one of {FAULT_KINDS}")
            if spec.call is None and spec.rate <= 0.0 and spec.kind != "preempt":
                raise ValueError(f"spec {spec!r} is unaddressed: set call= or rate>0")
            if spec.shard is not None and not (isinstance(spec.shard, int) and spec.shard >= 0):
                raise ValueError(f"spec {spec!r}: shard= must be a non-negative int or None")
            if spec.rank is not None and not (isinstance(spec.rank, int) and spec.rank >= 0):
                raise ValueError(f"spec {spec!r}: rank= must be a non-negative int or None")
        self.schedule: List[FaultSpec] = list(schedule)
        self.seed = seed
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self.calls: Dict[str, int] = {}
        self.injected: Dict[str, int] = {k: 0 for k in FAULT_KINDS}
        # rate-based firing is decided ONCE per (spec, call) — a retry of the
        # same call must see the same verdict, or rate faults would be
        # unrecoverable noise instead of deterministic scenarios
        self._rate_verdicts: Dict[tuple, bool] = {}

    # ------------------------------------------------------------- matching
    def _matches(
        self, spec: FaultSpec, site: str, idx: int,
        shard: Optional[int] = None, rank: Optional[int] = None,
    ) -> bool:
        if spec.site != site:
            return False
        if spec.call is not None:
            return spec.call == idx
        # the verdict key carries the caller's shard AND rank so two fleet
        # shards (or two mesh ranks) at the same per-caller call index draw
        # independent (but each stable) verdicts; callers without the
        # dimension pass None and keep one shared key
        key = (id(spec), site, idx, shard, rank)
        verdict = self._rate_verdicts.get(key)
        if verdict is None:
            verdict = self._rate_verdicts[key] = self._rng.random() < spec.rate
        return verdict

    def _fire(self, spec: FaultSpec) -> None:
        self.injected[spec.kind] += 1

    def verdict(self, spec: FaultSpec, site: str, idx: int) -> bool:
        """Whether ``spec`` fires on call ``idx`` at ``site`` — thread-safe,
        and STABLE: rate-based verdicts are decided once per (spec, call)
        from the seeded RNG and cached, so every thread (the service's
        background worker, deadline workers, the main thread) observing the
        same call sees the same answer. The determinism audit in
        ``tests/parallel/test_faults.py`` pins this."""
        with self._lock:
            return self._matches(spec, site, idx)

    # ------------------------------------------------------- hook interface
    def note_call(self, site: str) -> int:
        """Assign the next site-relative call index (sync.py calls this once
        per logical gather call, before any attempt)."""
        with self._lock:
            idx = self.calls.get(site, 0)
            self.calls[site] = idx + 1
        return idx

    def before_call(self, site: str, idx: int, attempt: int) -> None:
        """Runs before attempt ``attempt`` of gather call ``idx`` at ``site``.

        May sleep (stall), raise ``InjectedFaultError`` (drop), or raise
        ``PreemptionError`` (preempt). Called from the guarded gather path —
        possibly on a deadline worker thread.
        """
        with self._lock:
            for spec in self.schedule:
                if not self._matches(spec, site, idx) or attempt >= spec.times:
                    continue
                if spec.kind == "preempt":
                    self._fire(spec)
                    raise PreemptionError(
                        f"injected preemption at {site} call {idx} (attempt {attempt})"
                    )
                if spec.kind == "drop":
                    self._fire(spec)
                    raise InjectedFaultError(
                        f"injected dropped participation at {site} call {idx} (attempt {attempt})"
                    )
                if spec.kind == "stall":
                    self._fire(spec)
                    duration = spec.duration_s
                    break
            else:
                return
        time.sleep(duration)  # outside the lock: a stall must not block peers

    def ingest_faults(
        self, site: str, idx: int, shard: Optional[int] = None, rank: Optional[int] = None,
    ) -> List[FaultSpec]:
        """The service-plane specs firing on ingest call ``idx`` at ``site``
        (kinds in :data:`SERVICE_FAULT_KINDS`; the serving loop applies the
        semantics — sleep, time shift, preemption — itself).

        Unlike the gather hook there are no retries at ingest, so ``times``
        here means CONSECUTIVE CALLS: a call-pinned spec fires on calls
        ``call .. call + times - 1``. ``shard`` is the caller's fleet shard
        index (the ``MetricFleet`` shards report theirs; a spec with
        ``shard=`` set fires only on that shard — ``idx`` is then that
        shard's OWN ingest call counter, so a kill is addressable to "shard
        2's fifth batch"). ``rank`` is the caller's mesh/stream rank the same
        way (a ``MetricService(fault_rank=i)`` reports it): a spec with
        ``rank=`` set fires only on that rank, so a ``clock_skew`` or
        ``ingest_stall`` is addressable to exactly one rank of a virtual
        mesh. Thread-safe and seeded like the gather path; fired kinds count
        into ``injected``.
        """
        fired: List[FaultSpec] = []
        with self._lock:
            for spec in self.schedule:
                if spec.kind not in SERVICE_FAULT_KINDS or spec.site != site:
                    continue
                if spec.shard is not None and spec.shard != shard:
                    continue
                if spec.rank is not None and spec.rank != rank:
                    continue
                if spec.call is not None:
                    if not (spec.call <= idx < spec.call + spec.times):
                        continue
                elif not self._matches(spec, site, idx, shard, rank):
                    continue
                self._fire(spec)
                fired.append(spec)
        return fired

    def ingest_addressed(
        self, site: str, idx: int, shard: Optional[int] = None, rank: Optional[int] = None,
    ) -> bool:
        """Pure preview of :meth:`ingest_faults`: would ANY service-plane
        spec fire on ingest call ``idx``? Fires nothing — rate-based
        verdicts are decided (and cached) exactly like the firing call, so
        the answer a later ``ingest_faults`` at the same ``idx`` sees is the
        one previewed here. The service's queue-drain coalescer uses this to
        END a span before a fault-addressed batch without consuming the
        fault: the addressed batch then goes through the ordinary firing
        path alone, and existing chaos schedules keep their per-submission
        meaning under coalescing.
        """
        with self._lock:
            for spec in self.schedule:
                if spec.kind not in SERVICE_FAULT_KINDS or spec.site != site:
                    continue
                if spec.shard is not None and spec.shard != shard:
                    continue
                if spec.rank is not None and spec.rank != rank:
                    continue
                if spec.call is not None:
                    if spec.call <= idx < spec.call + spec.times:
                        return True
                elif self._matches(spec, site, idx, shard, rank):
                    return True
        return False

    def after_call(self, site: str, idx: int, attempt: int, result: Any) -> Any:
        """Runs on the gathered result; may corrupt payloads (NaN-poison)."""
        with self._lock:
            corrupt = any(
                spec.kind == "corrupt" and self._matches(spec, site, idx) and attempt < spec.times
                for spec in self.schedule
            )
            if corrupt:
                self.injected["corrupt"] += 1
        if not corrupt:
            return result
        return [_poison(arr) for arr in result]

    # ----------------------------------------------------------- lifecycle
    def install(self) -> "ChaosInjector":
        from metrics_tpu.parallel import sync as _sync

        if _sync._FAULT_HOOK is not None and _sync._FAULT_HOOK is not self:
            raise RuntimeError("another ChaosInjector is already installed")
        _sync._FAULT_HOOK = self
        return self

    def uninstall(self) -> None:
        from metrics_tpu.parallel import sync as _sync

        if _sync._FAULT_HOOK is self:
            _sync._FAULT_HOOK = None

    def __enter__(self) -> "ChaosInjector":
        return self.install()

    def __exit__(self, *exc: Any) -> bool:
        self.uninstall()
        return False


def _poison(arr: Any) -> Any:
    """Corrupt one gathered payload: floats are NaN-filled, integers are
    filled with their dtype max (saturated garbage — the int analogue of
    NaN, and exactly what the guard's integrity scan flags). Other dtypes
    (bool) pass through."""
    import jax.numpy as jnp

    a = np.asarray(arr)
    if np.issubdtype(a.dtype, np.floating):
        return jnp.full(a.shape, np.nan, dtype=a.dtype)
    if np.issubdtype(a.dtype, np.integer):
        return jnp.full(a.shape, np.iinfo(a.dtype).max, dtype=a.dtype)
    return arr


def current_injector() -> Optional[ChaosInjector]:
    """The installed injector, if any (sync.py consults this indirectly)."""
    from metrics_tpu.parallel import sync as _sync

    hook = _sync._FAULT_HOOK
    return hook if isinstance(hook, ChaosInjector) else None


def chaos(*specs: FaultSpec, seed: int = 0) -> ChaosInjector:
    """Sugar: ``with chaos(FaultSpec(...), FaultSpec(...)) as inj: ...``."""
    return ChaosInjector(specs, seed=seed)


def corrupt_pytree(state: Any, seed: int = 0, fraction: float = 1.0) -> Any:
    """NaN-poison float leaves of a state pytree (the in-jit plane's fault
    model: staged collectives can't be intercepted at runtime, so the payload
    is corrupted BEFORE it enters ``sync_state``; psum/all_gather then
    propagate the NaN on flat and hierarchical planes alike).

    ``fraction`` poisons that share of each float leaf's elements (the
    leading elements — deterministic for a given pytree); ``seed`` is kept
    in the signature for schedule bookkeeping parity with the injector.
    """
    import jax
    import jax.numpy as jnp

    del seed  # deterministic either way; kept for API symmetry

    def poison(leaf: Any) -> Any:
        arr = jnp.asarray(leaf) if hasattr(leaf, "dtype") else None
        if arr is None or not jnp.issubdtype(arr.dtype, jnp.floating):
            return leaf
        if fraction >= 1.0 or arr.size == 0:
            return jnp.full(arr.shape, jnp.nan, dtype=arr.dtype)
        flat = jnp.ravel(arr)
        n = max(1, int(flat.size * fraction))
        return flat.at[:n].set(jnp.nan).reshape(arr.shape)

    return jax.tree_util.tree_map(poison, state)
