"""Stateful-API dispatch to sharded epoch compute.

The reference gives every metric its distributed story through ONE interface:
``compute()`` syncs transparently (reference torchmetrics/metric.py:179-197,
208-239) — but always by materializing the gathered epoch on every rank. This
module gives the TPU build the same one-interface story at pod scale WITHOUT
the materialization: when a cat-state metric's PaddedBuffer states live
row-sharded over a mesh axis (``parallel.placement.row_sharded``),
``compute()`` detects the placement here and dispatches the exact ring /
``all_to_all`` engine (``parallel/sharded_epoch.py``) inside one jitted
``shard_map`` — sklearn-exact results with O(capacity / n) per-device memory
and no user-written ``shard_map``.

Detection is purely structural (the buffers' ``NamedSharding``), so the same
metric object transparently uses the gather path on a single device and the
sharded engine on a mesh; numerics agree either way.

Each metric family has an ``*_applicable`` predicate and a ``*_sharded``
runner. The predicate is also what ``Metric._states_own_sync`` consults to
suppress the host-plane gather — the two MUST agree, so the runners assert
applicability instead of re-deriving it.
"""
from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import Array
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from metrics_tpu.observability.counters import record_cache
from metrics_tpu.observability.devtime import DEVTIME as _DEVTIME, fence as _fence
from metrics_tpu.observability.jaxprof import annotate
from metrics_tpu.observability.trace import TRACE, span as _span
from metrics_tpu.parallel.buffer import PaddedBuffer
from metrics_tpu.parallel.placement import MeshHierarchy
from metrics_tpu.utils import compat
from metrics_tpu.parallel.sharded_epoch import (
    sharded_auroc_matrix,
    sharded_average_precision_matrix,
    sharded_clf_curve_matrix,
    sharded_kendall,
    sharded_retrieval_sums,
    sharded_spearman,
)

# jitted shard_map launchers shared across config-identical instances
# (fresh metric per eval epoch must not retrace); bounded FIFO
_LAUNCH_CACHE: Dict[Any, Callable] = {}
_LAUNCH_CACHE_MAX = 64


def _world_of(mesh: Mesh, axis: Any) -> int:
    """Device count an engine axis spans on ``mesh``."""
    if isinstance(axis, MeshHierarchy):
        return mesh.shape[axis.dcn_axis] * mesh.shape[axis.ici_axis]
    return mesh.shape[axis]


def _spec_entry(axis: Any) -> Any:
    """The ``PartitionSpec`` leading entry for an engine axis."""
    return axis.axes if isinstance(axis, MeshHierarchy) else axis


def epoch_shard_info_of_state(value: Any) -> Optional[Tuple[Mesh, Any]]:
    """(mesh, axis) when ``value`` is a PaddedBuffer whose rows are sharded
    over exactly one mesh axis — or one 2-LEVEL axis pair — else None.

    A two-name leading spec entry ``P((a, b), ...)`` is read as a 2-level
    hierarchy with ``a`` the outer cross-slice (dcn) axis and ``b`` the
    intra-slice (ici) axis — the ``parallel.placement`` slice-major
    convention — and the returned axis is the :class:`MeshHierarchy`, so
    ``compute()`` dispatches the hierarchical engines.
    """
    if not isinstance(value, PaddedBuffer):
        return None
    sharding = getattr(value.data, "sharding", None)
    if not isinstance(sharding, NamedSharding):
        return None
    spec = sharding.spec
    if len(spec) == 0 or spec[0] is None:
        return None
    axis = spec[0]
    if isinstance(axis, (tuple, list)):
        if len(axis) == 1:
            axis = axis[0]
        elif len(axis) == 2:
            axis = MeshHierarchy(dcn_axis=axis[0], ici_axis=axis[1])
        else:
            return None
    if any(s is not None for s in spec[1:]):
        return None
    mesh = sharding.mesh
    world = _world_of(mesh, axis)
    if world <= 1 or value.data.shape[0] % world:
        return None
    # the dispatch (and the host-sync suppression keyed off it) is only sound
    # when the mesh's collectives span EVERY process — a local-devices-only
    # mesh on a multi-process job would silently compute per-host values
    if len({d.process_index for d in mesh.devices.flat}) != jax.process_count():
        return None
    return mesh, axis


def _shared_info(*states: Any) -> Optional[Tuple[Mesh, str]]:
    """One (mesh, axis) shared by ALL the given states, else None."""
    infos = [epoch_shard_info_of_state(s) for s in states]
    if not infos or any(i is None for i in infos) or any(i != infos[0] for i in infos):
        return None
    return infos[0]


def _check_counts(metric: Any, *buffers: PaddedBuffer) -> int:
    """Host-side epoch-end validation: overflow raises (same contract as
    ``buffer_values``), lockstep appends verified. One scalar readback per
    buffer, at epoch end only."""
    counts = [int(b.count) for b in buffers]
    if any(c != counts[0] for c in counts):
        raise RuntimeError(
            f"{type(metric).__name__}: sharded cat-states disagree on row count {counts};"
            " states must be appended in lockstep."
        )
    if counts[0] > buffers[0].capacity:
        raise RuntimeError(
            f"PaddedBuffer overflow: {counts[0]} rows appended into capacity "
            f"{buffers[0].capacity}. Increase the metric's `capacity` argument."
        )
    return counts[0]


def _launch(
    key: Any,
    mesh: Mesh,
    axis: str,
    datas: Tuple[Array, ...],
    count: Array,
    body_factory: Callable[[], Callable],
    out_specs: Any = P(),
    check_vma: bool = True,
):
    """Run ``body(local_blocks, valid_mask) -> outputs`` as ONE jitted
    ``shard_map`` over the row-sharded epoch states.

    ``valid_mask`` marks the rows of the LOCAL block that hold real epoch
    data (global row id < count); ghost capacity rows are neutralized by the
    engines via zero weights / pre-routing exclusion. ``body_factory`` is
    called only on a cache miss (it may build closures that should not be
    rebuilt per epoch); the compiled launcher is cached by (config key, mesh,
    axis, shapes) so repeated epochs and config-identical instances pay one
    trace.
    """
    n = _world_of(mesh, axis)
    local = datas[0].shape[0] // n
    full_key = (key, mesh, axis, out_specs, tuple((d.shape, str(d.dtype)) for d in datas))
    fn = _LAUNCH_CACHE.get(full_key)
    record_cache("launch", fn is not None)
    if fn is None:
        body = body_factory()

        def shard_fn(cnt, *blocks):
            with annotate("sharded.engine"):
                if isinstance(axis, MeshHierarchy):
                    # slice-major world index: P((dcn, ici)) row blocks are
                    # laid out dcn-major, matching this linearization
                    i = jax.lax.axis_index(axis.dcn_axis) * mesh.shape[
                        axis.ici_axis
                    ] + jax.lax.axis_index(axis.ici_axis)
                else:
                    i = jax.lax.axis_index(axis)
                rows = i * local + jnp.arange(local)
                return body(blocks, rows < cnt)

        entry = _spec_entry(axis)
        in_specs = (P(),) + tuple(P(entry, *([None] * (d.ndim - 1))) for d in datas)
        fn = jax.jit(
            compat.shard_map(
                shard_fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=check_vma
            )
        )
        from metrics_tpu.core.metric import _bounded_insert

        _bounded_insert(_LAUNCH_CACHE, full_key, fn, _LAUNCH_CACHE_MAX)
    if TRACE.enabled:
        with _span("sharded.launch", {"key": str(key[1]) if isinstance(key, tuple) and len(key) > 1 else str(key)}):
            out = fn(count, *datas)
            if _DEVTIME.enabled:  # phase fence: the engine's device time lands here
                _fence(out)
            return out
    return fn(count, *datas)


# --------------------------------------------------------------- AUROC / AP
class _CurvePlan(NamedTuple):
    """Resolved sharded-dispatch plan for a curve-scalar metric."""

    mesh: Mesh
    axis: str
    form: str  # 'binary' | 'binary-partial' | 'micro' | 'classes' | 'multilabel'


def _warn_gather_fallback(metric: Any, reason: str, *states: Any) -> None:
    """Loud degradation notice: the metric's epoch states ARE row-sharded but
    this configuration has no sharded engine, so compute() will gather —
    the O(dataset)-per-device behavior the placement opted out of."""
    if _shared_info(*states) is None:
        return
    from metrics_tpu.utils.prints import rank_zero_warn_once

    rank_zero_warn_once(
        f"{type(metric).__name__}: row-sharded epoch states fall back to the"
        f" gathered compute path ({reason}); every device will materialize the"
        " full epoch."
    )


def auroc_applicable(metric: Any) -> Optional[_CurvePlan]:
    """The dispatch plan when ``AUROC.compute()`` will run sharded, else None.

    Covers binary (full AND partial AUC via ``max_fpr`` — the reference's
    whole binary surface, functional/classification/auroc.py:91-133),
    multiclass (macro/weighted/none), and multilabel
    (micro/macro/weighted/none).
    """
    from metrics_tpu.utils.enums import AverageMethod, DataType

    # sketch-mode metrics have no buffer states at all (getattr: the
    # predicate must answer "not applicable", not AttributeError)
    info = _shared_info(getattr(metric, "preds", None), getattr(metric, "target", None))
    if info is None or metric.mode is None:
        return None
    if metric.max_fpr is not None and metric.max_fpr != 1:
        if metric.mode == DataType.BINARY:
            return _CurvePlan(*info, "binary-partial")
        return None  # let the gather path raise the max_fpr/mode error
    if metric.mode == DataType.BINARY:
        return _CurvePlan(*info, "binary")
    if metric.mode == DataType.MULTILABEL and metric.average == AverageMethod.MICRO:
        return _CurvePlan(*info, "micro")
    if metric.average in (AverageMethod.NONE, AverageMethod.MACRO, AverageMethod.WEIGHTED):
        return _CurvePlan(*info, "classes")
    return None  # let the gather path raise its exact average error


def average_precision_applicable(metric: Any) -> Optional[_CurvePlan]:
    """The dispatch plan when ``AveragePrecision.compute()`` runs sharded.

    Binary, multiclass one-vs-rest, AND the multilabel layout (per-column
    step integrals against positives == 1) — the reference's full AP surface
    (``functional/classification/average_precision.py``)."""
    info = _shared_info(getattr(metric, "preds", None), getattr(metric, "target", None))
    if info is None or metric.num_classes is None:
        return None
    if metric.num_classes == 1:
        return _CurvePlan(*info, "binary")
    if metric.preds.data.ndim == 2 and metric.target.data.ndim == 1:
        return _CurvePlan(*info, "classes")
    if metric.preds.data.ndim == 2 and metric.target.data.ndim == 2:
        return _CurvePlan(*info, "multilabel")
    return None


def _class_scores_sharded(
    kind: str,
    plan: _CurvePlan,
    preds: PaddedBuffer,
    target: PaddedBuffer,
    columns: str,
    num_classes: int,
    key: Any,
) -> Tuple[Array, Array]:
    """(C,) per-class scores + (C,) supports over the sharded epoch, one program."""
    engine = sharded_auroc_matrix if kind == "auroc" else sharded_average_precision_matrix
    axis = plan.axis

    def factory():
        def body(blocks, valid):
            p, t = blocks
            if columns == "labels":
                onehot = (t[:, None] == jnp.arange(num_classes)).astype(jnp.int32)
            else:  # multilabel columns: positives are 1 (reference per-class sweep)
                onehot = (t == 1).astype(jnp.int32)
            w = valid.astype(jnp.float32)
            # the engine's per-class positive weight IS the valid-row support
            # (w * onehot summed globally), so the support rides the engine's
            # own coalesced collective — no separate psum
            return engine(p, onehot, axis, w, with_support=True)

        return body

    return _launch(
        key, plan.mesh, axis, (preds.data, target.data), preds.count, factory, out_specs=(P(), P())
    )


def _default_pos_label(metric: Any) -> int:
    """The gather path's binary pos_label defaulting (warn + 1)."""
    pos_label = metric.pos_label
    if pos_label is None:
        from metrics_tpu.utils.prints import rank_zero_warn_once

        rank_zero_warn_once("`pos_label` automatically set 1.")
        pos_label = 1
    return pos_label


def _squeeze_binary(p: Array, t: Array) -> Array:
    """Drop the (rows, 1) binary column layout (gather path: auroc.py:172-173)."""
    return p[:, 0] if p.ndim > t.ndim else p


def _binary_scalar_sharded(
    kind: str,
    plan: _CurvePlan,
    preds: PaddedBuffer,
    target: PaddedBuffer,
    pos_label: int,
    key: Any,
    flatten: bool = False,
) -> Array:
    """Exact binary scalar over the sharded epoch (``flatten`` ravels a
    (rows, C) multilabel block into micro-averaged rows)."""
    engine = sharded_auroc_matrix if kind == "auroc" else sharded_average_precision_matrix
    axis = plan.axis

    def factory():
        def body(blocks, valid):
            p, t = blocks
            if not flatten:
                p = _squeeze_binary(p, t)
            y = (t == pos_label).astype(jnp.int32)
            if flatten:
                w = jnp.repeat(valid.astype(jnp.float32), p.shape[1])
                p, y = p.reshape(-1), y.reshape(-1)
            else:
                w = valid.astype(jnp.float32)
            return engine(p[:, None], y[:, None], axis, w[:, None])[0]

        return body

    return _launch(key, plan.mesh, axis, (preds.data, target.data), preds.count, factory)


def auroc_sharded(metric: Any) -> Optional[Array]:
    """Sharded-state ``AUROC.compute()``: exact ring engine when
    ``auroc_applicable``; ``None`` -> caller falls back to the gather path.

    Degenerate classes yield ``nan`` (the static-kernel convention; the
    eager value checks cannot run inside the collective program)."""
    from metrics_tpu.utils.enums import AverageMethod, DataType
    from metrics_tpu.utils.prints import rank_zero_warn_once

    plan = auroc_applicable(metric)
    if plan is None:
        _warn_gather_fallback(
            metric, "no sharded engine for this mode/average configuration",
            metric.preds, metric.target,
        )
        return None
    _check_counts(metric, metric.preds, metric.target)

    if plan.form == "binary-partial":
        from metrics_tpu.functional.classification.curve_static import (
            partial_auroc_from_roc,
            roc_from_clf_curve,
        )

        pos_label = _default_pos_label(metric)
        max_fpr = float(metric.max_fpr)

        def partial_factory():
            def body(blocks, valid):
                p, t = blocks
                p = _squeeze_binary(p, t)
                y = (t == pos_label).astype(jnp.float32)
                fps, tps, th, counts = sharded_clf_curve_matrix(
                    p[None, :], y[None, :], valid.astype(jnp.float32)[None, :], plan.axis
                )
                fpr, tpr, _, _ = roc_from_clf_curve(fps[0], tps[0], th[0], counts[0])
                return partial_auroc_from_roc(fpr, tpr, max_fpr)

            return body

        key = (type(metric), "auroc-binary-partial", pos_label, max_fpr)
        return _launch(
            key, plan.mesh, plan.axis, (metric.preds.data, metric.target.data),
            metric.preds.count, partial_factory, check_vma=False,
        )

    if plan.form in ("binary", "micro"):
        pos_label = _default_pos_label(metric)
        key = (type(metric), f"auroc-{plan.form}", pos_label)
        return _binary_scalar_sharded(
            "auroc", plan, metric.preds, metric.target, pos_label, key, flatten=plan.form == "micro"
        )

    columns = "multilabel" if metric.mode == DataType.MULTILABEL else "labels"
    if columns == "labels" and metric.pos_label is not None:
        rank_zero_warn_once(
            "Argument `pos_label` should be `None` when running"
            f" multiclass AUROC. Got {metric.pos_label}"
        )
    num_classes = metric.preds.data.shape[1]
    key = (type(metric), "auroc-classes", columns, num_classes)
    scores, support = _class_scores_sharded(
        "auroc", plan, metric.preds, metric.target, columns, num_classes, key
    )
    return _average(scores, support, metric.average)


def average_precision_sharded(metric: Any) -> Optional[Any]:
    """Sharded-state ``AveragePrecision.compute()``; ``None`` -> gather path."""
    plan = average_precision_applicable(metric)
    if plan is None:
        _warn_gather_fallback(
            metric, "no sharded engine for this layout", metric.preds, metric.target
        )
        return None
    _check_counts(metric, metric.preds, metric.target)

    if plan.form == "binary":
        pos_label = 1 if metric.pos_label is None else metric.pos_label
        key = (type(metric), "ap-binary", pos_label)
        return _binary_scalar_sharded("ap", plan, metric.preds, metric.target, pos_label, key)

    # multiclass: one-vs-rest against the label column; multilabel: per
    # column against positives == 1 (the reference per-class sweep)
    columns = "multilabel" if plan.form == "multilabel" else "labels"
    num_classes = metric.preds.data.shape[1]
    key = (type(metric), "ap-classes", columns, num_classes)
    scores, _ = _class_scores_sharded(
        "ap", plan, metric.preds, metric.target, columns, num_classes, key
    )
    from metrics_tpu.utils.data import ClassScores

    return ClassScores(scores)


def _average(scores: Array, support: Array, average: Any) -> Any:
    from metrics_tpu.utils.data import ClassScores
    from metrics_tpu.utils.enums import AverageMethod

    if average == AverageMethod.MACRO:
        return jnp.mean(scores)
    if average == AverageMethod.WEIGHTED:
        return jnp.sum(scores * support / jnp.sum(support))
    return ClassScores(scores)


# ------------------------------------------------------------- curve vectors
def curve_applicable(metric: Any) -> Optional[Tuple[Mesh, str]]:
    """(mesh, axis) when ``ROC`` / ``PrecisionRecallCurve`` compute their
    padded curve VECTORS over row-sharded states, else None."""
    return _shared_info(getattr(metric, "preds", None), getattr(metric, "target", None))


def curve_sharded(metric: Any, kind: str) -> Optional[tuple]:
    """Sharded-state curve-vector compute for ``ROC`` (``kind='roc'``) /
    ``PrecisionRecallCurve`` (``kind='prc'``); ``None`` -> padded gather path.

    Same output contract as ``padded_curve_compute``: capacity-length
    compacted curves + valid counts (class axis for 2-D preds), REPLICATED —
    the counting runs distributed (ring + key-sort of finished points); only
    the O(N) finished curve is assembled per device, which a replicated
    curve output costs by definition.
    """
    from metrics_tpu.functional.classification.curve_static import (
        precision_recall_from_clf_curve,
        roc_from_clf_curve,
    )

    info = curve_applicable(metric)
    if info is None:
        return None
    mesh, axis = info
    _check_counts(metric, metric.preds, metric.target)

    pos_label = metric.pos_label if metric.pos_label is not None else 1
    p_data, t_data = metric.preds.data, metric.target.data
    multilabel = p_data.ndim == 2 and t_data.ndim == 2
    num_classes = p_data.shape[1] if p_data.ndim == 2 else 1
    transform = roc_from_clf_curve if kind == "roc" else precision_recall_from_clf_curve

    def factory():
        def body(blocks, valid):
            p, t = blocks
            w = valid.astype(jnp.float32)
            if p.ndim == 1:
                p_cm = p[None, :]
                y_cm = (t == pos_label).astype(jnp.float32)[None, :]
                w_cm = w[None, :]
            elif multilabel:
                p_cm = p.T
                y_cm = (t == 1).T.astype(jnp.float32)
                w_cm = jnp.broadcast_to(w[:, None], p.shape).T
            else:  # multiclass one-vs-rest against the label column
                p_cm = p.T
                y_cm = (t[None, :] == jnp.arange(num_classes)[:, None]).astype(jnp.float32)
                w_cm = jnp.broadcast_to(w[:, None], p.shape).T
            clf = sharded_clf_curve_matrix(p_cm, y_cm, w_cm, axis)
            out = jax.vmap(transform)(*clf)
            if p.ndim == 1:
                return tuple(o[0] for o in out)
            return out

        return body

    key = (type(metric), f"curve-{kind}", pos_label, num_classes, multilabel)
    # check_vma off: the curve outputs come from all_gather + a deterministic
    # sort/compact, so every device holds the identical replicated value, but
    # the varying-axis type system cannot demote gathered (varying) values to
    # invariant — there is no varying->invariant pcast
    return _launch(
        key, mesh, axis, (p_data, t_data), metric.preds.count, factory,
        out_specs=(P(), P(), P(), P()), check_vma=False,
    )


# ----------------------------------------------------------- rank correlation
def rank_corr_applicable(metric: Any) -> Optional[Tuple[Mesh, str]]:
    """(mesh, axis) when a rank-correlation metric (Spearman / Kendall)
    will compute over its row-sharded cat-states, else None."""
    return _shared_info(getattr(metric, "preds_all", None), getattr(metric, "target_all", None))


def _rank_corr_sharded(metric: Any, kind: str) -> Optional[Array]:
    """Shared runner: exact ring rank statistics over the sharded epoch.

    Spearman: global midranks via the sorted-pack ring, psum Pearson.
    Kendall: the O(N^2) pairwise contraction split evenly over the ring.
    Empty epoch yields ``nan`` (the gather-path convention) without a
    host-side early exit, so the launcher stays one cached program.
    """
    info = rank_corr_applicable(metric)
    if info is None:
        return None
    mesh, axis = info
    count = _check_counts(metric, metric.preds_all, metric.target_all)
    if kind == "kendall":
        # the ring splits the O(N^2) contraction n ways but total work stays
        # quadratic — same loud warning as the gather path
        from metrics_tpu.functional.regression.kendall import _warn_if_quadratic

        _warn_if_quadratic(count)
    engine = sharded_spearman if kind == "spearman" else sharded_kendall

    def factory():
        def body(blocks, valid):
            p, t = blocks
            return engine(p, t, axis, valid.astype(jnp.float32))

        return body

    key = (type(metric), kind)
    return _launch(
        key, mesh, axis, (metric.preds_all.data, metric.target_all.data), metric.preds_all.count, factory
    )


def spearman_sharded(metric: Any) -> Optional[Array]:
    """Sharded-state ``SpearmanCorrcoef.compute()``; ``None`` -> gather path."""
    return _rank_corr_sharded(metric, "spearman")


def kendall_sharded(metric: Any) -> Optional[Array]:
    """Sharded-state ``KendallRankCorrCoef.compute()``; ``None`` -> gather path."""
    return _rank_corr_sharded(metric, "kendall")


# ---------------------------------------------------------------- retrieval
def retrieval_applicable(metric: Any) -> Optional[Tuple[Mesh, str]]:
    """(mesh, axis) when ``RetrievalMetric.compute()`` will run sharded."""
    return _shared_info(metric.idx, metric.preds, metric.target)


def retrieval_sharded(metric: Any) -> Optional[Array]:
    """Sharded-state ``RetrievalMetric.compute()``: ``all_to_all`` regroup +
    grouped engine when the epoch buffers are row-sharded; ``None`` -> gather.

    Bucket overflow from a skewed query-id distribution raises loudly with
    the knob to turn (``metric.regroup_capacity``); the ``'error'`` policy
    check runs on the globally-reduced flag, matching the gather path.
    """
    info = retrieval_applicable(metric)
    if info is None:
        return None
    mesh, axis = info
    _check_counts(metric, metric.idx, metric.preds, metric.target)
    bucket_capacity = getattr(metric, "regroup_capacity", None)
    if bucket_capacity is None:
        # 4x the balanced per-destination load: headroom for skewed query-id
        # distributions while keeping the regrouped block O(local rows)
        n = _world_of(mesh, axis)
        local = metric.idx.data.shape[0] // n
        bucket_capacity = max(4 * -(-local // n), 8)

    def factory():
        # the cached launcher must pin only config, never an epoch of state:
        # close over a detached EMPTY-state copy (built only on cache miss)
        from copy import deepcopy

        saved = metric._current_state()
        metric._set_state({name: [] for name in metric._defaults})
        try:
            carrier = deepcopy(metric)
        finally:
            metric._set_state(saved)

        def body(blocks, valid):
            i, p, t = blocks
            return sharded_retrieval_sums(
                carrier, i, p, t, axis, capacity=bucket_capacity, valid=valid
            )

        return body

    key = (
        type(metric),
        "retrieval",
        metric.query_without_relevant_docs,
        metric.exclude,
        getattr(metric, "k", None),
        bucket_capacity,
    )
    mean, flag, dropped = _launch(
        key,
        mesh,
        axis,
        (metric.idx.data, metric.preds.data, metric.target.data),
        metric.idx.count,
        factory,
        out_specs=(P(), P(), P()),
    )
    if int(dropped):
        raise RuntimeError(
            f"{type(metric).__name__}: {int(dropped)} rows overflowed the sharded regroup's"
            " per-destination buckets (skewed query-id distribution). Set"
            " `metric.regroup_capacity` to a larger per-shard bucket capacity."
        )
    if metric.query_without_relevant_docs == "error" and bool(flag):
        raise ValueError(
            f"`{type(metric).__name__}.compute()` was provided with a query {metric._EMPTY_QUERY_ERROR}"
        )
    return mean
