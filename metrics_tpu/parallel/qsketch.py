"""Mergeable quantile sketches: the latency/distribution state kind.

The sketch family so far answers curve questions (``HistogramSketch``), rank
questions (``RankSketch``) and open-world key questions (``CountMinSketch``)
— but nothing in the library can answer the canonical production-serving
question "what is the p99 latency?", because ``HistogramSketch``'s fixed
``sketch_range`` linear grid cannot hold unbounded, heavy-tailed values
(request latency, token counts, scores drifting over time) without either
clipping the tail into an end bin or wasting the whole grid on it.

The streaming literature's answer (Masson, Rim & Lee, "DDSketch: a fast and
fully-mergeable quantile sketch with relative-error guarantees", VLDB 2019;
Karnin, Lang & Liberty's KLL for the comparison point) is a LOG-BUCKETED
histogram: bucket ``j`` covers ``[min_value * gamma^j, min_value *
gamma^(j+1))`` with ``gamma = (1 + alpha) / (1 - alpha)``, so reporting the
bucket's multiplicative midpoint answers any quantile within RELATIVE error
``alpha`` — at any scale, with no range tuning beyond the representable
magnitude span. This module specializes that design to the library's
mergeable-state contract (the same move ``sketch.py`` made for KLL):

- :class:`QuantileSketch` — ONE integer counts leaf over the fixed grid
  below. ``update`` is a jittable scatter-add, ``merge`` is elementwise
  integer addition (associative, commutative, BIT-exact — a ``psum`` of
  per-device sketches equals the single-process sketch), and ``sync`` rides
  the existing per-dtype sum buckets of ``coalesced_sync_state`` with ZERO
  new collective kinds. State size is traffic-independent: the default
  ``alpha=0.01`` grid over 18 decades is ~16 KB forever.
- :class:`QSketchSpec` — the host-side state declaration (the fourth
  first-class state kind next to ``_BufferSpec`` / ``SketchSpec`` /
  ``SlabSpec`` / ``CMSSpec``), fingerprintable so config-identical qsketch
  metrics share compiled steps and compute groups.

Grid layout (``m`` log buckets per sign, ``B = 2 m + 3`` total)::

    index 0            : negative overflow   (x <= -min_value * gamma^m)
    index 1 .. m       : negative log buckets (ascending in x)
    index m + 1        : zero bucket          (|x| < min_value)
    index m + 2 .. 2m+1: positive log buckets
    index 2 m + 2      : positive overflow    (x >= min_value * gamma^m)

The index map is STRICTLY MONOTONE in the value — which is why the same
grid doubles as a range-free binning for the target-conditioned curve
histograms (auto-ranged sketch AUROC / AveragePrecision: no more
``sketch_range=(0, 1)`` assumption on un-sigmoided scores) and for the 2-D
joint rank histograms (range-free Spearman/Kendall, retiring the soft-sign
squash-grid compromise): the curve/rank math in ``sketch.py`` only needs a
monotone grid, never a linear one.

NaN/±inf follow PR 7's convention exactly: NaN samples are DROPPED via a
masked (zero-increment) scatter — ``astype(int32)`` of NaN is undefined in
XLA — and ``±inf`` clips into the signed overflow end buckets, where the
certificate reports the estimate as uncertified (``inf`` bound).

Certificate of record (:func:`quantile_error_bound`): any quantile whose
selected bucket is a log or zero bucket satisfies
``|estimate - true| <= alpha * |true| + min_value`` — the ``alpha`` term is
the log-bucket guarantee, the additive ``min_value`` covers the zero bucket
(values below the smallest resolvable magnitude report exactly 0.0). Mass
resolved from an overflow bucket is flagged ``inf`` (out of the certified
span), data-dependently, in the spirit of ``sketch.auroc_error_bound``.
"""
import math
from typing import Any, NamedTuple, Optional, Tuple

import jax.numpy as jnp
import numpy as np
from jax import Array

__all__ = [
    "QSKETCH_ALPHA",
    "QSKETCH_CURVE_ALPHA",
    "QSKETCH_CURVE_RANGE",
    "QSKETCH_MAX_VALUE",
    "QSKETCH_MIN_VALUE",
    "QSKETCH_RANK_ALPHA",
    "QSKETCH_RANK_RANGE",
    "QSketchSpec",
    "QuantileSketch",
    "is_qsketch",
    "is_qsketch_spec",
    "qsketch_bucket",
    "qsketch_bucket_values",
    "qsketch_curve_group_key",
    "qsketch_curve_spec",
    "qsketch_curve_update",
    "qsketch_init",
    "qsketch_merge",
    "qsketch_nbytes",
    "qsketch_num_buckets",
    "qsketch_rank_group_key",
    "qsketch_rank_spec",
    "qsketch_rank_update",
    "qsketch_update",
    "qsketch_value_group_key",
    "quantile_error_bound",
    "quantile_from_counts",
    "quantile_sketch_spec",
]

# defaults of record. The plain quantile grid spans 18 decades (nanoseconds
# to ~30 years if the unit is seconds) at 1% relative accuracy — ~16 KB of
# int32 counts. The curve grid narrows to 12 decades (scores/logits); the
# rank grid trades accuracy for the JOINT histogram's quadratic footprint
# (alpha=0.1 -> a 279x279 joint, ~311 KB — rank statistics only consume the
# ORDER of the grid, so coarse alpha costs collision mass, not correctness).
QSKETCH_ALPHA = 0.01
QSKETCH_MIN_VALUE = 1e-9
QSKETCH_MAX_VALUE = 1e9
QSKETCH_CURVE_ALPHA = 0.01
QSKETCH_CURVE_RANGE = (1e-6, 1e6)
QSKETCH_RANK_ALPHA = 0.1
QSKETCH_RANK_RANGE = (1e-6, 1e6)

# a rank spec's joint histogram is (B, B): cap B so a typo'd alpha cannot
# silently request a multi-GB state (279^2 at the default, ~4096^2 = 64 MB
# at the cap)
_MAX_RANK_GRID = 4096


class QuantileSketch(NamedTuple):
    """Log-bucketed quantile sketch state: one ``(..., B)`` integer counts
    leaf over the module's fixed relative-accuracy grid.

    A pytree of one integer leaf: jit/scan/donation-safe,
    ``dist_reduce_fx="sum"`` semantics (merge = elementwise add, sync = one
    psum, both bit-exact). Registered in the sketch state family
    (``sketch.is_sketch``), so the sync planes, slab scatters, checkpoint
    paths and wrappers handle it through the counts-based arms they already
    have. Layouts: ``(B,)`` for a plain value sketch, ``(2, B)`` /
    ``(C, 2, B)`` for target-conditioned curve histograms on the qsketch
    grid, ``(B, B)`` for the joint rank histogram.
    """

    counts: Array


def is_qsketch(value: Any) -> bool:
    return isinstance(value, QuantileSketch)


class QSketchSpec(NamedTuple):
    """Host-side quantile-sketch state declaration (what ``Metric.add_state``
    records in ``self._defaults`` — the qsketch analogue of ``SketchSpec``).

    ``kind``: ``"q"`` (plain ``(B,)`` value sketch), ``"hist"``
    (target-conditioned ``(..., 2, B)`` curve layout on the qsketch grid) or
    ``"rank"`` (``(B, B)`` joint). ``alpha`` is the relative accuracy;
    ``min_value``/``max_value`` bound the representable magnitude span (the
    grid is log-spaced between them, with a zero bucket below and signed
    overflow buckets beyond). Pure config — materialization is
    :func:`qsketch_init` — and fingerprintable, so config-identical qsketch
    metrics share compiled steps and compute groups.
    """

    kind: str
    shape: Tuple[int, ...]
    dtype: Any
    alpha: float
    min_value: float
    max_value: float


def is_qsketch_spec(value: Any) -> bool:
    return isinstance(value, QSketchSpec)


def qsketch_init(spec: QSketchSpec) -> QuantileSketch:
    """Fresh zero-count qsketch for ``spec`` (jit-safe: zeros stage as
    compile-time constants under tracing)."""
    return QuantileSketch(jnp.zeros(spec.shape, dtype=spec.dtype))


def qsketch_merge(a: QuantileSketch, b: QuantileSketch) -> QuantileSketch:
    """Pairwise merge: elementwise integer addition — associative,
    commutative, bit-exact (the psum-mergeability property)."""
    return QuantileSketch(a.counts + b.counts)


def qsketch_nbytes(value: QuantileSketch) -> int:
    """State bytes of one qsketch (traffic-independent by construction)."""
    return int(value.counts.size) * int(jnp.dtype(value.counts.dtype).itemsize)


def _accum_dtype():
    from metrics_tpu.utils.data import accum_int_dtype

    return accum_int_dtype()


# ---------------------------------------------------------------- the grid
def _validate_grid(alpha: float, min_value: float, max_value: float) -> None:
    if not (isinstance(alpha, float) and 0.0 < alpha < 1.0):
        raise ValueError(f"`alpha` must be a float in (0, 1), got {alpha!r}")
    if not (0.0 < min_value < max_value):
        raise ValueError(
            f"qsketch magnitude span must satisfy 0 < min_value < max_value,"
            f" got ({min_value!r}, {max_value!r})"
        )


def _grid_params(alpha: float, min_value: float, max_value: float) -> Tuple[int, float]:
    """``(m, gamma)``: log buckets per sign and the bucket growth factor."""
    _validate_grid(alpha, min_value, max_value)
    gamma = (1.0 + alpha) / (1.0 - alpha)
    m = int(math.ceil(math.log(max_value / min_value) / math.log(gamma)))
    return max(m, 1), gamma


def qsketch_num_buckets(alpha: float, min_value: float, max_value: float) -> int:
    """Total grid size ``B = 2 m + 3``: ``m`` log buckets per sign plus the
    zero bucket and the two signed overflow end buckets."""
    m, _ = _grid_params(alpha, min_value, max_value)
    return 2 * m + 3


def qsketch_bucket(x: Array, alpha: float, min_value: float, max_value: float) -> Array:
    """Strictly monotone bucket index of ``x`` on the qsketch grid.

    ``±inf`` lands in the signed overflow end buckets (documented
    out-of-span behavior, certificate-flagged); exact zeros and values below
    ``min_value`` in magnitude land in the zero bucket. ``NaN`` has no
    defined bucket (``astype(int32)`` of NaN is undefined in XLA): callers
    must mask NaN before binning, as every qsketch update plane does (NaN
    samples are dropped via a zero scatter increment) — the same contract as
    ``sketch.score_to_bin``.
    """
    m, gamma = _grid_params(alpha, min_value, max_value)
    ln_gamma = math.log(gamma)
    top = min_value * gamma**m  # first non-representable magnitude
    xf = jnp.asarray(x, jnp.float32)
    mag = jnp.abs(xf)
    # clip BEFORE the int cast: log(inf)=inf must resolve through the float
    # clip, never through an undefined float->int conversion
    j = jnp.clip(
        jnp.floor(jnp.log(jnp.maximum(mag, min_value) / min_value) / ln_gamma), 0, m - 1
    ).astype(jnp.int32)
    idx = jnp.where(xf > 0, m + 2 + j, m - j)
    idx = jnp.where(mag < min_value, m + 1, idx)
    idx = jnp.where((mag >= top) & (xf > 0), 2 * m + 2, idx)
    idx = jnp.where((mag >= top) & (xf < 0), 0, idx)
    return idx.astype(jnp.int32)


def qsketch_bucket_values(alpha: float, min_value: float, max_value: float) -> np.ndarray:
    """The ``(B,)`` representative value per bucket — the multiplicative
    midpoint ``2 gamma / (gamma + 1)`` of each log bucket's span, which is
    what makes any in-bucket value's estimate land within relative error
    ``alpha`` (at both bucket edges the error is exactly
    ``(gamma - 1) / (gamma + 1) = alpha``). The zero bucket reports exactly
    ``0.0``; the overflow buckets report ``±top_edge * gamma`` — one bucket
    beyond the certified span, flagged by :func:`quantile_error_bound`.

    Host-side numpy on purpose (grids are metric config; under jit they
    stage as constants), matching ``sketch.sketch_thresholds``.
    """
    m, gamma = _grid_params(alpha, min_value, max_value)
    rep = min_value * gamma ** np.arange(m, dtype=np.float64) * (2.0 * gamma / (gamma + 1.0))
    vals = np.zeros(2 * m + 3, dtype=np.float64)
    vals[m + 2 : 2 * m + 2] = rep
    vals[1 : m + 1] = -rep[::-1]  # vals[m - j] == -rep[j]: monotone ascending
    top = min_value * gamma**m
    vals[0] = -top * gamma
    vals[2 * m + 2] = top * gamma
    return vals


# ------------------------------------------------------------------- updates
def qsketch_update(
    counts: Array, values: Array, alpha: float, min_value: float, max_value: float
) -> Array:
    """Scatter one batch of raw values into a ``(B,)`` quantile sketch — the
    shared update plane of the ``Quantile``/``Percentile`` family (equal
    grid config -> one compute-group delta serves every requested quantile).

    Pure and jittable: one log binning plus one scatter-add. NaN values are
    DROPPED (zero scatter increment); ``±inf`` clips into the signed
    overflow buckets — PR 7's sketch convention, verbatim.
    """
    x = jnp.asarray(values).reshape(-1)
    nan = jnp.isnan(x)
    b = qsketch_bucket(jnp.where(nan, 0.0, x), alpha, min_value, max_value)
    return counts.at[b].add((~nan).astype(counts.dtype))


def qsketch_curve_update(
    counts: Array,
    preds: Array,
    target: Array,
    alpha: float,
    min_value: float,
    max_value: float,
    pos_label: int,
) -> Array:
    """Scatter one batch into per-class positive/negative score histograms
    on the AUTO-RANGED qsketch grid — the ``approx="qsketch"`` twin of
    ``sketch.sketch_curve_update`` (same layouts: binary ``(2, B)``,
    multiclass/multilabel ``(C, 2, B)``), shared across AUROC /
    AveragePrecision instances with equal config.

    The qsketch grid is strictly monotone in the score, which is all the
    thresholded-count derivation (``sketch.curve_counts_from_histogram``)
    ever needed — so raw logits, un-sigmoided scores and heavy-tailed
    calibration outputs bin losslessly-ordered with NO ``sketch_range``
    assumption. NaN predictions are dropped via the masked scatter; ``±inf``
    clips into the signed overflow buckets (which the suffix cumsum treats
    as the extreme thresholds, exactly like any end bin).
    """
    num_bins = counts.shape[-1]
    del num_bins  # layout is carried by the spec; shapes checked below
    if preds.ndim == 1:
        if counts.ndim != 2:
            raise ValueError(
                f"qsketch expects per-class input (N, {counts.shape[0]}); got 1-D"
                " predictions. Construct the metric without num_classes for binary"
                " qsketch mode."
            )
        nan = jnp.isnan(preds)
        b = qsketch_bucket(jnp.where(nan, 0.0, preds), alpha, min_value, max_value)
        row = jnp.where(target == pos_label, 0, 1)
        return counts.at[row, b].add((~nan).astype(counts.dtype))
    if preds.ndim != 2 or counts.ndim != 3 or preds.shape[1] != counts.shape[0]:
        raise ValueError(
            f"qsketch/state layout mismatch: preds {preds.shape} vs counts"
            f" {counts.shape}. Multiclass/multilabel qsketch mode needs num_classes"
            " at construction."
        )
    num_classes = preds.shape[1]
    nan = jnp.isnan(preds)
    b = qsketch_bucket(jnp.where(nan, 0.0, preds), alpha, min_value, max_value)  # (N, C)
    if target.ndim == 1:
        pos = target[:, None] == jnp.arange(num_classes)[None, :]
    else:
        pos = target == pos_label
    cls = jnp.broadcast_to(jnp.arange(num_classes)[None, :], b.shape)
    row = jnp.where(pos, 0, 1)
    return counts.at[cls, row, b].add((~nan).astype(counts.dtype))


def qsketch_rank_update(
    counts: Array,
    preds: Array,
    target: Array,
    alpha: float,
    min_value: float,
    max_value: float,
) -> Array:
    """Scatter one batch of (preds, target) pairs into the 2-D joint
    histogram on the qsketch grid — the RANGE-FREE ``approx="qsketch"`` twin
    of ``sketch.sketch_rank_update`` (Spearman/Kendall share it; rank
    statistics are invariant under the grid's strictly increasing index
    map, so the log binning changes only which values COLLIDE in a bucket,
    never their order). Pairs with a NaN on either side are dropped via the
    masked scatter; ``±inf`` lands in the signed overflow buckets (end bins
    of the order)."""
    nan = jnp.isnan(preds) | jnp.isnan(target)
    bi = qsketch_bucket(jnp.where(nan, 0.0, preds), alpha, min_value, max_value)
    bj = qsketch_bucket(jnp.where(nan, 0.0, target), alpha, min_value, max_value)
    return counts.at[bi, bj].add((~nan).astype(counts.dtype))


# ------------------------------------------------------------------- queries
def _rank_select(counts: Array, q: Array) -> Tuple[Array, Array]:
    """``(idx, n)``: the bucket each quantile's rank resolves to (DDSketch
    convention — the first bucket whose cumulative count exceeds
    ``q * (n - 1)``) and the total count."""
    c = counts.astype(jnp.float32)
    n = jnp.sum(c)
    cum = jnp.cumsum(c)
    target = jnp.asarray(q, jnp.float32) * jnp.maximum(n - 1.0, 0.0)
    idx = jnp.clip(
        jnp.searchsorted(cum, target, side="right"), 0, counts.shape[-1] - 1
    )
    return idx, n


def quantile_from_counts(
    counts: Array, q: Any, alpha: float, min_value: float, max_value: float
) -> Array:
    """Quantile estimates from a ``(B,)`` qsketch: the selected bucket's
    representative value, within relative error ``alpha`` (plus the
    ``min_value`` zero-bucket slack) for any rank resolving inside the
    certified span — see :func:`quantile_error_bound`.

    ``q`` may be a scalar or a vector (one read answers all of p50/p95/p99
    from the same counts). Jittable and vmap-safe (``Keyed`` vmaps it over
    the slot axis); ``nan`` on an empty sketch, matching the buffer-backed
    kernels' degenerate-input convention.
    """
    values = jnp.asarray(qsketch_bucket_values(alpha, min_value, max_value), jnp.float32)
    qa = jnp.atleast_1d(jnp.asarray(q, jnp.float32))
    idx, n = _rank_select(counts, qa)
    out = jnp.where(n > 0, values[idx], jnp.nan)
    return out if np.ndim(q) else out[0]


def quantile_error_bound(
    counts: Array, q: Any, alpha: float, min_value: float, max_value: float
) -> Array:
    """Data-dependent certificate for :func:`quantile_from_counts`:
    per-quantile relative-error bound ``alpha`` whenever the selected rank
    resolves in a log or zero bucket (the estimate then satisfies
    ``|estimate - true| <= alpha * |true| + min_value``, the additive term
    covering sub-``min_value`` magnitudes reported as 0.0), and ``inf``
    when it resolves in a signed overflow bucket — mass beyond
    ``max_value`` is counted and ordered but not certified, the qsketch
    analogue of ``sketch.auroc_error_bound``'s collision-mass certificate.
    ``nan`` on an empty sketch."""
    m, _ = _grid_params(alpha, min_value, max_value)
    qa = jnp.atleast_1d(jnp.asarray(q, jnp.float32))
    idx, n = _rank_select(counts, qa)
    bound = jnp.where((idx == 0) | (idx == 2 * m + 2), jnp.inf, alpha)
    out = jnp.where(n > 0, bound, jnp.nan)
    return out if np.ndim(q) else out[0]


# ----------------------------------------------------- metric-side plumbing
def quantile_sketch_spec(
    alpha: float = QSKETCH_ALPHA,
    min_value: float = QSKETCH_MIN_VALUE,
    max_value: float = QSKETCH_MAX_VALUE,
    dtype: Any = None,
) -> QSketchSpec:
    """The :class:`QSketchSpec` a value-distribution metric registers
    (``Quantile``/``Percentile``/``MedianAbsoluteError``)."""
    shape = (qsketch_num_buckets(alpha, min_value, max_value),)
    return QSketchSpec(
        "q", shape, dtype or _accum_dtype(), float(alpha), float(min_value), float(max_value)
    )


def qsketch_curve_spec(
    alpha: float = QSKETCH_CURVE_ALPHA,
    num_classes: Optional[int] = None,
    min_value: float = QSKETCH_CURVE_RANGE[0],
    max_value: float = QSKETCH_CURVE_RANGE[1],
    dtype: Any = None,
) -> QSketchSpec:
    """The :class:`QSketchSpec` a curve metric registers for
    ``approx="qsketch"`` (auto-ranged AUROC / AveragePrecision)."""
    num_buckets = qsketch_num_buckets(alpha, min_value, max_value)
    shape = (
        (2, num_buckets) if num_classes in (None, 1) else (num_classes, 2, num_buckets)
    )
    return QSketchSpec(
        "hist", shape, dtype or _accum_dtype(), float(alpha), float(min_value), float(max_value)
    )


def qsketch_rank_spec(
    alpha: float = QSKETCH_RANK_ALPHA,
    min_value: float = QSKETCH_RANK_RANGE[0],
    max_value: float = QSKETCH_RANK_RANGE[1],
    dtype: Any = None,
) -> QSketchSpec:
    """The :class:`QSketchSpec` a rank metric registers for
    ``approx="qsketch"`` (range-free Spearman/Kendall)."""
    num_buckets = qsketch_num_buckets(alpha, min_value, max_value)
    if num_buckets > _MAX_RANK_GRID:
        raise ValueError(
            f"a rank qsketch keeps a (B, B) joint histogram; alpha={alpha!r} over"
            f" ({min_value!r}, {max_value!r}) needs B={num_buckets} > {_MAX_RANK_GRID}."
            " Rank statistics only consume the grid's ORDER — use a coarser alpha"
            " (the default 0.1 gives B=279) or a narrower magnitude span."
        )
    return QSketchSpec(
        "rank",
        (num_buckets, num_buckets),
        dtype or _accum_dtype(),
        float(alpha),
        float(min_value),
        float(max_value),
    )


def _spec_key(tag: str, spec: QSketchSpec) -> tuple:
    return (
        tag, spec.kind, spec.shape, str(jnp.dtype(spec.dtype)),
        spec.alpha, spec.min_value, spec.max_value,
    )


def qsketch_value_group_key(metric: Any, state: str = "qsketch") -> tuple:
    """Compute-group fingerprint of a value-sketch metric's update plane:
    any two ``Quantile``/``Percentile`` instances with equal grid config run
    the identical :func:`qsketch_update` scatter — the requested ``q`` is
    compute-only, so ONE synced sketch serves p50, p95 and p99 members of a
    collection."""
    return _spec_key("qsketch_q", metric._defaults[state])


def qsketch_curve_group_key(metric: Any) -> tuple:
    """Compute-group fingerprint of a curve metric's qsketch update plane
    (shared across AUROC / AveragePrecision instances with equal config)."""
    spec = metric._defaults["hist"]
    pos_label = metric.pos_label if getattr(metric, "pos_label", None) is not None else 1
    return _spec_key("qsketch_curve", spec) + (int(pos_label),)


def qsketch_rank_group_key(metric: Any) -> tuple:
    """Compute-group fingerprint of a rank metric's qsketch update plane
    (shared across Spearman / Kendall instances with equal config)."""
    return _spec_key("qsketch_rank", metric._defaults["joint"])
