from metrics_tpu.parallel.buffer import (
    PaddedBuffer,
    buffer_all_gather,
    buffer_append,
    buffer_compact_gathered,
    buffer_init,
    buffer_mask,
    buffer_merge,
    buffer_values,
    handle_overflow,
    overflow_policy,
    set_overflow_policy,
)
from metrics_tpu.parallel.faults import (
    ChaosInjector,
    FaultSpec,
    chaos,
    corrupt_pytree,
)
from metrics_tpu.parallel.placement import (
    HostHierarchy,
    MeshHierarchy,
    batch_sharded,
    class_sharded,
    hierarchical_mesh,
    host_hierarchy,
    mesh_hierarchy,
    row_sharded,
)
from metrics_tpu.parallel.sharded_epoch import (
    regroup_by_query,
    sharded_auroc,
    sharded_auroc_matrix,
    sharded_average_precision,
    sharded_average_precision_matrix,
    sharded_clf_curve_matrix,
    sharded_kendall,
    sharded_rank,
    sharded_retrieval_sums,
    sharded_spearman,
)
from metrics_tpu.parallel.sync import (
    SyncGuard,
    coalesced_sync_state,
    current_sync_guard,
    gather_all_arrays,
    host_gather,
    merge_values,
    packable_gather,
    set_sync_guard,
    slice_leader_gather,
    sync_state,
    sync_value,
)
