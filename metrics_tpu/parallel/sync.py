"""Cross-device / cross-process state synchronization — the distributed backend.

Parity target: reference ``torchmetrics/utilities/distributed.py`` whose single
collective is ``gather_all_tensors`` (distributed.py:91-118, a barrier +
``torch.distributed.all_gather``), applied per-state and followed by a
stack/flatten + reduction (reference torchmetrics/metric.py:179-197).

TPU-native design — two sync planes instead of one NCCL call:

1. **In-jit plane** (``sync_state``): states live on a ``jax.sharding.Mesh``;
   sync is an XLA collective over a named axis inside ``shard_map``/``pmap``:
   ``sum→lax.psum``, ``mean→lax.pmean``, ``min→lax.pmin``, ``max→lax.pmax``,
   stack-semantics (``dist_reduce_fx=None``) → ``lax.all_gather``, cat-states
   (PaddedBuffer) → ``buffer_all_gather``. Collectives ride ICI within a slice
   and DCN across slices; XLA routes automatically.

2. **Host plane** (``host_gather``): for eval loops driven outside jit on
   multi-host deployments — per-leaf ``multihost_utils.process_allgather``
   (the DCN analogue of the reference's Gloo path), identity on one process.

3. **Deferred plane** (``metrics_tpu.parallel.deferred``): the
   future-returning form of both planes above. ``deferred_sync_state`` /
   ``DeferredSyncPlane`` dispatch the in-jit staging WITHOUT fencing (the
   identical ``coalesced_sync_state`` program — only the fence moves) and
   ``deferred_host_gather`` runs :func:`host_gather` verbatim — the active
   :class:`SyncGuard`, the chaos hook, payload packing, everything below —
   on a single-worker background executor, so deferred gathers keep the
   submission order this module's collectives pair by. ``Metric.sync_state
   (..., deferred=True)`` and ``Metric.sync_lag = 1`` are the bound forms.

Both planes are TOPOLOGY-AWARE: pass a :class:`~metrics_tpu.parallel.placement.
MeshHierarchy` (``hierarchy=``, or directly as the axis argument) and every
staged collective splits into two stages — reductions run over the fast
intra-slice ``ici`` axis first and only the per-slice result crosses the slow
``dcn`` axis; gathers exchange each device's payload across slices FIRST
(payload ``p`` over the S-sized dcn axis — the slice-leader exchange
load-balanced over the slice's devices) and then replicate the cross-slice
stacks intra-slice. DCN ring traffic per payload byte drops from ``W-1``
hops (flat world axis, W = S*L) to ``S-1``. A single-slice hierarchy
(dcn axis size 1) collapses to the flat plane over the ici axis — same
collective count, same program. The host plane's analogue is
:func:`slice_leader_gather`.
"""
import functools
import threading
import time
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.observability.counters import (
    record_collective,
    record_fault,
    record_gather_skip,
    record_states_synced,
)
from metrics_tpu.observability.jaxprof import annotate
from metrics_tpu.parallel.buffer import PaddedBuffer, buffer_all_gather, handle_overflow
from metrics_tpu.parallel.placement import HostHierarchy, MeshHierarchy
from metrics_tpu.parallel.sketch import is_sketch, sketch_merge
from metrics_tpu.utils.data import dim_zero_cat, dim_zero_max, dim_zero_mean, dim_zero_min, dim_zero_sum
from metrics_tpu.utils.exceptions import InjectedFaultError, StateCorruptionError, SyncTimeoutError

# A reduction spec as accepted by ``Metric.add_state`` (reference metric.py:88-148),
# extended with 'min'/'max' (the reference passes torch.min/torch.max callables
# for PSNR, reference torchmetrics/regression/psnr.py:102-103).
ReduceFx = Union[str, Callable, None]

_STR_REDUCTIONS = ("sum", "mean", "cat", "min", "max")


def associative(fn: Callable) -> Callable:
    """Mark a callable ``dist_reduce_fx`` as an associative fold over axis 0.

    A plain callable reduction (reference metric.py:135-142 semantics) is
    applied once to the ``(world, ...)`` stack and nothing more can be assumed
    about it. A callable marked associative promises ``fn(stack([a, b]))`` is a
    valid pairwise merge — which lets the fused forward merge a batch delta
    into the accumulator (``merge_values``) and checkpoint shards fold
    pairwise, exactly like the built-in ``sum``/``min``/``max`` strings.
    """
    fn._mtpu_associative = True
    return fn


def is_associative(fx: ReduceFx) -> bool:
    return callable(fx) and getattr(fx, "_mtpu_associative", False)


def canonicalize_reduce_fx(fx: ReduceFx) -> ReduceFx:
    """Validate and canonicalize a ``dist_reduce_fx`` argument."""
    if fx is None or callable(fx):
        return fx
    if isinstance(fx, str) and fx in _STR_REDUCTIONS:
        return fx
    raise ValueError(f"`dist_reduce_fx` must be callable or one of {list(_STR_REDUCTIONS) + [None]}, got {fx!r}")


def stacked_reduction(fx: ReduceFx) -> Optional[Callable]:
    """The post-gather reduction applied to states stacked as ``(world, ...)``.

    Mirrors the reference mapping at metric.py:135-142: strings map to the
    dim-zero reductions, ``None`` keeps the stacked tensor, callables are
    applied to the stacked tensor directly.
    """
    if fx == "sum":
        return dim_zero_sum
    if fx == "mean":
        return dim_zero_mean
    if fx == "cat":
        return dim_zero_cat
    if fx == "min":
        return dim_zero_min
    if fx == "max":
        return dim_zero_max
    if fx is None:
        return None
    return fx


def merge_values(fx: ReduceFx, acc: Any, delta: Any) -> Any:
    """Pairwise associative merge of two state values (accumulate plane).

    This is the generalization the TPU build adds over the reference: the same
    per-state reduction that powers cross-rank sync also powers merging a
    batch-delta into the accumulator (single fused update per ``forward``)
    and merging checkpoint shards.
    """
    if isinstance(acc, PaddedBuffer):
        from metrics_tpu.parallel.buffer import buffer_merge

        return buffer_merge(acc, delta)
    if is_sketch(acc):
        # elementwise integer addition: associative, commutative, bit-exact
        return sketch_merge(acc, delta)
    if isinstance(acc, list):
        if isinstance(delta, PaddedBuffer):
            # the delta update lazily promoted this cat state to a buffer
            # (capacity metric, first batch); an empty list accumulator is
            # absorbed, a non-empty one cannot merge into fixed capacity
            if acc:
                raise ValueError(
                    "Cannot merge a PaddedBuffer delta into a non-empty eager list state."
                )
            return delta
        return acc + list(delta)
    if fx == "sum":
        return acc + delta
    if fx == "min":
        return jnp.minimum(acc, delta)
    if fx == "max":
        return jnp.maximum(acc, delta)
    if is_associative(fx):
        return fx(jnp.stack([acc, delta]))
    raise ValueError(f"Reduction {fx!r} has no pairwise merge; metric must use the unfused update path.")


def merge_values_stacked(fx: ReduceFx, acc: Any, stacked: Any) -> Any:
    """Merge a ``(steps, ...)`` stack of state deltas into the accumulator in
    ONE reduction op (the batched-forward plane: per-step deltas come from a
    ``vmap``-ed update, and the whole stack folds at once — no serial scan,
    which pays per-iteration overhead on remote-attached devices)."""
    if is_sketch(acc):
        # stacked sketch deltas: counts carry a leading (steps,) axis
        return type(acc)(acc.counts + jnp.sum(stacked.counts, axis=0))
    if fx == "sum":
        return acc + jnp.sum(stacked, axis=0)
    if fx == "min":
        return jnp.minimum(acc, jnp.min(stacked, axis=0))
    if fx == "max":
        return jnp.maximum(acc, jnp.max(stacked, axis=0))
    if is_associative(fx):
        return fx(jnp.concatenate([acc[None], stacked], axis=0))
    raise ValueError(f"Reduction {fx!r} has no stacked merge; use the per-step path.")


def is_stack_mergeable(fx: ReduceFx, default: Any) -> bool:
    """Whether a state supports the one-op stacked merge (no lists/buffers)."""
    from metrics_tpu.parallel.cms import CMSSpec
    from metrics_tpu.parallel.qsketch import QSketchSpec
    from metrics_tpu.parallel.sketch import SketchSpec
    from metrics_tpu.parallel.slab import SlabSpec

    if isinstance(default, (list, PaddedBuffer)):
        return False
    if is_sketch(default) or isinstance(default, (SketchSpec, CMSSpec, QSketchSpec)):
        return True  # one stacked-sum fold of the counts
    if isinstance(default, SlabSpec):
        # slab rows register sum/min/max sync reductions, all of which have
        # one-op stacked folds over the (steps, K, ...) axis
        return True
    return fx in ("sum", "min", "max") or is_associative(fx)


def is_mergeable(fx: ReduceFx, default: Any) -> bool:
    """Whether a state with this reduction supports pairwise merge (fused forward)."""
    from metrics_tpu.parallel.cms import CMSSpec
    from metrics_tpu.parallel.qsketch import QSketchSpec
    from metrics_tpu.parallel.sketch import SketchSpec
    from metrics_tpu.parallel.slab import SlabSpec

    if isinstance(default, (list, PaddedBuffer)) or fx == "cat":
        return True
    if is_sketch(default) or isinstance(default, (SketchSpec, CMSSpec, QSketchSpec)):
        # count-min tails and quantile sketches are one more counts leaf:
        # merge = elementwise add
        return True
    if isinstance(default, SlabSpec):
        return True  # per-slot sum/min/max rows merge elementwise
    return fx in ("sum", "min", "max") or is_associative(fx)


# ------------------------------------------------------ hierarchy plumbing
def _fanout(axis_name: Any) -> Optional[int]:
    """Trace-time participant count of a (possibly tuple) named axis, or
    None outside an axis binding — counters then fall back to payload bytes."""
    from metrics_tpu.utils.compat import axis_size

    try:
        return int(axis_size(axis_name))
    except Exception:
        return None


def _rec(kind: str, value: Any, axis_name: Any, crossing: str) -> None:
    record_collective(kind, value, crossing=crossing, fanout=_fanout(axis_name))


def _resolve_hierarchy(axis_name: Any, hierarchy: Union[MeshHierarchy, bool, None]):
    """(axis_name, hierarchy, crossing) with the degenerate cases folded.

    A :class:`MeshHierarchy` passed AS the axis is hoisted to ``hierarchy``;
    a single-slice hierarchy (dcn axis size 1 at trace time) collapses to
    the FLAT plane over the ici axis — identical program and collective
    count, attributed to the ``ici`` crossing.

    AUTO-DERIVATION: ``hierarchy=None`` with a 2-tuple axis named exactly
    ``(dcn, ici)`` — the span a 2-level multi-slice mesh exposes — derives
    the :class:`MeshHierarchy` itself, so ici-first/DCN-last two-stage
    staging is the multi-slice DEFAULT instead of an explicit kwarg (and
    planes built on this resolver, the sparse delta plane included, inherit
    it for free). ``hierarchy=False`` is the opt-out sentinel: force the
    FLAT plane over whatever span the axis names (one world-crossing
    collective), never deriving.
    """
    if hierarchy is False:
        if isinstance(axis_name, MeshHierarchy):
            axis_name = (axis_name.dcn_axis, axis_name.ici_axis)
        return axis_name, None, "world"
    if hierarchy is None and isinstance(axis_name, MeshHierarchy):
        hierarchy = axis_name
    if (
        hierarchy is None
        and isinstance(axis_name, tuple)
        and len(axis_name) == 2
        and set(axis_name) == {"dcn", "ici"}
    ):
        hierarchy = MeshHierarchy(ici_axis="ici", dcn_axis="dcn")
    if hierarchy is None:
        return axis_name, None, "world"
    dcn = _fanout(hierarchy.dcn_axis)
    if dcn is not None and dcn == 1:
        return hierarchy.ici_axis, None, "ici"
    return axis_name, hierarchy, None


def _hier_reduce(kind: str, op: Callable, value: Any, h: MeshHierarchy) -> Any:
    """Two-stage reduction: the fast ici axis first, so only the per-slice
    reduced value crosses dcn."""
    _rec(kind, value, h.ici_axis, "ici")
    local = op(value, h.ici_axis)
    _rec(kind, local, h.dcn_axis, "dcn")
    return op(local, h.dcn_axis)


def _hier_gather_stack(value: Array, h: MeshHierarchy, kind: str = "all_gather") -> Array:
    """``(world, *shape)`` stack in slice-major world order via two stages.

    The DCN stage runs FIRST with the unexpanded payload: each device
    exchanges its own rows with its same-position peers across slices —
    the slice's payload crosses DCN exactly once, sharded over the slice's
    devices instead of funneled through one leader (same DCN bytes as a
    leader exchange, no leader bottleneck). The ICI stage then replicates
    the cross-slice stacks within each slice. Equivalent to a flat
    world-axis ``all_gather`` over slice-major device order.
    """
    _rec(kind, value, h.dcn_axis, "dcn")
    g1 = jax.lax.all_gather(value, h.dcn_axis)  # (S, ...)
    _rec(kind, g1, h.ici_axis, "ici")
    g2 = jax.lax.all_gather(g1, h.ici_axis)  # (L, S, ...)
    g = jnp.swapaxes(g2, 0, 1)  # (S, L, ...): slice-major world order
    return g.reshape((-1, *g.shape[2:]))


def _hier_buffer_all_gather(buf: PaddedBuffer, h: MeshHierarchy) -> PaddedBuffer:
    """Hierarchical :func:`buffer_all_gather`: two-stage data + counts
    gathers, then the ordinary per-buffer compaction."""
    from metrics_tpu.parallel.buffer import buffer_compact_gathered

    data = _hier_gather_stack(buf.data, h)  # (W, cap, *item)
    counts = _hier_gather_stack(buf.count, h)  # (W,)
    return buffer_compact_gathered(data, counts)


def sync_value(
    fx: ReduceFx,
    value: Any,
    axis_name: Any,
    hierarchy: Union[MeshHierarchy, bool, None] = None,
    _crossing: Optional[str] = None,
) -> Any:
    """In-jit sync of one state value over a named mesh axis.

    ``axis_name`` may be a single axis, a tuple of axes (the flat world
    span of a 2-level mesh), or a :class:`MeshHierarchy`; ``hierarchy=``
    stages every collective as ici-then-dcn (see the module docstring).
    With ``hierarchy=None`` a ``("dcn", "ici")`` tuple axis AUTO-DERIVES
    the two-stage hierarchy (the multi-slice default); pass
    ``hierarchy=False`` to force the flat plane over that span.

    Collective accounting: this function runs at *trace* time, so the
    counters record ops staged into the compiled program — which IS the
    per-step collective cost (the program replays them every step). See
    ``metrics_tpu.observability.counters``.
    """
    axis_name, hierarchy, crossing = _resolve_hierarchy(axis_name, hierarchy)
    crossing = _crossing or crossing  # a caller that already resolved a
    # degenerate hierarchy passes its crossing down (ici, not world)
    if isinstance(value, list):
        raise TypeError(
            "Eager list states cannot be synced inside jit; construct the metric "
            "with a `capacity` so cat-states use PaddedBuffers."
        )
    if hierarchy is not None:
        return _sync_value_hier(fx, value, hierarchy)
    if is_sketch(value):
        # the sketch contract: one psum of the counts, bit-exact merge
        _rec("psum", value.counts, axis_name, crossing)
        return type(value)(jax.lax.psum(value.counts, axis_name))
    if isinstance(value, PaddedBuffer):
        _rec("all_gather", value.data, axis_name, crossing)
        _rec("all_gather", value.count, axis_name, crossing)
        return buffer_all_gather(value, axis_name)
    if fx == "sum":
        _rec("psum", value, axis_name, crossing)
        return jax.lax.psum(value, axis_name)
    if fx == "mean":
        _rec("pmean", value, axis_name, crossing)
        return jax.lax.pmean(value, axis_name)
    if fx == "min":
        _rec("pmin", value, axis_name, crossing)
        return jax.lax.pmin(value, axis_name)
    if fx == "max":
        _rec("pmax", value, axis_name, crossing)
        return jax.lax.pmax(value, axis_name)
    _rec("all_gather", value, axis_name, crossing)
    gathered = jax.lax.all_gather(value, axis_name)  # (world, ...)
    if fx is None:
        return gathered
    if fx == "cat":
        return gathered.reshape((-1, *gathered.shape[2:])) if gathered.ndim > 1 else gathered.reshape(-1)
    return fx(gathered)


def _sync_value_hier(fx: ReduceFx, value: Any, h: MeshHierarchy) -> Any:
    """The two-stage per-leaf plane (multi-slice hierarchy already proven)."""
    if is_sketch(value):
        # integer psum is exactly associative: ici-first staging is bit-exact
        return type(value)(_hier_reduce("psum", jax.lax.psum, value.counts, h))
    if isinstance(value, PaddedBuffer):
        return _hier_buffer_all_gather(value, h)
    if fx == "sum":
        return _hier_reduce("psum", jax.lax.psum, value, h)
    if fx == "mean":
        # pmean nests cleanly: slices are equal-sized, so the mean of
        # per-slice means IS the world mean
        return _hier_reduce("pmean", jax.lax.pmean, value, h)
    if fx == "min":
        return _hier_reduce("pmin", jax.lax.pmin, value, h)
    if fx == "max":
        return _hier_reduce("pmax", jax.lax.pmax, value, h)
    gathered = _hier_gather_stack(value, h)  # (world, ...) slice-major
    if fx is None:
        return gathered
    if fx == "cat":
        return gathered.reshape((-1, *gathered.shape[2:])) if gathered.ndim > 1 else gathered.reshape(-1)
    return fx(gathered)


def sync_state(
    state: Dict[str, Any],
    reductions: Dict[str, ReduceFx],
    axis_name: Any,
    hierarchy: Union[MeshHierarchy, bool, None] = None,
) -> Dict[str, Any]:
    """In-jit sync of a whole state dict over a named mesh axis (pure,
    jit-safe). ``hierarchy=`` follows :func:`sync_value`'s auto-derivation:
    a ``("dcn", "ici")`` tuple axis stages two-level by default,
    ``hierarchy=False`` forces the flat plane."""
    record_states_synced(len(state))
    with annotate("metric.sync"):
        return {
            name: sync_value(reductions[name], value, axis_name, hierarchy)
            for name, value in state.items()
        }


def coalesced_sync_state(
    state: Dict[Any, Any],
    reductions: Dict[Any, ReduceFx],
    axis_name: Any,
    hierarchy: Union[MeshHierarchy, bool, None] = None,
) -> Dict[Any, Any]:
    """In-jit sync with COALESCED collectives: a handful of bucketed
    collectives instead of one (or two) per state leaf. ``hierarchy=``
    follows :func:`sync_value`'s auto-derivation: a ``("dcn", "ici")``
    tuple axis stages two-level by default, ``hierarchy=False`` forces the
    flat plane.

    Three bucket planes, all keyed by dtype:

    - **Reduce plane** (``sum``/``min``/``max`` array leaves): every ``sum``
      bucket folds into ONE byte-packed ``psum`` per crossing — 4-byte
      integer dtypes bitcast into a single concatenated int32 lane (the
      buffer plane's PR 4 counts trick, applied to the reduce plane; the
      reinterpretation is lossless and two's-complement addition is
      width-exact for signed and unsigned alike), float and odd-width
      dtypes riding as sibling operands of the same variadic call — so the
      staged dispatch count is independent of how many dtypes the
      collection mixes. ``pmin``/``pmax`` buckets ride as separate ops only
      for the dtypes that need them. Element values are unchanged —
      cross-device reduction is elementwise, so concatenation cannot alter
      any element's result. Floating ``mean`` leaves FOLD INTO the packed
      ``sum`` payload (psum, then divide by the axis size after slicing),
      eliminating the separate ``pmean`` per leaf. (JAX lowers a variadic
      ``psum`` to one all-reduce per operand dtype; XLA's all-reduce
      combiner re-merges them on real backends — the counters pin the
      library-level staged dispatch, one per crossing.)
    - **Gather plane** (``cat``/``None``/callable array leaves): flattened
      into one payload per dtype bucket, gathered with ONE ``all_gather``,
      then sliced per leaf into the exact ``(world, *shape)`` stack the
      per-leaf path would have produced before the leaf's own finishing step
      (keep / dim-zero cat / callable) runs. Gather is concatenation per
      leaf, so slicing the shared payload is semantics-preserving for every
      reduction, callables included.
    - **Sketch leaves** (:class:`~metrics_tpu.parallel.sketch.
      HistogramSketch` / ``RankSketch``) FOLD INTO the ``sum`` reduce bucket
      of their counts dtype — zero new collective kinds: a sketch-state
      collection syncs with the same single bucketed ``psum`` a StatScores
      collection uses, and integer addition is exactly associative, so the
      bucketed (and hierarchical ici-first) staging is bit-exact.
    - **Keyed slab leaves** (``parallel/slab.py``: ``(K, *shape)`` segment
      slabs registered with ``sum``/``min``/``max`` reductions, sketch slabs
      with a leading K axis) need NO arm of their own — they are exactly the
      array/sketch leaves above, so one bucketed ``psum``/``pmin``/``pmax``
      moves all K segments and the staged collective count is K-independent
      (the property ``bench.py --check-collectives`` pins at K=10 000).
    - **Buffer plane** (:class:`PaddedBuffer` cat-states): same-dtype
      buffers ravel their ``(capacity, *item)`` rows into one concatenated
      payload gathered with ONE ``all_gather`` — and for 4-byte bucket
      dtypes the int32 counts vector rides INSIDE that payload (bitcast to
      the bucket dtype, appended after the data, bitcast back after the
      gather), so the whole bucket stages a single collective. The bitcast
      is a pure reinterpretation and ``all_gather`` is data movement (no
      arithmetic, no canonicalization), so counts round-trip bit-exactly.
      Non-4-byte bucket dtypes (bool, f16, f64) keep the separate counts
      gather — 2 collectives per bucket, still never 2 per buffer. Each
      buffer's slice then runs the ordinary compaction
      (``buffer_compact_gathered``'s prefix-sum scatter) on its view, so
      results are bit-identical to per-buffer :func:`buffer_all_gather`.

    A collection's whole sync plane collapses from one collective per leaf
    per metric to a handful of bucketed collectives (latency-bound on
    ICI/DCN at small state sizes). Single-member buckets delegate to the
    per-leaf :func:`sync_value` — no flatten/slice overhead, identical
    collective count. Eager list leaves still raise (no jit-safe sync).

    With ``hierarchy=`` (or a :class:`MeshHierarchy` as ``axis_name``) every
    bucketed collective stages HIERARCHICALLY: reduce buckets psum/pmin/pmax
    over the ici axis first and cross dcn only with the reduced bucket;
    gather/buffer buckets exchange the bucket payload across slices first
    (payload ``p`` over the S-sized dcn axis) and replicate intra-slice —
    per-leaf values are bit-identical to the flat plane, only the DCN
    traffic shrinks (see ``observability.counters`` ``bytes_by_crossing``).
    """
    from metrics_tpu.parallel.buffer import buffer_compact_gathered
    from metrics_tpu.utils.compat import axis_size

    axis_name, hierarchy, crossing = _resolve_hierarchy(axis_name, hierarchy)

    if hierarchy is None:

        def creduce(kind: str, op: Callable, flat: Array) -> Array:
            _rec(kind, flat, axis_name, crossing)
            return op(flat, axis_name)

        def cgather(flat: Array) -> Array:
            _rec("coalesced_gather", flat, axis_name, crossing)
            return jax.lax.all_gather(flat, axis_name)

        def world_size() -> int:
            return axis_size(axis_name)

    else:

        def creduce(kind: str, op: Callable, flat: Array) -> Array:
            return _hier_reduce(kind, op, flat, hierarchy)

        def cgather(flat: Array) -> Array:
            return _hier_gather_stack(flat, hierarchy, kind="coalesced_gather")

        def world_size() -> int:
            return axis_size(hierarchy.ici_axis) * axis_size(hierarchy.dcn_axis)

    record_states_synced(len(state))
    with annotate("metric.sync"):
        out: Dict[Any, Any] = {}
        buckets: Dict[tuple, list] = {}  # (op, dtype str) -> [leaf name]
        gather_buckets: Dict[str, list] = {}  # dtype str -> [array leaf name]
        buffer_buckets: Dict[str, list] = {}  # dtype str -> [buffer leaf name]
        for name, value in state.items():
            fx = reductions[name]
            if isinstance(value, PaddedBuffer):
                buffer_buckets.setdefault(str(value.data.dtype), []).append(name)
            elif is_sketch(value):
                # sketch counts ride the sum bucket of their dtype: zero new
                # collective kinds, one shared psum with every other sum leaf
                buckets.setdefault(("sum", str(value.counts.dtype)), []).append(name)
            elif isinstance(value, list):
                out[name] = sync_value(fx, value, axis_name, hierarchy, _crossing=crossing)  # raises: not jit-safe
            elif fx in ("sum", "min", "max"):
                buckets.setdefault((fx, str(value.dtype)), []).append(name)
            elif fx == "mean" and jnp.issubdtype(value.dtype, jnp.inexact):
                # psum-then-divide == pmean elementwise; ride the sum bucket
                buckets.setdefault(("sum", str(value.dtype)), []).append(name)
            else:
                # cat / None / callable reductions: the gather plane
                gather_buckets.setdefault(str(value.dtype), []).append(name)

        ops = {"min": jax.lax.pmin, "max": jax.lax.pmax}
        kinds = {"min": "pmin", "max": "pmax"}
        def _payload(v):
            return v.counts if is_sketch(v) else v

        def _unpack_sum(synced: Array, names: list) -> None:
            offset = 0
            for n in names:
                value = state[n]
                arr = _payload(value)
                piece = synced[offset: offset + arr.size].reshape(arr.shape)
                if reductions[n] == "mean":
                    piece = piece / world_size()
                out[n] = type(value)(piece) if is_sketch(value) else piece
                offset += arr.size

        # -- sum plane: ONE packed psum per crossing. Every sum bucket folds
        # into a single variadic ``psum`` call: 4-byte integer dtypes bitcast
        # into one concatenated int32 lane (reinterpretation is lossless and
        # two's-complement addition is width-exact for signed and unsigned
        # alike, so the packed add is bit-exact), while float and odd-width
        # dtypes ride as sibling operands of the SAME staged call. The
        # counters record one staged dispatch per crossing with the summed
        # payload (dtype label ``packed`` when more than one operand rides).
        sum_items = [(d, names) for (op, d), names in buckets.items() if op == "sum"]
        if sum(len(names) for _, names in sum_items) == 1:
            n = sum_items[0][1][0]
            out[n] = sync_value(reductions[n], state[n], axis_name, hierarchy, _crossing=crossing)
        elif sum_items:
            i32 = jnp.dtype(jnp.int32)
            lane_parts: list = []   # i32-bitcast segments, in concat order
            lane_layout: list = []  # (names, orig dtype, segment size) per part
            native_ops: list = []   # one flat operand per unpackable dtype
            native_layout: list = []
            for d, names in sum_items:
                dt = jnp.dtype(d)
                flat = jnp.concatenate([jnp.ravel(_payload(state[n])) for n in names])
                if dt.itemsize == 4 and jnp.issubdtype(dt, jnp.integer):
                    lane_parts.append(
                        flat if dt == i32 else jax.lax.bitcast_convert_type(flat, i32)
                    )
                    lane_layout.append((names, dt, flat.size))
                else:
                    native_ops.append(flat)
                    native_layout.append(names)
            operands: list = []
            if lane_parts:
                operands.append(
                    jnp.concatenate(lane_parts) if len(lane_parts) > 1 else lane_parts[0]
                )
            operands.extend(native_ops)
            payload = tuple(operands) if len(operands) > 1 else operands[0]
            synced = creduce("psum", jax.lax.psum, payload)
            synced = synced if isinstance(synced, tuple) else (synced,)
            next_op = 0
            if lane_parts:
                lane, next_op = synced[0], 1
                lane_off = 0
                for names, dt, size in lane_layout:
                    seg = lane[lane_off: lane_off + size]
                    lane_off += size
                    _unpack_sum(
                        seg if dt == i32 else jax.lax.bitcast_convert_type(seg, dt), names
                    )
            for names, arr in zip(native_layout, synced[next_op:]):
                _unpack_sum(arr, names)

        # -- min/max riders: one pmin/pmax per (op, dtype) bucket that needs it
        for (op, _dtype), names in buckets.items():
            if op == "sum":
                continue
            if len(names) == 1:
                out[names[0]] = sync_value(reductions[names[0]], state[names[0]], axis_name, hierarchy, _crossing=crossing)
                continue
            flat = jnp.concatenate([jnp.ravel(state[n]) for n in names])
            synced = creduce(kinds[op], ops[op], flat)
            offset = 0
            for n in names:
                value = state[n]
                piece = synced[offset: offset + value.size].reshape(value.shape)
                out[n] = piece
                offset += value.size

        for _dtype, names in gather_buckets.items():
            if len(names) == 1:
                out[names[0]] = sync_value(reductions[names[0]], state[names[0]], axis_name, hierarchy, _crossing=crossing)
                continue
            flat = jnp.concatenate([jnp.ravel(state[n]) for n in names])
            gathered = cgather(flat)  # (W, sum of sizes)
            offset = 0
            for n in names:
                value = state[n]
                g = gathered[:, offset: offset + value.size].reshape(
                    (gathered.shape[0], *value.shape)
                )
                offset += value.size
                fx = reductions[n]
                if fx is None:
                    out[n] = g
                elif fx == "cat":
                    out[n] = g.reshape((-1, *g.shape[2:])) if g.ndim > 1 else g.reshape(-1)
                else:
                    out[n] = fx(g)

        for _dtype, names in buffer_buckets.items():
            if len(names) == 1:
                out[names[0]] = sync_value(reductions[names[0]], state[names[0]], axis_name, hierarchy, _crossing=crossing)
                continue
            flat = jnp.concatenate([jnp.ravel(state[n].data) for n in names])
            counts = jnp.stack([state[n].count for n in names])  # (n buffers,)
            bucket_dtype = jnp.dtype(flat.dtype)
            if bucket_dtype.itemsize == 4 and jnp.dtype(counts.dtype).itemsize == 4:
                # counts ride the data payload: ONE all_gather per bucket
                payload = jnp.concatenate(
                    [flat, jax.lax.bitcast_convert_type(counts, bucket_dtype)]
                )
                gathered = cgather(payload)
                g_data = gathered[:, : flat.size]  # (W, sum of data sizes)
                g_counts = jax.lax.bitcast_convert_type(
                    gathered[:, flat.size:], counts.dtype
                )  # (W, n buffers)
            else:
                g_data = cgather(flat)  # (W, sum of data sizes)
                g_counts = cgather(counts)  # (W, n buffers)
            offset = 0
            for i, n in enumerate(names):
                buf = state[n]
                size = buf.data.size
                view = g_data[:, offset: offset + size].reshape(
                    (g_data.shape[0], *buf.data.shape)
                )
                offset += size
                out[n] = buffer_compact_gathered(view, g_counts[:, i])
    return out


# ------------------------------------------------- host-plane fault tolerance
class SyncGuard(NamedTuple):
    """Deadline/retry/degrade policy for the host sync plane.

    Applied per gather CALL by :func:`host_gather` (and everything routed
    through it: the packed plane, slice-leader mode, the collection's grouped
    host sync). The default guard — no deadline, no finite-checking — keeps
    the exact pre-guard fast path: zero wrapping, zero threads.

    - ``deadline_s``: bound on how long one gather attempt may be *waited on*
      (the attempt itself keeps running on a daemon worker — a stalled
      collective cannot be cancelled, only abandoned — so the rank still
      ENTERS the collective and peers' rendezvous completes).
    - ``max_retries`` / ``backoff_s``: transient failures (injected drops,
      deadline expiries, detected payload corruption) are retried up to
      ``max_retries`` times with exponential backoff
      (``backoff_s * 2**attempt``).
    - ``policy``: on exhaustion, ``"raise"`` throws a typed
      :class:`~metrics_tpu.utils.exceptions.SyncTimeoutError`; ``"degrade"``
      falls back to LOCAL-ONLY state for the rest of this sync plane — the
      enclosing span is stamped ``degraded=yes`` and ``degraded_computes``
      bumps. A degrading rank still issues (fire-and-forget) every remaining
      collective it would have entered, preserving world-collective entry
      order so it never deadlocks the others.
    - ``check_finite``: scan gathered payloads and treat non-finite values
      that were NOT in the local payload as transient corruption (retry).
    """

    deadline_s: Optional[float] = None
    max_retries: int = 2
    backoff_s: float = 0.05
    policy: str = "raise"  # 'raise' | 'degrade'
    check_finite: bool = False


_SYNC_GUARD = SyncGuard()

# host-plane fault hook (a parallel.faults.ChaosInjector when installed);
# consulted only on the guarded path
_FAULT_HOOK: Optional[Any] = None


def set_sync_guard(guard: Optional[SyncGuard]) -> SyncGuard:
    """Set the process-wide default :class:`SyncGuard`; returns the old one
    (``None`` restores the trivial default)."""
    global _SYNC_GUARD
    old = _SYNC_GUARD
    guard = guard if guard is not None else SyncGuard()
    if guard.policy not in ("raise", "degrade"):
        raise ValueError(f"SyncGuard.policy must be 'raise' or 'degrade', got {guard.policy!r}")
    _SYNC_GUARD = guard
    return old


def current_sync_guard() -> SyncGuard:
    return _SYNC_GUARD


class _DeadlineExceeded(Exception):
    """Internal: one gather attempt exceeded ``deadline_s`` (retryable)."""


def _attempt_with_deadline(call: Callable[[], Any], deadline_s: float) -> Any:
    """Run ``call`` on a daemon worker, waiting at most ``deadline_s``.

    On expiry the WAIT is abandoned, not the call: a collective cannot be
    cancelled once entered, and abandoning the entry would strand the peers'
    rendezvous. The daemon flag keeps an injected infinite stall from
    blocking process exit.
    """
    box: Dict[str, Any] = {}
    done = threading.Event()

    def work() -> None:
        try:
            box["result"] = call()
        except BaseException as err:  # noqa: BLE001 - transported to the waiter
            box["error"] = err
        finally:
            done.set()

    worker = threading.Thread(target=work, daemon=True, name="mtpu-sync-guard")
    worker.start()
    if not done.wait(deadline_s):
        raise _DeadlineExceeded(f"gather attempt exceeded its {deadline_s}s deadline")
    if "error" in box:
        raise box["error"]
    return box["result"]


def _fire_and_forget(call: Callable[[], Any]) -> None:
    """Issue a collective without waiting on it (the degraded rank's
    entry-order obligation)."""
    threading.Thread(target=lambda: _swallow(call), daemon=True, name="mtpu-sync-degraded").start()


def _swallow(call: Callable[[], Any]) -> None:
    try:
        call()
    except BaseException:  # noqa: BLE001 - the result is abandoned by design
        pass


def _payload_suspect(arr: "np.ndarray") -> bool:
    """Corruption signature of one payload array: non-finite floats, or
    integers within the saturation margin of their dtype range (the int
    analogue of NaN — see ``core.metric.saturated_count``)."""
    if np.issubdtype(arr.dtype, np.floating):
        return not np.isfinite(arr).all()
    if np.issubdtype(arr.dtype, np.integer):
        info = np.iinfo(arr.dtype)
        margin = max(info.max // 2048, 1)
        return bool(((arr >= info.max - margin) | (arr <= info.min + margin)).any())
    return False


def _payload_corrupted(local: Any, gathered: List[Any]) -> bool:
    """Corruption signatures in the gathered payload that the LOCAL payload
    did not carry (a genuinely-NaN or genuinely-saturated state must not
    retry forever)."""
    if _payload_suspect(np.asarray(local)):
        return False
    return any(_payload_suspect(np.asarray(part)) for part in gathered)


def _guard_gather_fn(gather_fn: Callable, guard: SyncGuard, plane: Dict[str, Any]) -> Callable:
    """Wrap one gather fn with the deadline/retry/degrade machinery.

    ``plane`` is the per-``host_gather`` shared state: the site-relative call
    counter (fault addressing), the degraded latch, and the installed fault
    hook. The wrapper transports exactly ``gather_fn(value) -> [per-rank]``,
    so it rides the packed and per-leaf paths unchanged.
    """

    def guarded(value: Any) -> List[Any]:
        hook = plane["hook"]
        site = plane["site"]
        idx = hook.note_call(site) if hook is not None else plane["calls"]
        plane["calls"] += 1

        def attempt_call(attempt: int) -> List[Any]:
            if hook is not None:
                hook.before_call(site, idx, attempt)
            result = gather_fn(value)
            if hook is not None:
                result = hook.after_call(site, idx, attempt, result)
            return result

        if plane["degraded"]:
            # entry order preserved: the degraded rank still ISSUES every
            # collective it would have entered, so peers' rendezvous
            # completes; it just never waits on the result again
            _fire_and_forget(lambda: attempt_call(0))
            return [value]

        attempt = 0
        while True:
            try:
                if guard.deadline_s is not None:
                    result = _attempt_with_deadline(lambda a=attempt: attempt_call(a), guard.deadline_s)
                else:
                    result = attempt_call(attempt)
                if guard.check_finite and _payload_corrupted(value, result):
                    raise StateCorruptionError(
                        f"non-finite values appeared in gathered sync payload (call {idx})"
                    )
                return result
            except (InjectedFaultError, _DeadlineExceeded, StateCorruptionError) as err:
                attempt += 1
                record_fault("sync_retries")
                if attempt <= guard.max_retries:
                    time.sleep(guard.backoff_s * (2 ** (attempt - 1)))
                    continue
                record_fault("sync_deadline_exceeded")
                if guard.policy == "degrade":
                    plane["degraded"] = True
                    return [value]
                if isinstance(err, StateCorruptionError):
                    raise
                raise SyncTimeoutError(
                    f"host-plane gather call {idx} failed after {guard.max_retries} retries"
                    f" (deadline {guard.deadline_s}s, policy 'raise'): {err}"
                ) from err

    return guarded


def _stamp_degraded_span() -> None:
    """Mark the innermost open span ``degraded=yes`` (the sync span in
    ``Metric._sync_dist`` / the collection's host-sync span)."""
    from metrics_tpu.observability.trace import current_span

    span = current_span()
    if span is None:
        return
    if span.attrs is None:
        span.attrs = {}
    span.attrs["degraded"] = "yes"


def canonicalize_group(group: Any) -> Optional[tuple]:
    """Validate a ``process_group`` (reference metric.py:66,185 semantics).

    A group is an iterable of distinct process indices that includes the
    local process. ``None`` means the whole world. Anything else raises —
    never a silent no-op.
    """
    if group is None:
        return None
    if isinstance(group, (str, bytes)):
        raise TypeError(f"`process_group` must be None or an iterable of process indices, got {group!r}")
    try:
        members = tuple(int(i) for i in group)
    except (TypeError, ValueError):
        raise TypeError(
            f"`process_group` must be None or an iterable of process indices, got {group!r}"
        ) from None
    if len(set(members)) != len(members):
        raise ValueError(f"`process_group` has duplicate members: {members}")
    world = jax.process_count()
    if any(i < 0 or i >= world for i in members):
        raise ValueError(f"`process_group` members must be in [0, {world}); got {members}")
    if jax.process_index() not in members:
        raise ValueError(
            f"process {jax.process_index()} is not a member of its own `process_group` {members};"
            " a rank may only sync through a group it belongs to"
        )
    return members


def gather_all_arrays(value: Array, group: Any = None) -> List[Array]:
    """Host-plane all-gather: a list of per-process arrays, in rank order.

    The TPU-native analogue of reference ``gather_all_tensors``
    (distributed.py:91-118). On a single process this is ``[value]``; on
    multi-host it uses ``process_allgather`` over DCN.

    ``group`` scopes the result to a process subset (reference
    ``group`` semantics, distributed.py:96-116): every process still enters
    the ONE world collective — concurrent disjoint groups therefore cannot
    deadlock, unlike real sub-communicators — but each process keeps only
    its group members' slices, so the downstream reduction spans exactly the
    group. For the in-jit plane, scope by choosing the mesh axis passed to
    ``sync_state`` (a 2-D mesh's ``dp`` axis is a process subset by
    construction).
    """
    members = canonicalize_group(group)
    if jax.process_count() == 1:
        return [value]
    from jax.experimental import multihost_utils

    # host-plane collectives run eagerly (a real per-call count) and cross
    # DCN by definition: multi-host payloads move over the data-center link
    record_collective("process_allgather", value, crossing="dcn", fanout=jax.process_count())
    gathered = multihost_utils.process_allgather(value, tiled=False)
    indices = range(gathered.shape[0]) if members is None else members
    return [gathered[i] for i in indices]


def slice_leader_gather(hierarchy: HostHierarchy) -> Callable:
    """A packable host gather that moves ONE copy per slice over DCN.

    For states REPLICATED within a slice — the invariant after an in-jit
    ici-axis sync, or any replicated eval state — the flat host plane
    gathers every process's identical copy: the same payload crosses DCN
    once per process. This gather returns one array per slice (the slice
    leader's copy, in slice order), so the downstream reduction spans
    slices exactly once and the DCN exchange is attributed at slice fanout,
    not world fanout. Every process still enters the ONE world collective
    (no sub-communicator, no deadlock — the ``gather_all_arrays`` group
    convention) and redistributes by keeping the leader rows, so all
    processes of a slice see the identical result.

    The caller owns the replication invariant: states that DIVERGE within a
    slice must use the flat plane (summing leader copies would drop the
    non-leaders' contributions).
    """
    if not isinstance(hierarchy, HostHierarchy):
        raise TypeError(
            f"slice_leader_gather needs a HostHierarchy (process -> slice map), got {hierarchy!r}"
        )

    @packable_gather
    def leader_gather(value: Array) -> List[Array]:
        if jax.process_count() == 1 or hierarchy.n_slices <= 1:
            return [value]  # degenerate: one slice IS the flat single gather
        from jax.experimental import multihost_utils

        record_collective(
            "process_allgather", value, crossing="dcn", fanout=hierarchy.n_slices
        )
        gathered = multihost_utils.process_allgather(value, tiled=False)
        return [gathered[p] for p in hierarchy.leaders]

    return leader_gather


def packable_gather(fn: Callable) -> Callable:
    """Mark a custom host gather as VALUE-based, opting it into payload packing.

    ``host_gather`` packs same-dtype leaves into one flat payload per gather
    call — but that is only sound for a gather that transports exactly the
    array it was handed (``fn(x) -> [x per rank]``), like the default
    ``process_allgather`` plane. A custom ``dist_sync_fn`` that instead
    treats its argument as a *reference* (e.g. a test-world gather that
    identity-matches the array to a named state on every rank) must keep the
    per-leaf calls, so packing is opt-in for custom functions.
    """
    fn._mtpu_packable = True
    return fn


def is_packable_gather(fn: Callable) -> bool:
    """Whether ``host_gather`` may pack payloads through this gather."""
    if fn is gather_all_arrays or getattr(fn, "_mtpu_packable", False):
        return True
    if isinstance(fn, functools.partial):
        return is_packable_gather(fn.func)
    return False


def _packed_gather_units(units: List[Any], gather_fn: Callable) -> List[List[Array]]:
    """Gather many arrays with one ``gather_fn`` call per dtype bucket.

    ``units`` is a list of (possibly scalar) arrays; the result is, per
    unit, the list of per-process arrays ``gather_fn`` would have returned
    for it individually. Same-dtype units ravel into ONE flat payload, ride
    ONE gather call, and are sliced back per process — the host-plane
    analogue of the in-jit bucketed gather (each small DCN collective is
    latency-bound, so packing trades a copy for round-trips). Single-member
    buckets pass the original array through untouched (shape-sensitive
    custom ``dist_sync_fn`` implementations see no change).
    """
    results: List[Optional[List[Array]]] = [None] * len(units)
    buckets: Dict[str, List[int]] = {}
    for i, arr in enumerate(units):
        buckets.setdefault(str(arr.dtype), []).append(i)
    for _dtype, indices in buckets.items():
        if len(indices) == 1:
            i = indices[0]
            results[i] = gather_fn(units[i])
            continue
        flat = jnp.concatenate([jnp.ravel(units[i]) for i in indices])
        per_process = gather_fn(flat)
        offset = 0
        for i in indices:
            arr = units[i]
            results[i] = [
                p[offset: offset + arr.size].reshape(arr.shape) for p in per_process
            ]
            offset += arr.size
    return results  # type: ignore[return-value]


def host_gather(
    state: Dict[str, Any],
    reductions: Dict[str, ReduceFx],
    gather_fn: Optional[Callable] = None,
    slice_leaders: Optional[HostHierarchy] = None,
    guard: Optional[SyncGuard] = None,
    overflow: Optional[str] = None,
    timer: Optional[Callable[[float], None]] = None,
) -> Dict[str, Any]:
    """Host-plane sync of a state dict, reproducing reference ``_sync_dist``
    semantics (metric.py:179-197): gather every array, stack tensor states /
    flatten list states, then apply the per-state reduction.

    Gather calls are PACKED when the gather is value-based (the default
    ``process_allgather`` plane, or a custom fn marked with
    :func:`packable_gather`): every array entering the plane — plain leaves,
    PaddedBuffer data and counts, list elements — joins a per-dtype flat
    payload, and each payload moves with ONE ``gather_fn`` call (one
    ``process_allgather`` over DCN when multi-host). Values are identical to
    the per-leaf plane: per-process slices reconstruct exactly the arrays an
    individual gather would have returned before any reduction runs.
    Reference-semantics custom ``dist_sync_fn``s keep one call per array.

    ``slice_leaders`` is the SLICE-LEADER mode: with a
    :class:`HostHierarchy` (and no explicit ``gather_fn``) the packed
    payloads move through :func:`slice_leader_gather` — one copy per slice
    instead of one per process, for states replicated within a slice.

    FAULT TOLERANCE: every gather call runs under the active
    :class:`SyncGuard` (``guard=`` or the :func:`set_sync_guard` default) —
    per-call deadlines, bounded retry with exponential backoff, and on
    exhaustion either a typed ``SyncTimeoutError`` (policy ``raise``) or a
    LOCAL-ONLY fallback (policy ``degrade``: the enclosing span is stamped
    ``degraded=yes``, ``degraded_computes`` bumps, and remaining collectives
    are still issued fire-and-forget so entry order — and therefore the
    other ranks — is preserved). The trivial default guard takes the
    unwrapped fast path. A state pytree that is empty (or all-``None``)
    skips the collective entirely: a zero-payload gather still costs every
    rank a rendezvous (``gather_skips`` counts the savings).

    ``overflow`` is the PaddedBuffer overflow policy for gathered counts
    (``error``/``warn_drop``; default: the process-wide
    ``parallel.buffer.set_overflow_policy`` setting).

    ``timer`` receives the wall milliseconds the gather calls themselves
    blocked this thread (guard retries/backoff included, the pre/post
    reduction arithmetic excluded) — the ``fenced_block_ms`` measurement at
    its source. The adaptive lag controller
    (:class:`~metrics_tpu.parallel.deferred.LagController`) feeds on it to
    decide whether the synchronous plane is effectively free.
    """
    if gather_fn is None and slice_leaders is not None:
        gather_fn = slice_leader_gather(slice_leaders)
    gather_fn = gather_fn or gather_all_arrays

    # pass 1: enumerate every array that must move, in a stable order.
    # None leaves (un-promoted optional states) carry no payload and pass
    # through untouched.
    units: List[Array] = []
    slots: Dict[str, Any] = {}  # name -> unit indices, shaped per leaf kind
    for name, value in state.items():
        if value is None:
            slots[name] = ("none",)
        elif is_sketch(value):
            # one counts payload; the post-gather reduction is a sum of the
            # per-process counts (the host-plane analogue of the psum)
            slots[name] = ("sketch", len(units))
            units.append(value.counts)
        elif isinstance(value, PaddedBuffer):
            slots[name] = ("buffer", len(units), len(units) + 1)
            units.extend([value.data, value.count])
        elif isinstance(value, list):
            slots[name] = ("list", list(range(len(units), len(units) + len(value))))
            units.extend(v if hasattr(v, "dtype") else jnp.asarray(v) for v in value)
        else:
            slots[name] = ("array", len(units))
            units.append(value if hasattr(value, "dtype") else jnp.asarray(value))

    if not units:
        # nothing to move: skip the collective entirely instead of staging a
        # zero-payload gather every rank must rendezvous for
        record_gather_skip()
        return dict(state)

    guard = guard if guard is not None else _SYNC_GUARD
    hook = _FAULT_HOOK
    guard_active = hook is not None or guard.deadline_s is not None or guard.check_finite
    plane = {"calls": 0, "degraded": False, "site": "host_gather", "hook": hook}
    plane_fn = _guard_gather_fn(gather_fn, guard, plane) if guard_active else gather_fn

    # packability is a property of the ORIGINAL gather fn; the guard wrapper
    # transports values unchanged, so it inherits the verdict
    t0 = time.perf_counter() if timer is not None else 0.0
    if is_packable_gather(gather_fn):
        gathered_units = _packed_gather_units(units, plane_fn)
    else:
        gathered_units = [plane_fn(u) for u in units]
    if timer is not None:
        timer((time.perf_counter() - t0) * 1e3)

    if plane["degraded"]:
        record_fault("degraded_computes")
        _stamp_degraded_span()

    # pass 2: per-leaf reduction over the reconstructed per-process arrays
    out: Dict[str, Any] = {}
    for name, value in state.items():
        fx = reductions[name]
        slot = slots[name]
        if slot[0] == "none":
            out[name] = None
            continue
        if slot[0] == "sketch":
            gathered = gathered_units[slot[1]]
            out[name] = type(value)(jnp.sum(jnp.stack(gathered), axis=0))
            continue
        if slot[0] == "buffer":
            gathered = gathered_units[slot[1]]
            counts = gathered_units[slot[2]]
            for g, c in zip(gathered, counts):
                handle_overflow(name, int(c), g.shape[0], policy=overflow)
            parts = [g[: int(c)] for g, c in zip(gathered, counts)]
            out[name] = dim_zero_cat(parts) if parts else value.data[:0]
            continue
        if slot[0] == "list":
            # flatten in element-major order (reference metric.py:192-193)
            gathered_elems = [gathered_units[i] for i in slot[1]]
            flat = [g for elem in gathered_elems for g in elem]
            reduction = stacked_reduction(fx)
            out[name] = reduction(flat) if fx == "cat" else (reduction(flat) if reduction else flat)
            continue
        gathered = gathered_units[slot[1]]
        stacked = jnp.stack(gathered)
        reduction = stacked_reduction(fx)
        out[name] = reduction(stacked) if reduction is not None else stacked
    return out
