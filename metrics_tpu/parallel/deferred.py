"""Deferred sync plane: double-buffered state snapshots, future-returning
collectives, and a background host plane.

Every sync plane in this library was, until now, ON the critical path: the
in-jit collectives (``sync_state``/``coalesced_sync_state``) ride the step
program and the devtime fencing waits on them, and the packed
``process_allgather`` host plane blocks the calling thread until the DCN
rendezvous completes. ``BENCH_r05`` makes the cost concrete: the 8-device
``dist_sync_on_step`` collection step is ~4.67 ms of which sync dominates,
against a 0.02 ms fused update. This module moves sync OFF the critical path
the way training stacks overlap gradient all-reduce with backprop:

- **Double-buffered snapshots.** A deferred sync SNAPSHOTS the state pytree
  at dispatch time. jax arrays are immutable, so holding the refs IS the
  double buffer: buffer A (the snapshot) is what the collective moves, while
  the live metric keeps accumulating into buffer B — no copy, no torn reads.
- **Future-returning collectives.** :func:`deferred_sync_state` dispatches
  the compiled sync program (the IDENTICAL ``coalesced_sync_state`` staging
  as the synchronous plane — same collective count, same kinds; the
  ``bench.py --check-async`` gate pins it) WITHOUT fencing and returns a
  :class:`SyncHandle`. jax dispatch is asynchronous, so XLA overlaps the
  collective's device time with whatever the host dispatches next —
  typically the next step's updates. ``SyncHandle.result()`` fences and
  returns the merged state.
- **Background host plane.** :func:`deferred_host_gather` runs the packed
  ``process_allgather`` plane on a dedicated SINGLE-WORKER executor under
  the caller's :class:`~metrics_tpu.parallel.sync.SyncGuard` — deadline /
  retry / degrade semantics are exactly the synchronous plane's (the task
  calls :func:`~metrics_tpu.parallel.sync.host_gather` verbatim, chaos
  injection included). One worker means deferred gathers execute in
  SUBMISSION order: a deferring rank enters its collectives in exactly the
  order the synchronous plane would have, so entry-order — and therefore
  the peers' rendezvous pairing — is preserved and a deferring rank can
  never deadlock the others. A degrade-policy exhaustion latches to
  local-only state inside the background task (the step never stalls);
  a raise-policy exhaustion surfaces as ``SyncTimeoutError`` from
  ``result()``.
- **Epoch watermark.** Every handle carries the dispatching metric's epoch
  watermark, so a consumer of the lagged view knows exactly which step's
  merge it is reading (``dist_sync_on_step`` consumers with ``sync_lag=k``
  read the view from k steps back through a bounded handle ring — see
  ``core.metric.Metric``; :data:`MAX_SYNC_LAG` caps the ring).
- **Adaptive lag.** :class:`LagController` closes the loop between the
  measured fence-wait split and the ring depth: lag 0 when the collective
  is effectively free, deeper toward the cap when the (DCN) gather is slow.
  ``Metric(sync_lag="auto")`` wires it in.

Observability: dispatch / fence / completion are span-stamped
(``deferred.dispatch`` / ``deferred.fence`` / ``deferred.complete``) and
counted (the ``deferred`` gauge block in every counters snapshot), so the
overlap is a measured number — the fence span's wait is what the overlap
saved, and ``bench.py --check-async`` reports it next to the synchronous
plane's blocking wait.
"""
import atexit
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, Optional

import jax

from metrics_tpu.observability.counters import record_deferred
from metrics_tpu.observability.trace import TRACE, span as _span
from metrics_tpu.parallel.placement import MeshHierarchy
from metrics_tpu.parallel.sync import (
    ReduceFx,
    SyncGuard,
    coalesced_sync_state,
    current_sync_guard,
    host_gather,
)
from metrics_tpu.utils.exceptions import TracingUnsupportedError

__all__ = [
    "DeferredSyncPlane",
    "LagController",
    "MAX_SYNC_LAG",
    "SyncHandle",
    "clear_program_cache",
    "deferred_host_gather",
    "deferred_sparse_sync",
    "deferred_sync_state",
    "drain_host_plane",
    "host_plane_submit",
]

# The lag-k handle ring's hard depth cap. Each in-flight handle pins a
# snapshot (device buffers) and one queued task on the single-worker host
# plane; on the in-jit plane each unfenced dispatch additionally holds an
# XLA:CPU rendezvous slot. A bounded ring keeps both pools finite no matter
# what lag a controller or caller asks for — a runaway depth would wedge the
# rendezvous pool (device) or grow the host queue without bound (host).
MAX_SYNC_LAG = 8


# ------------------------------------------------------ background host plane
class _HostPlane:
    """The executor the deferred host plane runs on.

    SINGLE worker by construction — not an optimization knob: collectives
    pair across ranks by entry order, so deferred gathers must execute in
    submission order or a deferring rank would mismatch its peers'
    rendezvous. The worker is created lazily (importing this module costs
    no thread) and marked daemon via the pool's default so an in-flight
    deadline-abandoned gather cannot block process exit.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._pool: Optional[ThreadPoolExecutor] = None

    def submit(self, fn: Callable, *args: Any):
        with self._lock:
            if self._pool is None:
                self._pool = ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="mtpu-deferred-host"
                )
            return self._pool.submit(fn, *args)

    def drain(self) -> None:
        """Wait for every queued task (a barrier, not a shutdown)."""
        with self._lock:
            pool = self._pool
        if pool is None:
            return
        pool.submit(lambda: None).result()

    def shutdown(self) -> None:
        """Run every queued task to completion, then join the worker.

        Registered with ``atexit`` so interpreter teardown cannot leak the
        daemon worker mid-task: tasks queued at exit (a deep publish
        pipeline, an unfenced lag-k ring) finish before the join instead of
        being killed wherever the daemon thread happened to be. Idempotent,
        and a later ``submit`` lazily builds a fresh pool — shutdown is a
        drain point, not a poison pill.
        """
        with self._lock:
            pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=True)


_HOST_PLANE = _HostPlane()
# interpreter teardown drains the plane instead of abandoning the daemon
# worker with tasks still queued (see _HostPlane.shutdown)
atexit.register(_HOST_PLANE.shutdown)


def host_plane_submit(fn: Callable, *args: Any):
    """Submit work to the deferred host plane (single worker, FIFO).

    The serving runtime routes its deferred publish stage through this so
    publish-time guarded syncs share the entry-order domain with every other
    deferred gather in the process.
    """
    return _HOST_PLANE.submit(fn, *args)


def drain_host_plane() -> None:
    """Barrier: block until every task submitted so far has finished."""
    _HOST_PLANE.drain()


# ------------------------------------------------------ adaptive lag control
class LagController:
    """Feedback loop choosing a deferred-sync depth from the measured
    fence-wait split.

    The split ``bench.py --check-async`` reports (``async_fence_wait_ms`` vs
    ``fenced_block_ms``) is exactly the signal a lag choice needs: how long
    the caller actually BLOCKED on sync this step. The controller keeps an
    EWMA of that blocking wait and turns it into a ring depth:

    - **wait above ``free_ms``** — the gather is slower than the work the
      current depth overlaps it with: DEEPEN one step toward ``max_lag``
      (at lag 0 the observation is the synchronous plane's full blocking
      gather; at lag k it is the oldest handle's fence wait).
    - **wait at/below ``free_ms``** — the collective is effectively free at
      this depth. After ``calm_steps`` consecutive calm observations the
      depth SHALLOWS one step (hysteresis: a single fast gather must not
      collapse a ring that a slow DCN will refill next step).

    A metric opts in with ``sync_lag="auto"`` (``core.metric.Metric``): lag
    0 — the synchronous plane, zero staleness — when sync is free, deeper
    rings only when the (DCN) gather is actually slow. ``observe`` is the
    whole feedback interface; ``lag`` is the current verdict.
    """

    def __init__(
        self,
        max_lag: int = MAX_SYNC_LAG,
        free_ms: float = 1.0,
        alpha: float = 0.5,
        calm_steps: int = 16,
    ) -> None:
        if not (isinstance(max_lag, int) and 0 < max_lag <= MAX_SYNC_LAG):
            raise ValueError(
                f"`max_lag` must be an int in [1, {MAX_SYNC_LAG}] (the ring depth"
                f" cap bounds the rendezvous pool), got {max_lag!r}"
            )
        if not free_ms > 0:
            raise ValueError(f"`free_ms` must be > 0, got {free_ms!r}")
        if not 0 < alpha <= 1:
            raise ValueError(f"`alpha` must be in (0, 1], got {alpha!r}")
        if not calm_steps >= 1:
            raise ValueError(f"`calm_steps` must be >= 1, got {calm_steps!r}")
        self.max_lag = max_lag
        self.free_ms = float(free_ms)
        self.alpha = float(alpha)
        self.calm_steps = int(calm_steps)
        self.lag = 0
        self.wait_ms = 0.0  # EWMA of the measured blocking wait
        self._calm = 0
        self._observed = 0

    def observe(self, wait_ms: float) -> int:
        """Feed one measured blocking wait (ms); returns the updated lag.

        At lag 0 callers feed the synchronous gather's wall time (the
        ``fenced_block_ms`` analogue); at lag k the oldest handle's fence
        wait (``async_fence_wait_ms``). Same unit, same meaning: host time
        sync stole from the step.
        """
        wait_ms = float(wait_ms)
        self._observed += 1
        if self._observed == 1:
            self.wait_ms = wait_ms
        else:
            self.wait_ms = self.alpha * wait_ms + (1.0 - self.alpha) * self.wait_ms
        if self.wait_ms > self.free_ms:
            self._calm = 0
            if self.lag < self.max_lag:
                self.lag += 1
        else:
            self._calm += 1
            if self._calm >= self.calm_steps and self.lag > 0:
                self.lag -= 1
                self._calm = 0
        return self.lag

    def __repr__(self) -> str:
        return (
            f"LagController(lag={self.lag}, wait_ms={self.wait_ms:.3f},"
            f" max_lag={self.max_lag}, free_ms={self.free_ms})"
        )


# ---------------------------------------------------------------- the future
class SyncHandle:
    """Future for a deferred sync: fence/join on demand, read once, cached.

    Two backings share the interface:

    - **device** (:func:`deferred_sync_state`): the staged collective is
      already dispatched; ``result()`` is a ``block_until_ready`` fence over
      the output arrays (``timeout`` is ignored — a dispatched XLA program
      cannot be abandoned mid-flight).
    - **host** (:func:`deferred_host_gather`): the packed gather runs on the
      background executor; ``result(timeout)`` joins the task. Guard-policy
      ``raise`` exhaustion re-raises here (``SyncTimeoutError``); policy
      ``degrade`` returns the local-only snapshot — the handle resolves
      either way, the step never stalls.

    ``result()`` is idempotent: the first call fences and caches, later
    calls return the cached state (or re-raise the cached error).
    ``watermark`` is the dispatching metric's epoch watermark at snapshot
    time — which step's merged view this handle resolves to.
    """

    __slots__ = ("_kind", "_payload", "_finish", "_resolved", "_result", "_error",
                 "_lock", "watermark", "label")

    def __init__(
        self,
        kind: str,
        payload: Any,
        finish: Optional[Callable[[Any], Any]] = None,
        watermark: Optional[int] = None,
        label: str = "sync",
    ) -> None:
        if kind not in ("device", "host", "ready"):
            raise ValueError(f"unknown SyncHandle kind {kind!r}")
        self._kind = kind
        self._payload = payload
        self._finish = finish
        self._resolved = kind == "ready"
        self._result = payload if kind == "ready" else None
        self._error: Optional[BaseException] = None
        self._lock = threading.Lock()
        self.watermark = watermark
        self.label = label

    def done(self) -> bool:
        """Whether ``result()`` would return without waiting."""
        if self._resolved:
            return True
        if self._kind == "host":
            return self._payload.done()
        try:  # jax.Array.is_ready on current jax; conservative False without it
            return all(
                leaf.is_ready()
                for leaf in jax.tree_util.tree_leaves(self._payload)
                if hasattr(leaf, "is_ready")
            )
        except Exception:  # noqa: BLE001 - readiness is advisory, never fatal
            return False

    def result(self, timeout: Optional[float] = None) -> Any:
        """Fence/join and return the synced state (cached after the first call)."""
        with self._lock:
            if self._resolved:
                if self._error is not None:
                    raise self._error
                return self._result
            attrs = {"plane": self._kind, "label": self.label} if TRACE.enabled else None
            try:
                with _span("deferred.fence", attrs):
                    if self._kind == "host":
                        out = self._payload.result(timeout)
                    else:
                        jax.block_until_ready(self._payload)
                        out = self._payload
                        record_deferred("completed")  # device completion == fence
                if self._finish is not None:
                    out = self._finish(out)
            except BaseException as err:
                self._error = err
                self._resolved = True
                self._payload = self._finish = None
                record_deferred("fenced")
                raise
            self._result = out
            self._resolved = True
            self._payload = self._finish = None
            record_deferred("fenced")
            return out


# --------------------------------------------------- the deferred host plane
def deferred_host_gather(
    state: Dict[str, Any],
    reductions: Dict[str, ReduceFx],
    gather_fn: Optional[Callable] = None,
    guard: Optional[SyncGuard] = None,
    watermark: Optional[int] = None,
    label: str = "host_gather",
    attrs: Optional[Dict[str, Any]] = None,
    finish: Optional[Callable[[Dict[str, Any]], Any]] = None,
) -> SyncHandle:
    """Run the host sync plane in the background; returns a :class:`SyncHandle`.

    Snapshots ``state`` at call time (the double buffer — the caller may keep
    accumulating immediately) and submits ``host_gather(snapshot, ...)`` to
    the single-worker host plane under ``guard`` (default: the process-wide
    :func:`~metrics_tpu.parallel.sync.current_sync_guard`, CAPTURED NOW so a
    later guard change cannot retroactively alter an in-flight sync). The
    task is the synchronous plane verbatim — deadline/retry/degrade,
    check_finite vetting, chaos injection at site ``host_gather``, packed
    payloads — only the thread it blocks changes.

    ``attrs`` are extra span attributes stamped onto the ``deferred.dispatch``
    span (the lag-k metric plane stamps its chosen depth here as
    ``lag_controller``, so a trace shows WHY each dispatch happened at the
    depth it did).

    ``finish`` runs on the gathered result ON THE WORKER, not at ``result()``
    time — a consumer that only needs the side effect (the watermark
    agreement folding an exchanged min into its registry) observes it as soon
    as the gather lands, even if nobody ever fences the handle. A ``finish``
    that raises surfaces from ``result()`` like any task failure.
    """
    snapshot = dict(state)  # immutable leaves: holding the refs IS buffer A
    guard = guard if guard is not None else current_sync_guard()

    def task() -> Any:
        task_attrs = {"plane": label} if TRACE.enabled else None
        with _span("deferred.complete", task_attrs):
            out = host_gather(snapshot, reductions, gather_fn=gather_fn, guard=guard)
            if finish is not None:
                out = finish(out)
        record_deferred("completed")
        return out

    span_attrs = None
    if TRACE.enabled:
        span_attrs = {"plane": label}
        if attrs:
            span_attrs.update(attrs)
    with _span("deferred.dispatch", span_attrs):
        future = _HOST_PLANE.submit(task)
    record_deferred("dispatched")
    return SyncHandle("host", future, watermark=watermark, label=label)


def deferred_sparse_sync(
    plane: Any,
    state: Dict[str, Any],
    touched: Any = None,
    watermark: Optional[int] = None,
    label: str = "sparse_sync",
    attrs: Optional[Dict[str, Any]] = None,
) -> SyncHandle:
    """Run one sparse delta-sync round in the background; returns a
    :class:`SyncHandle`.

    ``plane`` is a :class:`~metrics_tpu.parallel.sparse.SparseSyncPlane`;
    the task is ``plane.sync(snapshot, touched)`` VERBATIM — bitmap psum,
    host union readback, fixed-capacity row exchange or dense fallback,
    guard retries, chaos at site ``sparse_sync``, the round ledger — on the
    single-worker host plane, so deferred sparse rounds share the
    submission-order domain with every other deferred gather (a sparse
    round cannot ride the unfenced device-dispatch plane: the union
    readback between its two programs is host control flow by design).
    Snapshots ``state`` at call time — immutable leaves, so holding the
    refs IS the double buffer and the caller keeps accumulating.
    """
    snapshot = dict(state)

    def task() -> Any:
        task_attrs = {"plane": label} if TRACE.enabled else None
        with _span("deferred.complete", task_attrs):
            out = plane.sync(snapshot, touched)
        record_deferred("completed")
        return out

    span_attrs = None
    if TRACE.enabled:
        span_attrs = {"plane": label, "capacity": plane.capacity}
        if attrs:
            span_attrs.update(attrs)
    with _span("deferred.dispatch", span_attrs):
        future = _HOST_PLANE.submit(task)
    record_deferred("dispatched")
    return SyncHandle("host", future, watermark=watermark, label=label)


# ------------------------------------------------- the deferred in-jit plane
# compiled sync programs keyed by (mesh, axis, state schema): a fresh handle
# per step replays the compiled program, never retraces. Entries pin the
# callable reductions whose id() appears in the key.
_PROGRAM_CACHE: Dict[Any, Any] = {}
_PROGRAM_CACHE_MAX = 64
_PROGRAM_LOCK = threading.Lock()


def clear_program_cache() -> None:
    """Drop every cached compiled program (forces a retrace): the deferred
    sync programs here AND the collection-level fused-step cache.

    The sync cache is keyed by (mesh, axis, state schema), so two planes over
    the same schema share one compiled program — which also means the second
    plane stages ZERO new collectives. A staged-collective capture that
    wants to re-count the program (``bench.py``'s lag-depth counters, tests)
    clears first; dropping the fused-step cache alongside keeps one clear
    call sufficient for collection-level captures too.
    """
    with _PROGRAM_LOCK:
        _PROGRAM_CACHE.clear()
    from metrics_tpu.core.collections import _COL_STEP_CACHE, _COL_STEP_CACHE_LOCK

    with _COL_STEP_CACHE_LOCK:
        _COL_STEP_CACHE.clear()


def _fx_key(fx: ReduceFx, pins: list) -> Any:
    if fx is None or isinstance(fx, str):
        return fx
    pins.append(fx)  # the cache entry keeps the id alive
    return ("fn", id(fx))


def _axis_spec(axis_name: Any) -> tuple:
    """The mesh axes the leading (world) dimension shards over."""
    if isinstance(axis_name, MeshHierarchy):
        # slice-major world order: dcn-major, ici-minor — the same convention
        # as _hier_gather_stack, so per-device rows land on their own device
        return (axis_name.dcn_axis, axis_name.ici_axis)
    if isinstance(axis_name, tuple):
        return axis_name
    return (axis_name,)


def _sync_program(mesh: Any, axis_name: Any, reductions: Dict[Any, ReduceFx], state: Dict[Any, Any]):
    from jax.sharding import PartitionSpec as P

    from metrics_tpu.utils.compat import shard_map

    pins: list = []
    schema = tuple(
        (name, tuple(v.shape), str(v.dtype), _fx_key(reductions[name], pins))
        for name, v in state.items()
    )
    key = (mesh, _axis_spec(axis_name), schema)
    with _PROGRAM_LOCK:
        hit = _PROGRAM_CACHE.get(key)
    if hit is not None:
        return hit[1]

    in_spec = P(_axis_spec(axis_name))
    fixed = dict(reductions)

    def body(stacked: Dict[Any, Any]) -> Dict[Any, Any]:
        # each device holds one row of the world-stacked snapshot; strip it
        # and run the SAME bucketed staging as the synchronous plane
        local = {name: v[0] for name, v in stacked.items()}
        return coalesced_sync_state(local, fixed, axis_name)

    # vma checking off: psum/gather outputs are replicated but the checker
    # cannot always prove it through the bucket slicing (same as bench.py)
    prog = jax.jit(
        shard_map(body, mesh, in_specs=(in_spec,), out_specs=P(), check_vma=False)
    )
    with _PROGRAM_LOCK:
        if len(_PROGRAM_CACHE) >= _PROGRAM_CACHE_MAX:
            _PROGRAM_CACHE.pop(next(iter(_PROGRAM_CACHE)), None)
        _PROGRAM_CACHE[key] = (pins, prog)
    return prog


class DeferredSyncPlane:
    """A precompiled deferred in-jit sync: resolve the program ONCE, then
    ``dispatch(state)`` per step with no per-call key building.

    The hot-loop form of :func:`deferred_sync_state`: a training loop builds
    the plane once (from a template state with the loop's schema) and pays
    only the compiled-program dispatch plus a handle allocation per step —
    the per-call overhead a future must not reintroduce on the path it
    exists to shorten. ``dispatch`` states the identical collectives as the
    synchronous plane for every call (it replays the one compiled program).
    """

    __slots__ = ("_prog", "_finish")

    def __init__(
        self,
        reductions: Dict[Any, ReduceFx],
        axis_name: Any,
        mesh: Any,
        template_state: Dict[Any, Any],
        finish: Optional[Callable[[Any], Any]] = None,
    ) -> None:
        self._prog = _sync_program(mesh, axis_name, reductions, template_state)
        self._finish = finish

    def dispatch(self, state: Dict[Any, Any], watermark: Optional[int] = None) -> SyncHandle:
        values = self._prog(state)  # async dispatch: no fence, no readback
        record_deferred("dispatched")
        return SyncHandle(
            "device", values, finish=self._finish, watermark=watermark, label="sync_state"
        )


def deferred_sync_state(
    state: Dict[Any, Any],
    reductions: Dict[Any, ReduceFx],
    axis_name: Any,
    mesh: Any = None,
    watermark: Optional[int] = None,
    finish: Optional[Callable[[Any], Any]] = None,
) -> SyncHandle:
    """Dispatch the in-jit sync plane WITHOUT fencing; returns a handle.

    ``state`` leaves carry the mesh axis as their LEADING dimension — one
    row per device, i.e. the output of a ``shard_map(update,
    out_specs=P(axis))`` delta program (for a :class:`MeshHierarchy` axis
    the rows are in slice-major world order, the library's convention).
    The compiled program strips the row and runs ``coalesced_sync_state``
    over ``axis_name`` — the IDENTICAL staged collectives (count and kinds)
    as the synchronous plane, because it IS the synchronous plane's staging;
    only the fence moves. jax dispatch is asynchronous, so the collective's
    device time overlaps whatever the host dispatches next.

    ``mesh`` defaults to the first leaf's ``NamedSharding`` mesh; pass it
    explicitly for host-built arrays. Must be called eagerly — under a
    trace there is no host-side future to return
    (``TracingUnsupportedError``).
    """
    from metrics_tpu.utils import compat

    if compat.under_trace():
        raise TracingUnsupportedError(
            "deferred_sync_state dispatches a compiled sync program and returns a"
            " host-side SyncHandle, which cannot exist under tracing; inside jit"
            " use the synchronous in-trace plane (coalesced_sync_state)"
        )
    if not state:
        return SyncHandle("ready", dict(state), watermark=watermark, label="sync_state")
    if mesh is None:
        for leaf in jax.tree_util.tree_leaves(state):
            mesh = getattr(getattr(leaf, "sharding", None), "mesh", None)
            if mesh is not None and getattr(mesh, "axis_names", None):
                break
        if mesh is None or not getattr(mesh, "axis_names", None):
            raise ValueError(
                "deferred_sync_state could not infer the mesh from the state's"
                " sharding; pass mesh= explicitly"
            )
    prog = _sync_program(mesh, axis_name, reductions, state)
    attrs = {"plane": "sync_state"} if TRACE.enabled else None
    with _span("deferred.dispatch", attrs):
        values = prog(dict(state))  # async dispatch: no fence, no readback
    record_deferred("dispatched")
    return SyncHandle(
        "device", values, finish=finish, watermark=watermark, label="sync_state"
    )
