"""Deferred sync plane: double-buffered state snapshots, future-returning
collectives, and a background host plane.

Every sync plane in this library was, until now, ON the critical path: the
in-jit collectives (``sync_state``/``coalesced_sync_state``) ride the step
program and the devtime fencing waits on them, and the packed
``process_allgather`` host plane blocks the calling thread until the DCN
rendezvous completes. ``BENCH_r05`` makes the cost concrete: the 8-device
``dist_sync_on_step`` collection step is ~4.67 ms of which sync dominates,
against a 0.02 ms fused update. This module moves sync OFF the critical path
the way training stacks overlap gradient all-reduce with backprop:

- **Double-buffered snapshots.** A deferred sync SNAPSHOTS the state pytree
  at dispatch time. jax arrays are immutable, so holding the refs IS the
  double buffer: buffer A (the snapshot) is what the collective moves, while
  the live metric keeps accumulating into buffer B — no copy, no torn reads.
- **Future-returning collectives.** :func:`deferred_sync_state` dispatches
  the compiled sync program (the IDENTICAL ``coalesced_sync_state`` staging
  as the synchronous plane — same collective count, same kinds; the
  ``bench.py --check-async`` gate pins it) WITHOUT fencing and returns a
  :class:`SyncHandle`. jax dispatch is asynchronous, so XLA overlaps the
  collective's device time with whatever the host dispatches next —
  typically the next step's updates. ``SyncHandle.result()`` fences and
  returns the merged state.
- **Background host plane.** :func:`deferred_host_gather` runs the packed
  ``process_allgather`` plane on a dedicated SINGLE-WORKER executor under
  the caller's :class:`~metrics_tpu.parallel.sync.SyncGuard` — deadline /
  retry / degrade semantics are exactly the synchronous plane's (the task
  calls :func:`~metrics_tpu.parallel.sync.host_gather` verbatim, chaos
  injection included). One worker means deferred gathers execute in
  SUBMISSION order: a deferring rank enters its collectives in exactly the
  order the synchronous plane would have, so entry-order — and therefore
  the peers' rendezvous pairing — is preserved and a deferring rank can
  never deadlock the others. A degrade-policy exhaustion latches to
  local-only state inside the background task (the step never stalls);
  a raise-policy exhaustion surfaces as ``SyncTimeoutError`` from
  ``result()``.
- **Epoch watermark.** Every handle carries the dispatching metric's epoch
  watermark, so a consumer of the lagged view knows exactly which step's
  merge it is reading (``dist_sync_on_step`` consumers with ``sync_lag=1``
  read the previous step's view — see ``core.metric.Metric``).

Observability: dispatch / fence / completion are span-stamped
(``deferred.dispatch`` / ``deferred.fence`` / ``deferred.complete``) and
counted (the ``deferred`` gauge block in every counters snapshot), so the
overlap is a measured number — the fence span's wait is what the overlap
saved, and ``bench.py --check-async`` reports it next to the synchronous
plane's blocking wait.
"""
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, Optional

import jax

from metrics_tpu.observability.counters import record_deferred
from metrics_tpu.observability.trace import TRACE, span as _span
from metrics_tpu.parallel.placement import MeshHierarchy
from metrics_tpu.parallel.sync import (
    ReduceFx,
    SyncGuard,
    coalesced_sync_state,
    current_sync_guard,
    host_gather,
)
from metrics_tpu.utils.exceptions import TracingUnsupportedError

__all__ = [
    "DeferredSyncPlane",
    "SyncHandle",
    "deferred_host_gather",
    "deferred_sync_state",
    "drain_host_plane",
    "host_plane_submit",
]


# ------------------------------------------------------ background host plane
class _HostPlane:
    """The executor the deferred host plane runs on.

    SINGLE worker by construction — not an optimization knob: collectives
    pair across ranks by entry order, so deferred gathers must execute in
    submission order or a deferring rank would mismatch its peers'
    rendezvous. The worker is created lazily (importing this module costs
    no thread) and marked daemon via the pool's default so an in-flight
    deadline-abandoned gather cannot block process exit.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._pool: Optional[ThreadPoolExecutor] = None

    def submit(self, fn: Callable, *args: Any):
        with self._lock:
            if self._pool is None:
                self._pool = ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="mtpu-deferred-host"
                )
            return self._pool.submit(fn, *args)

    def drain(self) -> None:
        """Wait for every queued task (a barrier, not a shutdown)."""
        with self._lock:
            pool = self._pool
        if pool is None:
            return
        pool.submit(lambda: None).result()


_HOST_PLANE = _HostPlane()


def host_plane_submit(fn: Callable, *args: Any):
    """Submit work to the deferred host plane (single worker, FIFO).

    The serving runtime routes its deferred publish stage through this so
    publish-time guarded syncs share the entry-order domain with every other
    deferred gather in the process.
    """
    return _HOST_PLANE.submit(fn, *args)


def drain_host_plane() -> None:
    """Barrier: block until every task submitted so far has finished."""
    _HOST_PLANE.drain()


# ---------------------------------------------------------------- the future
class SyncHandle:
    """Future for a deferred sync: fence/join on demand, read once, cached.

    Two backings share the interface:

    - **device** (:func:`deferred_sync_state`): the staged collective is
      already dispatched; ``result()`` is a ``block_until_ready`` fence over
      the output arrays (``timeout`` is ignored — a dispatched XLA program
      cannot be abandoned mid-flight).
    - **host** (:func:`deferred_host_gather`): the packed gather runs on the
      background executor; ``result(timeout)`` joins the task. Guard-policy
      ``raise`` exhaustion re-raises here (``SyncTimeoutError``); policy
      ``degrade`` returns the local-only snapshot — the handle resolves
      either way, the step never stalls.

    ``result()`` is idempotent: the first call fences and caches, later
    calls return the cached state (or re-raise the cached error).
    ``watermark`` is the dispatching metric's epoch watermark at snapshot
    time — which step's merged view this handle resolves to.
    """

    __slots__ = ("_kind", "_payload", "_finish", "_resolved", "_result", "_error",
                 "_lock", "watermark", "label")

    def __init__(
        self,
        kind: str,
        payload: Any,
        finish: Optional[Callable[[Any], Any]] = None,
        watermark: Optional[int] = None,
        label: str = "sync",
    ) -> None:
        if kind not in ("device", "host", "ready"):
            raise ValueError(f"unknown SyncHandle kind {kind!r}")
        self._kind = kind
        self._payload = payload
        self._finish = finish
        self._resolved = kind == "ready"
        self._result = payload if kind == "ready" else None
        self._error: Optional[BaseException] = None
        self._lock = threading.Lock()
        self.watermark = watermark
        self.label = label

    def done(self) -> bool:
        """Whether ``result()`` would return without waiting."""
        if self._resolved:
            return True
        if self._kind == "host":
            return self._payload.done()
        try:  # jax.Array.is_ready on current jax; conservative False without it
            return all(
                leaf.is_ready()
                for leaf in jax.tree_util.tree_leaves(self._payload)
                if hasattr(leaf, "is_ready")
            )
        except Exception:  # noqa: BLE001 - readiness is advisory, never fatal
            return False

    def result(self, timeout: Optional[float] = None) -> Any:
        """Fence/join and return the synced state (cached after the first call)."""
        with self._lock:
            if self._resolved:
                if self._error is not None:
                    raise self._error
                return self._result
            attrs = {"plane": self._kind, "label": self.label} if TRACE.enabled else None
            try:
                with _span("deferred.fence", attrs):
                    if self._kind == "host":
                        out = self._payload.result(timeout)
                    else:
                        jax.block_until_ready(self._payload)
                        out = self._payload
                        record_deferred("completed")  # device completion == fence
                if self._finish is not None:
                    out = self._finish(out)
            except BaseException as err:
                self._error = err
                self._resolved = True
                self._payload = self._finish = None
                record_deferred("fenced")
                raise
            self._result = out
            self._resolved = True
            self._payload = self._finish = None
            record_deferred("fenced")
            return out


# --------------------------------------------------- the deferred host plane
def deferred_host_gather(
    state: Dict[str, Any],
    reductions: Dict[str, ReduceFx],
    gather_fn: Optional[Callable] = None,
    guard: Optional[SyncGuard] = None,
    watermark: Optional[int] = None,
    label: str = "host_gather",
) -> SyncHandle:
    """Run the host sync plane in the background; returns a :class:`SyncHandle`.

    Snapshots ``state`` at call time (the double buffer — the caller may keep
    accumulating immediately) and submits ``host_gather(snapshot, ...)`` to
    the single-worker host plane under ``guard`` (default: the process-wide
    :func:`~metrics_tpu.parallel.sync.current_sync_guard`, CAPTURED NOW so a
    later guard change cannot retroactively alter an in-flight sync). The
    task is the synchronous plane verbatim — deadline/retry/degrade,
    check_finite vetting, chaos injection at site ``host_gather``, packed
    payloads — only the thread it blocks changes.
    """
    snapshot = dict(state)  # immutable leaves: holding the refs IS buffer A
    guard = guard if guard is not None else current_sync_guard()

    def task() -> Dict[str, Any]:
        attrs = {"plane": label} if TRACE.enabled else None
        with _span("deferred.complete", attrs):
            out = host_gather(snapshot, reductions, gather_fn=gather_fn, guard=guard)
        record_deferred("completed")
        return out

    attrs = {"plane": label} if TRACE.enabled else None
    with _span("deferred.dispatch", attrs):
        future = _HOST_PLANE.submit(task)
    record_deferred("dispatched")
    return SyncHandle("host", future, watermark=watermark, label=label)


# ------------------------------------------------- the deferred in-jit plane
# compiled sync programs keyed by (mesh, axis, state schema): a fresh handle
# per step replays the compiled program, never retraces. Entries pin the
# callable reductions whose id() appears in the key.
_PROGRAM_CACHE: Dict[Any, Any] = {}
_PROGRAM_CACHE_MAX = 64
_PROGRAM_LOCK = threading.Lock()


def _fx_key(fx: ReduceFx, pins: list) -> Any:
    if fx is None or isinstance(fx, str):
        return fx
    pins.append(fx)  # the cache entry keeps the id alive
    return ("fn", id(fx))


def _axis_spec(axis_name: Any) -> tuple:
    """The mesh axes the leading (world) dimension shards over."""
    if isinstance(axis_name, MeshHierarchy):
        # slice-major world order: dcn-major, ici-minor — the same convention
        # as _hier_gather_stack, so per-device rows land on their own device
        return (axis_name.dcn_axis, axis_name.ici_axis)
    if isinstance(axis_name, tuple):
        return axis_name
    return (axis_name,)


def _sync_program(mesh: Any, axis_name: Any, reductions: Dict[Any, ReduceFx], state: Dict[Any, Any]):
    from jax.sharding import PartitionSpec as P

    from metrics_tpu.utils.compat import shard_map

    pins: list = []
    schema = tuple(
        (name, tuple(v.shape), str(v.dtype), _fx_key(reductions[name], pins))
        for name, v in state.items()
    )
    key = (mesh, _axis_spec(axis_name), schema)
    with _PROGRAM_LOCK:
        hit = _PROGRAM_CACHE.get(key)
    if hit is not None:
        return hit[1]

    in_spec = P(_axis_spec(axis_name))
    fixed = dict(reductions)

    def body(stacked: Dict[Any, Any]) -> Dict[Any, Any]:
        # each device holds one row of the world-stacked snapshot; strip it
        # and run the SAME bucketed staging as the synchronous plane
        local = {name: v[0] for name, v in stacked.items()}
        return coalesced_sync_state(local, fixed, axis_name)

    # vma checking off: psum/gather outputs are replicated but the checker
    # cannot always prove it through the bucket slicing (same as bench.py)
    prog = jax.jit(
        shard_map(body, mesh, in_specs=(in_spec,), out_specs=P(), check_vma=False)
    )
    with _PROGRAM_LOCK:
        if len(_PROGRAM_CACHE) >= _PROGRAM_CACHE_MAX:
            _PROGRAM_CACHE.pop(next(iter(_PROGRAM_CACHE)), None)
        _PROGRAM_CACHE[key] = (pins, prog)
    return prog


class DeferredSyncPlane:
    """A precompiled deferred in-jit sync: resolve the program ONCE, then
    ``dispatch(state)`` per step with no per-call key building.

    The hot-loop form of :func:`deferred_sync_state`: a training loop builds
    the plane once (from a template state with the loop's schema) and pays
    only the compiled-program dispatch plus a handle allocation per step —
    the per-call overhead a future must not reintroduce on the path it
    exists to shorten. ``dispatch`` states the identical collectives as the
    synchronous plane for every call (it replays the one compiled program).
    """

    __slots__ = ("_prog", "_finish")

    def __init__(
        self,
        reductions: Dict[Any, ReduceFx],
        axis_name: Any,
        mesh: Any,
        template_state: Dict[Any, Any],
        finish: Optional[Callable[[Any], Any]] = None,
    ) -> None:
        self._prog = _sync_program(mesh, axis_name, reductions, template_state)
        self._finish = finish

    def dispatch(self, state: Dict[Any, Any], watermark: Optional[int] = None) -> SyncHandle:
        values = self._prog(state)  # async dispatch: no fence, no readback
        record_deferred("dispatched")
        return SyncHandle(
            "device", values, finish=self._finish, watermark=watermark, label="sync_state"
        )


def deferred_sync_state(
    state: Dict[Any, Any],
    reductions: Dict[Any, ReduceFx],
    axis_name: Any,
    mesh: Any = None,
    watermark: Optional[int] = None,
    finish: Optional[Callable[[Any], Any]] = None,
) -> SyncHandle:
    """Dispatch the in-jit sync plane WITHOUT fencing; returns a handle.

    ``state`` leaves carry the mesh axis as their LEADING dimension — one
    row per device, i.e. the output of a ``shard_map(update,
    out_specs=P(axis))`` delta program (for a :class:`MeshHierarchy` axis
    the rows are in slice-major world order, the library's convention).
    The compiled program strips the row and runs ``coalesced_sync_state``
    over ``axis_name`` — the IDENTICAL staged collectives (count and kinds)
    as the synchronous plane, because it IS the synchronous plane's staging;
    only the fence moves. jax dispatch is asynchronous, so the collective's
    device time overlaps whatever the host dispatches next.

    ``mesh`` defaults to the first leaf's ``NamedSharding`` mesh; pass it
    explicitly for host-built arrays. Must be called eagerly — under a
    trace there is no host-side future to return
    (``TracingUnsupportedError``).
    """
    from metrics_tpu.utils import compat

    if compat.under_trace():
        raise TracingUnsupportedError(
            "deferred_sync_state dispatches a compiled sync program and returns a"
            " host-side SyncHandle, which cannot exist under tracing; inside jit"
            " use the synchronous in-trace plane (coalesced_sync_state)"
        )
    if not state:
        return SyncHandle("ready", dict(state), watermark=watermark, label="sync_state")
    if mesh is None:
        for leaf in jax.tree_util.tree_leaves(state):
            mesh = getattr(getattr(leaf, "sharding", None), "mesh", None)
            if mesh is not None and getattr(mesh, "axis_names", None):
                break
        if mesh is None or not getattr(mesh, "axis_names", None):
            raise ValueError(
                "deferred_sync_state could not infer the mesh from the state's"
                " sharding; pass mesh= explicitly"
            )
    prog = _sync_program(mesh, axis_name, reductions, state)
    attrs = {"plane": "sync_state"} if TRACE.enabled else None
    with _span("deferred.dispatch", attrs):
        values = prog(dict(state))  # async dispatch: no fence, no readback
    record_deferred("dispatched")
    return SyncHandle(
        "device", values, finish=finish, watermark=watermark, label="sync_state"
    )
