"""Stateful intrinsic clustering scores (no ground-truth labels needed).

``CalinskiHarabaszScore`` streams ONE per-cluster ``[n, M2, mean]`` moment
block whose distributed reduction is a per-cluster Chan parallel merge
(the ``PearsonCorrcoef`` comoments pattern): each batch's moments are
computed exactly in two passes (the batch is in hand), and blocks combine
associatively across batches / devices / checkpoint shards without the
large-offset cancellation of raw sum-of-squares moments.
``DaviesBouldinScore`` needs mean Euclidean (not squared) distances — a
two-pass-over-everything quantity — so it keeps cat-states (bounded via
``capacity``) and runs one jitted epoch compute, like the curve metrics.
"""
from typing import Any, Callable, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.clustering_intrinsic import (
    _ch_from_cluster_moments,
    _check_data_labels,
    _cluster_moments_batch,
    cluster_chan_fold,
    cluster_chan_merge,
    davies_bouldin_score,
)
from metrics_tpu.parallel.buffer import as_values
from metrics_tpu.parallel.sync import associative

_ch_fold = associative(cluster_chan_fold)


class CalinskiHarabaszScore(Metric):
    """Streaming variance-ratio criterion
    (``sklearn.metrics.calinski_harabasz_score``).

    Example:
        >>> import jax.numpy as jnp
        >>> metric = CalinskiHarabaszScore(num_clusters=2, num_features=2)
        >>> data = jnp.array([[0.0, 0.0], [0.1, 0.0], [5.0, 5.0], [5.1, 5.0]])
        >>> labels = jnp.array([0, 0, 1, 1])
        >>> round(float(metric(data, labels)), 1)
        10000.0
    """

    def __init__(
        self,
        num_clusters: int,
        num_features: int,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        if not isinstance(num_clusters, int) or num_clusters < 1:
            raise ValueError(f"`num_clusters` must be a positive int, got {num_clusters!r}")
        if not isinstance(num_features, int) or num_features < 1:
            raise ValueError(f"`num_features` must be a positive int, got {num_features!r}")
        self.num_clusters = num_clusters
        self.num_features = num_features
        self.add_state(
            "moments",
            default=np.zeros((num_clusters, 2 + num_features), dtype=np.float32),
            dist_reduce_fx=_ch_fold,
        )

    def update(self, data: Array, labels: Array) -> None:
        data = jnp.asarray(data)
        if data.ndim == 2 and data.shape[1] != self.num_features:
            raise ValueError(
                f"data has {data.shape[1]} features, metric was built with "
                f"num_features={self.num_features}"
            )
        batch = _cluster_moments_batch(data, labels, self.num_clusters)
        self.moments = cluster_chan_merge(self.moments, batch)

    def compute(self) -> Array:
        return _ch_from_cluster_moments(self.moments)


class DaviesBouldinScore(Metric):
    """Accumulated Davies-Bouldin index
    (``sklearn.metrics.davies_bouldin_score``; lower is better).

    Example:
        >>> import jax.numpy as jnp
        >>> metric = DaviesBouldinScore(num_clusters=2)
        >>> data = jnp.array([[0.0, 0.0], [0.1, 0.0], [5.0, 5.0], [5.1, 5.0]])
        >>> labels = jnp.array([0, 0, 1, 1])
        >>> round(float(metric(data, labels)), 4)
        0.0141
    """

    def __init__(
        self,
        num_clusters: int,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
        capacity: Optional[int] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
            capacity=capacity,
        )
        if not isinstance(num_clusters, int) or num_clusters < 1:
            raise ValueError(f"`num_clusters` must be a positive int, got {num_clusters!r}")
        self.num_clusters = num_clusters
        self.add_state("data_all", default=[], dist_reduce_fx=None)
        self.add_state("labels_all", default=[], dist_reduce_fx=None, item_shape=(), item_dtype=jnp.int32)

    def update(self, data: Array, labels: Array) -> None:
        _check_data_labels(data, labels)
        self._append("data_all", jnp.asarray(data, dtype=jnp.float32))
        self._append("labels_all", jnp.asarray(labels, dtype=jnp.int32))

    def compute(self) -> Array:
        data = as_values(self.data_all)
        labels = as_values(self.labels_all)
        if data.shape[0] == 0:
            return jnp.asarray(jnp.nan)
        fn = (
            jax.jit(davies_bouldin_score, static_argnums=2)
            if (self._jit is not False and not self._jit_failed)
            else davies_bouldin_score
        )
        return fn(data, labels, self.num_clusters)
