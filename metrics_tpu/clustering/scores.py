"""Contingency-matrix clustering metrics (stateful layer).

One shared base streams the ``(num_clusters, num_classes)`` contingency
count matrix; each subclass applies its closed-form compute.

Precision: the contingency *cells* are int32-exact below 2^31 per cell
(the one-hot contraction accumulates in int32), but the
pair-counting scores (Rand/ARI/Fowlkes-Mallows) compute ``C(n,2)`` of the
marginals *and of the grand total*, so float32 integer exactness is lost
once the TOTAL accumulated epoch passes n = 5793 (``n(n-1)/2 > 2^24``),
after which the ``nij2 - expected`` cancellation accumulates relative noise
of order ``n^2 / 2^25``. For epochs beyond ~5k total samples, enable
``jax.config.update("jax_enable_x64", True)`` (the kernels then accumulate
in float64 automatically), which keeps the pair counts exact to epochs of
~9e7 samples.

Out-of-range labels (outside ``[0, num_clusters)`` / ``[0, num_classes)``)
are silently dropped by the one-hot contraction; see ``_contingency``.
"""
from typing import Any, Callable, Optional

import numpy as np
import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.clustering import (
    _adjusted_mutual_info_compute,
    _adjusted_rand_compute,
    _contingency,
    _fowlkes_mallows_compute,
    _homogeneity_completeness,
    _mutual_info_compute,
    _normalized_mutual_info_compute,
    _rand_compute,
    _v_measure_compute,
)


class _ContingencyMetric(Metric):
    """Shared base: stream the contingency matrix, compute a closed form."""

    def __init__(
        self,
        num_clusters: int,
        num_classes: int,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        if not isinstance(num_clusters, int) or num_clusters < 1:
            raise ValueError(f"`num_clusters` must be a positive int, got {num_clusters!r}")
        if not isinstance(num_classes, int) or num_classes < 1:
            raise ValueError(f"`num_classes` must be a positive int, got {num_classes!r}")
        self.num_clusters = num_clusters
        self.num_classes = num_classes
        self.add_state(
            "contingency",
            default=np.zeros((num_clusters, num_classes), dtype=np.int32),
            dist_reduce_fx="sum",
        )

    def update(self, preds: Array, target: Array) -> None:
        self.contingency = self.contingency + _contingency(
            preds, target, self.num_clusters, self.num_classes
        )

    def _score(self, cont: Array) -> Array:
        raise NotImplementedError

    def compute(self) -> Array:
        return self._score(self.contingency)


class RandScore(_ContingencyMetric):
    """Accumulated Rand index (``sklearn.metrics.rand_score``).

    Example:
        >>> import jax.numpy as jnp
        >>> metric = RandScore(num_clusters=2, num_classes=2)
        >>> float(metric(jnp.array([0, 0, 1, 1]), jnp.array([1, 1, 0, 0])))
        1.0
    """

    def _score(self, cont: Array) -> Array:
        return _rand_compute(cont)


class AdjustedRandScore(_ContingencyMetric):
    """Accumulated adjusted Rand index (``sklearn.metrics.adjusted_rand_score``).

    Example:
        >>> import jax.numpy as jnp
        >>> metric = AdjustedRandScore(num_clusters=2, num_classes=2)
        >>> float(metric(jnp.array([0, 0, 1, 1]), jnp.array([0, 0, 1, 1])))
        1.0
    """

    def _score(self, cont: Array) -> Array:
        return _adjusted_rand_compute(cont)


class MutualInfoScore(_ContingencyMetric):
    """Accumulated mutual information (``sklearn.metrics.mutual_info_score``).

    Example:
        >>> import jax.numpy as jnp
        >>> metric = MutualInfoScore(num_clusters=2, num_classes=2)
        >>> round(float(metric(jnp.array([0, 0, 1, 1]), jnp.array([0, 0, 1, 1]))), 4)
        0.6931
    """

    def _score(self, cont: Array) -> Array:
        return _mutual_info_compute(cont)


class NormalizedMutualInfoScore(_ContingencyMetric):
    """Accumulated NMI (``sklearn.metrics.normalized_mutual_info_score``).

    Example:
        >>> import jax.numpy as jnp
        >>> metric = NormalizedMutualInfoScore(num_clusters=2, num_classes=2)
        >>> float(metric(jnp.array([0, 0, 1, 1]), jnp.array([1, 1, 0, 0])))
        1.0
    """

    def __init__(self, num_clusters: int, num_classes: int, average_method: str = "arithmetic", **kwargs: Any):
        super().__init__(num_clusters, num_classes, **kwargs)
        if average_method not in ("arithmetic", "geometric", "min", "max"):
            raise ValueError(
                f"average_method must be 'arithmetic', 'geometric', 'min' or 'max', got {average_method!r}"
            )
        self.average_method = average_method

    def _score(self, cont: Array) -> Array:
        return _normalized_mutual_info_compute(cont, self.average_method)


class AdjustedMutualInfoScore(NormalizedMutualInfoScore):
    """Accumulated AMI (``sklearn.metrics.adjusted_mutual_info_score``).

    Same construction/validation as :class:`NormalizedMutualInfoScore`; the
    expected-MI chance correction (sklearn's dedicated cython loop) runs as
    one vectorized log-space device program over the streamed contingency
    matrix, with the epoch length read back once at compute time (the
    curve-family epoch-end pattern). Float32 ``gammaln`` limits EMI
    accuracy on large epochs — enable ``jax_enable_x64`` beyond ~10^4
    samples for sklearn-grade precision.

    Example:
        >>> import jax.numpy as jnp
        >>> metric = AdjustedMutualInfoScore(num_clusters=2, num_classes=2)
        >>> float(metric(jnp.array([0, 0, 1, 1]), jnp.array([1, 1, 0, 0])))
        1.0
    """

    def compute(self) -> Array:
        cont = self.contingency
        n = int(jnp.sum(cont))  # one epoch-end readback (static EMI loop bound)
        return _adjusted_mutual_info_compute(cont, n, self.average_method)


class HomogeneityScore(_ContingencyMetric):
    """Accumulated homogeneity (``sklearn.metrics.homogeneity_score``).

    Example:
        >>> import jax.numpy as jnp
        >>> metric = HomogeneityScore(num_clusters=4, num_classes=2)
        >>> float(metric(jnp.array([0, 1, 2, 3]), jnp.array([0, 0, 1, 1])))
        1.0
    """

    def _score(self, cont: Array) -> Array:
        return _homogeneity_completeness(cont)[0]


class CompletenessScore(_ContingencyMetric):
    """Accumulated completeness (``sklearn.metrics.completeness_score``).

    Example:
        >>> import jax.numpy as jnp
        >>> metric = CompletenessScore(num_clusters=1, num_classes=2)
        >>> float(metric(jnp.array([0, 0, 0, 0]), jnp.array([0, 0, 1, 1])))
        1.0
    """

    def _score(self, cont: Array) -> Array:
        return _homogeneity_completeness(cont)[1]


class VMeasureScore(_ContingencyMetric):
    """Accumulated V-measure (``sklearn.metrics.v_measure_score``).

    Example:
        >>> import jax.numpy as jnp
        >>> metric = VMeasureScore(num_clusters=2, num_classes=2)
        >>> float(metric(jnp.array([0, 0, 1, 1]), jnp.array([0, 0, 1, 1])))
        1.0
    """

    def __init__(self, num_clusters: int, num_classes: int, beta: float = 1.0, **kwargs: Any):
        super().__init__(num_clusters, num_classes, **kwargs)
        if beta < 0:
            raise ValueError(f"`beta` must be non-negative, got {beta!r}")
        self.beta = beta

    def _score(self, cont: Array) -> Array:
        return _v_measure_compute(cont, self.beta)


class FowlkesMallowsScore(_ContingencyMetric):
    """Accumulated Fowlkes-Mallows index (``sklearn.metrics.fowlkes_mallows_score``).

    Example:
        >>> import jax.numpy as jnp
        >>> metric = FowlkesMallowsScore(num_clusters=2, num_classes=2)
        >>> round(float(metric(jnp.array([0, 0, 1, 1]), jnp.array([0, 0, 1, 1]))), 4)
        1.0
    """

    def _score(self, cont: Array) -> Array:
        return _fowlkes_mallows_compute(cont)
