"""Stateful clustering metrics. Extension family beyond the reference
snapshot (later torchmetrics ships a ``clustering/`` package).

Every metric streams ONE ``(num_clusters, num_classes)`` contingency-matrix
state — accumulated per batch with the same one-hot MXU contraction the
confusion matrix uses, ``"sum"``-reducible across devices — and applies its
closed-form compute at the end. sklearn-exact; see
``metrics_tpu/functional/clustering.py``.
"""
from metrics_tpu.clustering.intrinsic import CalinskiHarabaszScore, DaviesBouldinScore
from metrics_tpu.clustering.scores import (
    AdjustedMutualInfoScore,
    AdjustedRandScore,
    CompletenessScore,
    FowlkesMallowsScore,
    HomogeneityScore,
    MutualInfoScore,
    NormalizedMutualInfoScore,
    RandScore,
    VMeasureScore,
)

__all__ = [
    "AdjustedMutualInfoScore",
    "AdjustedRandScore",
    "CalinskiHarabaszScore",
    "CompletenessScore",
    "DaviesBouldinScore",
    "FowlkesMallowsScore",
    "HomogeneityScore",
    "MutualInfoScore",
    "NormalizedMutualInfoScore",
    "RandScore",
    "VMeasureScore",
]
