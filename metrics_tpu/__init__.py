"""metrics_tpu: TPU-native machine-learning metrics for JAX.

Stateful metric accumulation with a pure-functional core (init/update/compute/
merge as jit-safe pure functions over pytree states), synchronized across TPU
meshes with XLA collectives. Capability parity target: TorchMetrics v0.2.1
(reference mounted at /root/reference).
"""
import logging

_logger = logging.getLogger("metrics_tpu")
_logger.addHandler(logging.StreamHandler())
_logger.setLevel(logging.INFO)

from metrics_tpu.info import __version__  # noqa: E402
from metrics_tpu import observability  # noqa: E402  (span tracing + collective accounting)
from metrics_tpu.core.collections import MetricCollection  # noqa: E402
from metrics_tpu.core.metric import (  # noqa: E402
    CompositionalMetric,
    Metric,
    PureMetric,
    nonfinite_count,
    saturated_count,
    set_default_jit,
    state_integrity_counts,
)
from metrics_tpu.parallel.deferred import SyncHandle  # noqa: E402  (deferred sync plane)
from metrics_tpu.utils.debug import enable_sync_count_check  # noqa: E402
from metrics_tpu.utils.profiling import profile_metric, time_fn  # noqa: E402
from metrics_tpu.classification import (  # noqa: E402
    AUC,
    AUROC,
    Accuracy,
    AveragePrecision,
    BinnedAUROC,
    BinnedAveragePrecision,
    BinnedPrecisionRecallCurve,
    BinnedROC,
    CalibrationError,
    CohenKappa,
    ConfusionMatrix,
    CoverageError,
    CriticalSuccessIndex,
    Dice,
    ExactMatch,
    F1,
    FBeta,
    HammingDistance,
    HingeLoss,
    IoU,
    JaccardIndex,
    LabelRankingAveragePrecision,
    LabelRankingLoss,
    MatthewsCorrcoef,
    Precision,
    PrecisionRecallCurve,
    ROC,
    Recall,
    Specificity,
    StatScores,
)
from metrics_tpu.regression import (  # noqa: E402
    ConcordanceCorrCoef,
    RelativeSquaredError,
    CosineSimilarity,
    ErrorRelativeGlobalDimensionlessSynthesis,
    PSNR,
    SSIM,
    ExplainedVariance,
    KLDivergence,
    KendallRankCorrCoef,
    LogCoshError,
    MeanAbsoluteError,
    MeanAbsolutePercentageError,
    MeanSquaredError,
    MeanSquaredLogError,
    MedianAbsoluteError,
    MinkowskiDistance,
    MultiScaleSSIM,
    PearsonCorrcoef,
    Percentile,
    Quantile,
    R2Score,
    SpearmanCorrcoef,
    TotalVariation,
    SpectralAngleMapper,
    SymmetricMeanAbsolutePercentageError,
    TweedieDevianceScore,
    UniversalImageQualityIndex,
    WeightedMeanAbsolutePercentageError,
)
from metrics_tpu.retrieval import (  # noqa: E402
    RetrievalFallOut,
    RetrievalHitRate,
    RetrievalMAP,
    RetrievalMetric,
    RetrievalMRR,
    RetrievalNormalizedDCG,
    RetrievalPrecision,
    RetrievalRPrecision,
    RetrievalRecall,
)
from metrics_tpu.text import BLEUScore, CHRFScore, CharErrorRate, MatchErrorRate, EditDistance, Perplexity, ROUGEScore, SQuAD, SacreBLEUScore, TranslationEditRate, WER, WordInfoLost, WordInfoPreserved  # noqa: E402
from metrics_tpu.audio import PIT, SI_SDR, SI_SNR, SNR  # noqa: E402
from metrics_tpu.detection import MeanAveragePrecision  # noqa: E402
from metrics_tpu.nominal import (  # noqa: E402
    CramersV,
    PearsonsContingencyCoefficient,
    TheilsU,
    TschuprowsT,
)
from metrics_tpu.clustering import (  # noqa: E402
    AdjustedMutualInfoScore,
    AdjustedRandScore,
    CalinskiHarabaszScore,
    CompletenessScore,
    DaviesBouldinScore,
    FowlkesMallowsScore,
    HomogeneityScore,
    MutualInfoScore,
    NormalizedMutualInfoScore,
    RandScore,
    VMeasureScore,
)
from metrics_tpu.wrappers import (  # noqa: E402
    BootStrapper,
    ClasswiseWrapper,
    HeavyHitters,
    Keyed,
    MetricTracker,
    MinMaxMetric,
    MultioutputWrapper,
    Running,
    Windowed,
)
from metrics_tpu.serving import (  # noqa: E402
    ExpositionServer,
    HeavyHitterFleet,
    MetricFleet,
    MetricService,
    RetentionStore,
)
from metrics_tpu.core.streaming import WatermarkAgreement  # noqa: E402
from metrics_tpu import functional  # noqa: E402
