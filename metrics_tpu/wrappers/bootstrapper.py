"""BootStrapper. Extension beyond the reference snapshot (later torchmetrics
``wrappers/bootstrapping.py``).

Each of ``num_bootstraps`` copies of the base metric sees a with-replacement
resample of every batch. Resample indices come from a host-side seeded
generator (cheap host ints; the gathers run on device), so runs are
reproducible via ``seed`` and no device randomness threads through the
metric API.
"""
from typing import Any, Callable, Dict, Optional

import jax.numpy as jnp
import numpy as np
from copy import deepcopy
from jax import Array

from metrics_tpu.core.metric import Metric


class BootStrapper(Metric):
    r"""Bootstrap-resampled uncertainty for any metric.

    ``compute()`` returns ``{"mean": ..., "std": ...}`` over the bootstrap
    copies' values (plus ``"raw"`` of shape ``(num_bootstraps,)`` when
    ``raw=True``).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import Accuracy
        >>> m = BootStrapper(Accuracy(), num_bootstraps=4, seed=7)
        >>> m.update(jnp.array([1, 1, 0, 0]), jnp.array([1, 0, 0, 0]))
        >>> out = m.compute()
        >>> sorted(out)
        ['mean', 'std']
    """

    def __init__(
        self,
        base_metric: Metric,
        num_bootstraps: int = 10,
        raw: bool = False,
        seed: int = 0,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        if not isinstance(base_metric, Metric):
            raise ValueError(f"`base_metric` must be a Metric, got {type(base_metric).__name__}")
        if not isinstance(num_bootstraps, int) or num_bootstraps < 2:
            raise ValueError(
                f"`num_bootstraps` must be an integer >= 2 (the std needs two samples), got {num_bootstraps!r}"
            )
        self.metrics = [deepcopy(base_metric) for _ in range(num_bootstraps)]
        self.num_bootstraps = num_bootstraps
        self.raw = raw
        self._resample_rng = np.random.RandomState(seed)

    def update(self, *args: Any, **kwargs: Any) -> None:
        """Update every copy with an independent with-replacement resample.

        Resampling indexes the leading axis of every array argument and
        kwarg (so preds/target stay paired)."""
        arrays = [a for a in (*args, *kwargs.values()) if hasattr(a, "shape") and a.ndim >= 1]
        n = arrays[0].shape[0] if arrays else None

        def resample(value: Any, idx: Array) -> Any:
            if hasattr(value, "shape") and value.ndim >= 1 and value.shape[0] == n:
                return value[idx]
            return value

        for metric in self.metrics:
            if n is None:
                metric.update(*args, **kwargs)
                continue
            idx = jnp.asarray(self._resample_rng.randint(0, n, n))
            metric.update(
                *(resample(a, idx) for a in args),
                **{k: resample(v, idx) for k, v in kwargs.items()},
            )

    def forward(self, *args: Any, **kwargs: Any) -> Optional[Dict[str, Array]]:
        """Accumulate the batch into every copy; with ``compute_on_step``
        return the batch-local mean/std (the base fused forward cannot be
        used here: the bootstrap copies are child metrics, not registered
        states). The batch-local pass replays the same resample draws the
        accumulation consumed, so both see identical resamples."""
        self._computed = None
        rng_state = self._resample_rng.get_state()
        self.update(*args, **kwargs)
        if not self.compute_on_step:
            return None
        # batch-local pass under the reference forward discipline: no
        # cross-process sync (unless dist_sync_on_step) and the overflow
        # bound survives the temp reset (core/metric.py _forward_reference)
        caches = [(m._current_state(), m._count_bound) for m in self.metrics]
        saved_sync = [(m._to_sync, m._in_forward) for m in self.metrics]
        self._to_sync, self._in_forward = self.dist_sync_on_step, True
        for m in self.metrics:
            m._to_sync, m._in_forward = self.dist_sync_on_step, True
            m.reset()
        self._resample_rng.set_state(rng_state)
        try:
            self.update(*args, **kwargs)
            value = self.compute()
        finally:
            for m, (cache, bound), (to_sync, in_fwd) in zip(self.metrics, caches, saved_sync):
                m._set_state(cache)
                m._count_bound = bound
                m._computed = None  # the batch-local compute cached batch values
                m._to_sync, m._in_forward = to_sync, in_fwd
            self._to_sync, self._in_forward = True, False
            self._computed = None
        self._forward_cache = value
        return value

    def compute(self) -> Dict[str, Array]:
        values = jnp.stack([jnp.asarray(m.compute(), dtype=jnp.float32) for m in self.metrics])
        out = {"mean": jnp.mean(values, axis=0), "std": jnp.std(values, axis=0, ddof=1)}
        if self.raw:
            out["raw"] = values
        return out

    def reset(self) -> None:
        super().reset()
        for metric in self.metrics:
            metric.reset()
