"""BootStrapper. Extension beyond the reference snapshot (later torchmetrics
``wrappers/bootstrapping.py``).

Each of ``num_bootstraps`` copies of the base metric sees a with-replacement
resample of every batch. Resample indices come from a host-side seeded
generator (cheap host ints; the gathers run on device), so runs are
reproducible via ``seed`` and no device randomness threads through the
metric API.

TPU-native design: the copies are not ``num_bootstraps`` stateful child
metrics but ONE stacked state pytree with a leading bootstrap axis. All
resample index matrices are drawn at once (``(K, n)``) and a single jitted
program vmaps the base update over the bootstrap axis, merges the stacked
delta into the stacked accumulator, and (under ``compute_on_step``) vmaps
the batch value — one device dispatch per step regardless of ``K``, where a
per-copy loop pays K dispatches (10-20 per step through a device tunnel at
the default K=10). Base metrics whose update cannot trace (data-dependent
mode inference) and multi-process host-plane deployments fall back to real
per-copy child metrics with identical seeded draws.
"""
import threading
from copy import deepcopy
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.core.metric import Metric


class BootStrapper(Metric):
    r"""Bootstrap-resampled uncertainty for any metric.

    ``compute()`` returns ``{"mean": ..., "std": ...}`` over the bootstrap
    copies' values (plus ``"raw"`` of shape ``(num_bootstraps,)`` when
    ``raw=True``).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import Accuracy
        >>> m = BootStrapper(Accuracy(), num_bootstraps=4, seed=7)
        >>> m.update(jnp.array([1, 1, 0, 0]), jnp.array([1, 0, 0, 0]))
        >>> out = m.compute()
        >>> sorted(out)
        ['mean', 'std']
    """

    def __init__(
        self,
        base_metric: Metric,
        num_bootstraps: int = 10,
        raw: bool = False,
        seed: int = 0,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        if not isinstance(base_metric, Metric):
            raise ValueError(f"`base_metric` must be a Metric, got {type(base_metric).__name__}")
        if not isinstance(num_bootstraps, int) or num_bootstraps < 2:
            raise ValueError(
                f"`num_bootstraps` must be an integer >= 2 (the std needs two samples), got {num_bootstraps!r}"
            )
        self._template = deepcopy(base_metric)  # detached config carrier
        self._template.reset()
        self.num_bootstraps = num_bootstraps
        self.raw = raw
        self._resample_rng = np.random.RandomState(seed)
        self._stacked = None  # (K, ...) state pytree, lazily initialized
        self.metrics = None  # per-copy children, built only on the loop fallback
        self._mode = None  # 'vmapped' | 'loop', decided at the first update
        self._vsteps: Dict[Any, Callable] = {}
        self._vcompute = None
        self._step_lock = threading.Lock()

    # ----------------------------------------------------------- vmapped path
    def _resample_plan(self, args: tuple, kwargs: dict) -> Tuple[Optional[int], tuple, tuple]:
        """(n, per-arg resample flags, per-kwarg flags) — the OLD loop rule:
        arrays whose leading axis matches the first array's are resampled."""
        arrays = [a for a in (*args, *kwargs.values()) if hasattr(a, "shape") and getattr(a, "ndim", 0) >= 1]
        n = arrays[0].shape[0] if arrays else None

        def flag(v: Any) -> bool:
            return hasattr(v, "shape") and getattr(v, "ndim", 0) >= 1 and v.shape[0] == n

        return n, tuple(flag(a) for a in args), tuple((k, flag(v)) for k, v in sorted(kwargs.items()))

    def _build_vstep(self, kind: str, aflags: tuple, kwflags: tuple) -> Callable:
        """One jitted program per step. ``kind``: 'none' -> merged only;
        'stats' -> merged + fused batch mean/std; 'deltas' -> merged + the
        stacked per-copy delta states (the compute-left-eager retry tier)."""
        template = self._template
        lock = self._step_lock
        donate = (0,) if jax.default_backend() == "tpu" else ()

        def step(stacked, idx_mat, args, kwargs):
            def one(idx):
                rs_args = tuple(a[idx] if f else a for a, f in zip(args, aflags))
                rs_kw = {k: (kwargs[k][idx] if f else kwargs[k]) for k, f in kwflags}
                with lock:
                    return template._run_update_on_state(template.init_state(), *rs_args, **rs_kw)

            deltas = jax.vmap(one)(idx_mat)
            merged = jax.vmap(template.merge_states)(stacked, deltas)
            if kind == "none":
                return merged, ()
            if kind == "deltas":
                return merged, deltas
            with lock:
                values = jax.vmap(
                    lambda s: jnp.asarray(template.compute_from_state(s), dtype=jnp.float32)
                )(deltas)
            return merged, self._stats(values)

        return jax.jit(step, donate_argnums=donate)

    def _stats(self, values: Array) -> Dict[str, Array]:
        out = {"mean": jnp.mean(values, axis=0), "std": jnp.std(values, axis=0, ddof=1)}
        if self.raw:
            out["raw"] = values
        return out

    def _init_stacked(self):
        base = self._template.init_state()
        return jax.tree_util.tree_map(
            lambda x: jnp.broadcast_to(x[None], (self.num_bootstraps, *x.shape)).copy()
            if hasattr(x, "shape")
            else x,
            base,
        )

    def _run_vmapped(self, args: tuple, kwargs: dict, idx_mat: Array, kind: str):
        n, aflags, kwflags = self._resample_plan(args, kwargs)
        key = (kind, aflags, kwflags)
        fn = self._vsteps.get(key)
        if fn is None:
            fn = self._build_vstep(kind, aflags, kwflags)
            self._vsteps[key] = fn
        if self._stacked is None:
            self._stacked = self._init_stacked()
        merged, extra = fn(self._stacked, idx_mat, args, kwargs)
        self._stacked = merged
        return extra

    def _eager_copy_values(self, stacked_states) -> Array:
        """Per-copy values computed EAGERLY from a stacked state pytree (for
        base computes that need concrete values — the base Metric's
        _fc_failed tier, one eager compute per copy, jitted update kept)."""
        template = self._template
        values = []
        for k in range(self.num_bootstraps):
            state_k = {name: value[k] for name, value in stacked_states.items()}
            with self._step_lock:
                values.append(jnp.asarray(template.compute_from_state(state_k), dtype=jnp.float32))
        return jnp.stack(values)

    # ------------------------------------------------------------- loop path
    def _ensure_children(self) -> None:
        if self.metrics is None:
            self.metrics = [deepcopy(self._template) for _ in range(self.num_bootstraps)]

    def _loop_update(self, args: tuple, kwargs: dict, idx_mat: Optional[Array]) -> None:
        self._ensure_children()
        n, aflags, kwflags = self._resample_plan(args, kwargs)
        kwflag_map = dict(kwflags)
        for k, metric in enumerate(self.metrics):
            if idx_mat is None:
                metric.update(*args, **kwargs)
                continue
            idx = idx_mat[k]
            metric.update(
                *(a[idx] if f else a for a, f in zip(args, aflags)),
                **{key: (v[idx] if kwflag_map[key] else v) for key, v in kwargs.items()},
            )

    # -------------------------------------------------------------- dispatch
    def _draw(self, args: tuple, kwargs: dict) -> Optional[Array]:
        n, _, _ = self._resample_plan(args, kwargs)
        if n is None:
            return None
        # one (K, n) draw == K sequential (n,) draws from the same stream:
        # the loop fallback and the vmapped path see identical resamples
        return jnp.asarray(self._resample_rng.randint(0, n, (self.num_bootstraps, n)))

    def _decide_mode(self) -> None:
        if self._mode is not None:
            return
        # multi-process host-plane deployments need per-copy children whose
        # compute() syncs individually (the reference interface discipline);
        # eager-list cat states cannot carry a bootstrap axis
        if (
            jax.process_count() > 1
            or self.dist_sync_fn is not None
            or any(isinstance(d, list) for d in self._template._defaults.values())
        ):
            self._mode = "loop"
        else:
            self._mode = "vmapped"

    def _accumulate(self, args: tuple, kwargs: dict, with_compute: bool):
        self._decide_mode()
        idx_mat = self._draw(args, kwargs)
        if self._mode == "vmapped":
            safe_idx = idx_mat if idx_mat is not None else jnp.zeros((self.num_bootstraps, 0), jnp.int32)
            try:
                if with_compute and not self._fc_failed:
                    try:
                        return self._run_vmapped(args, kwargs, safe_idx, "stats")
                    except self._TRACER_ERRORS:
                        # only the COMPUTE half may be untraceable: keep the
                        # vmapped update and leave the batch value eager (the
                        # base Metric's _fc_failed tier), instead of demoting
                        # to K dispatches per step forever
                        self._fc_failed = True
                if with_compute:
                    deltas = self._run_vmapped(args, kwargs, safe_idx, "deltas")
                    return self._stats(self._eager_copy_values(deltas))
                return self._run_vmapped(args, kwargs, safe_idx, "none")
            except self._TRACER_ERRORS:
                # the UPDATE itself needs concrete values -> permanent
                # per-copy fallback, replaying the SAME drawn resamples.
                # State already accumulated on the stacked path transfers to
                # the children (copy k inherits stacked[name][k]) so no
                # batch is lost.
                self._mode = "loop"
                if self._stacked is not None:
                    self._ensure_children()
                    for k, child in enumerate(self.metrics):
                        child._set_state(
                            {name: value[k] for name, value in self._stacked.items()}
                        )
                self._stacked = None
                self._vsteps.clear()
        self._loop_update(args, kwargs, idx_mat)
        if not with_compute:
            return ()
        return self._loop_batch_value(args, kwargs, idx_mat)

    def _loop_batch_value(self, args: tuple, kwargs: dict, idx_mat: Optional[Array]):
        """Batch-local mean/std under the reference forward discipline: the
        children's accumulated state is cached/restored around a replayed
        batch-only pass (core/metric.py _forward_reference semantics)."""
        caches = [(m._current_state(), m._count_bound) for m in self.metrics]
        saved_sync = [(m._to_sync, m._in_forward) for m in self.metrics]
        for m in self.metrics:
            m._to_sync, m._in_forward = self.dist_sync_on_step, True
            m.reset()
        try:
            self._loop_update(args, kwargs, idx_mat)
            values = jnp.stack([jnp.asarray(m.compute(), dtype=jnp.float32) for m in self.metrics])
        finally:
            for m, (cache, bound), (to_sync, in_fwd) in zip(self.metrics, caches, saved_sync):
                m._set_state(cache)
                m._count_bound = bound
                m._computed = None  # the batch-local compute cached batch values
                m._to_sync, m._in_forward = to_sync, in_fwd
        return self._stats(values)

    # ------------------------------------------------------------ public API
    def update(self, *args: Any, **kwargs: Any) -> None:
        """Accumulate an independent with-replacement resample per copy —
        ONE device dispatch for all copies on the vmapped path."""
        self._computed = None
        self._accumulate(args, kwargs, with_compute=False)

    def forward(self, *args: Any, **kwargs: Any) -> Optional[Dict[str, Array]]:
        """Accumulate the batch into every copy; with ``compute_on_step``
        return the batch-local mean/std — update, merge, AND the per-copy
        batch values in one jitted dispatch on the vmapped path."""
        self._computed = None
        stats = self._accumulate(args, kwargs, with_compute=self.compute_on_step)
        if not self.compute_on_step:
            return None
        self._forward_cache = stats
        return stats

    def compute(self) -> Dict[str, Array]:
        if self._mode == "loop":
            self._ensure_children()
            values = jnp.stack([jnp.asarray(m.compute(), dtype=jnp.float32) for m in self.metrics])
            return self._stats(values)
        stacked = self._stacked if self._stacked is not None else self._init_stacked()
        if not self._fc_failed:
            if self._vcompute is None:
                template = self._template
                lock = self._step_lock

                def epoch_values(st):
                    with lock:
                        return jax.vmap(
                            lambda s: jnp.asarray(template.compute_from_state(s), dtype=jnp.float32)
                        )(st)

                self._vcompute = jax.jit(epoch_values)
            try:
                return self._stats(self._vcompute(stacked))
            except self._TRACER_ERRORS:
                # compute needs concrete values: per-copy eager from the
                # SAME stacked accumulator (updates stay vmapped)
                self._fc_failed = True
        return self._stats(self._eager_copy_values(stacked))

    def reset(self) -> None:
        super().reset()
        self._stacked = None
        if self.metrics is not None:
            for metric in self.metrics:
                metric.reset()

    # jitted closures are neither picklable nor deep-copyable; rebuilt lazily
    def __getstate__(self) -> dict:
        state = super().__getstate__()
        for key in ("_vsteps", "_vcompute", "_step_lock"):
            state.pop(key, None)
        return state

    def __setstate__(self, state: dict) -> None:
        super().__setstate__(state)
        self._vsteps = {}
        self._vcompute = None
        self._step_lock = threading.Lock()

    def __deepcopy__(self, memo: dict) -> "BootStrapper":
        skip = {"_vsteps", "_vcompute", "_step_lock"}
        saved = {k: self.__dict__.pop(k) for k in skip}
        try:
            new = super().__deepcopy__(memo)
        finally:
            self.__dict__.update(saved)
        new._vsteps = {}
        new._vcompute = None
        new._step_lock = threading.Lock()
        return new
