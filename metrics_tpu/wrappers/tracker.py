"""MetricTracker. Extension beyond the reference snapshot (later torchmetrics
``wrappers/tracker.py``)."""
from copy import deepcopy
from typing import Any, List, Tuple, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.core.metric import Metric


class MetricTracker(Metric):
    r"""Track a metric (or collection) over multiple epochs/increments.

    Call ``increment()`` at each epoch boundary; update/forward route to the
    newest copy. ``compute_all()`` stacks every increment's value and
    ``best_metric()`` returns the best (optionally with its step index).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import Accuracy
        >>> tracker = MetricTracker(Accuracy())
        >>> for epoch in range(2):
        ...     tracker.increment()
        ...     _ = tracker(jnp.array([1, 1, 0, 0]), jnp.array([1, epoch, 0, 0]))
        >>> float(tracker.best_metric())
        1.0
    """

    def __init__(self, base_metric: Metric, maximize: bool = True):
        super().__init__(compute_on_step=base_metric.compute_on_step)
        if not isinstance(base_metric, Metric):
            raise ValueError(f"`base_metric` must be a Metric, got {type(base_metric).__name__}")
        self._base = base_metric
        self.maximize = maximize
        self._increments: List[Metric] = []

    @property
    def n_steps(self) -> int:
        return len(self._increments)

    def _current(self) -> Metric:
        if not self._increments:
            raise RuntimeError("call `tracker.increment()` before updating the tracker")
        return self._increments[-1]

    def increment(self) -> None:
        """Start tracking a fresh copy of the base metric."""
        self._computed = None
        fresh = deepcopy(self._base)
        fresh.reset()
        self._increments.append(fresh)

    def update(self, *args: Any, **kwargs: Any) -> None:
        self._current().update(*args, **kwargs)

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        self._computed = None  # bypasses the wrapped update: clear the cache here
        return self._current().forward(*args, **kwargs)

    def compute(self) -> Any:
        return self._current().compute()

    def compute_all(self) -> Array:
        """Values of every increment, stacked along a leading step axis."""
        return jnp.stack([jnp.asarray(m.compute(), dtype=jnp.float32) for m in self._increments])

    def best_metric(self, return_step: bool = False) -> Union[Array, Tuple[Array, int]]:
        """The best scalar value across increments (and its step index)."""
        values = np.asarray(self.compute_all())
        if values.ndim != 1:
            raise ValueError(
                "best_metric is defined for scalar metrics; use compute_all() for"
                f" higher-rank values (got shape {values.shape})"
            )
        step = int(np.argmax(values) if self.maximize else np.argmin(values))
        best = jnp.asarray(values[step])
        return (best, step) if return_step else best

    def reset(self) -> None:
        """Reset the CURRENT increment (keeps history)."""
        self._computed = None
        if self._increments:
            self._current().reset()

    def reset_all(self) -> None:
        """Drop all history."""
        self._computed = None
        self._increments = []
