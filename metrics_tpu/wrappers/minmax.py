"""MinMaxMetric wrapper. Extension beyond the reference snapshot (later
torchmetrics ``wrappers/minmax.py``)."""
from typing import Any, Callable, Dict, Optional

import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.core.metric import Metric


class MinMaxMetric(Metric):
    r"""Track a scalar metric together with the min/max of its computed values.

    ``compute()`` returns ``{"raw": current, "min": lowest-yet, "max":
    highest-yet}``. The extrema fold in EVERY computed value — the
    batch-local values each ``forward`` yields as well as epoch-level
    ``compute()`` results — and carry ``min``/``max`` reductions for
    cross-device sync. (Call only ``update`` + ``compute`` if you want
    extrema over epoch values alone.)

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import Accuracy
        >>> m = MinMaxMetric(Accuracy())
        >>> _ = m(jnp.array([1, 1, 0, 0]), jnp.array([1, 0, 0, 0]))
        >>> sorted(m.compute().items())  # doctest: +ELLIPSIS
        [('max', Array(0.75, ...)), ('min', Array(0.75, ...)), ('raw', Array(0.75, ...))]
    """

    def __init__(
        self,
        base_metric: Metric,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        if not isinstance(base_metric, Metric):
            raise ValueError(f"`base_metric` must be a Metric, got {type(base_metric).__name__}")
        self.base_metric = base_metric
        self.add_state("min_val", default=np.asarray(np.inf, dtype=np.float32), dist_reduce_fx="min")
        self.add_state("max_val", default=np.asarray(-np.inf, dtype=np.float32), dist_reduce_fx="max")

    def _extrema(self, raw: Array):
        # a nan raw value (e.g. compute with no data) must not poison the extrema
        lo = jnp.where(jnp.isnan(raw), self.min_val, jnp.minimum(self.min_val, raw))
        hi = jnp.where(jnp.isnan(raw), self.max_val, jnp.maximum(self.max_val, raw))
        return lo, hi

    def update(self, *args: Any, **kwargs: Any) -> None:
        self.base_metric.update(*args, **kwargs)

    def forward(self, *args: Any, **kwargs: Any) -> Optional[Dict[str, Array]]:
        """Accumulate and fold the batch-local value into the extrema (the
        base fused forward cannot run here: the wrapped metric is a child,
        not registered state)."""
        self._computed = None
        value = self.base_metric.forward(*args, **kwargs)
        if value is None:
            return None
        raw = jnp.asarray(value, dtype=jnp.float32)
        self.min_val, self.max_val = self._extrema(raw)
        self._forward_cache = {"raw": raw, "min": self.min_val, "max": self.max_val}
        return self._forward_cache

    def compute(self) -> Dict[str, Array]:
        raw = jnp.asarray(self.base_metric.compute(), dtype=jnp.float32)
        lo, hi = self._extrema(raw)
        return {"raw": raw, "min": lo, "max": hi}

    def _after_compute(self, result: Dict[str, Array]) -> None:
        # persist the extrema AFTER the wrapped compute's sync restore (state
        # written inside compute itself would be discarded under ddp sync)
        self.min_val = result["min"]
        self.max_val = result["max"]

    def reset(self) -> None:
        super().reset()
        self.base_metric.reset()
