"""MultioutputWrapper. Extension beyond the reference snapshot (later
torchmetrics ``wrappers/multioutput.py``)."""
from typing import Any, List, Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric


class MultioutputWrapper(Metric):
    r"""Apply a base metric independently to each output column.

    Wraps ``num_outputs`` clones of ``base_metric``; every ``update`` /
    ``forward`` slices column ``i`` of the (..., ``num_outputs``) preds and
    target into clone ``i``, and ``compute()`` stacks the per-column results
    into a ``(num_outputs,)`` vector. The clones are ordinary child metrics,
    so sync/reset/pickling follow the normal rules.

    Args:
        base_metric: the metric to replicate per output column.
        num_outputs: number of trailing-axis output columns.
        output_dim: axis holding the outputs (default ``-1``).
        remove_nans: drop rows where either preds or target is NaN in a
            column before updating that column's clone (matching the
            torchmetrics wrapper's default).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import MeanSquaredError
        >>> m = MultioutputWrapper(MeanSquaredError(), num_outputs=2)
        >>> preds = jnp.array([[1.0, 10.0], [2.0, 20.0]])
        >>> target = jnp.array([[1.0, 14.0], [3.0, 22.0]])
        >>> [round(float(v), 2) for v in m(preds, target)]
        [0.5, 10.0]
    """

    def __init__(
        self,
        base_metric: Metric,
        num_outputs: int,
        output_dim: int = -1,
        remove_nans: bool = True,
    ):
        if not isinstance(base_metric, Metric):
            raise ValueError(f"`base_metric` must be a Metric, got {type(base_metric).__name__}")
        if not isinstance(num_outputs, int) or num_outputs < 1:
            raise ValueError(f"`num_outputs` must be a positive int, got {num_outputs!r}")
        super().__init__(compute_on_step=base_metric.compute_on_step)
        self.metrics: List[Metric] = [base_metric.clone() for _ in range(num_outputs)]
        self.num_outputs = num_outputs
        self.output_dim = output_dim
        self.remove_nans = remove_nans

    def _columns(self, value: Array, i: int) -> Array:
        return jnp.take(value, i, axis=self.output_dim)

    def _any_nans(self, preds: Array, target: Array) -> bool:
        """At most ONE device readback per update, and none for int dtypes.

        The per-column boolean compression is data-dependent (eager-only,
        like the torchmetrics wrapper), so the NaN probe is a forced host
        sync; doing it once on the full arrays instead of per column keeps
        a clean-data K-output update readback-free except this single check.
        """
        if not self.remove_nans:
            return False
        checks = [x for x in (preds, target) if jnp.issubdtype(x.dtype, jnp.floating)]
        if not checks:
            return False
        return bool(jnp.any(jnp.stack([jnp.isnan(x).any() for x in checks])))

    def _pair(self, preds: Array, target: Array, i: int, filter_nans: bool):
        p = self._columns(preds, i)
        t = self._columns(target, i)
        if filter_nans:
            keep = ~(jnp.isnan(p.astype(jnp.float32)) | jnp.isnan(t.astype(jnp.float32)))
            p, t = p[keep], t[keep]
        return p, t

    def update(self, preds: Array, target: Array) -> None:
        preds, target = jnp.asarray(preds), jnp.asarray(target)
        filter_nans = self._any_nans(preds, target)
        for i, m in enumerate(self.metrics):
            p, t = self._pair(preds, target, i, filter_nans)
            m.update(p, t)

    def forward(self, preds: Array, target: Array) -> Optional[Array]:
        preds, target = jnp.asarray(preds), jnp.asarray(target)
        filter_nans = self._any_nans(preds, target)
        values = []
        for i, m in enumerate(self.metrics):
            p, t = self._pair(preds, target, i, filter_nans)
            values.append(m.forward(p, t))
        self._computed = None
        if any(v is None for v in values):
            return None
        return jnp.stack(values)

    def compute(self) -> Array:
        return jnp.stack([m.compute() for m in self.metrics])

    def reset(self) -> None:
        super().reset()
        for m in self.metrics:
            m.reset()

    def persistent(self, mode: bool = False) -> None:
        for m in self.metrics:
            m.persistent(mode)
