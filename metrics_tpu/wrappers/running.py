"""Running-window wrapper. Extension beyond the reference snapshot (later
torchmetrics ``wrappers/running.py``)."""
from typing import Any, List, Optional

from metrics_tpu.core.metric import Metric


class Running(Metric):
    r"""A sliding-window view of any metric: the value over the last
    ``window`` updates.

    Each ``update`` stages the batch as an independent state delta via the
    base metric's pure functions (``init -> update``); ``compute()`` merges
    the last ``window`` deltas and computes on the result. Nothing is
    recomputed per step beyond the one new delta, and every stored delta is
    a device pytree, so the window costs ``window x state_size`` memory.

    The window is process-local by design (like the torchmetrics wrapper):
    cross-process sync of a sliding window is ill-defined, so the wrapper
    never syncs.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import MeanSquaredError
        >>> running = Running(MeanSquaredError(), window=2)
        >>> for step in range(4):
        ...     _ = running(jnp.array([float(step)]), jnp.array([0.0]))
        >>> float(running.compute())  # last two steps: (2^2 + 3^2) / 2
        6.5
    """

    def __init__(self, base_metric: Metric, window: int = 5):
        if not isinstance(base_metric, Metric):
            raise ValueError(f"`base_metric` must be a Metric, got {type(base_metric).__name__}")
        if not isinstance(window, int) or window < 1:
            raise ValueError(f"`window` must be a positive int, got {window!r}")
        super().__init__(compute_on_step=base_metric.compute_on_step)
        self.base_metric = base_metric
        self.window = window
        self._pure = base_metric.pure()
        self._deltas: List[Any] = []

    def update(self, *args: Any, **kwargs: Any) -> None:
        delta = self._pure.update(self._pure.init(), *args, **kwargs)
        self._deltas.append(delta)
        if len(self._deltas) > self.window:
            self._deltas.pop(0)

    def forward(self, *args: Any, **kwargs: Any) -> Optional[Any]:
        self.update(*args, **kwargs)
        self._computed = None
        if not self.compute_on_step:
            return None
        return self.compute()

    def compute(self) -> Any:
        state = self._pure.init()
        for delta in self._deltas:
            state = self._pure.merge(state, delta)
        return self._pure.compute(state)

    def reset(self) -> None:
        super().reset()
        self._deltas = []
