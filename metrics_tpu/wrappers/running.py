"""Running-window wrapper. Extension beyond the reference snapshot (later
torchmetrics ``wrappers/running.py``)."""
from typing import Any, List, Optional

import jax.numpy as jnp
import numpy as np

from metrics_tpu.core.metric import Metric
from metrics_tpu.parallel.buffer import PaddedBuffer
from metrics_tpu.parallel.sketch import is_sketch


class Running(Metric):
    r"""A sliding-window view of any metric: the value over the last
    ``window`` updates.

    Each ``update`` stages the batch as an independent state delta via the
    base metric's pure functions (``init -> update``); ``compute()`` merges
    the last ``window`` deltas and computes on the result. Nothing is
    recomputed per step beyond the one new delta, and every stored delta is
    a device pytree, so the window costs ``window x state_size`` memory.

    The window is process-local by design (like the torchmetrics wrapper):
    cross-process sync of a sliding window is ill-defined, so the wrapper
    never syncs.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import MeanSquaredError
        >>> running = Running(MeanSquaredError(), window=2)
        >>> for step in range(4):
        ...     _ = running(jnp.array([float(step)]), jnp.array([0.0]))
        >>> float(running.compute())  # last two steps: (2^2 + 3^2) / 2
        6.5
    """

    def __init__(self, base_metric: Metric, window: int = 5):
        if not isinstance(base_metric, Metric):
            raise ValueError(f"`base_metric` must be a Metric, got {type(base_metric).__name__}")
        if not isinstance(window, int) or window < 1:
            raise ValueError(f"`window` must be a positive int, got {window!r}")
        super().__init__(compute_on_step=base_metric.compute_on_step)
        self.base_metric = base_metric
        self.window = window
        self._pure = base_metric.pure()
        self._deltas: List[Any] = []

    def update(self, *args: Any, **kwargs: Any) -> None:
        delta = self._pure.update(self._pure.init(), *args, **kwargs)
        self._deltas.append(delta)
        if len(self._deltas) > self.window:
            self._deltas.pop(0)

    def forward(self, *args: Any, **kwargs: Any) -> Optional[Any]:
        self.update(*args, **kwargs)
        self._computed = None
        if not self.compute_on_step:
            return None
        return self.compute()

    def compute(self) -> Any:
        state = self._pure.init()
        for delta in self._deltas:
            state = self._pure.merge(state, delta)
        return self._pure.compute(state)

    def reset(self) -> None:
        super().reset()
        self._deltas = []

    # ------------------------------------------------------------ checkpoint
    # The window IS the state: ``_deltas`` holds one state pytree per
    # retained step. The base ``state_dict`` only serializes REGISTERED
    # states, and this wrapper registers none — without the override below a
    # restored ``Running`` silently computed over an empty window (the data
    # loss the round-trip test in tests/bases/test_wrappers.py pins).
    _DELTAS_KEY = "_running_deltas"

    def state_dict(self, destination: Optional[dict] = None, prefix: str = "") -> dict:
        """The retained window deltas as host numpy, plus the base entries
        (including the epoch watermark, so a restored ``Running`` replays
        its in-flight step idempotently via ``guarded_update``)."""
        destination = super().state_dict(destination, prefix=prefix)
        destination[prefix + self._DELTAS_KEY] = [
            {name: _encode_leaf(value) for name, value in delta.items()}
            for delta in self._deltas
        ]
        return destination

    def load_state_dict(self, state_dict: dict, prefix: str = "") -> None:
        super().load_state_dict(state_dict, prefix=prefix)
        key = prefix + self._DELTAS_KEY
        if key not in state_dict:
            return  # pre-fix checkpoint: nothing to restore (window was lost at save)
        template = self._pure.init()
        self._deltas = [
            {name: _decode_leaf(entry[name], template.get(name)) for name in entry}
            for entry in state_dict[key]
        ][-self.window:]


def _encode_leaf(value: Any) -> Any:
    """One delta state leaf as checkpoint-friendly host data (mirrors the
    base ``state_dict`` leaf conventions)."""
    if isinstance(value, PaddedBuffer):
        return {"data": np.asarray(value.data), "count": np.asarray(value.count)}
    if is_sketch(value):
        return {"sketch_counts": np.asarray(value.counts)}
    if isinstance(value, list):
        return [np.asarray(v) for v in value]
    return np.asarray(value)


def _decode_leaf(value: Any, template: Any) -> Any:
    if isinstance(value, dict) and set(value) == {"data", "count"}:
        return PaddedBuffer(jnp.asarray(value["data"]), jnp.asarray(value["count"]))
    if isinstance(value, dict) and set(value) == {"sketch_counts"}:
        kind = type(template) if is_sketch(template) else None
        if kind is None:
            raise ValueError("checkpoint delta holds sketch counts but the state is not a sketch")
        return kind(jnp.asarray(value["sketch_counts"]))
    if isinstance(value, list):
        return [jnp.asarray(v) for v in value]
    return jnp.asarray(value)
