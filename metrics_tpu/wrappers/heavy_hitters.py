"""HeavyHitters: open-world key cardinality — exact hot slab, certified
count-min tail, no key ever loses mass.

``Keyed(metric, num_slots)`` caps the segment space at ``num_slots``: LRU
eviction destroys an evicted tenant's history, and sizing K for the worst
case wastes slab memory on the 99% of keys that are cold. ``HeavyHitters``
is the two-tier answer from the streaming-frequency literature — exact
Space-Saving-style counters for the hot set (Metwally et al., "Efficient
Computation of Frequent and Top-k Elements in Data Streams") over a
Count-Min sketch tail (Cormode & Muthukrishnan) — specialized so both tiers
are ordinary mergeable states:

- **Hot tier**: the top-K keys own exact ``(K, *shape)`` slab rows through
  the existing :class:`~metrics_tpu.parallel.slab.SlabSpec` machinery —
  bit-exact per-key values, one scatter per update, one leading state axis.
- **Tail tier**: every other key folds its per-sample state delta into a
  :class:`~metrics_tpu.parallel.cms.CountMinSketch` per inner leaf —
  ``(depth, width, *shape)``, constant memory in the LIVE KEY COUNT, reads
  certified as overcounts by at most ``(e/width) * N`` samples with
  probability ``1 - e^-depth`` (:func:`~metrics_tpu.parallel.cms.
  cms_error_bound`).
- **Promotion/demotion**: a host-side Space-Saving table
  (:class:`SpaceSavingTable`, the open-world analogue of ``LRUSlotTable``)
  migrates keys as traffic shifts — a tail key whose estimated count
  overtakes the coldest hot key's takes its slot, and the demoted key's
  slab rows are FOLDED into the tail (``slab_take_rows`` + ``cms_scatter``)
  before the slot resets: demotion conserves mass instead of destroying
  history, so hot + tail totals are bit-exact the whole stream's.

Both tiers are sum-reduced integer/float leaves, so sync rides the existing
coalesced ``psum`` buckets of ``coalesced_sync_state`` UNCHANGED: the staged
collective count is identical to the unkeyed metric's at ANY simulated key
count (``bench.py --check-collectives`` pins it at K=1,000,000), and state
bytes are constant in the live-key count by construction.

Like ``Keyed(lru=True)``, key resolution is host-side by construction (the
whole point of the table is data-dependent key management jit cannot
express), so updates run the eager path; every scatter that consumes the
resolved routing is still one XLA op. The contract on the inner metric is
the ``Keyed`` contract narrowed to the tail's soundness requirement:
fixed-shape ``sum``/``mean`` states or sketch states with NON-NEGATIVE
per-sample deltas (counts, histogram increments) — ``min``/``max`` states
have no certified tail form (use ``Keyed`` for those), and cat/buffer
states have no slab form (use ``approx="sketch"``).
"""
import math
from typing import Any, Callable, Dict, Hashable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.core.metric import Metric, State
from metrics_tpu.observability.counters import (
    COUNTERS as _COUNTERS,
    record_heavy_hitters,
)
from metrics_tpu.parallel.buffer import PaddedBuffer
from metrics_tpu.parallel.cms import (
    CMSSpec,
    CMSTail,
    CountMinSketch,
    cms_buckets,
    cms_error_bound,
    cms_row_state,
    cms_scatter,
    cms_total,
    make_cms_spec,
    stable_key_hashes,
)
from metrics_tpu.parallel.qsketch import QSketchSpec, QuantileSketch
from metrics_tpu.parallel.sketch import SketchSpec, is_sketch, sketch_init
from metrics_tpu.parallel.slab import (
    SlabSpec,
    make_slab_spec,
    slab_init,
    slab_merge,
    slab_rows_spec,
    slab_scatter,
    slab_take_rows,
)
from metrics_tpu.utils.data import accum_int_dtype
from metrics_tpu.utils.exceptions import TracingUnsupportedError

__all__ = ["HeavyHitters", "SpaceSavingTable"]

# the hot tier's per-slot sample-count slab and the tail tier's sample-count
# sketch: occupancy masks, sum-backed mean division, and the certificate's N
_ROWS_STATE = "hh_rows"
_TAIL_ROWS_STATE = "hh_tail_rows"
_TAIL_SUFFIX = "_tail"

_EMPTY_POLICIES = ("nan", "zero")


class SpaceSavingTable:
    """Host-side Space-Saving key -> slot table over an OPEN key space.

    Maps the estimated-heaviest ``num_slots`` keys onto exact slab rows and
    routes everyone else to the count-min tail. Per hot key it tracks
    ``hot`` (exact samples scattered into the key's slab row since
    admission — always equal to the device rows slab, zero readbacks) and
    ``credit`` (the key's tail-count estimate at admission — Space-Saving's
    carried overestimate; that mass physically STAYS in the tail, so credit
    is bookkeeping, never double-counted). The Space-Saving count of a hot
    key is ``hot + credit``; a non-resident key whose estimate exceeds the
    minimum hot count takes that key's slot, and the demoted key's exact
    ``hot`` mass is folded back into the tail (the caller folds the device
    rows; the table mirrors the counts).

    The table also keeps a HOST MIRROR of the tail's sample-count sketch
    (same buckets, same increments as the device ``hh_tail_rows`` state):
    promotion decisions and gauges read it with zero device readbacks. The
    mirror is process-local advisory state — the device CMS remains the
    synced state of record — and it rides checkpoints so a restored table
    resumes with the same promotion behavior.

    Resolution is eager host work by construction (data-dependent key
    management jit cannot express); the scatters that CONSUME the resolved
    slot ids and buckets stay jittable.
    """

    def __init__(self, num_slots: int, depth: int, width: int, seed: int):
        if not isinstance(num_slots, int) or num_slots < 1:
            raise ValueError(f"`num_slots` must be a positive int, got {num_slots!r}")
        self.num_slots = num_slots
        self.depth, self.width, self.seed = depth, width, seed
        self._map: Dict[Hashable, int] = {}
        self._free: List[int] = list(range(num_slots - 1, -1, -1))  # pop() ascends
        self._hot: Dict[Hashable, int] = {}
        self._credit: Dict[Hashable, int] = {}
        self._residue: Dict[Hashable, bool] = {}
        self._mirror = np.zeros((depth, width), dtype=np.int64)
        self.promotions = 0
        self.demotions = 0

    def __len__(self) -> int:
        return len(self._map)

    def __contains__(self, key: Hashable) -> bool:
        return key in self._map

    def keys(self) -> Tuple[Hashable, ...]:
        """Current hot keys (insertion order — ranking is by count, not order)."""
        return tuple(self._map)

    def slot_of(self, key: Hashable) -> int:
        if key not in self._map:
            raise KeyError(
                f"key {key!r} is not hot-resident; {len(self._map)}/{self.num_slots}"
                " slots occupied (tail keys read through tail_estimate)"
            )
        return self._map[key]

    def count_of(self, key: Hashable) -> int:
        """The Space-Saving count: exact hot samples + admission credit."""
        return self._hot[key] + self._credit[key]

    def hot_samples_of(self, key: Hashable) -> int:
        return self._hot[key]

    def is_exact(self, key: Hashable) -> bool:
        """Whether the key's slab row holds its WHOLE history: admitted with
        zero estimated tail mass and never demoted since."""
        return not self._residue[key]

    def buckets_for(self, keys: Sequence[Hashable]) -> np.ndarray:
        """``(N, depth)`` tail buckets for a batch of keys (the seeded
        multiply-shift family over ``stable_key_hash``)."""
        return cms_buckets(stable_key_hashes(keys), self.depth, self.width, self.seed)

    def tail_estimate(self, key: Hashable) -> int:
        """Mirror count-min read: certified overcount of the key's tail mass."""
        buckets = self.buckets_for([key])[0]
        return int(self._mirror[np.arange(self.depth), buckets].min())

    def tail_mass(self) -> int:
        """Total tail samples (every insert lands in every row — row 0's sum)."""
        return int(self._mirror[0].sum())

    def resolve(self, keys: Sequence[Hashable]) -> Tuple[np.ndarray, List[Tuple[Hashable, int]]]:
        """Route one batch: ``(slot_ids int32 (N,), demoted)``.

        ``slot_ids[i]`` is the sample's hot slot, or ``-1`` for the tail.
        ``demoted`` lists ``(key, slot)`` pairs whose slab rows the caller
        must FOLD into the tail (``HeavyHitters`` does, before resetting the
        slots and scattering the batch). Decisions are per DISTINCT key in
        first-appearance order, and a key already routed (or admitted) this
        batch is never a demotion victim — the fold always reads pre-batch
        rows, so no same-batch sample can be split across tiers.
        """
        distinct: Dict[Hashable, int] = {}
        for key in keys:
            distinct[key] = distinct.get(key, 0) + 1

        decisions: Dict[Hashable, int] = {}
        touched: set = set()
        demoted: List[Tuple[Hashable, int]] = []
        for key, cnt in distinct.items():
            if key in self._map:
                slot = self._map[key]
                touched.add(key)
            elif self._free:
                slot = self._free.pop()
                self._admit(key, slot)
                touched.add(key)
            else:
                est = self.tail_estimate(key) + cnt
                victim, victim_count = None, None
                for k in self._map:
                    if k in touched:
                        continue
                    c = self._hot[k] + self._credit[k]
                    if victim_count is None or c < victim_count:
                        victim, victim_count = k, c
                if victim is not None and est > victim_count:
                    slot = self._demote(victim)
                    demoted.append((victim, slot))
                    self._admit(key, slot)
                    touched.add(key)
                else:
                    slot = -1  # tail-routed: constant memory, certified read
            decisions[key] = slot

        slot_ids = np.empty(len(keys), dtype=np.int32)
        for i, key in enumerate(keys):
            slot_ids[i] = decisions[key]
        for key, cnt in distinct.items():
            if decisions[key] >= 0:
                self._hot[key] += cnt
            else:
                buckets = self.buckets_for([key])[0]
                self._mirror[np.arange(self.depth), buckets] += cnt
        return slot_ids, demoted

    def _admit(self, key: Hashable, slot: int) -> None:
        credit = self.tail_estimate(key)
        self._map[key] = slot
        self._hot[key] = 0
        self._credit[key] = credit
        # nonzero credit = the key has tail residue: its pre-promotion mass
        # stays in the tail, so the slab row is exact-since-promotion only
        self._residue[key] = credit > 0
        self.promotions += 1

    def _demote(self, key: Hashable) -> int:
        slot = self._map.pop(key)
        # the key's exact hot mass returns to the tail (the caller folds the
        # device rows; this mirrors the sample counts) — no mass destroyed
        buckets = self.buckets_for([key])[0]
        self._mirror[np.arange(self.depth), buckets] += self._hot.pop(key)
        self._credit.pop(key)
        self._residue.pop(key)
        self.demotions += 1
        return slot

    def state(self) -> dict:
        """Checkpointable view (keys + per-key bookkeeping + the mirror)."""
        keys = list(self._map)
        return {
            "keys": keys,
            "slots": np.asarray([self._map[k] for k in keys], dtype=np.int64),
            "hot": np.asarray([self._hot[k] for k in keys], dtype=np.int64),
            "credit": np.asarray([self._credit[k] for k in keys], dtype=np.int64),
            "residue": np.asarray([self._residue[k] for k in keys], dtype=np.bool_),
            "mirror": self._mirror.copy(),
            "promotions": np.asarray(self.promotions, dtype=np.int64),
            "demotions": np.asarray(self.demotions, dtype=np.int64),
        }

    def load_state(self, state: dict) -> None:
        keys = list(state["keys"])
        slots = np.asarray(state["slots"])
        self._map = {k: int(s) for k, s in zip(keys, slots)}
        self._hot = {k: int(v) for k, v in zip(keys, np.asarray(state["hot"]))}
        self._credit = {k: int(v) for k, v in zip(keys, np.asarray(state["credit"]))}
        self._residue = {k: bool(v) for k, v in zip(keys, np.asarray(state["residue"]))}
        used = set(self._map.values())
        self._free = [s for s in range(self.num_slots - 1, -1, -1) if s not in used]
        self._mirror = np.asarray(state["mirror"], dtype=np.int64).copy()
        self.promotions = int(state["promotions"])
        self.demotions = int(state["demotions"])

    def reset(self) -> None:
        """Forget every key and the mirror (the epoch-reset path). Lifetime
        promotion/demotion counts are process gauges and survive, like the
        LRU table's eviction count."""
        self._map.clear()
        self._hot.clear()
        self._credit.clear()
        self._residue.clear()
        self._free = list(range(self.num_slots - 1, -1, -1))
        self._mirror[:] = 0


class HeavyHitters(Metric):
    r"""Two-tier open-world fan-out of ``metric``: exact top-K slab rows
    over a certified count-min tail.

    Args:
        metric: the inner metric. Its states become ``(K, *shape)`` hot
            slabs PLUS ``(depth, width, *shape)`` count-min tails; its
            ``update``/``compute`` are reused as the per-sample delta and
            the per-slot finisher — the instance itself never accumulates.
            States must be ``sum``/``mean`` arrays or sketch states with
            non-negative per-sample deltas (the tail's certified-overcount
            contract); ``min``/``max`` states are rejected (use ``Keyed``)
            and cat/buffer states are rejected (use ``approx="sketch"``).
        num_hot_slots: K, the exact hot rows.
        tail: the count-min grid — a :class:`~metrics_tpu.parallel.cms.
            CMSTail`, a ``(depth, width)`` pair, or a bare width int.
        empty: what reads report when nothing is resident — ``"nan"``
            (default; non-float results fall back to 0) or ``"zero"``.

    ``update(*data, key=keys)`` takes one hashable key per sample (str /
    bytes / int — the ``stable_key_hash`` canonical types). ``compute()``
    returns the hot tier's ``(K,)`` values; ``compute(key=k)`` reads one
    key from whichever tier holds it (hot: exact slab row; tail: certified
    overcount estimate — see :meth:`tail_estimate` for the certificate);
    :meth:`compute_heavy_hitters` returns the current top-K with their
    guarantee flags. Sync rides the base machinery: both tiers are
    sum-reduced leaves, so the wrapper syncs through the same coalesced
    psum buckets as the unkeyed metric — the staged collective count is
    identical at ANY key-space size, and no key ever loses mass (demotion
    folds, never destroys).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import Accuracy
        >>> hh = HeavyHitters(Accuracy(), num_hot_slots=2, tail=(4, 64))
        >>> preds = jnp.array([0.9, 0.8, 0.3, 0.1])
        >>> target = jnp.array([1, 0, 0, 0])
        >>> hh.update(preds, target, key=["a", "b", "b", "a"])
        >>> [r["key"] for r in hh.compute_heavy_hitters()]
        ['a', 'b']
    """

    def __init__(
        self,
        metric: Metric,
        num_hot_slots: int,
        tail: Any = CMSTail(),
        empty: str = "nan",
        compute_on_step: Optional[bool] = None,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ):
        if not isinstance(metric, Metric):
            raise ValueError(f"`metric` must be a Metric, got {type(metric).__name__}")
        if empty not in _EMPTY_POLICIES:
            raise ValueError(f"`empty` must be one of {_EMPTY_POLICIES}, got {empty!r}")
        super().__init__(
            compute_on_step=metric.compute_on_step if compute_on_step is None else compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
            # key resolution is host-side by construction: the fused jitted
            # step can never trace the space-saving table
            jit=False,
        )
        self.metric = metric
        self.num_hot_slots = int(num_hot_slots)
        rows_spec = make_cms_spec(tail, (), np.dtype(accum_int_dtype()))
        self.tail = CMSTail(rows_spec.depth, rows_spec.width, rows_spec.seed)
        self.empty = empty
        self._metric_label = f"HeavyHitters({type(metric).__name__})"

        if not metric._defaults:
            raise ValueError("the inner metric declares no states; nothing to key")
        reserved = {_ROWS_STATE, _TAIL_ROWS_STATE}
        reserved |= {name + _TAIL_SUFFIX for name in metric._defaults}
        if reserved & set(metric._defaults):
            raise ValueError(
                f"the inner metric's state names collide with the wrapper's"
                f" ({sorted(reserved & set(metric._defaults))})"
            )
        self._slab_reduce: Dict[str, str] = {}
        for name, spec in metric._defaults.items():
            slab = self._slab_spec_for(name, spec, metric._reductions[name])
            self._slab_reduce[name] = slab.reduce
            self.add_state(name, default=slab, dist_reduce_fx="sum", persistent=True)
            self.add_state(
                name + _TAIL_SUFFIX,
                default=CMSSpec(self.tail.depth, self.tail.width, slab.item_shape,
                                slab.dtype, self.tail.seed),
                dist_reduce_fx="sum", persistent=True,
            )
        self.add_state(_ROWS_STATE, default=slab_rows_spec(self.num_hot_slots),
                       dist_reduce_fx="sum", persistent=True)
        self.add_state(_TAIL_ROWS_STATE, default=rows_spec, dist_reduce_fx="sum",
                       persistent=True)
        self._table = SpaceSavingTable(
            self.num_hot_slots, self.tail.depth, self.tail.width, self.tail.seed
        )

    def _slab_spec_for(self, name: str, spec: Any, fx: Any) -> SlabSpec:
        """The hot-tier ``SlabSpec`` one inner state maps onto, or a loud
        rejection. Narrower than ``Keyed``: the tail's certified-overcount
        read needs non-negative additive deltas, so only sum/mean/sketch."""
        if isinstance(spec, (SketchSpec, QSketchSpec)):
            # quantile sketches qualify for the tail too: their deltas are
            # non-negative bucket counts, so the CMS overcount certificate
            # holds per cell (per-key tail quantiles stay an overcount-
            # bounded histogram read)
            kind = "qsketch" if isinstance(spec, QSketchSpec) else spec.kind
            return make_slab_spec(self.num_hot_slots, np.zeros(spec.shape, np.dtype(spec.dtype)),
                                  "sum", kind=kind)
        if isinstance(spec, (list, PaddedBuffer)) or fx == "cat" or fx is None:
            raise ValueError(
                f"state {name!r} of {type(self.metric).__name__} is a cat/list/buffer"
                " state with no slab/tail form; HeavyHitters supports fixed-shape"
                " sum/mean states and sketch states (curve/rank metrics: construct"
                " the inner metric with approx='sketch')"
            )
        if isinstance(spec, (SlabSpec, CMSSpec)) or not isinstance(spec, np.ndarray):
            raise ValueError(
                f"state {name!r} has an unsupported default kind for HeavyHitters:"
                f" {type(spec).__name__}"
            )
        if not (isinstance(fx, str) and fx in ("sum", "mean")):
            raise ValueError(
                f"state {name!r} uses dist_reduce_fx={fx!r}; the count-min tail"
                " certifies overcounts only for additive non-negative states, so"
                " HeavyHitters supports 'sum'/'mean' array states and sketch states"
                " (min/max segment states: use Keyed, whose slots are exact)"
            )
        canonical = jax.dtypes.canonicalize_dtype(spec.dtype)
        if canonical != spec.dtype:
            spec = spec.astype(canonical)
        return make_slab_spec(self.num_hot_slots, spec, fx)

    # ---------------------------------------------------------------- update
    def update(self, *args: Any, key: Any = None, **kwargs: Any) -> None:
        """Route one batch across the tiers.

        ``key`` (required, keyword-only) is one hashable segment key per
        sample (str/bytes/int — the ``stable_key_hash`` canonical types);
        all positional/keyword data arguments must share the leading sample
        axis with it. Hot keys scatter into their exact slab rows, tail keys
        fold into the count-min tail, and a tail key whose estimated count
        overtakes the coldest hot key's is promoted in place (the demoted
        key's rows fold into the tail first — mass is conserved).
        """
        if key is None:
            raise ValueError("HeavyHitters.update requires `key=` (one key per sample)")
        if self._under_trace():
            raise TracingUnsupportedError(
                "HeavyHitters resolves keys through a host-side space-saving table"
                " and cannot run under jit tracing; drive it eagerly — every"
                " scatter consuming the resolved routing is still one XLA op."
            )
        keys = (
            [k.item() for k in np.asarray(key).reshape(-1)]
            if isinstance(key, (np.ndarray, jnp.ndarray, Array))
            else list(key)
        )
        data = (*args, *kwargs.values())
        if not data:
            raise ValueError("HeavyHitters.update needs at least one data argument")
        if not keys:
            return

        slot_ids_np, demoted = self._table.resolve(keys)
        if demoted:
            self._fold_demoted(demoted)
        slot_ids = jnp.asarray(slot_ids_np)
        # per-sample tail buckets; hot samples get the out-of-range sentinel
        # (width) so the tail scatter DROPS them — mirror of the hot scatter
        # dropping the tail samples' slot -1
        buckets_np = self._table.buckets_for(keys)
        buckets = jnp.asarray(
            np.where(slot_ids_np[:, None] >= 0, self.tail.width, buckets_np)
        )

        kw_keys = tuple(kwargs)
        n_args = len(args)

        def one(*sample):
            batch = tuple(a[None] for a in sample)  # per-sample size-1 batches
            return self.metric.update_state(
                self.metric.init_state(), *batch[:n_args], **dict(zip(kw_keys, batch[n_args:]))
            )

        deltas = jax.vmap(one)(*data)  # {name: (N, *shape) / sketch with (N, ...) counts}
        for name in self.metric._defaults:
            reduce = self._slab_reduce[name]
            current = getattr(self, name)
            leaf = deltas[name]
            payload = leaf.counts if is_sketch(leaf) else leaf
            scattered = slab_scatter("sum", payload, slot_ids, self.num_hot_slots)
            if is_sketch(current):
                setattr(self, name, type(current)(current.counts + scattered))
            else:
                setattr(self, name, slab_merge(reduce, current, scattered))
            tail = getattr(self, name + _TAIL_SUFFIX)
            setattr(self, name + _TAIL_SUFFIX,
                    CountMinSketch(cms_scatter(tail.counts, buckets, payload)))
        rows = getattr(self, _ROWS_STATE)
        ones = jnp.ones(slot_ids.shape, dtype=rows.dtype)
        setattr(self, _ROWS_STATE,
                rows + slab_scatter("sum", ones, slot_ids, self.num_hot_slots))
        tail_rows = getattr(self, _TAIL_ROWS_STATE)
        setattr(self, _TAIL_ROWS_STATE,
                CountMinSketch(cms_scatter(tail_rows.counts, buckets, ones)))
        self._note_hh_gauges()

    def _fold_demoted(self, demoted: List[Tuple[Hashable, int]]) -> None:
        """Fold demoted keys' exact slab rows into the tail, then reset their
        slots — the mass-conserving eviction (``Keyed``'s LRU zeroes here)."""
        keys = [k for k, _ in demoted]
        slots = [s for _, s in demoted]
        buckets = jnp.asarray(self._table.buckets_for(keys))  # (M, depth)
        for name in self.metric._defaults:
            value = getattr(self, name)
            payload = slab_take_rows(value, slots)  # (M, *item), pre-batch rows
            tail = getattr(self, name + _TAIL_SUFFIX)
            setattr(self, name + _TAIL_SUFFIX,
                    CountMinSketch(cms_scatter(tail.counts, buckets, payload)))
        rows = getattr(self, _ROWS_STATE)
        tail_rows = getattr(self, _TAIL_ROWS_STATE)
        setattr(self, _TAIL_ROWS_STATE, CountMinSketch(
            cms_scatter(tail_rows.counts, buckets, slab_take_rows(rows, slots))
        ))
        # reset the recycled rows (hot states + the rows slab only; the tail
        # states just RECEIVED the folded mass)
        idx = jnp.asarray(np.asarray(slots, dtype=np.int32))
        for name in (*self.metric._defaults, _ROWS_STATE):
            value = getattr(self, name)
            fresh = slab_init(self._defaults[name])
            if is_sketch(value):
                setattr(self, name, type(value)(value.counts.at[idx].set(fresh.counts[idx])))
            else:
                setattr(self, name, value.at[idx].set(fresh[idx]))

    def _note_hh_gauges(self) -> None:
        """Feed the heavy-hitter gauges (zero readbacks: occupancy and
        promotion counts are table bookkeeping, tail mass and the certificate
        come from the host mirror)."""
        if not _COUNTERS.enabled:
            return
        mass = self._table.tail_mass()
        record_heavy_hitters(
            self._metric_label,
            hot_slots=self.num_hot_slots,
            hot_occupied=len(self._table),
            promotions=self._table.promotions,
            demotions=self._table.demotions,
            tail_mass=mass,
            tail_bound=math.e / self.tail.width * mass,
        )

    # --------------------------------------------------------------- compute
    def compute(self) -> Any:
        """The hot tier's K per-segment values: the inner finisher vmapped
        over the hot slab (empty slots per the ``empty`` policy). The public
        wrapped form also accepts ``compute(key=k)`` for a single-key read
        from whichever tier holds the key."""
        state = self._current_state()
        rows = state[_ROWS_STATE]
        hot = {name: state[name] for name in self.metric._defaults}
        return self._finish_hot(hot, rows)

    def _finish_hot(self, state: State, rows: Array) -> Any:
        inner_state: State = {}
        for name, value in state.items():
            if self._slab_reduce[name] == "mean":
                denom = jnp.maximum(rows, 1).astype(value.dtype).reshape(
                    (self.num_hot_slots,) + (1,) * (value.ndim - 1)
                )
                value = value / denom
            inner_state[name] = value
        results = jax.vmap(self.metric.compute_from_state)(inner_state)
        occupied = rows > 0

        def mask(r: Array) -> Array:
            r = jnp.asarray(r)
            occ = occupied.reshape((self.num_hot_slots,) + (1,) * (r.ndim - 1))
            if self.empty == "nan" and jnp.issubdtype(r.dtype, jnp.inexact):
                return jnp.where(occ, r, jnp.nan)
            return jnp.where(occ, r, jnp.zeros((), dtype=r.dtype))

        return jax.tree_util.tree_map(mask, results)

    def _wrap_compute(self, compute: Callable) -> Callable:
        """The base wrapper (sync + cache) plus the ``key=`` read form: hot
        keys slice the cached (K, ...) results, tail keys read the certified
        count-min estimate (local state — the tail read is the serving-time
        point query, not an epoch sync)."""
        wrapped = super()._wrap_compute(compute)

        def with_key(key: Any = None) -> Any:
            out = wrapped()
            if key is None:
                return out
            if key in self._table:
                slot = self._table.slot_of(key)
                return jax.tree_util.tree_map(lambda v: v[slot], out)
            return self.tail_estimate(key)["value"]

        return with_key

    def tail_estimate(self, key: Hashable) -> Dict[str, Any]:
        """Certified tail read of one key: ``{"value", "count", "bound",
        "exact": False}``.

        ``count`` is the count-min sample estimate (always >= the true
        count); every state leaf is read from the SAME argmin row so the
        estimate is an internally consistent state; ``bound`` is the
        ``(e/width) * N`` overcount certificate (samples, probability
        ``1 - e^-depth`` — :func:`~metrics_tpu.parallel.cms.
        cms_error_bound`). Reads local state by design, like
        ``Windowed.compute_window``: point queries must not force a sync.
        """
        buckets = jnp.asarray(self._table.buckets_for([key])[0])  # (depth,)
        tail_rows = getattr(self, _TAIL_ROWS_STATE).counts
        per_row = cms_row_state(tail_rows, buckets)  # (depth,)
        row = int(jnp.argmin(per_row))
        count = int(per_row[row])
        bound = float(cms_error_bound(tail_rows))
        inner_state: State = {}
        for name, spec in self.metric._defaults.items():
            tail = getattr(self, name + _TAIL_SUFFIX).counts
            leaf = cms_row_state(tail, buckets)[row]
            if self._slab_reduce[name] == "mean":
                leaf = leaf / jnp.maximum(
                    jnp.asarray(count, dtype=leaf.dtype), jnp.ones((), dtype=leaf.dtype)
                )
            if isinstance(spec, SketchSpec):
                leaf = type(sketch_init(spec))(leaf)
            elif isinstance(spec, QSketchSpec):
                leaf = QuantileSketch(leaf)
            inner_state[name] = leaf
        result = self.metric.compute_from_state(inner_state)

        def mask(r: Array) -> Array:
            r = jnp.asarray(r)
            if count > 0:
                return r
            if self.empty == "nan" and jnp.issubdtype(r.dtype, jnp.inexact):
                return jnp.full_like(r, jnp.nan)
            return jnp.zeros_like(r)

        value = jax.tree_util.tree_map(mask, result)
        return {"value": value, "count": count, "bound": bound, "exact": False}

    def compute_heavy_hitters(self, k: Optional[int] = None) -> List[Dict[str, Any]]:
        """The current top-K, heaviest first: ``[{"key", "slot", "count",
        "samples", "exact", "value"}, ...]``.

        ``count`` is the Space-Saving count (exact hot samples + the
        admission credit carried from the tail); ``samples`` the exact hot
        samples; ``exact`` the guarantee flag — True iff the key's slab row
        holds its whole history (admitted with zero tail estimate, never
        demoted since), else the value is exact-since-promotion with the
        remainder certified in the tail. ``value`` slices the ordinary
        (synced, cached) ``compute()`` results.
        """
        values = self.compute()
        records = []
        for key in self._table.keys():
            slot = self._table.slot_of(key)
            records.append({
                "key": key,
                "slot": slot,
                "count": self._table.count_of(key),
                "samples": self._table.hot_samples_of(key),
                "exact": self._table.is_exact(key),
                "value": jax.tree_util.tree_map(lambda v: v[slot], values),
            })
        records.sort(key=lambda r: (-r["count"], str(r["key"])))
        return records[:k] if k is not None else records

    def tail_mass(self) -> int:
        """Total samples resident in the tail (device state of record)."""
        return int(cms_total(getattr(self, _TAIL_ROWS_STATE).counts))

    def tail_overcount_bound(self) -> float:
        """The tail's current ``(e/width) * N`` certificate, in samples."""
        return float(cms_error_bound(getattr(self, _TAIL_ROWS_STATE).counts))

    # ---------------------------------------------------- sparse delta sync
    def sparse_plane(self, axis_name: Any, mesh: Any = None, *,
                     capacity: int = 64, **kwargs: Any) -> Any:
        """A :class:`~metrics_tpu.parallel.sparse.SparseSyncPlane` over the
        two-tier state: the hot ``(K, *item)`` slabs (plus ``hh_rows``) ride
        the sparse row exchange, while the constant-size count-min tails
        (``*_tail`` and ``hh_tail_rows``) are DENSE residuals whose int32
        deltas ride the bitmap psum payload — per-round bytes stay
        proportional to the touched hot rows plus the fixed tail footprint,
        with zero extra collectives for the tails. All HH states are
        sum-reduced, so the whole split is delta-exact. Build the plane
        while the metric is RESET (see the plane's docstring).
        """
        from metrics_tpu.parallel.sparse import SparseSyncPlane

        state = self._current_state()
        rows = tuple(
            n for n in state
            if not (n.endswith(_TAIL_SUFFIX) or n == _TAIL_ROWS_STATE)
        )
        return SparseSyncPlane(
            state, dict(self._reductions), self.num_hot_slots, axis_name,
            mesh, capacity=capacity, row_leaves=rows, **kwargs,
        )

    # ------------------------------------------------------------- lifecycle
    def reset(self) -> None:
        super().reset()
        self._table.reset()

    _TABLE_KEY = "_hh_table"

    def state_dict(self, destination: Optional[dict] = None, prefix: str = "") -> dict:
        """Slab and tail states persist through the base path (plain arrays /
        counts sketches); the space-saving table — key map, counts, credit,
        residue flags, the host mirror — rides along so a restored metric
        resolves the same keys to the same rows with the same promotion
        behavior."""
        destination = super().state_dict(destination, prefix=prefix)
        destination[prefix + self._TABLE_KEY] = self._table.state()
        return destination

    def load_state_dict(self, state_dict: dict, prefix: str = "") -> None:
        super().load_state_dict(state_dict, prefix=prefix)
        key = prefix + self._TABLE_KEY
        if key in state_dict:
            self._table.load_state(state_dict[key])

    def __repr__(self) -> str:
        return (
            f"HeavyHitters({self.metric!r}, num_hot_slots={self.num_hot_slots},"
            f" tail={self.tail})"
        )
