"""Metric wrappers: BootStrapper, ClasswiseWrapper, MinMaxMetric,
MetricTracker, MultioutputWrapper, Running.

Extension family beyond the reference snapshot (later torchmetrics ships
these under ``wrappers/``)."""
from metrics_tpu.wrappers.bootstrapper import BootStrapper
from metrics_tpu.wrappers.classwise import ClasswiseWrapper
from metrics_tpu.wrappers.minmax import MinMaxMetric
from metrics_tpu.wrappers.multioutput import MultioutputWrapper
from metrics_tpu.wrappers.running import Running
from metrics_tpu.wrappers.tracker import MetricTracker

__all__ = ["BootStrapper", "ClasswiseWrapper", "MinMaxMetric", "MetricTracker", "MultioutputWrapper", "Running"]
