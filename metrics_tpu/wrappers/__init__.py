"""Metric wrappers: BootStrapper, ClasswiseWrapper, Keyed, MinMaxMetric,
MetricTracker, MultioutputWrapper, Running, Windowed.

Extension family beyond the reference snapshot (later torchmetrics ships
these under ``wrappers/``). ``Keyed`` is the multi-tenant slab wrapper: one
metric x thousands of segments as a leading state axis, where the cloning
wrappers (Classwise/Multioutput) fan out whole modules."""
from metrics_tpu.wrappers.bootstrapper import BootStrapper
from metrics_tpu.wrappers.classwise import ClasswiseWrapper
from metrics_tpu.wrappers.heavy_hitters import HeavyHitters, SpaceSavingTable
from metrics_tpu.wrappers.keyed import Keyed
from metrics_tpu.wrappers.minmax import MinMaxMetric
from metrics_tpu.wrappers.multioutput import MultioutputWrapper
from metrics_tpu.wrappers.running import Running
from metrics_tpu.wrappers.tracker import MetricTracker
from metrics_tpu.wrappers.windowed import Windowed

__all__ = [
    "BootStrapper", "ClasswiseWrapper", "HeavyHitters", "Keyed", "MinMaxMetric",
    "MetricTracker", "MultioutputWrapper", "Running", "SpaceSavingTable", "Windowed",
]
