"""ClasswiseWrapper. Extension beyond the reference snapshot (later
torchmetrics ``wrappers/classwise.py``)."""
from typing import Any, Dict, List, Optional

from jax import Array

from metrics_tpu.core.metric import Metric


class ClasswiseWrapper(Metric):
    r"""Unpack a per-class metric vector into a flat, labelled dict.

    Wraps a metric whose ``compute()`` returns a ``(C,)`` vector (e.g.
    ``Precision(average=None)``) and returns
    ``{f"{prefix}{label}": value}`` instead — the loggable form.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import Precision
        >>> m = ClasswiseWrapper(Precision(num_classes=3, average=None), labels=["cat", "dog", "fox"])
        >>> out = m(jnp.array([0, 1, 2, 1]), jnp.array([0, 1, 1, 1]))
        >>> sorted(out)
        ['precision_cat', 'precision_dog', 'precision_fox']
    """

    def __init__(
        self,
        base_metric: Metric,
        labels: Optional[List[str]] = None,
        prefix: Optional[str] = None,
    ):
        super().__init__(compute_on_step=base_metric.compute_on_step)
        if not isinstance(base_metric, Metric):
            raise ValueError(f"`base_metric` must be a Metric, got {type(base_metric).__name__}")
        if labels is not None and not (isinstance(labels, list) and all(isinstance(x, str) for x in labels)):
            raise ValueError(f"`labels` must be a list of strings or None, got {labels!r}")
        self.base_metric = base_metric
        self.labels = labels
        self._prefix = prefix if prefix is not None else type(base_metric).__name__.lower() + "_"

    def _to_dict(self, values: Array) -> Dict[str, Array]:
        if values.ndim != 1:
            raise ValueError(
                f"the wrapped metric must compute a 1-D per-class vector, got shape {values.shape}"
            )
        labels = self.labels if self.labels is not None else [str(i) for i in range(values.shape[0])]
        if len(labels) != values.shape[0]:
            raise ValueError(f"{len(labels)} labels for {values.shape[0]} classes")
        return {f"{self._prefix}{lab}": values[i] for i, lab in enumerate(labels)}

    def update(self, *args: Any, **kwargs: Any) -> None:
        self.base_metric.update(*args, **kwargs)

    def forward(self, *args: Any, **kwargs: Any) -> Optional[Dict[str, Array]]:
        value = self.base_metric.forward(*args, **kwargs)
        self._computed = None
        if value is None:
            return None
        return self._to_dict(value)

    def compute(self) -> Dict[str, Array]:
        return self._to_dict(self.base_metric.compute())

    def reset(self) -> None:
        super().reset()
        self.base_metric.reset()
