"""Windowed: the serving runtime's window plane — "AUROC over the last 5
minutes" as a slot rotation, not a copy.

``Windowed(metric, window_s, num_windows)`` turns any per-sample-decomposable
metric into a tumbling-window ring: every registered state of the inner
metric becomes a ``(W, *shape)`` slab (one row per window slot, reusing
``parallel/slab.py`` with WINDOW-INDEX slots instead of segment slots), and
``update(..., event_time=)`` routes each sample to its window by timestamp
through an advancing watermark (``core/streaming.route_events``):

- in-window events scatter normally into the head slot;
- late-but-within-``allowed_lateness_s`` events route to their still-open
  prior slot;
- too-late events are DROPPED AND COUNTED (slot ``-1`` -> the slab scatter's
  XLA out-of-bounds drop + ``slab_dropped_samples``), never misrouted.

A window roll is a SLOT ROTATION: when the watermark opens window ``w``, the
ring slot ``w % W`` (which held the expired window ``w - W``) is reset in
place — no state copies, no shape changes — and sync still rides the
existing coalesced ``psum``/``pmin``/``pmax`` buckets of
``coalesced_sync_state`` with zero new collective kinds: the staged
collective count is identical to the unwindowed metric's (``bench.py
--check-service`` pins it).

``compute()`` merges all resident slots — the sliding view over the last
``W x window_s`` seconds; ``compute_window(w)`` reads one resident window
(the per-window publish the serving loop emits as windows close).

With ``decay_half_life_s=`` instead of ``window_s=``, the wrapper is an
EXPONENTIAL TIME-DECAY accumulator for ``sum``/``mean``-kind states: one
slot, where the accumulator scales by ``0.5 ** (dt / half_life)`` as the
watermark advances and each sample's delta is weighted by its age —
``compute()`` is then the exponentially-weighted value (for sum-backed
means: the EW mean). Integer sum states are promoted to float32 slabs so
the decay is representable.

Like ``Keyed(lru=True)``, the routing decision is host-side by construction
(data-dependent watermark bookkeeping jit cannot express), so ``Windowed``
runs the eager update path and raises ``TracingUnsupportedError`` under
tracing; the scatter that consumes the resolved slot ids is still one XLA
``segment_sum`` per state. The contract on the inner metric is the ``Keyed``
contract: fixed-shape sum/mean/min/max states or sketch states, per-sample-
decomposable update (cat/buffer states have no slab form — use
``approx="sketch"``).
"""
import itertools
import math
import time
from copy import deepcopy
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.core.metric import Metric, State
from metrics_tpu.core.streaming import (
    WatermarkAgreement,
    WindowSpec,
    decay_scale,
    route_events,
    window_index,
)
from metrics_tpu.observability.counters import record_slab_dropped
from metrics_tpu.observability.lifecycle import LEDGER as _LEDGER
from metrics_tpu.wrappers.keyed import Keyed
from metrics_tpu.parallel.buffer import PaddedBuffer
from metrics_tpu.parallel.cms import CMSSpec
from metrics_tpu.parallel.qsketch import QSketchSpec
from metrics_tpu.parallel.sketch import SketchSpec, is_sketch
from metrics_tpu.parallel.slab import (
    PARTIAL_SCHEMA_VERSION,
    SLAB_SKETCH_KINDS,
    check_partial_version,
    SlabProgramCache,
    SlabSpec,
    bucket_size,
    dropped_slot_count,
    make_slab_spec,
    pad_samples,
    pad_slot_ids,
    shared_ingest_program,
    slab_init,
    slab_merge,
    slab_rows_spec,
    slab_scatter,
    slab_sync_reduce,
)
from metrics_tpu.utils.exceptions import TracingUnsupportedError

# the per-slot sample-count slab every Windowed wrapper carries: occupancy
# masks (empty-slot policy), the sum-backed mean division, and — in decay
# mode — the exponentially-decayed effective sample count
_ROWS_STATE = "windowed_rows"

_EMPTY_POLICIES = ("nan", "zero")


class Windowed(Metric):
    r"""Tumbling-window (or time-decay) view of ``metric`` over event time.

    Args:
        metric: the inner metric. Its states become ``(W, *shape)`` window
            slabs; its ``update``/``compute`` are reused as the per-sample
            delta and the per-window finisher — the instance itself never
            accumulates.
        window_s: tumbling-window length in seconds (event-time). Mutually
            exclusive with ``decay_half_life_s``.
        num_windows: W, the ring size — how many consecutive windows stay
            resident (``compute()`` spans all of them; a window expires, and
            its slot is recycled, W windows after it opens).
        allowed_lateness_s: how far behind the watermark an event may arrive
            and still be routed to its (still-open) window. Capped at
            ``(W - 1) * window_s`` so a within-lateness slot can never have
            been recycled. Events later than this are dropped and counted
            (``slab_dropped_samples`` + :attr:`dropped_samples`). Default
            0 for the ring, unbounded for decay mode.
        decay_half_life_s: exponential time-decay half-life. The accumulator
            becomes a single decayed slab (``sum``/``mean``-kind inner
            states only); mutually exclusive with ``window_s``.
        empty: what ``compute()`` reports when no samples are resident —
            ``"nan"`` (default; non-float results fall back to 0) or
            ``"zero"``.
        slide_s: SLIDING windows — a new window opens every ``slide_s``
            seconds, each spanning ``window_s`` (must divide it evenly), so
            every event scatters into ``window_s/slide_s`` overlapping ring
            slots. ``compute()`` then returns the newest FULL-span window
            (``head - overlap + 1`` — the trailing ``window_s`` view; the
            head window has only accumulated the newest ``slide_s``
            seconds); per-window reads and publishes are per sliding
            window. Lateness is capped at
            ``num_windows*slide_s - window_s``.
        agreement / rank: join a cross-rank
            :class:`~metrics_tpu.core.streaming.WatermarkAgreement` as
            participant ``rank`` (see :meth:`attach_agreement`) — windows
            then open/close/judge lateness by the AGREED (global-min)
            watermark instead of this rank's local clock.

    ``update(*data, event_time=t)`` takes per-sample event timestamps
    (seconds; an ``(N,)`` array, or a scalar stamping the whole batch).
    The watermark is the max event time seen; it never goes backwards.
    Cross-process sync rides the base machinery unchanged (slab leaves are
    ordinary sum/min/max array or sketch leaves); the watermark itself is
    host metadata — ranks of a distributed stream are expected to observe
    the same event-time clock.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import Accuracy
        >>> acc = Windowed(Accuracy(), window_s=60.0, num_windows=2)
        >>> preds = jnp.array([0.9, 0.2, 0.8])
        >>> target = jnp.array([1, 0, 0])
        >>> acc.update(preds, target, event_time=jnp.array([3.0, 65.0, 70.0]))
        >>> float(acc.compute())  # both windows resident: 2/3 correct
        0.6666666865348816
    """

    def __init__(
        self,
        metric: Metric,
        window_s: Optional[float] = None,
        num_windows: int = 4,
        allowed_lateness_s: Optional[float] = None,
        decay_half_life_s: Optional[float] = None,
        empty: str = "nan",
        compute_on_step: Optional[bool] = None,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
        slide_s: Optional[float] = None,
        agreement: Optional[WatermarkAgreement] = None,
        rank: Optional[Any] = None,
    ):
        if not isinstance(metric, Metric):
            raise ValueError(f"`metric` must be a Metric, got {type(metric).__name__}")
        if (window_s is None) == (decay_half_life_s is None):
            raise ValueError(
                "set exactly one of `window_s` (tumbling ring) or"
                " `decay_half_life_s` (exponential time-decay accumulator)"
            )
        if empty not in _EMPTY_POLICIES:
            raise ValueError(f"`empty` must be one of {_EMPTY_POLICIES}, got {empty!r}")
        super().__init__(
            compute_on_step=metric.compute_on_step if compute_on_step is None else compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
            # event routing is host-side watermark bookkeeping: the fused
            # jitted step can never trace it, so don't build one
            jit=False,
        )
        self.metric = metric
        self.decay = decay_half_life_s is not None
        if self.decay:
            if not (isinstance(decay_half_life_s, (int, float)) and decay_half_life_s > 0):
                raise ValueError(
                    f"`decay_half_life_s` must be a positive number, got {decay_half_life_s!r}"
                )
            if slide_s is not None:
                raise ValueError(
                    "`slide_s` slides the window ring; the decay accumulator has no"
                    " windows — use the windowed ring (window_s=)"
                )
            self.decay_half_life_s = float(decay_half_life_s)
            self.num_windows = 1
            self.allowed_lateness_s = (
                math.inf if allowed_lateness_s is None else float(allowed_lateness_s)
            )
            self._spec = None
        else:
            self.decay_half_life_s = None
            self.num_windows = int(num_windows)
            self.allowed_lateness_s = 0.0 if allowed_lateness_s is None else float(allowed_lateness_s)
            self._spec = WindowSpec(
                float(window_s), self.num_windows, self.allowed_lateness_s,
                None if slide_s is None else float(slide_s),
            ).validate()
        self.window_s = None if self.decay else float(window_s)
        self.slide_s = None if self.decay else (None if slide_s is None else float(slide_s))
        self.empty = empty
        self._metric_label = f"Windowed({type(metric).__name__})"
        # the lifecycle ledger's stamp key: set by the owning MetricService
        # (its label) so per-window stage stamps attribute to the serving
        # loop; None (the default) keeps the ledger out of standalone use
        self.lifecycle_label: Optional[str] = None

        # compiled routed-scatter programs, one per (sample bucket, tree
        # structure): the ingest fast path's retrace guard. Deliberately
        # deep-copies/pickles as empty (programs are pure derived state).
        self._ingest_programs = SlabProgramCache()

        # stream position (host metadata, checkpointed): None until the
        # first event arrives
        self._watermark: Optional[float] = None
        self._head: Optional[int] = None
        self._origin: Optional[int] = None  # oldest window ever accepted into
        self._dropped = 0  # lifetime too-late drops (mirrors slab_dropped_samples)
        self._late = 0  # lifetime accepted-but-late routings

        # the cross-rank agreed clock (attach_agreement): None = local clock
        self._agreement: Optional[WatermarkAgreement] = None
        self._rank: Optional[Any] = None
        self._agreed_seen: Optional[float] = None  # monotone view of agreed()
        if agreement is not None:
            self.attach_agreement(agreement, rank=rank)
        elif rank is not None:
            raise ValueError("`rank` has no meaning without `agreement`")

        if not metric._defaults:
            raise ValueError("the inner metric declares no states; nothing to window")
        if _ROWS_STATE in metric._defaults:
            raise ValueError(f"the inner metric already has a state named {_ROWS_STATE!r}")
        self._slab_reduce: Dict[str, str] = {}
        for name, spec in metric._defaults.items():
            slab = self._slab_spec_for(name, spec, metric._reductions[name])
            self._slab_reduce[name] = slab.reduce
            self.add_state(name, default=slab, dist_reduce_fx=slab_sync_reduce(slab.reduce),
                           persistent=True)
        rows_dtype = np.float32 if self.decay else None  # decayed effective counts
        self.add_state(_ROWS_STATE, default=slab_rows_spec(self.num_windows, dtype=rows_dtype),
                       dist_reduce_fx="sum", persistent=True)

    def _slab_spec_for(self, name: str, spec: Any, fx: Any) -> SlabSpec:
        """The ``SlabSpec`` one inner state maps onto, or a loud rejection."""
        if isinstance(spec, (SketchSpec, QSketchSpec, CMSSpec)):
            if self.decay:
                raise ValueError(
                    f"state {name!r} is a sketch state; integer sketch counts have no"
                    " exponential-decay form — use the windowed ring (window_s=) for"
                    " sketch metrics"
                )
            if isinstance(spec, CMSSpec):
                # windowed count-min: counts grow a leading W axis like every
                # other sketch kind (merge = add), so the constant-memory tail
                # gets per-window form — the inner update must resolve its
                # row buckets host-side (cms_buckets) and feed them as data,
                # since the vmapped per-sample delta path stays jit-pure
                kind = "cms"
            else:
                kind = "qsketch" if isinstance(spec, QSketchSpec) else spec.kind
            return make_slab_spec(self.num_windows, np.zeros(spec.shape, np.dtype(spec.dtype)),
                                  "sum", kind=kind)
        if isinstance(spec, (list, PaddedBuffer)) or fx == "cat" or fx is None:
            raise ValueError(
                f"state {name!r} of {type(self.metric).__name__} is a cat/list/buffer"
                " state with no per-window slab form; Windowed supports fixed-shape"
                " sum/mean/min/max states and sketch states (curve/rank metrics:"
                " construct the inner metric with approx='sketch')"
            )
        if isinstance(spec, SlabSpec):
            # a nested slab — the inner metric is a Keyed wrapper: windows
            # wrap the segment axis, so the state becomes (W, K, *item) and
            # "AUROC over the last 5 minutes, per cohort" is one state.
            # Scatter/merge use the slab's SYNC reduction (sum-backed means
            # stay sums; Keyed's own finisher divides by its rows slab).
            if self.decay:
                raise ValueError(
                    f"state {name!r} is a segment slab; the decay accumulator"
                    " does not nest over Keyed (its sum-backed mean division"
                    " clamps at 1 sample) — use the windowed ring"
                )
            if spec.kind in SLAB_SKETCH_KINDS:
                return make_slab_spec(
                    self.num_windows, np.zeros(spec.row_shape, np.dtype(spec.dtype)),
                    "sum", kind=spec.kind,
                )
            if spec.fill is not None:
                template = np.broadcast_to(
                    spec.fill_template()[None], spec.row_shape
                ).copy()
            else:
                template = np.zeros(spec.row_shape, np.dtype(spec.dtype))
            return make_slab_spec(self.num_windows, template, slab_sync_reduce(spec.reduce))
        if not isinstance(spec, np.ndarray):
            raise ValueError(
                f"state {name!r} has an unsupported default kind for Windowed:"
                f" {type(spec).__name__}"
            )
        if not (isinstance(fx, str) and fx in ("sum", "mean", "min", "max")):
            raise ValueError(
                f"state {name!r} uses dist_reduce_fx={fx!r}; Windowed supports"
                " 'sum'/'mean'/'min'/'max' array states and sketch states"
            )
        # canonicalize wide host templates to the dtype the inner metric
        # actually materializes under jax defaults (float64 numpy zeros ->
        # float32 device state) so the slab matches the unwindowed state
        canonical = jax.dtypes.canonicalize_dtype(spec.dtype)
        if canonical != spec.dtype:
            spec = spec.astype(canonical)
        if self.decay:
            if fx not in ("sum", "mean"):
                raise ValueError(
                    f"state {name!r} uses dist_reduce_fx={fx!r}; the exponential-decay"
                    " accumulator only applies to 'sum'/'mean'-kind states (min/max"
                    " have no decayed form) — use the windowed ring instead"
                )
            if np.issubdtype(spec.dtype, np.integer) or np.issubdtype(spec.dtype, np.bool_):
                # decayed accumulation needs a representable fraction
                spec = spec.astype(np.float32)
        return make_slab_spec(self.num_windows, spec, fx)

    # ------------------------------------------------------- stream position
    @property
    def watermark(self) -> Optional[float]:
        """Max event time observed (``None`` before the first event)."""
        return self._watermark

    @property
    def head_window(self) -> Optional[int]:
        """Index of the newest open window (``None`` before the first event;
        always ``None`` in decay mode, which has no windows)."""
        return None if self.decay else self._head

    @property
    def dropped_samples(self) -> int:
        """Lifetime count of too-late events dropped (never misrouted)."""
        return self._dropped

    @property
    def late_samples(self) -> int:
        """Lifetime count of accepted events routed to a non-head window."""
        return self._late

    def resident_windows(self) -> tuple:
        """Window indices currently resident in the ring, oldest first.
        Starts at the stream origin: windows before the first accepted event
        never existed and are not reported (or publishable)."""
        if self.decay or self._head is None or self._origin is None:
            return ()
        lo = max(self._origin, self._head - self.num_windows + 1)
        return tuple(range(lo, self._head + 1))

    @property
    def window_stride(self) -> Optional[float]:
        """Seconds between consecutive window starts (``slide_s`` for
        sliding windows, ``window_s`` for tumbling; ``None`` in decay
        mode)."""
        return None if self.decay else self._spec.stride

    def window_start(self, window: int) -> float:
        """Event-time start of window ``window`` (``window * stride``)."""
        if self.decay:
            raise ValueError("the decay accumulator has no windows")
        return self._spec.window_start(window)

    # ------------------------------------------------------ the agreed clock
    _rank_ids = itertools.count()

    def attach_agreement(
        self, agreement: WatermarkAgreement, rank: Optional[Any] = None
    ) -> "Windowed":
        """Join a cross-rank :class:`WatermarkAgreement` as participant
        ``rank``.

        From then on every update reports this rank's local running-max
        watermark to the agreement, and routing verdicts — plus window
        closing wherever this metric serves (``MetricService`` /
        ``MetricFleet``) — are judged against the AGREED (global-min)
        watermark instead of the local clock: a skewed rank cannot close a
        window its peers still feed, and "late" means the same thing on
        every rank. Until a first agreement forms (a registered peer has not
        reported yet) the rank routes by its local clock, exactly the
        pre-agreement behavior. Attribute-set convention like
        ``check_finite``/``sync_lag``: callable post-construction, also
        reachable via ``Windowed(..., agreement=, rank=)``.
        """
        if not isinstance(agreement, WatermarkAgreement):
            raise ValueError(
                f"`agreement` must be a WatermarkAgreement, got {type(agreement).__name__}"
            )
        if self.decay:
            raise ValueError(
                "the decay accumulator has no windows to close; watermark"
                " agreement applies to the windowed ring (window_s=)"
            )
        self._agreement = agreement
        self._rank = rank if rank is not None else f"rank{next(Windowed._rank_ids)}"
        agreement.register(self._rank)
        if self._watermark is not None:
            agreement.report(self._rank, self._watermark)
        self._refresh_agreed()
        return self

    @property
    def agreement(self) -> Optional[WatermarkAgreement]:
        return self._agreement

    @property
    def rank(self) -> Optional[Any]:
        """This metric's participant id in the attached agreement."""
        return self._rank

    def _refresh_agreed(self) -> Optional[float]:
        """This rank's monotone view of the agreed watermark (an agreement
        whose membership momentarily dips to ``None`` — a recovering peer
        re-registering — must never regress verdicts already made)."""
        if self._agreement is None:
            return None
        agreed = self._agreement.agreed()
        if agreed is not None and (self._agreed_seen is None or agreed > self._agreed_seen):
            self._agreed_seen = agreed
        return self._agreed_seen

    @property
    def agreed_watermark(self) -> Optional[float]:
        """The agreed (global-min) watermark as this rank last saw it
        (``None`` without an agreement, or before one forms)."""
        return self._refresh_agreed()

    @property
    def close_watermark(self) -> Optional[float]:
        """The clock windows CLOSE by: the agreed watermark when an
        agreement governs this stream (``None`` until it forms — nothing
        closes before the fleet agrees), the local watermark otherwise."""
        if self._agreement is None:
            return self._watermark
        return self._refresh_agreed()

    @property
    def agreement_degraded(self) -> bool:
        """True while the attached agreement is excluding a straggler —
        the stamp publishes carry while the agreed clock is partial."""
        return self._agreement is not None and self._agreement.degraded

    # ---------------------------------------------------------------- update
    def update(
        self, *args: Any, event_time: Any = None, judge_prefix: Any = None, **kwargs: Any
    ) -> None:
        """Route one batch into the window slabs by event time.

        ``event_time`` (required, keyword-only) is one timestamp per sample
        (seconds; scalar = whole batch at one instant). All positional/
        keyword data arguments must share the leading sample axis.

        ``judge_prefix`` (keyword-only, coalesced-ingest plane) is a
        per-event prefix running-max watermark: when several queued batches
        are concatenated into one update, each event must still be judged
        late/dropped against the watermark AS OF ITS OWN batch, not the
        concatenation's final max. The service coalescer builds the prefix
        (running max through the end of each original batch) and passes it
        here; ``route_events`` proves the form bit-exact vs the sequential
        plane. Mutually exclusive with an attached agreement (the agreed
        clock already fixes the judging watermark per round) and with decay
        mode (no late/close verdicts to judge).
        """
        if event_time is None:
            raise ValueError("Windowed.update requires `event_time=` (one timestamp per sample)")
        if judge_prefix is not None and self.decay:
            raise ValueError("judge_prefix has no meaning for the decay accumulator")
        if self._under_trace():
            raise TracingUnsupportedError(
                "Windowed resolves event-time routing host-side (watermark"
                " advance, window roll) and cannot run under jit tracing;"
                " drive it eagerly — the per-state scatter is still one XLA"
                " segment_sum."
            )
        data = (*args, *kwargs.values())
        if not data:
            raise ValueError("Windowed.update needs at least one data argument")
        first = data[0]
        n = int(first.shape[0]) if getattr(first, "ndim", 0) else 1
        times = np.asarray(event_time, dtype=np.float64).reshape(-1)
        if times.size == 1 and n > 1:
            times = np.full(n, times[0])
        if times.size != n:
            raise ValueError(
                f"event_time has {times.size} entries but the batch has {n} samples"
            )
        if isinstance(self.metric, Keyed) and not self.metric.lru and "slot" in kwargs:
            # the nested Windowed(Keyed) plane: out-of-range segment ids are
            # dropped by the INNER slab scatter inside the vmapped delta —
            # a device-side non-event the eager Keyed path would have
            # counted. Count it here, from the host-routed update, so fleet
            # shards surface misrouted-sample drops uniformly with the
            # too-late drops below.
            misrouted = dropped_slot_count(kwargs["slot"], self.metric.num_slots)
            if misrouted:
                record_slab_dropped(misrouted)
        if self.decay:
            slot_ids, weights = self._route_decay(times)
            overlap_rows = ()
        else:
            agreed = None
            if self._agreement is not None and times.size:
                # report BEFORE judging: this batch's peak is this rank's
                # contribution to the very agreement round that judges it
                peak = float(times.max())
                candidate = peak if self._watermark is None else max(self._watermark, peak)
                self._agreement.report(self._rank, candidate)
                agreed = self._refresh_agreed()
                if agreed is None:
                    # no agreement yet (a registered peer is still silent):
                    # the close clock is None — no window has closed — so no
                    # event can be late either; only ring residency drops
                    agreed = -math.inf
            route = route_events(
                times, self._watermark, self._head, self._spec,
                agreed=agreed, judge_prefix=judge_prefix,
            )
            if route.opened and self._head is not None:
                # the roll: recycled slots held now-expired windows
                self._reset_slots(sorted({w % self.num_windows for w in route.opened}))
            self._watermark, self._head = route.watermark, route.head
            if route.min_window is not None:
                self._origin = (
                    route.min_window
                    if self._origin is None
                    else min(self._origin, route.min_window)
                )
            self._late += route.n_late
            if route.n_dropped:
                self._dropped += route.n_dropped
                record_slab_dropped(route.n_dropped)
            if _LEDGER.enabled and self.lifecycle_label is not None:
                # lifecycle open/ingest stamps: every window this batch's
                # ACCEPTED samples touched gets first_event (first wins) and
                # last_event (last wins). Host arithmetic over data the
                # router already produced — no device work, no extra reads.
                accepted = np.asarray(route.slot_ids) >= 0
                touched = set()
                if accepted.any():
                    touched.update(
                        int(w)
                        for w in np.unique(window_index(times[accepted], self._spec.stride))
                    )
                for j, row in enumerate(route.overlap_slots):
                    covered = np.asarray(row) >= 0
                    if covered.any():
                        touched.update(
                            int(w) - (j + 1)
                            for w in np.unique(
                                window_index(times[covered], self._spec.stride)
                            )
                        )
                now_ns = time.perf_counter_ns()
                for w in sorted(touched):
                    _LEDGER.stamp(self.lifecycle_label, w, "first_event", ns=now_ns)
                    _LEDGER.stamp(self.lifecycle_label, w, "last_event", ns=now_ns)
            if n and all(getattr(a, "ndim", 0) for a in data):
                # the bucketed compiled path: pad to a power-of-two sample
                # bucket (padded rows -> slot -1 -> XLA scatter drop) and run
                # ONE cached jitted routed-scatter program with donated slab
                # buffers, so variable coalesced drain sizes never retrace
                # and the eager path stops copying the (W, *shape) slabs.
                self._scatter_bucketed(
                    args, kwargs,
                    np.asarray(route.slot_ids),
                    tuple(np.asarray(r) for r in route.overlap_slots),
                )
                return
            slot_ids, weights = jnp.asarray(route.slot_ids), None
            overlap_rows = tuple(jnp.asarray(r) for r in route.overlap_slots)

        kw_keys = tuple(kwargs)
        n_args = len(args)

        def one(*sample):
            batch = tuple(a[None] for a in sample)  # per-sample size-1 batches
            return self.metric.update_state(
                self.metric.init_state(), *batch[:n_args], **dict(zip(kw_keys, batch[n_args:]))
            )

        deltas = jax.vmap(one)(*data)  # {name: (N, *shape) / sketch with (N, ...) counts}

        def scatter_rows(reduce: str, payload: Array) -> Array:
            # sliding windows: the SAME per-sample delta scatters once per
            # covering window (slot_ids = the newest covering row, then the
            # overlap rows); tumbling windows have no extra rows
            out = slab_scatter(reduce, payload, slot_ids, self.num_windows)
            for row in overlap_rows:
                out = slab_merge(
                    reduce, out, slab_scatter(reduce, payload, row, self.num_windows)
                )
            return out

        for name in self.metric._defaults:
            reduce = self._slab_reduce[name]
            current = getattr(self, name)
            leaf = deltas[name]
            if is_sketch(current):
                scattered = scatter_rows("sum", leaf.counts)
                setattr(self, name, type(current)(current.counts + scattered))
            else:
                payload = leaf
                if weights is not None:
                    payload = payload.astype(current.dtype) * weights.reshape(
                        (-1,) + (1,) * (payload.ndim - 1)
                    )
                scattered = scatter_rows(reduce, payload)
                acc = current if weights is None else current * self._decay_step_scale
                setattr(self, name, slab_merge(reduce, acc, scattered))
        rows = getattr(self, _ROWS_STATE)
        ones = jnp.ones(slot_ids.shape, dtype=rows.dtype) if weights is None else weights
        acc_rows = rows if weights is None else rows * self._decay_step_scale
        setattr(self, _ROWS_STATE, acc_rows + scatter_rows("sum", ones))

    def _scatter_bucketed(
        self,
        args: tuple,
        kwargs: Dict[str, Any],
        slot_ids: np.ndarray,
        overlap: tuple,
    ) -> None:
        """Scatter one routed batch through the cached compiled program for
        its (sample bucket, tree structure).

        Padding is arithmetic-free: padded data rows carry slot id ``-1`` in
        BOTH the primary and every overlap id vector, so XLA's out-of-bounds
        scatter drop guarantees they never touch a slab row and the result
        is bit-identical to the unpadded eager scatter.
        """
        data = (*args, *kwargs.values())
        n = int(slot_ids.shape[0])
        bucket = bucket_size(n)
        # everything stays host numpy until the compiled call's boundary:
        # eager jnp pads/converts would compile per DISTINCT unpadded n,
        # which is exactly the shape churn the bucket exists to absorb
        padded = tuple(pad_samples(a, bucket) for a in data)
        ids = pad_slot_ids(slot_ids, bucket)
        overlap_ids = tuple(pad_slot_ids(r, bucket) for r in overlap)
        key = (
            bucket,
            len(overlap_ids),
            len(args),
            tuple(kwargs),
            tuple((a.dtype.name, a.shape[1:]) for a in padded),
        )
        program = self._ingest_programs.get(
            key,
            lambda: self._build_ingest_program(len(args), tuple(kwargs), len(overlap_ids)),
        )
        slabs = {name: getattr(self, name) for name in self.metric._defaults}
        new_slabs, new_rows = program(slabs, getattr(self, _ROWS_STATE), ids, overlap_ids, padded)
        for name, value in new_slabs.items():
            setattr(self, name, value)
        setattr(self, _ROWS_STATE, new_rows)

    def _build_ingest_program(self, n_args: int, kw_keys: tuple, n_overlap: int):
        """Compile the routed-scatter program for one tree structure: the
        vmapped per-sample inner delta + one segment scatter per state (plus
        one per sliding-overlap row) + the slab merges, as ONE jitted call.

        The slab accumulators and rows state are DONATED (off CPU): the
        update consumes the old buffers in place instead of copying the
        ``(W, *shape)`` slabs every batch. CPU XLA cannot honor donation, so
        it is skipped there to keep the eager tests warning-free.

        Config-identical wrappers share ONE jit callable process-wide via
        :func:`~metrics_tpu.parallel.slab.shared_ingest_program` (jax's own
        signature cache then compiles each (bucket, dtypes) shape once per
        process, not once per instance) — without it an 8-shard fleet pays 8
        serialized compiles per bucket inside its shard workers. The shared
        closure captures a detached reset carrier, never the live inner.
        """
        num_windows = self.num_windows
        reduces = dict(self._slab_reduce)

        def build(metric):
            def one(*sample):
                batch = tuple(a[None] for a in sample)  # per-sample size-1 batches
                return metric.update_state(
                    metric.init_state(), *batch[:n_args], **dict(zip(kw_keys, batch[n_args:]))
                )

            def program(slabs, rows, slot_ids, overlap_rows, data):
                deltas = jax.vmap(one)(*data)

                def scatter_rows(reduce: str, payload: Array) -> Array:
                    out = slab_scatter(reduce, payload, slot_ids, num_windows)
                    for row in overlap_rows:
                        out = slab_merge(
                            reduce, out, slab_scatter(reduce, payload, row, num_windows)
                        )
                    return out

                out_slabs = {}
                for name, current in slabs.items():
                    reduce = reduces[name]
                    leaf = deltas[name]
                    if is_sketch(current):
                        out_slabs[name] = type(current)(
                            current.counts + scatter_rows("sum", leaf.counts)
                        )
                    else:
                        out_slabs[name] = slab_merge(reduce, current, scatter_rows(reduce, leaf))
                ones = jnp.ones(slot_ids.shape, dtype=rows.dtype)
                return out_slabs, rows + scatter_rows("sum", ones)

            donate = (0, 1) if jax.default_backend() != "cpu" else ()
            return jax.jit(program, donate_argnums=donate)

        fp = self.metric._config_fingerprint()
        if fp is None:
            return build(self.metric)  # unfingerprintable config: private program
        key_body, pins = fp

        def detached():
            carrier = deepcopy(self.metric)
            carrier.reset()
            return build(carrier)

        key = (
            "windowed", key_body, num_windows,
            tuple(sorted(reduces.items())), n_args, kw_keys, n_overlap,
        )
        return shared_ingest_program(key, pins, detached)

    def _route_decay(self, times: np.ndarray):
        """(slot_ids, per-sample weights) for the decay accumulator, and
        stash the accumulator's forward scale for this batch."""
        new_wm = float(times.max()) if self._watermark is None else max(
            self._watermark, float(times.max())
        )
        accepted = times >= new_wm - self.allowed_lateness_s
        dropped = int((~accepted).sum())
        if dropped:
            self._dropped += dropped
            record_slab_dropped(dropped)
        self._decay_step_scale = (
            1.0
            if self._watermark is None
            else float(decay_scale(new_wm - self._watermark, self.decay_half_life_s))
        )
        weights = np.where(
            accepted, decay_scale(new_wm - times, self.decay_half_life_s), 0.0
        ).astype(np.float32)
        slot_ids = np.where(accepted, 0, -1).astype(np.int32)
        self._watermark = new_wm
        return jnp.asarray(slot_ids), jnp.asarray(weights)

    def _reset_slots(self, slots) -> None:
        """Return recycled ring slots to their per-slot defaults (the roll)."""
        idx = jnp.asarray(np.asarray(slots, dtype=np.int32))
        for name, spec in self._defaults.items():
            value = getattr(self, name)
            fresh = slab_init(spec)
            if is_sketch(value):
                setattr(self, name, type(value)(value.counts.at[idx].set(fresh.counts[idx])))
            else:
                setattr(self, name, value.at[idx].set(fresh[idx]))

    # --------------------------------------------------------------- compute
    def compute(self) -> Any:
        """The merged view over every resident window — the sliding value
        over the last ``W x window_s`` seconds (decay mode: the
        exponentially-weighted value).

        With ``slide_s`` set the resident windows OVERLAP (each event lives
        in ``window_s/slide_s`` of them), so a sum over slots would
        multi-count; ``compute()`` instead returns the newest window whose
        FULL ``window_s`` span has opened — window ``head - overlap + 1``,
        spanning the ``window_s`` seconds ending at ``(head+1)*slide_s``,
        the trailing sliding view. (The head window itself extends past the
        watermark: it has only accumulated the newest ``slide_s`` seconds
        and reads near-empty right after a slide boundary.)
        """
        if self.slide_s is not None:
            resident = self.resident_windows()
            if resident:
                view = max(self._head - self._spec.overlap + 1, resident[0])
                return self.compute_window(view)
        state = self._current_state()
        rows = state.pop(_ROWS_STATE)
        inner_state: State = {}
        for name, value in state.items():
            reduce = self._slab_reduce[name]
            if is_sketch(value):
                merged = type(value)(jnp.sum(value.counts, axis=0))
            elif reduce in ("sum", "mean"):
                merged = jnp.sum(value, axis=0)
            elif reduce == "min":
                merged = jnp.min(value, axis=0)
            else:
                merged = jnp.max(value, axis=0)
            if reduce == "mean":
                merged = merged / self._mean_denom(jnp.sum(rows), merged.dtype)
            inner_state[name] = merged
        result = self.metric.compute_from_state(inner_state)
        return self._mask_empty(result, jnp.sum(rows) > 0)

    def compute_window(self, window: int) -> Any:
        """One resident window's value (the per-window publish read).

        ``window`` is the ABSOLUTE window index (``floor(t / stride)`` of
        its newest event — the stride is ``slide_s`` for sliding windows,
        ``window_s`` for tumbling); it must still be resident in the ring —
        expired or never-opened windows raise. Reads local state directly
        (no sync, no compute cache): the serving loop syncs once per roll
        via the ordinary ``compute()``/host plane and then reads windows off
        the slab.
        """
        if self.decay:
            raise ValueError("the decay accumulator has no windows; use compute()")
        if window not in self.resident_windows():
            raise KeyError(
                f"window {window} is not resident (resident: {self.resident_windows()});"
                " it expired from the ring or has not opened yet"
            )
        slot = window % self.num_windows
        state = self._current_state()
        rows = state.pop(_ROWS_STATE)
        inner_state: State = {}
        for name, value in state.items():
            row = type(value)(value.counts[slot]) if is_sketch(value) else value[slot]
            if self._slab_reduce[name] == "mean":
                row = row / self._mean_denom(rows[slot], row.dtype)
            inner_state[name] = row
        result = self.metric.compute_from_state(inner_state)
        return self._mask_empty(result, rows[slot] > 0)

    # -------------------------------------------------- mergeable partials
    def window_partial(self, window: int) -> Dict[str, Any]:
        """One resident window's RAW state rows as a host-transferable,
        mergeable partial: ``{"version", "window", "window_start_s", "rows",
        "state"}``.

        This is the fleet merge tier's unit of exchange
        (``serving/fleet.py``) and the retention tier's banked record
        (``serving/retention.py``): every leaf is the slot's untouched
        accumulator — sum-backed means stay SUMS, sketch leaves keep their
        integer counts — so partials from N ingest shards merge by the
        slot's own reduce kind (:meth:`value_from_partials`) and the merged
        value is bit-exact the value one process accumulating all the
        samples would compute. Leaves are host numpy (a partial is meant to
        cross a process/queue boundary, not to stay a device reference).
        ``version`` is :data:`~metrics_tpu.parallel.slab.
        PARTIAL_SCHEMA_VERSION`, validated at every ingest point — partials
        outlive the producing process, so format drift fails loudly.
        """
        if self.decay:
            raise ValueError("the decay accumulator has no windows; partials are per-window")
        if window not in self.resident_windows():
            raise KeyError(
                f"window {window} is not resident (resident: {self.resident_windows()});"
                " it expired from the ring or has not opened yet"
            )
        slot = window % self.num_windows
        state = self._current_state()
        rows = state.pop(_ROWS_STATE)
        out: Dict[str, Any] = {}
        for name, value in state.items():
            if is_sketch(value):
                out[name] = type(value)(np.asarray(value.counts[slot]))
            else:
                out[name] = np.asarray(value[slot])
        return {
            "version": PARTIAL_SCHEMA_VERSION,
            "window": int(window),
            "window_start_s": self.window_start(window),
            "rows": np.asarray(rows[slot]),
            "state": out,
        }

    def _empty_partial(self) -> Dict[str, Any]:
        """The identity partial (a shard that saw no samples): per-slot
        defaults, zero rows — merging it in changes nothing."""
        state: Dict[str, Any] = {}
        for name, spec in self._defaults.items():
            if name == _ROWS_STATE:
                continue
            fresh = slab_init(spec)
            state[name] = (
                type(fresh)(np.asarray(fresh.counts[0])) if is_sketch(fresh)
                else np.asarray(fresh[0])
            )
        return {
            "version": PARTIAL_SCHEMA_VERSION,
            "window": -1,
            "rows": np.zeros((), np.float32),
            "state": state,
        }

    # the wire-format validator, surfaced here for API discoverability (the
    # partial producers and the version constant live in parallel/slab.py)
    check_partial_version = staticmethod(check_partial_version)

    def merge_partials(self, partials) -> tuple:
        """Merge :meth:`window_partial` outputs by pure state addition (sum/
        mean leaves and sketch counts add; min/min, max/max) — returns the
        ``(inner_state, rows)`` pair still in RAW (sum-backed) form. The
        partials need not come from the same window: merging one window's
        partials across shards gives that window's global state, merging
        every resident window's partials gives the sliding view's. Every
        partial's wire-format version is validated first
        (:meth:`check_partial_version`)."""
        if not partials:
            partials = [self._empty_partial()]
        acc: State = {}
        rows = jnp.zeros((), jnp.float32)
        for partial in partials:
            self.check_partial_version(partial)
            rows = rows + jnp.asarray(partial["rows"], jnp.float32)
            for name, leaf in partial["state"].items():
                reduce = self._slab_reduce[name]
                if name not in acc:
                    acc[name] = (
                        type(leaf)(jnp.asarray(leaf.counts)) if is_sketch(leaf)
                        else jnp.asarray(leaf)
                    )
                elif is_sketch(leaf):
                    acc[name] = type(leaf)(acc[name].counts + jnp.asarray(leaf.counts))
                else:
                    acc[name] = slab_merge(reduce, acc[name], jnp.asarray(leaf))
        return acc, rows

    def value_from_partials(self, partials) -> Any:
        """The finished inner value over merged partials: merge, divide the
        sum-backed means by the merged sample count, run the inner finisher,
        and apply the ``empty`` policy when no samples are resident — the
        merge tier's read, bit-exact vs a single accumulating process."""
        merged, rows = self.merge_partials(partials)
        inner_state: State = {}
        for name, value in merged.items():
            if self._slab_reduce[name] == "mean" and not is_sketch(value):
                value = value / self._mean_denom(rows, value.dtype)
            inner_state[name] = value
        result = self.metric.compute_from_state(inner_state)
        return self._mask_empty(result, rows > 0)

    @staticmethod
    def _mean_denom(rows: Array, dtype: Any) -> Array:
        """Sum-backed mean divisor: the (possibly decayed) sample count,
        floored away from zero so empty slots divide by 1 (masked after)."""
        rows = rows.astype(dtype)
        return jnp.where(rows > 0, rows, jnp.ones((), dtype=dtype))

    def _mask_empty(self, result: Any, occupied: Array) -> Any:
        def mask(r: Array) -> Array:
            r = jnp.asarray(r)
            if self.empty == "nan" and jnp.issubdtype(r.dtype, jnp.inexact):
                return jnp.where(occupied, r, jnp.nan)
            return jnp.where(occupied, r, jnp.zeros((), dtype=r.dtype))

        return jax.tree_util.tree_map(mask, result)

    # ------------------------------------------------------- integrity guard
    def _integrity_state(self) -> State:
        """Mask never-touched slots before the ``check_finite`` scan: min/max
        identity fills sit at the dtype extremes the saturation scan would
        otherwise flag as pre-wraparound corruption."""
        state = self._current_state()
        rows = state[_ROWS_STATE]
        occupied = np.asarray(rows) > 0
        out: State = {}
        for name, value in state.items():
            reduce = self._slab_reduce.get(name)
            if reduce in ("min", "max") and not is_sketch(value):
                occ = jnp.asarray(occupied).reshape(
                    (self.num_windows,) + (1,) * (value.ndim - 1)
                )
                value = jnp.where(occ, value, jnp.zeros((), dtype=value.dtype))
            out[name] = value
        return out

    # ---------------------------------------------------- sparse delta sync
    def sparse_plane(self, axis_name: Any, mesh: Any = None, *,
                     capacity: Optional[int] = None, **kwargs: Any) -> Any:
        """A :class:`~metrics_tpu.parallel.sparse.SparseSyncPlane` over the
        window ring: every leaf is a ``(num_windows, ...)`` slab, so a round
        exchanges only the windows a step actually wrote — typically the
        head window (and a late-routed neighbour), not the whole ring. The
        default capacity is the full ring (``num_windows`` is small; the
        win here is skipping the per-window payloads, which for a nested
        ``Keyed`` inner are ``(W, K, *item)``-sized). Decay mode's float32
        rows delta-add exactly while the effective counts are integer-valued
        floats; ring mode is integer-exact throughout. Build the plane
        while the metric is RESET (see the plane's docstring).
        """
        from metrics_tpu.parallel.sparse import SparseSyncPlane

        if capacity is None:
            capacity = self.num_windows
        return SparseSyncPlane(
            self._current_state(), dict(self._reductions), self.num_windows,
            axis_name, mesh, capacity=capacity, **kwargs,
        )

    # ------------------------------------------------------------- lifecycle
    def reset(self) -> None:
        super().reset()
        self._watermark = None
        self._head = None
        self._origin = None
        self._dropped = 0
        self._late = 0
        self._agreed_seen = None

    _STREAM_KEYS = ("_windowed_watermark", "_windowed_head", "_windowed_dropped", "_windowed_late")

    def state_dict(self, destination: Optional[dict] = None, prefix: str = "") -> dict:
        """Window slabs persist through the base path (plain arrays/
        sketches); the host-side stream position — watermark, head window,
        drop/late counters — rides along so a restored runtime resumes
        MID-WINDOW with the same routing verdicts (and ``guarded_update``
        replay of the in-flight step stays a no-op via the base epoch
        watermark entry)."""
        destination = super().state_dict(destination, prefix=prefix)
        destination[prefix + "_windowed_watermark"] = np.asarray(
            np.nan if self._watermark is None else self._watermark, dtype=np.float64
        )
        destination[prefix + "_windowed_head"] = np.asarray(
            0 if self._head is None else self._head, dtype=np.int64
        )
        destination[prefix + "_windowed_origin"] = np.asarray(
            0 if self._origin is None else self._origin, dtype=np.int64
        )
        destination[prefix + "_windowed_dropped"] = np.asarray(self._dropped, dtype=np.int64)
        destination[prefix + "_windowed_late"] = np.asarray(self._late, dtype=np.int64)
        # the agreed clock as this rank last saw it: a restored rank resumes
        # judging from AT LEAST this point, so a closed window can never
        # reopen and the global watermark can never regress through replay
        destination[prefix + "_windowed_agreed"] = np.asarray(
            np.nan if self._agreed_seen is None else self._agreed_seen, dtype=np.float64
        )
        return destination

    def load_state_dict(self, state_dict: dict, prefix: str = "") -> None:
        super().load_state_dict(state_dict, prefix=prefix)
        key = prefix + "_windowed_watermark"
        if key in state_dict:
            wm = float(np.asarray(state_dict[key]))
            self._watermark = None if math.isnan(wm) else wm
            head = int(np.asarray(state_dict[prefix + "_windowed_head"]))
            self._head = None if self._watermark is None or self.decay else head
            origin_key = prefix + "_windowed_origin"
            if origin_key in state_dict:
                origin = int(np.asarray(state_dict[origin_key]))
                self._origin = None if self._head is None else origin
            self._dropped = int(np.asarray(state_dict[prefix + "_windowed_dropped"]))
            self._late = int(np.asarray(state_dict[prefix + "_windowed_late"]))
            agreed_key = prefix + "_windowed_agreed"
            if agreed_key in state_dict:
                loaded = float(np.asarray(state_dict[agreed_key]))
                if not math.isnan(loaded) and (
                    self._agreed_seen is None or loaded > self._agreed_seen
                ):
                    self._agreed_seen = loaded
            if self._agreement is not None and self._watermark is not None:
                # the restored rank rejoins the agreement at its checkpointed
                # clock: the report is monotone per rank, so replaying an old
                # checkpoint into a live agreement can never pull the global
                # min backwards
                self._agreement.report(self._rank, self._watermark)
                self._refresh_agreed()

    def __getstate__(self) -> dict:
        # the agreement is a live process-wide registry (locks, an in-flight
        # exchange) that never pickles; a restored metric re-attaches via
        # attach_agreement — the checkpointed agreed high-water rides
        # state_dict, so the rejoin can never regress verdicts
        state = super().__getstate__()
        state.pop("_agreement", None)
        return state

    def __setstate__(self, state: dict) -> None:
        super().__setstate__(state)
        self.__dict__.setdefault("_agreement", None)
        self.__dict__.setdefault("_rank", None)
        self.__dict__.setdefault("_agreed_seen", None)
        self.__dict__.setdefault("slide_s", None)

    def __repr__(self) -> str:
        if self.decay:
            return (
                f"Windowed({self.metric!r}, decay_half_life_s={self.decay_half_life_s})"
            )
        slide = "" if self.slide_s is None else f" slide_s={self.slide_s},"
        return (
            f"Windowed({self.metric!r}, window_s={self.window_s},{slide}"
            f" num_windows={self.num_windows},"
            f" allowed_lateness_s={self.allowed_lateness_s})"
        )
