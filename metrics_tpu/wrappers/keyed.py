"""Keyed: one metric x thousands of segments, zero new collectives.

``Keyed(metric, num_slots)`` turns any per-sample-decomposable metric into a
multi-tenant slab metric: every registered state of the inner metric becomes
a ``(K, *shape)`` slab (one row per segment slot, see
``metrics_tpu/parallel/slab.py``), ``update(..., slot=segment_ids)`` routes
each sample's contribution to its segment's row with ONE
``segment_sum``-style scatter, ``compute()`` vmaps the inner finisher over
the slab and returns all K values at once, and sync rides the existing
per-dtype coalesced ``psum``/``pmin``/``pmax`` buckets unchanged — the
staged collective count is identical at K=1 and K=10 000.

Contrast with the module-cloning wrappers (``ClasswiseWrapper``,
``MultioutputWrapper``): those multiply compiled steps, state pytrees and
sync calls by K; ``Keyed`` multiplies only the state's leading axis.

Contract on the inner metric: every state must be a fixed-shape array with a
``sum``/``mean``/``min``/``max`` reduction or a sketch state
(``approx="sketch"`` curve/rank metrics) — list/buffer cat-states have no
per-slot slab form (use ``approx="sketch"`` instead) — and the inner
``update`` must be per-sample decomposable: updating with a batch must equal
merging per-sample updates (the n -> 1 limit of the pairwise-merge property
the fused forward already assumes). Sum/mean state defaults must be zero.
"""
from copy import deepcopy
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.core.metric import Metric, State
from metrics_tpu.observability.counters import (
    COUNTERS as _COUNTERS,
    record_evicted_mass,
    record_slab_dropped,
    record_slab_slots,
)
from metrics_tpu.parallel.buffer import PaddedBuffer
from metrics_tpu.parallel.qsketch import QSketchSpec
from metrics_tpu.parallel.sketch import SketchSpec, is_sketch, sketch_init
from metrics_tpu.parallel.slab import (
    LRUSlotTable,
    PARTIAL_SCHEMA_VERSION,
    SlabProgramCache,
    SlabSpec,
    bucket_size,
    check_partial_version,
    dropped_slot_count,
    make_slab_spec,
    pad_samples,
    pad_slot_ids,
    shared_ingest_program,
    slab_init,
    slab_merge,
    slab_rows_spec,
    slab_scatter,
    slab_sync_reduce,
)
from metrics_tpu.utils.exceptions import TracingUnsupportedError
from metrics_tpu.utils.prints import rank_zero_warn_once

# the per-slot sample-count state every Keyed wrapper carries: occupancy
# masks (empty-slot policy), the sum-backed mean division, and the gauges
_ROWS_STATE = "keyed_rows"

_EMPTY_POLICIES = ("nan", "zero")


class Keyed(Metric):
    r"""Per-segment fan-out of ``metric`` over ``num_slots`` slab rows.

    Args:
        metric: the inner metric. Its states become ``(K, *shape)`` slabs;
            its ``update``/``compute`` are reused as the per-sample delta
            and the per-slot finisher — the instance itself never
            accumulates.
        num_slots: K, the number of segment rows.
        lru: accept arbitrary hashable segment KEYS in ``update(...,
            slot=keys)`` and map them onto the K rows with an
            :class:`~metrics_tpu.parallel.slab.LRUSlotTable` (least-recently-
            used eviction; evicted rows reset, the eviction count feeds the
            ``slab_slots`` observability gauge). Key resolution is host-side
            by construction, so LRU mode runs the eager update path; with
            ``lru=False`` (default) ``slot`` is an int array of slot ids in
            ``[0, K)`` and the whole update is one jittable scatter.
            Out-of-range ids are dropped, never misrouted.
        empty: what ``compute()`` reports for never-updated slots —
            ``"nan"`` (default; non-float results fall back to 0) or
            ``"zero"``.

    ``compute()`` returns the inner result with a leading ``(K,)`` axis;
    ``compute(slot=k)`` reads one segment (in LRU mode ``k`` is the segment
    KEY). Sync (``dist_sync_on_step``, host plane, in-jit ``sync_state``)
    rides the base machinery: slab leaves are ordinary sum/min/max array (or
    sketch) leaves, so the whole wrapper syncs through the same coalesced
    buckets as the unkeyed metric — one psum for all K segments.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import Accuracy
        >>> acc = Keyed(Accuracy(), num_slots=3)
        >>> preds = jnp.array([0.9, 0.8, 0.3, 0.1])
        >>> target = jnp.array([1, 0, 0, 0])
        >>> acc.update(preds, target, slot=jnp.array([0, 1, 1, 0]))
        >>> [round(float(v), 2) for v in acc.compute()[:2]]
        [1.0, 0.5]
    """

    def __init__(
        self,
        metric: Metric,
        num_slots: int,
        lru: bool = False,
        empty: str = "nan",
        compute_on_step: Optional[bool] = None,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
        jit: Optional[bool] = None,
    ):
        if not isinstance(metric, Metric):
            raise ValueError(f"`metric` must be a Metric, got {type(metric).__name__}")
        if empty not in _EMPTY_POLICIES:
            raise ValueError(f"`empty` must be one of {_EMPTY_POLICIES}, got {empty!r}")
        super().__init__(
            compute_on_step=metric.compute_on_step if compute_on_step is None else compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
            # LRU key resolution is host-side: the fused jitted step can
            # never trace it, so don't build one per instance
            jit=False if lru else jit,
        )
        self.metric = metric
        self.num_slots = int(num_slots)
        self.lru = bool(lru)
        self.empty = empty
        self._metric_label = f"Keyed({type(metric).__name__})"
        self._slots = LRUSlotTable(self.num_slots) if lru else None
        self._occupied_host: set = set()  # gauge bookkeeping, not state
        # compiled routed-scatter programs, one per (sample bucket, tree
        # structure): the eager-path retrace guard (deep-copies/pickles empty)
        self._ingest_programs = SlabProgramCache()

        # every inner state becomes a (K, *shape) slab state of this wrapper
        if not metric._defaults:
            raise ValueError("the inner metric declares no states; nothing to key")
        if _ROWS_STATE in metric._defaults:
            raise ValueError(f"the inner metric already has a state named {_ROWS_STATE!r}")
        self._slab_reduce: Dict[str, str] = {}
        for name, spec in metric._defaults.items():
            slab = self._slab_spec_for(name, spec, metric._reductions[name])
            self._slab_reduce[name] = slab.reduce
            self.add_state(name, default=slab, dist_reduce_fx=slab_sync_reduce(slab.reduce),
                           persistent=True)
        self.add_state(_ROWS_STATE, default=slab_rows_spec(self.num_slots),
                       dist_reduce_fx="sum", persistent=True)

    def _slab_spec_for(self, name: str, spec: Any, fx: Any) -> SlabSpec:
        """The ``SlabSpec`` one inner state maps onto, or a loud rejection."""
        if isinstance(spec, SketchSpec):
            kind = spec.kind  # "hist" | "rank": counts grow a leading K axis
            return make_slab_spec(self.num_slots, np.zeros(spec.shape, np.dtype(spec.dtype)),
                                  "sum", kind=kind)
        if isinstance(spec, QSketchSpec):
            # quantile sketches slab like any sketch: the counts grow a
            # leading K axis and every row stays a QuantileSketch — this is
            # the per-tenant p99 state (Keyed(Quantile(q=0.99), K))
            return make_slab_spec(self.num_slots, np.zeros(spec.shape, np.dtype(spec.dtype)),
                                  "sum", kind="qsketch")
        if isinstance(spec, (list, PaddedBuffer)) or fx == "cat" or fx is None:
            raise ValueError(
                f"state {name!r} of {type(self.metric).__name__} is a cat/list/buffer"
                " state with no per-slot slab form; Keyed supports fixed-shape"
                " sum/mean/min/max states and sketch states (curve/rank metrics:"
                " construct the inner metric with approx='sketch')"
            )
        if isinstance(spec, (SlabSpec,)) or not isinstance(spec, np.ndarray):
            raise ValueError(
                f"state {name!r} has an unsupported default kind for Keyed:"
                f" {type(spec).__name__}"
            )
        if not (isinstance(fx, str) and fx in ("sum", "mean", "min", "max")):
            raise ValueError(
                f"state {name!r} uses dist_reduce_fx={fx!r}; Keyed supports"
                " 'sum'/'mean'/'min'/'max' array states and sketch states"
            )
        return make_slab_spec(self.num_slots, spec, fx)

    # ---------------------------------------------------------------- update
    def update(self, *args: Any, slot: Any = None, **kwargs: Any) -> None:
        """Scatter one batch into the segment slabs.

        ``slot`` (required, keyword-only) is one segment id per sample: an
        int array in ``[0, num_slots)``, or — with ``lru=True`` — a sequence
        of arbitrary hashable segment keys. All positional/keyword data
        arguments must share the leading sample axis with ``slot``.
        """
        if slot is None:
            raise ValueError("Keyed.update requires `slot=` (one segment id per sample)")
        slot_ids = self._resolve_slot_ids(slot)
        if not self._under_trace():
            # out-of-range ids are DROPPED by the scatter with no device-side
            # trace; count them host-side (records even with observability
            # off, like the fault counters). LRU mode cannot produce one.
            dropped = 0 if self.lru else dropped_slot_count(slot_ids, self.num_slots)
            if dropped:
                record_slab_dropped(dropped)
        data = (*args, *kwargs.values())
        if not data:
            raise ValueError("Keyed.update needs at least one data argument")
        if (
            not self._under_trace()
            and int(slot_ids.shape[0]) > 0
            and all(getattr(a, "ndim", 0) for a in data)
        ):
            # the bucketed compiled path (eager updates only — megafusion
            # traces this whole method, where shapes are already static):
            # pad to a power-of-two sample bucket (padded rows -> slot -1 ->
            # XLA scatter drop) and run ONE cached jitted scatter program.
            self._scatter_bucketed(args, kwargs, np.asarray(slot_ids))
            self._note_slab_gauges(slot_ids)
            return
        kw_keys = tuple(kwargs)
        n_args = len(args)

        def one(*sample):
            batch = tuple(a[None] for a in sample)  # per-sample size-1 batches
            return self.metric.update_state(
                self.metric.init_state(), *batch[:n_args], **dict(zip(kw_keys, batch[n_args:]))
            )

        deltas = jax.vmap(one)(*data)  # {name: (N, *shape) / sketch with (N, ...) counts}
        for name in self.metric._defaults:
            reduce = self._slab_reduce[name]
            current = getattr(self, name)
            leaf = deltas[name]
            if is_sketch(current):
                scattered = slab_scatter("sum", leaf.counts, slot_ids, self.num_slots)
                setattr(self, name, type(current)(current.counts + scattered))
            else:
                scattered = slab_scatter(reduce, leaf, slot_ids, self.num_slots)
                setattr(self, name, slab_merge(reduce, current, scattered))
        rows = getattr(self, _ROWS_STATE)
        ones = jnp.ones(slot_ids.shape, dtype=rows.dtype)
        setattr(self, _ROWS_STATE, rows + slab_scatter("sum", ones, slot_ids, self.num_slots))
        self._note_slab_gauges(slot_ids)

    def _scatter_bucketed(self, args: tuple, kwargs: Dict[str, Any], slot_ids: np.ndarray) -> None:
        """Scatter one batch through the cached compiled program for its
        (sample bucket, tree structure); padded rows carry slot ``-1`` and
        are dropped by XLA scatter, so the result is bit-identical to the
        unpadded eager scatter."""
        data = (*args, *kwargs.values())
        bucket = bucket_size(int(slot_ids.shape[0]))
        # host numpy until the compiled call's boundary — eager jnp
        # pads/converts would compile per DISTINCT unpadded n, the exact
        # shape churn the bucket absorbs
        padded = tuple(pad_samples(a, bucket) for a in data)
        ids = pad_slot_ids(slot_ids, bucket)
        key = (
            bucket,
            len(args),
            tuple(kwargs),
            tuple((a.dtype.name, a.shape[1:]) for a in padded),
        )
        program = self._ingest_programs.get(
            key, lambda: self._build_ingest_program(len(args), tuple(kwargs))
        )
        slabs = {name: getattr(self, name) for name in self.metric._defaults}
        new_slabs, new_rows = program(slabs, getattr(self, _ROWS_STATE), ids, padded)
        for name, value in new_slabs.items():
            setattr(self, name, value)
        setattr(self, _ROWS_STATE, new_rows)

    def _build_ingest_program(self, n_args: int, kw_keys: tuple):
        """Compile the scatter program for one tree structure: vmapped
        per-sample inner delta + one segment scatter per state + the slab
        merges, as ONE jitted call with donated slab buffers (off CPU).

        Config-identical wrappers share ONE jit callable process-wide via
        :func:`~metrics_tpu.parallel.slab.shared_ingest_program`, so a fresh
        instance (fleet shard, A/B twin) replays compiled signatures instead
        of re-tracing them; the shared closure captures a detached reset
        carrier, never the live inner."""
        num_slots = self.num_slots
        reduces = dict(self._slab_reduce)

        def build(metric):
            def one(*sample):
                batch = tuple(a[None] for a in sample)  # per-sample size-1 batches
                return metric.update_state(
                    metric.init_state(), *batch[:n_args], **dict(zip(kw_keys, batch[n_args:]))
                )

            def program(slabs, rows, slot_ids, data):
                deltas = jax.vmap(one)(*data)
                out_slabs = {}
                for name, current in slabs.items():
                    reduce = reduces[name]
                    leaf = deltas[name]
                    if is_sketch(current):
                        out_slabs[name] = type(current)(
                            current.counts + slab_scatter("sum", leaf.counts, slot_ids, num_slots)
                        )
                    else:
                        out_slabs[name] = slab_merge(
                            reduce, current, slab_scatter(reduce, leaf, slot_ids, num_slots)
                        )
                ones = jnp.ones(slot_ids.shape, dtype=rows.dtype)
                return out_slabs, rows + slab_scatter("sum", ones, slot_ids, num_slots)

            donate = (0, 1) if jax.default_backend() != "cpu" else ()
            return jax.jit(program, donate_argnums=donate)

        fp = self.metric._config_fingerprint()
        if fp is None:
            return build(self.metric)  # unfingerprintable config: private program
        key_body, pins = fp

        def detached():
            carrier = deepcopy(self.metric)
            carrier.reset()
            return build(carrier)

        key = (
            "keyed", key_body, num_slots,
            tuple(sorted(reduces.items())), n_args, kw_keys,
        )
        return shared_ingest_program(key, pins, detached)

    def _resolve_slot_ids(self, slot: Any) -> Array:
        if self.lru:
            if self._under_trace():
                raise TracingUnsupportedError(
                    "Keyed(lru=True) resolves segment keys host-side and cannot run"
                    " under jit tracing; drive it eagerly, or use lru=False with"
                    " integer slot ids."
                )
            keys = list(np.asarray(slot).reshape(-1)) if isinstance(
                slot, (np.ndarray, jnp.ndarray, Array)
            ) else list(slot)
            slot_ids, evicted = self._slots.resolve(keys)
            if evicted:
                # LRU eviction DESTROYS the recycled rows' history: count the
                # mass it is about to zero (evidence, recorded even with
                # observability off — before this counter the loss was
                # invisible in every gauge) and name the lossless alternative
                mass = int(np.asarray(getattr(self, _ROWS_STATE))[np.asarray(evicted)].sum())
                if mass:
                    record_evicted_mass(mass)
                    rank_zero_warn_once(
                        "Keyed(lru=True) evicted a resident segment and zeroed its"
                        " accumulated history (evicted_mass_dropped counts the lost"
                        " samples). If tenants must never lose mass, use"
                        " HeavyHitters(metric, num_hot_slots, tail=...): demotion"
                        " folds the evicted row into a count-min tail instead of"
                        " destroying it."
                    )
                self._reset_slots(evicted)
            return jnp.asarray(slot_ids)
        return jnp.asarray(slot, dtype=jnp.int32).reshape(-1)

    def _reset_slots(self, slots) -> None:
        """Return recycled rows to their per-slot defaults (eviction path)."""
        idx = jnp.asarray(np.asarray(slots, dtype=np.int32))
        for name, spec in self._defaults.items():
            value = getattr(self, name)
            fresh = slab_init(spec)
            if is_sketch(value):
                setattr(self, name, type(value)(value.counts.at[idx].set(fresh.counts[idx])))
            else:
                setattr(self, name, value.at[idx].set(fresh[idx]))
        self._occupied_host.difference_update(int(s) for s in np.asarray(slots))

    def _note_slab_gauges(self, slot_ids: Array) -> None:
        """Feed the slot occupancy/eviction gauges (observability only —
        reading the slot ids back is a device readback, so the non-LRU path
        pays it only while counting is enabled, and never under tracing)."""
        if self._under_trace():
            return
        if self.lru:
            occupied = len(self._slots)
            evictions = self._slots.evictions
        elif _COUNTERS.enabled:
            self._occupied_host.update(
                int(s) for s in np.unique(np.asarray(slot_ids)) if 0 <= int(s) < self.num_slots
            )
            occupied = len(self._occupied_host)
            evictions = 0
        else:
            return
        record_slab_slots(self._metric_label, self.num_slots, occupied, evictions)

    # --------------------------------------------------------------- compute
    def compute(self) -> Any:
        """All K per-segment values: the inner finisher vmapped over the slab
        (empty slots per the ``empty`` policy). The public wrapped form also
        accepts ``compute(slot=k)`` for a single-segment read."""
        state = self._current_state()
        rows = state.pop(_ROWS_STATE)
        return self._finish_slab(state, rows)

    def _finish_slab(self, state: State, rows: Array) -> Any:
        """The shared per-slot finisher: sum-backed mean division, vmapped
        inner compute, empty-slot masking (``compute`` over the live slab and
        :meth:`value_from_partials` over a merged one)."""
        inner_state: State = {}
        for name, value in state.items():
            if self._slab_reduce[name] == "mean":
                # sum-backed mean: divide by the per-slot sample count
                denom = jnp.maximum(rows, 1).astype(value.dtype).reshape(
                    (self.num_slots,) + (1,) * (value.ndim - 1)
                )
                value = value / denom
            inner_state[name] = value
        results = jax.vmap(self.metric.compute_from_state)(inner_state)
        occupied = rows > 0

        def mask(r: Array) -> Array:
            r = jnp.asarray(r)
            occ = occupied.reshape((self.num_slots,) + (1,) * (r.ndim - 1))
            if self.empty == "nan" and jnp.issubdtype(r.dtype, jnp.inexact):
                return jnp.where(occ, r, jnp.nan)
            return jnp.where(occ, r, jnp.zeros((), dtype=r.dtype))

        return jax.tree_util.tree_map(mask, results)

    # -------------------------------------------------- mergeable partials
    def mergeable_partial(self) -> Dict[str, Any]:
        """The full slab state as a host-transferable, mergeable partial:
        ``{"version", "rows", "state"}`` with every leaf in RAW (sum-backed)
        form (``version`` is the wire-format stamp every ingest point
        validates — see ``parallel.slab.PARTIAL_SCHEMA_VERSION``).

        Partials from N ingest shards — each shard accumulating a disjoint
        (or overlapping: merge is pure addition / min / max per the slot's
        reduce kind) share of the traffic over the SAME slot layout — merge
        through :meth:`value_from_partials` into the global per-segment
        values, bit-exact vs one process accumulating everything. LRU mode
        is excluded: two shards' key->slot maps need not agree, so their
        slabs are not row-aligned (use ``lru=False`` with stable slot ids —
        e.g. the fleet's stable key hash — for mergeable deployments).
        """
        if self.lru:
            raise ValueError(
                "Keyed(lru=True) slabs are not mergeable across processes: each"
                " LRU table maps keys to rows independently, so two slabs'"
                " rows need not describe the same segment — use lru=False"
                " with stable slot ids"
            )
        state = self._current_state()
        rows = state.pop(_ROWS_STATE)
        out: Dict[str, Any] = {}
        for name, value in state.items():
            if is_sketch(value):
                out[name] = type(value)(np.asarray(value.counts))
            else:
                out[name] = np.asarray(value)
        return {"version": PARTIAL_SCHEMA_VERSION, "rows": np.asarray(rows), "state": out}

    def value_from_partials(self, partials) -> Any:
        """All K per-segment values over merged partials (pure state
        addition per the reduce kind, then the ordinary finisher) — the
        aggregation-tier read for a sharded keyed deployment. Every
        partial's wire-format version is validated first (the
        ``Windowed.check_partial_version`` contract: drifted layouts fail
        loudly, they never merge)."""
        acc: State = {}
        rows = jnp.zeros((self.num_slots,), jnp.float32)
        for partial in partials:
            check_partial_version(partial)
            rows = rows + jnp.asarray(partial["rows"], jnp.float32)
            for name, leaf in partial["state"].items():
                reduce = self._slab_reduce[name]
                if name not in acc:
                    acc[name] = (
                        type(leaf)(jnp.asarray(leaf.counts)) if is_sketch(leaf)
                        else jnp.asarray(leaf)
                    )
                elif is_sketch(leaf):
                    acc[name] = type(leaf)(acc[name].counts + jnp.asarray(leaf.counts))
                else:
                    acc[name] = slab_merge(reduce, acc[name], jnp.asarray(leaf))
        if not acc:  # no partials: every slot empty
            state = {
                name: slab_init(spec)
                for name, spec in self._defaults.items() if name != _ROWS_STATE
            }
            return self._finish_slab(state, rows)
        return self._finish_slab(acc, rows)

    def _wrap_compute(self, compute: Callable) -> Callable:
        """The base wrapper (sync + cache) plus the ``slot=`` read form.

        The cache always holds the FULL (K, ...) results — a slot read
        slices the cached vector, so ``compute(slot=2)`` can never poison a
        later full ``compute()``.
        """
        wrapped = super()._wrap_compute(compute)

        def with_slot(slot: Any = None) -> Any:
            out = wrapped()
            if slot is None:
                return out
            if self.lru:
                slot = self._slots.slot_of(slot)
            return jax.tree_util.tree_map(lambda v: v[slot], out)

        return with_slot

    # ------------------------------------------------------- integrity guard
    def _integrity_state(self) -> State:
        """Mask never-touched slots before the ``check_finite`` scan: min/max
        identity fills sit at the dtype extremes (finfo/iinfo max) that the
        saturation scan would otherwise flag as pre-wraparound corruption."""
        state = self._current_state()
        rows = state[_ROWS_STATE]
        occupied = np.asarray(rows) > 0
        out: State = {}
        for name, value in state.items():
            reduce = self._slab_reduce.get(name)
            if reduce in ("min", "max") and not is_sketch(value):
                occ = jnp.asarray(occupied).reshape(
                    (self.num_slots,) + (1,) * (value.ndim - 1)
                )
                value = jnp.where(occ, value, jnp.zeros((), dtype=value.dtype))
            out[name] = value
        return out

    # ---------------------------------------------------- sparse delta sync
    def sparse_plane(self, axis_name: Any, mesh: Any = None, *,
                     capacity: int = 64, **kwargs: Any) -> Any:
        """A :class:`~metrics_tpu.parallel.sparse.SparseSyncPlane` over this
        wrapper's full slab state: cross-rank sync whose collective bytes
        scale with the rows a round actually TOUCHED, not with K.

        Every ``Keyed`` leaf is a ``(K, *item)`` slab (the rows slab
        included), so all of them ride the sparse row exchange; the merged
        view a round returns feeds :meth:`_finish_slab` exactly like a dense
        ``coalesced_sync_state`` result. Build the plane while the metric is
        RESET (the plane seeds its merged view from the construction state —
        see the plane's docstring), and pass
        :func:`~metrics_tpu.parallel.slab.slab_touched_mask` over a step's
        slot ids as the ``touched=`` hint to skip the full-slab compare.
        """
        from metrics_tpu.parallel.sparse import SparseSyncPlane

        return SparseSyncPlane(
            self._current_state(), dict(self._reductions), self.num_slots,
            axis_name, mesh, capacity=capacity, **kwargs,
        )

    # ------------------------------------------------------------- lifecycle
    def reset(self) -> None:
        super().reset()
        if self._slots is not None:
            self._slots.reset()
        self._occupied_host = set()

    _SLOT_TABLE_KEY = "_keyed_slot_table"

    def state_dict(self, destination: Optional[dict] = None, prefix: str = "") -> dict:
        """Slab states persist through the base path (plain arrays/sketches);
        the LRU key->slot map rides along so a restored metric resolves the
        same keys to the same rows."""
        destination = super().state_dict(destination, prefix=prefix)
        if self._slots is not None:
            destination[prefix + self._SLOT_TABLE_KEY] = self._slots.state()
        return destination

    def load_state_dict(self, state_dict: dict, prefix: str = "") -> None:
        super().load_state_dict(state_dict, prefix=prefix)
        key = prefix + self._SLOT_TABLE_KEY
        if self._slots is not None and key in state_dict:
            self._slots.load_state(state_dict[key])

    def __repr__(self) -> str:
        return f"Keyed({self.metric!r}, num_slots={self.num_slots}, lru={self.lru})"
