"""SI-SDR / SI-SNR modules. Extension beyond the reference snapshot (later
torchmetrics ``torchmetrics/audio/si_sdr.py`` / ``si_snr.py``)."""
from typing import Any, Callable, Optional

from jax import Array

from metrics_tpu.audio.base import _PerExampleDbMetric
from metrics_tpu.functional.audio.si_sdr import _si_sdr_per_example


class SI_SDR(_PerExampleDbMetric):
    r"""Accumulated scale-invariant signal-to-distortion ratio (mean, dB).

    Args:
        zero_mean: mean-center both signals over time before scaling.

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.array([2.5, 0.0, 2.0, 8.0])
        >>> si_sdr = SI_SDR()
        >>> round(float(si_sdr(preds, target)), 4)
        18.403
    """

    def __init__(
        self,
        zero_mean: bool = False,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        self.zero_mean = zero_mean

    def _per_example(self, preds: Array, target: Array) -> Array:
        return _si_sdr_per_example(preds, target, self.zero_mean)


class SI_SNR(_PerExampleDbMetric):
    r"""Accumulated scale-invariant signal-to-noise ratio (mean, dB).

    Equivalent to SI-SDR with both signals mean-centered over time.

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.array([2.5, 0.0, 2.0, 8.0])
        >>> si_snr = SI_SNR()
        >>> round(float(si_snr(preds, target)), 4)
        15.0918
    """

    def _per_example(self, preds: Array, target: Array) -> Array:
        return _si_sdr_per_example(preds, target, zero_mean=True)
