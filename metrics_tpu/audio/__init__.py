"""Audio metrics: SNR, SI_SDR, SI_SNR, PIT.

Extension family beyond the reference snapshot (later torchmetrics ships
these in its audio package)."""
from metrics_tpu.audio.snr import SNR
from metrics_tpu.audio.si_sdr import SI_SDR, SI_SNR
from metrics_tpu.audio.pit import PIT

__all__ = ["SNR", "SI_SDR", "SI_SNR", "PIT"]
