"""Audio metrics. Extension family beyond the reference snapshot (later
torchmetrics ships an audio package: SNR, SI_SDR, SI_SNR)."""
from metrics_tpu.audio.snr import SNR
from metrics_tpu.audio.si_sdr import SI_SDR, SI_SNR

__all__ = ["SNR", "SI_SDR", "SI_SNR"]
