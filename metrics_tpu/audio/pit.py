"""PIT module. Extension beyond the reference snapshot (later torchmetrics
``audio/pit.py``). Streams the per-example best-permutation values through
the sum/count base."""
from typing import Any, Callable, Optional, Tuple

import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.streaming import SumCountMetric
from metrics_tpu.functional.audio.pit import permutation_invariant_training


class PIT(SumCountMetric):
    r"""Accumulated permutation-invariant metric (mean of per-example best
    values over source permutations).

    Args:
        metric_func: per-example kernel reducing the trailing time axis
            (e.g. ``lambda p, t: _si_sdr_per_example(p, t, False)``).
        eval_func: "max" (higher is better) or "min".

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional.audio.si_sdr import _si_sdr_per_example
        >>> pit = PIT(lambda p, t: _si_sdr_per_example(p, t, False))
        >>> target = jnp.stack([jnp.ones((2, 16)), jnp.zeros((2, 16)) + 0.5], axis=1)
        >>> _ = pit(target[:, ::-1, :], target)  # swapped sources: perfect after matching
        >>> float(pit.compute()) > 40  # ~inf dB capped by eps
        True
    """

    def __init__(
        self,
        metric_func: Callable,
        eval_func: str = "max",
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        if eval_func not in ("max", "min"):
            raise ValueError(f"`eval_func` must be 'max' or 'min', got {eval_func!r}")
        self.metric_func = metric_func
        self.eval_func = eval_func

    def _update_stats(self, preds: Array, target: Array) -> Tuple[Array, Any]:
        best, _ = permutation_invariant_training(preds, target, self.metric_func, self.eval_func)
        return jnp.sum(best), best.shape[0]
