"""SNR module. Extension beyond the reference snapshot (later torchmetrics
``torchmetrics/audio/snr.py``)."""
from typing import Any, Callable, Optional

from jax import Array

from metrics_tpu.audio.base import _PerExampleDbMetric
from metrics_tpu.functional.audio.snr import _snr_per_example


class SNR(_PerExampleDbMetric):
    r"""Accumulated signal-to-noise ratio (mean over examples, dB).

    Args:
        zero_mean: mean-center both signals over time before the ratio.

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.array([2.5, 0.0, 2.0, 8.0])
        >>> snr = SNR()
        >>> round(float(snr(preds, target)), 4)
        16.1805
    """

    def __init__(
        self,
        zero_mean: bool = False,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        self.zero_mean = zero_mean

    def _per_example(self, preds: Array, target: Array) -> Array:
        return _snr_per_example(preds, target, self.zero_mean)
