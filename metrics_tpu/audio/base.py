"""Shared base for per-example dB audio metrics.

Every metric in this family reduces each example's trailing (time) axis to a
scalar in dB and reports the mean over all examples seen — two scalar
``"sum"`` states, so accumulation is O(1) memory and cross-device sync is one
fused ``psum``.
"""
from typing import Any, Callable, Optional

import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.utils.data import accum_int_dtype


class _PerExampleDbMetric(Metric):

    def __init__(
        self,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        self.add_state("sum_db", default=np.zeros(()), dist_reduce_fx="sum")
        self.add_state("n_examples", default=np.zeros((), dtype=accum_int_dtype()), dist_reduce_fx="sum")

    def _per_example(self, preds: Array, target: Array) -> Array:
        raise NotImplementedError  # pragma: no cover - subclasses define the kernel

    def update(self, preds: Array, target: Array) -> None:
        values = self._per_example(preds, target)
        self.sum_db = self.sum_db + jnp.sum(values)
        self.n_examples = self.n_examples + values.size

    def compute(self) -> Array:
        return self.sum_db / jnp.maximum(self.n_examples, 1).astype(jnp.float32)
