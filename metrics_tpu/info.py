"""Package metadata for metrics_tpu.

A TPU-native (JAX/XLA) metrics framework with the capabilities of
TorchMetrics v0.2.1 (reference: /root/reference/torchmetrics/info.py:1).
"""

__version__ = "0.5.0"
__author__ = "metrics_tpu authors"
__license__ = "Apache-2.0"
__docs__ = "TPU-native machine-learning metrics for JAX: stateful accumulation, XLA-collective sync, pure-functional core."
