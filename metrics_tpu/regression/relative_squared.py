"""RelativeSquaredError module. Extension beyond the reference snapshot
(later torchmetrics ``regression/rse.py``).

RSE = sum((t - p)^2) / sum((t - mean(t))^2) over the WHOLE epoch — the
denominator needs the global target mean, so the streamed statistics are
the raw moments (sum of squared errors, sum t, sum t^2, count), all
"sum"-reducible; the denominator expands to ``sum t^2 - n * mean^2`` at
compute. ``num_outputs`` keeps per-column moments; ``squared=False``
returns the root.
"""
from typing import Any, Callable, Optional

import numpy as np
import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.utils.checks import _check_same_shape
from metrics_tpu.utils.data import upcast_accum


class RelativeSquaredError(Metric):
    r"""Accumulated relative squared error (optionally rooted).

    Example:
        >>> import jax.numpy as jnp
        >>> metric = RelativeSquaredError()
        >>> preds = jnp.array([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.array([3.0, -0.5, 2.0, 7.0])
        >>> round(float(metric(preds, target)), 4)
        0.0514
    """

    def __init__(
        self,
        num_outputs: int = 1,
        squared: bool = True,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        if not isinstance(num_outputs, int) or num_outputs < 1:
            raise ValueError(f"`num_outputs` must be a positive int, got {num_outputs!r}")
        self.num_outputs = num_outputs
        self.squared = squared
        shape = (num_outputs,)
        self.add_state("sum_sq_error", default=np.zeros(shape), dist_reduce_fx="sum")
        self.add_state("sum_target", default=np.zeros(shape), dist_reduce_fx="sum")
        self.add_state("sum_sq_target", default=np.zeros(shape), dist_reduce_fx="sum")
        self.add_state("total", default=np.zeros(()), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        _check_same_shape(preds, target)
        preds = upcast_accum(jnp.asarray(preds))
        target = upcast_accum(jnp.asarray(target))
        if self.num_outputs == 1:
            if preds.ndim == 2 and preds.shape[1] == 1:
                preds, target = preds[:, 0], target[:, 0]
            if preds.ndim != 1:
                raise ValueError(
                    f"Expected 1-D inputs (or (N, 1)) with num_outputs=1, got {preds.shape}"
                )
            preds, target = preds[:, None], target[:, None]
        else:
            if preds.ndim != 2 or preds.shape[1] != self.num_outputs:
                raise ValueError(
                    f"Expected (N, {self.num_outputs}) inputs, got {preds.shape}"
                )
        self.sum_sq_error = self.sum_sq_error + jnp.sum((target - preds) ** 2, axis=0)
        self.sum_target = self.sum_target + jnp.sum(target, axis=0)
        self.sum_sq_target = self.sum_sq_target + jnp.sum(target**2, axis=0)
        self.total = self.total + target.shape[0]

    def compute(self) -> Array:
        n = jnp.maximum(self.total, 1.0)
        denom = self.sum_sq_target - self.sum_target**2 / n
        rse = jnp.where(denom > 0, self.sum_sq_error / jnp.where(denom > 0, denom, 1.0), jnp.nan)
        if not self.squared:
            rse = jnp.sqrt(rse)
        # reference parity (later torchmetrics regression/rse.py): one scalar,
        # the mean over outputs
        return jnp.mean(rse)
