"""UniversalImageQualityIndex module. Extension beyond the reference
snapshot (later torchmetrics ``image/uqi.py``). Streams the per-window map
mean through the sum/count base (exact for the default mean reduction)."""
from typing import Any, Callable, Optional, Sequence, Tuple

import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.streaming import SumCountMetric
from metrics_tpu.functional.regression.uqi import universal_image_quality_index


class UniversalImageQualityIndex(SumCountMetric):
    r"""Accumulated UQI (mean over all windows of all images seen).

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.arange(0, 16 * 16, dtype=jnp.float32).reshape(1, 1, 16, 16) / 256
        >>> preds = target * 0.75
        >>> uqi = UniversalImageQualityIndex()
        >>> round(float(uqi(preds, target)), 4)
        0.9216
    """

    def __init__(
        self,
        kernel_size: Sequence[int] = (11, 11),
        sigma: Sequence[float] = (1.5, 1.5),
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        self.kernel_size = tuple(kernel_size)
        self.sigma = tuple(sigma)

    def _update_stats(self, preds: Array, target: Array) -> Tuple[Array, Any]:
        q_map = universal_image_quality_index(preds, target, self.kernel_size, self.sigma, "none")
        return jnp.sum(q_map), q_map.size
