"""CosineSimilarity module. Extension beyond the reference snapshot.

With 'mean'/'sum' reduction the metric streams (two scalar sum-states, one
fused psum to sync); 'none' keeps a cat-state of per-sample similarities.
"""
from typing import Any, Callable, Optional

import numpy as np
import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.regression.cosine_similarity import _cosine_similarity_rows
from metrics_tpu.utils.data import accum_int_dtype


class CosineSimilarity(Metric):
    r"""Accumulated per-sample cosine similarity.

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.array([[1.0, 0.0], [1.0, 1.0]])
        >>> target = jnp.array([[1.0, 0.0], [0.0, 1.0]])
        >>> cos = CosineSimilarity()
        >>> round(float(cos(preds, target)), 4)
        0.8536
    """

    def __init__(
        self,
        reduction: str = "mean",
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
        capacity: Optional[int] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
            capacity=capacity,
        )
        if reduction not in ("mean", "sum", "none", None):
            raise ValueError(f"Expected reduction to be one of 'mean', 'sum', 'none', got {reduction}")
        self.reduction = reduction

        if reduction in ("mean", "sum"):
            self.add_state("sim_sum", default=np.zeros((), dtype=np.float32), dist_reduce_fx="sum")
            self.add_state("n_total", default=np.zeros((), dtype=accum_int_dtype()), dist_reduce_fx="sum")
        else:
            # per-row scalars: item_shape=() lets `capacity` build the
            # jit-safe PaddedBuffer instead of an eager list
            self.add_state("sims", default=[], dist_reduce_fx=None, item_shape=())

    def update(self, preds: Array, target: Array) -> None:
        sim = _cosine_similarity_rows(preds, target)
        if self.reduction in ("mean", "sum"):
            self.sim_sum = self.sim_sum + jnp.sum(sim)
            self.n_total = self.n_total + sim.shape[0]
        else:
            self._append("sims", sim)

    def compute(self) -> Array:
        if self.reduction == "sum":
            return self.sim_sum
        if self.reduction == "mean":
            return self.sim_sum / jnp.maximum(self.n_total.astype(jnp.float32), 1.0)
        from metrics_tpu.parallel.buffer import as_values

        return as_values(self.sims)
