"""MeanAbsoluteError module (reference torchmetrics/regression/mean_absolute_error.py:26)."""
from typing import Any, Callable, Optional

import numpy as np
import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.regression.mean_absolute_error import (
    _mean_absolute_error_compute,
    _mean_absolute_error_update,
)
from metrics_tpu.utils.data import accum_int_dtype


class MeanAbsoluteError(Metric):
    """Accumulated mean absolute error.

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.array([2.5, 0.0, 2.0, 8.0])
        >>> mean_absolute_error = MeanAbsoluteError()
        >>> float(mean_absolute_error(preds, target))
        0.5
    """

    def __init__(
        self,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        self.add_state("sum_abs_error", default=np.zeros(()), dist_reduce_fx="sum")
        self.add_state("total", default=np.zeros((), dtype=accum_int_dtype()), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        sum_abs_error, n_obs = _mean_absolute_error_update(preds, target)
        self.sum_abs_error = self.sum_abs_error + sum_abs_error
        self.total = self.total + n_obs

    def compute(self) -> Array:
        return _mean_absolute_error_compute(self.sum_abs_error, self.total)
