"""KLDivergence module. Extension beyond the reference snapshot.

Streams through two scalar sum-states (one fused psum to sync).
"""
from typing import Any, Callable, Optional

import numpy as np
import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.regression.kl_divergence import _kld_update
from metrics_tpu.utils.data import accum_int_dtype


class KLDivergence(Metric):
    r"""Accumulated KL(p || q) over pairs of distributions.

    Example:
        >>> import jax.numpy as jnp
        >>> p = jnp.array([[0.36, 0.48, 0.16]])
        >>> q = jnp.array([[1/3, 1/3, 1/3]])
        >>> kld = KLDivergence()
        >>> round(float(kld(p, q)), 4)
        0.0853
    """

    def __init__(
        self,
        log_prob: bool = False,
        reduction: str = "mean",
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        if reduction not in ("mean", "sum"):
            raise ValueError(f"Expected reduction to be 'mean' or 'sum', got {reduction}")
        self.log_prob = log_prob
        self.reduction = reduction
        self.add_state("measure_sum", default=np.zeros((), dtype=np.float32), dist_reduce_fx="sum")
        self.add_state("total", default=np.zeros((), dtype=accum_int_dtype()), dist_reduce_fx="sum")

    def update(self, p: Array, q: Array) -> None:
        total, n = _kld_update(p, q, self.log_prob)
        self.measure_sum = self.measure_sum + total
        self.total = self.total + n

    def compute(self) -> Array:
        if self.reduction == "sum":
            return self.measure_sum
        return self.measure_sum / jnp.maximum(self.total.astype(jnp.float32), 1.0)
