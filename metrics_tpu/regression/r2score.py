"""R2Score module (reference torchmetrics/regression/r2score.py:23, states :121-124)."""
from typing import Any, Callable, Optional

import numpy as np
import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.regression.r2score import _r2score_compute, _r2score_update
from metrics_tpu.utils.data import accum_int_dtype


class R2Score(Metric):
    r"""Accumulated R² (coefficient of determination).

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([3, -0.5, 2, 7])
        >>> preds = jnp.array([2.5, 0.0, 2, 8])
        >>> r2score = R2Score()
        >>> round(float(r2score(preds, target)), 4)
        0.9486
        >>> target = jnp.array([[0.5, 1], [-1, 1], [7, -6]])
        >>> preds = jnp.array([[0, 2], [-1, 2], [8, -5]])
        >>> r2score = R2Score(num_outputs=2, multioutput='raw_values')
        >>> [round(float(v), 4) for v in r2score(preds, target)]
        [0.9654, 0.9082]
    """

    def __init__(
        self,
        num_outputs: int = 1,
        adjusted: int = 0,
        multioutput: str = "uniform_average",
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )

        self.num_outputs = num_outputs

        if adjusted < 0 or not isinstance(adjusted, int):
            raise ValueError("`adjusted` parameter should be an integer larger or equal to 0.")
        self.adjusted = adjusted

        allowed_multioutput = ("raw_values", "uniform_average", "variance_weighted")
        if multioutput not in allowed_multioutput:
            raise ValueError(
                f"Invalid input to argument `multioutput`. Choose one of the following: {allowed_multioutput}"
            )
        self.multioutput = multioutput

        self.add_state("sum_squared_error", default=np.zeros(num_outputs), dist_reduce_fx="sum")
        self.add_state("sum_error", default=np.zeros(num_outputs), dist_reduce_fx="sum")
        self.add_state("residual", default=np.zeros(num_outputs), dist_reduce_fx="sum")
        self.add_state("total", default=np.zeros((), dtype=accum_int_dtype()), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        sum_squared_error, sum_error, residual, total = _r2score_update(preds, target)
        self.sum_squared_error = self.sum_squared_error + sum_squared_error
        self.sum_error = self.sum_error + sum_error
        self.residual = self.residual + residual
        self.total = self.total + total

    def compute(self) -> Array:
        return _r2score_compute(
            self.sum_squared_error, self.sum_error, self.residual, self.total, self.adjusted, self.multioutput
        )
