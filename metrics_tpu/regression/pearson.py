"""PearsonCorrcoef module.

Extension beyond the reference snapshot (later torchmetrics ships it). The
whole metric is one ``(6,)`` co-moment state ``[n, mean_x, mean_y, M2x, M2y,
Cxy]`` with a Chan parallel-merge fold as its distributed reduction — centered
accumulation (no raw-moment cancellation), O(1) memory, and the same
associative merge powers the fused forward, cross-device sync, and checkpoint
shard merging. See ``metrics_tpu.functional.regression.pearson``.
"""
from typing import Any, Callable, Optional

import numpy as np
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.regression.pearson import batch_comoments, chan_fold, chan_merge, comoments_corrcoef


class PearsonCorrcoef(Metric):
    r"""Accumulated Pearson correlation coefficient.

    Returns ``nan`` when either accumulated input has zero variance
    (scipy convention).

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.array([2.5, 0.0, 2.0, 8.0])
        >>> pearson = PearsonCorrcoef()
        >>> round(float(pearson(preds, target)), 4)
        0.9849
    """

    def __init__(
        self,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        from metrics_tpu.utils.data import accum_int_dtype

        self.add_state("comoments", default=np.zeros((6,), dtype=np.float32), dist_reduce_fx=chan_fold)
        # exact integer sample count alongside the float32 n carried in the
        # comoment vector: float32 counts saturate at 2^24 (the merge weights
        # then degrade to a moving window), and int states get the shared
        # async overflow probe
        self.add_state("n_total", default=np.zeros((), dtype=accum_int_dtype()), dist_reduce_fx="sum")

    # float32 integers stop incrementing at 2^24; past this the comoment
    # merge weights nb/n are computed against a frozen n
    _F32_COUNT_SATURATION = 2**24

    def update(self, preds: Array, target: Array) -> None:
        self.comoments = chan_merge(self.comoments, batch_comoments(preds, target))
        self.n_total = self.n_total + preds.shape[0]

    def _host_warnings(self) -> None:
        # host-side bound (elements processed), NOT a device readback — a
        # single device->host readback per compute dominates wall-clock on
        # remote-attached accelerators. Runs from _wrap_compute even when the
        # compute cache is pre-seeded by forward_batched.
        super()._host_warnings()
        from metrics_tpu.utils.prints import rank_zero_warn

        if self._count_bound >= self._F32_COUNT_SATURATION:
            rank_zero_warn(
                f"{self.__class__.__name__} has processed ~{self._count_bound} samples; the float32"
                " sample count carried in the co-moment state saturates at 2^24, so further"
                " accumulation behaves as a ~16.7M-sample moving window rather than a true"
                " running mean.",
                UserWarning,
            )

    def compute(self) -> Array:
        return comoments_corrcoef(self.comoments)
