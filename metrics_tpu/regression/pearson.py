"""PearsonCorrcoef module.

Extension beyond the reference snapshot (later torchmetrics ships it);
streaming raw-moment sum-states, so the whole metric accumulates and syncs
like the other regression moments (one fused psum, no sample buffers).
"""
from typing import Any, Callable, Optional

import numpy as np
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.regression.pearson import _pearson_compute, _pearson_update


class PearsonCorrcoef(Metric):
    r"""Accumulated Pearson correlation coefficient.

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.array([2.5, 0.0, 2.0, 8.0])
        >>> pearson = PearsonCorrcoef()
        >>> round(float(pearson(preds, target)), 4)
        0.9849
    """

    def __init__(
        self,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        from metrics_tpu.utils.data import accum_int_dtype

        for name in ("sum_x", "sum_y", "sum_xx", "sum_yy", "sum_xy"):
            self.add_state(name, default=np.zeros((), dtype=np.float32), dist_reduce_fx="sum")
        # integer count in the package accumulator dtype: float32 counts stop
        # incrementing near 2^28 samples, and the int path gets the shared
        # overflow probe warning
        self.add_state("n_total", default=np.zeros((), dtype=accum_int_dtype()), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        sx, sy, sxx, syy, sxy, _ = _pearson_update(preds, target)
        self.sum_x = self.sum_x + sx
        self.sum_y = self.sum_y + sy
        self.sum_xx = self.sum_xx + sxx
        self.sum_yy = self.sum_yy + syy
        self.sum_xy = self.sum_xy + sxy
        self.n_total = self.n_total + preds.shape[0]

    def compute(self) -> Array:
        import jax.numpy as jnp

        return _pearson_compute(
            self.sum_x,
            self.sum_y,
            self.sum_xx,
            self.sum_yy,
            self.sum_xy,
            self.n_total.astype(jnp.float32),
        )
