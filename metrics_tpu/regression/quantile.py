"""Quantile / Percentile modules: the latency/distribution metric family.

The canonical production-serving question — "what is the p99 latency?" — has
no answer in moment-style regression metrics, and an exact answer needs the
whole sample. These metrics keep a constant-memory
:class:`~metrics_tpu.parallel.qsketch.QuantileSketch` instead (log-bucketed,
relative-accuracy ``alpha``): ``update`` is one jittable scatter-add,
``sync`` is one psum riding the coalesced sum buckets (bit-exact mergeable
across devices, processes, windows, and fleet shards), and ``compute``
answers ANY quantile within relative error ``alpha`` with a data-dependent
certificate (:meth:`Quantile.error_bound`).

Composition is the point: ``Keyed(Quantile(q=0.99), K)`` is per-tenant p99,
``Windowed(Keyed(Quantile(q=0.99), K), window_s=60)`` is per-tenant sliding
p99 — the canonical dashboard metric — and both sync with the IDENTICAL
staged collective program as the unkeyed scalar metric (the sketch is one
sum leaf; slots/windows are leading state axes). See
``docs/streaming.md`` for the recipe of record.
"""
from typing import Any, Callable, Optional, Sequence, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.parallel.qsketch import (
    QSKETCH_ALPHA,
    QSKETCH_MAX_VALUE,
    QSKETCH_MIN_VALUE,
    QuantileSketch,
    qsketch_update,
    qsketch_value_group_key,
    quantile_error_bound,
    quantile_from_counts,
    quantile_sketch_spec,
)

__all__ = ["Percentile", "Quantile"]


def _canonical_q(q: Union[float, Sequence[float]]) -> Union[float, tuple]:
    """Validate and canonicalize ``q`` to a float or tuple of floats (both
    hashable: the requested quantiles are ordinary fingerprintable config)."""
    if np.ndim(q) == 0:
        qf = float(q)
        if not 0.0 <= qf <= 1.0:
            raise ValueError(f"`q` must be in [0, 1], got {q!r}")
        return qf
    qs = tuple(float(v) for v in np.asarray(q).reshape(-1))
    if not qs:
        raise ValueError("`q` must name at least one quantile")
    if any(not 0.0 <= v <= 1.0 for v in qs):
        raise ValueError(f"every `q` must be in [0, 1], got {q!r}")
    return qs


class Quantile(Metric):
    r"""Accumulated quantile(s) of a value stream, to relative accuracy
    ``alpha``.

    Args:
        q: the quantile(s) to report — a float in ``[0, 1]`` (scalar
            ``compute()``) or a sequence (vector ``compute()``, one synced
            sketch answering all of them). ``q`` is COMPUTE-ONLY config:
            ``Quantile(q=0.5)``, ``Quantile(q=0.99)`` and
            ``Percentile(95)`` instances with equal grid config share one
            compute-group update plane inside a ``MetricCollection``.
        alpha: relative accuracy of the log-bucketed grid (DDSketch-style).
        min_value / max_value: the certified magnitude span. Values below
            ``min_value`` in magnitude report exactly ``0.0`` (absolute
            error under ``min_value``); values beyond ``max_value`` land in
            the signed overflow buckets, counted and ordered but flagged
            uncertified by :meth:`error_bound`.

    NaN values are DROPPED (masked scatter, PR 7's sketch convention);
    ``±inf`` clips into the signed overflow buckets. ``compute()`` is
    ``nan`` on an empty sketch.

    Example:
        >>> import jax.numpy as jnp
        >>> latency = Quantile(q=0.99)
        >>> latency.update(jnp.asarray([0.12, 0.31, 0.09, 4.2]))
        >>> float(latency.compute())  # doctest: +SKIP
        4.2
    """

    def __init__(
        self,
        q: Union[float, Sequence[float]] = 0.5,
        alpha: float = QSKETCH_ALPHA,
        min_value: float = QSKETCH_MIN_VALUE,
        max_value: float = QSKETCH_MAX_VALUE,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
        jit: Optional[bool] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
            jit=jit,
        )
        self.q = _canonical_q(q)
        spec = quantile_sketch_spec(alpha, min_value, max_value)
        self.alpha = spec.alpha
        self.min_value = spec.min_value
        self.max_value = spec.max_value
        self.add_state("qsketch", default=spec, dist_reduce_fx="sum")

    def update(self, values: Array) -> None:
        """Fold one batch of raw values into the sketch (any shape; raveled)."""
        self.qsketch = QuantileSketch(
            qsketch_update(
                self.qsketch.counts, jnp.asarray(values),
                self.alpha, self.min_value, self.max_value,
            )
        )

    def _group_fingerprint(self) -> Optional[Any]:
        # the requested q is compute-only: equal-grid Quantile/Percentile
        # instances share ONE scatter-add update plane and one synced sketch
        return qsketch_value_group_key(self)

    def compute(self) -> Array:
        return quantile_from_counts(
            self.qsketch.counts, self.q, self.alpha, self.min_value, self.max_value
        )

    def error_bound(self) -> Array:
        """Data-dependent certificate for the current :meth:`compute` value:
        per-quantile relative bound ``alpha`` (``|estimate - true| <=
        alpha * |true| + min_value``) wherever the rank resolves inside the
        certified span, ``inf`` where it resolves in an overflow bucket."""
        return quantile_error_bound(
            self.qsketch.counts, self.q, self.alpha, self.min_value, self.max_value
        )

    def __repr__(self) -> str:
        return f"{self.__class__.__name__}(q={self.q!r}, alpha={self.alpha!r})"


class Percentile(Quantile):
    """:class:`Quantile` addressed on the 0–100 percentile scale:
    ``Percentile(99)`` is ``Quantile(q=0.99)`` (same state, same compute
    group, same certificate)."""

    def __init__(
        self,
        p: Union[float, Sequence[float]] = 50.0,
        alpha: float = QSKETCH_ALPHA,
        min_value: float = QSKETCH_MIN_VALUE,
        max_value: float = QSKETCH_MAX_VALUE,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
        jit: Optional[bool] = None,
    ):
        if np.ndim(p) == 0:
            q: Union[float, tuple] = float(p) / 100.0
        else:
            q = tuple(float(v) / 100.0 for v in np.asarray(p).reshape(-1))
        super().__init__(
            q=q, alpha=alpha, min_value=min_value, max_value=max_value,
            compute_on_step=compute_on_step, dist_sync_on_step=dist_sync_on_step,
            process_group=process_group, dist_sync_fn=dist_sync_fn, jit=jit,
        )
