"""ExplainedVariance module.

Parity: reference torchmetrics/regression/explained_variance.py:26 — 5 "sum"
sufficient statistics (:101-105, changed from cat-state per reference
CHANGELOG "#68") so state is O(num_outputs) regardless of dataset size.
"""
from typing import Any, Callable, Optional, Sequence, Union

import numpy as np
import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.regression.explained_variance import (
    _explained_variance_compute,
    _explained_variance_update,
)


class ExplainedVariance(Metric):
    """Accumulated explained variance.

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([3, -0.5, 2, 7])
        >>> preds = jnp.array([2.5, 0.0, 2, 8])
        >>> explained_variance = ExplainedVariance()
        >>> round(float(explained_variance(preds, target)), 4)
        0.9572
    """

    def __init__(
        self,
        multioutput: str = "uniform_average",
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        allowed_multioutput = ("raw_values", "uniform_average", "variance_weighted")
        if multioutput not in allowed_multioutput:
            raise ValueError(
                f"Invalid input to argument `multioutput`. Choose one of the following: {allowed_multioutput}"
            )
        self.multioutput = multioutput
        self.add_state("sum_error", default=np.zeros(()), dist_reduce_fx="sum")
        self.add_state("sum_squared_error", default=np.zeros(()), dist_reduce_fx="sum")
        self.add_state("sum_target", default=np.zeros(()), dist_reduce_fx="sum")
        self.add_state("sum_squared_target", default=np.zeros(()), dist_reduce_fx="sum")
        self.add_state("n_obs", default=np.zeros(()), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        n_obs, sum_error, sum_squared_error, sum_target, sum_squared_target = _explained_variance_update(preds, target)
        self.n_obs = self.n_obs + n_obs
        self.sum_error = self.sum_error + sum_error
        self.sum_squared_error = self.sum_squared_error + sum_squared_error
        self.sum_target = self.sum_target + sum_target
        self.sum_squared_target = self.sum_squared_target + sum_squared_target

    def compute(self) -> Union[Array, Sequence[Array]]:
        return _explained_variance_compute(
            self.n_obs,
            self.sum_error,
            self.sum_squared_error,
            self.sum_target,
            self.sum_squared_target,
            self.multioutput,
        )
