"""TotalVariation module. Extension beyond the reference snapshot.

Streams two scalar sum-states (TV total + image count) — one fused psum to
sync, no cat-state growth.
"""
from typing import Any, Callable, Optional

import numpy as np
import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.regression.total_variation import _total_variation_update
from metrics_tpu.utils.data import accum_int_dtype


class TotalVariation(Metric):
    r"""Accumulated anisotropic total variation over image batches.

    Args:
        reduction: ``'sum'`` (total TV over all images) or ``'mean'``
            (average per-image TV).

    Example:
        >>> import jax.numpy as jnp
        >>> tv = TotalVariation()
        >>> img = jnp.arange(16.0).reshape(1, 1, 4, 4)
        >>> float(tv(img))
        60.0
    """

    def __init__(
        self,
        reduction: str = "sum",
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        if reduction not in ("sum", "mean"):
            raise ValueError(f"Expected reduction to be 'sum' or 'mean', got {reduction}")
        self.reduction = reduction
        self.add_state("score", default=np.zeros((), dtype=np.float32), dist_reduce_fx="sum")
        self.add_state("num_images", default=np.zeros((), dtype=accum_int_dtype()), dist_reduce_fx="sum")

    def update(self, img: Array) -> None:
        score, n = _total_variation_update(img)
        self.score = self.score + score
        self.num_images = self.num_images + n

    def compute(self) -> Array:
        if self.reduction == "mean":
            return self.score / jnp.maximum(self.num_images.astype(jnp.float32), 1.0)
        return self.score
