"""SpearmanCorrcoef module. Extension beyond the reference snapshot.

Ranks are global over the accumulated data, so the metric keeps cat-states
(bounded via ``capacity``); the epoch compute (ranking + correlation) runs as
one jitted device program shared across instances.

At pod scale, keep the epoch sharded instead of gathered: construct with
``capacity`` and place with ``metrics_tpu.parallel.row_sharded(mesh)`` —
``compute()`` then dispatches the exact sorted-pack ring
(``parallel/sharded_epoch.py::sharded_spearman``) with O(capacity / n)
per-device memory and no epoch materialization.
"""
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.regression.spearman import _spearman_jitted, _spearman_kernel
from metrics_tpu.parallel.buffer import as_values
from metrics_tpu.utils.checks import _check_same_shape


class SpearmanCorrcoef(Metric):
    r"""Accumulated Spearman rank correlation.

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([3.0, -0.5, 2.0, 1.0])
        >>> preds = jnp.array([2.5, 0.0, 2.0, 1.5])
        >>> spearman = SpearmanCorrcoef()
        >>> float(spearman(preds, target))
        1.0
    """

    def __init__(
        self,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
        capacity: Optional[int] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
            capacity=capacity,
        )
        self.add_state("preds_all", default=[], dist_reduce_fx=None, item_shape=())
        self.add_state("target_all", default=[], dist_reduce_fx=None, item_shape=())

    def update(self, preds: Array, target: Array) -> None:
        _check_same_shape(preds, target)
        if preds.ndim != 1:
            raise ValueError("Expected both `preds` and `target` to be 1D arrays of scalar predictions")
        self._append("preds_all", jnp.asarray(preds, dtype=jnp.float32))
        self._append("target_all", jnp.asarray(target, dtype=jnp.float32))

    def _states_own_sync(self) -> bool:
        from metrics_tpu.parallel.sharded_dispatch import rank_corr_applicable

        return rank_corr_applicable(self) is not None

    def compute(self) -> Array:
        from metrics_tpu.parallel.sharded_dispatch import spearman_sharded

        sharded = spearman_sharded(self)  # row-sharded epoch states: exact ring
        if sharded is not None:
            return sharded
        preds = as_values(self.preds_all)
        target = as_values(self.target_all)
        if preds.shape[0] == 0:
            return jnp.asarray(jnp.nan)  # no data: nan, matching the functional
        fn = _spearman_jitted if (self._jit is not False and not self._jit_failed) else _spearman_kernel
        return fn(preds, target)
