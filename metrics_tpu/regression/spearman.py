"""SpearmanCorrcoef module. Extension beyond the reference snapshot.

Ranks are global over the accumulated data, so the metric keeps cat-states
(bounded via ``capacity``); the epoch compute (ranking + correlation) runs as
one jitted device program shared across instances.

At pod scale, keep the epoch sharded instead of gathered: construct with
``capacity`` and place with ``metrics_tpu.parallel.row_sharded(mesh)`` —
``compute()`` then dispatches the exact sorted-pack ring
(``parallel/sharded_epoch.py::sharded_spearman``) with O(capacity / n)
per-device memory and no epoch materialization.
"""
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.regression.spearman import _spearman_jitted, _spearman_kernel
from metrics_tpu.parallel.buffer import as_values
from metrics_tpu.parallel.qsketch import (
    QSKETCH_RANK_ALPHA,
    QuantileSketch,
    qsketch_rank_group_key,
    qsketch_rank_spec,
    qsketch_rank_update,
)
from metrics_tpu.parallel.sketch import (
    RankSketch,
    canonicalize_approx,
    rank_collision_bound,
    rank_sketch_group_key,
    rank_sketch_spec,
    sketch_rank_update,
    spearman_from_joint,
)
from metrics_tpu.utils.checks import _check_same_shape
from metrics_tpu.utils.prints import rank_zero_warn_once


class SpearmanCorrcoef(Metric):
    r"""Accumulated Spearman rank correlation.

    ``approx="sketch"`` drops the O(samples) buffers for a constant-memory
    :class:`~metrics_tpu.parallel.sketch.RankSketch` — a ``num_bins ×
    num_bins`` joint histogram over per-variable grids (``sketch_range=
    (lo, hi)`` for a linear grid; the default ``None`` bins through a
    range-free monotone squash, which rank statistics are invariant to).
    ``compute`` is then the binned-rank (midrank) correlation: exactly
    scipy's tie-averaged Spearman for the binned data, approaching the
    unbinned value as the grid refines. ``update`` is one scatter-add and
    ``sync`` one psum (bit-exact mergeable across devices/processes).

    ``approx="qsketch"`` bins the joint histogram on the log-bucketed
    relative-accuracy grid of :mod:`~metrics_tpu.parallel.qsketch` instead
    (``alpha`` sets the grid; ``sketch_range`` must stay ``None``): a
    RANGE-FREE grid with real resolution at every magnitude — heavy-tailed
    and drifting value distributions keep per-decade bucket density where
    the soft-sign squash collapses them toward its end bins. Same one-psum
    sync contract; :meth:`collision_bound` reports the data-dependent
    resolution certificate.

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([3.0, -0.5, 2.0, 1.0])
        >>> preds = jnp.array([2.5, 0.0, 2.0, 1.5])
        >>> spearman = SpearmanCorrcoef()
        >>> float(spearman(preds, target))
        1.0
    """

    def __init__(
        self,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
        capacity: Optional[int] = None,
        approx: Optional[str] = None,
        num_bins: int = 512,
        sketch_range: Optional[Tuple[float, float]] = None,
        alpha: float = QSKETCH_RANK_ALPHA,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
            capacity=capacity,
        )
        self.approx = canonicalize_approx(approx, allowed=("sketch", "qsketch"))
        self.num_bins = num_bins
        self.sketch_range = None if sketch_range is None else tuple(sketch_range)
        self.alpha = float(alpha)
        if self.sketch_range is not None and len(self.sketch_range) != 2:
            raise ValueError(f"`sketch_range` must be None or a (lo, hi) pair, got {sketch_range!r}")
        if self.approx == "qsketch":
            if self.sketch_range is not None:
                raise ValueError(
                    "approx='qsketch' is range-free by construction (the log-bucketed"
                    " grid has no (lo, hi)); drop `sketch_range`, or use"
                    " approx='sketch' for the fixed linear grid"
                )
            self.add_state("joint", default=qsketch_rank_spec(self.alpha), dist_reduce_fx="sum")
            return
        if self.approx == "sketch":
            lo, hi = self.sketch_range if self.sketch_range is not None else (None, None)
            self.add_state("joint", default=rank_sketch_spec(num_bins, lo, hi), dist_reduce_fx="sum")
            return
        self.add_state("preds_all", default=[], dist_reduce_fx=None, item_shape=())
        self.add_state("target_all", default=[], dist_reduce_fx=None, item_shape=())
        rank_zero_warn_once(
            "Metric `SpearmanCorrcoef` stores every prediction and target in an"
            " O(samples) buffer state (ranks are global over the epoch), so"
            " memory and sync traffic grow with the dataset. Construct with"
            " `approx=\"qsketch\"` for a constant-memory RANGE-FREE joint rank"
            " sketch on the log-bucketed relative-accuracy grid, or"
            " `approx=\"sketch\"` for the fixed-grid variant — both sync with"
            " one psum; exact buffers remain the default."
        )

    def update(self, preds: Array, target: Array) -> None:
        _check_same_shape(preds, target)
        if preds.ndim != 1:
            raise ValueError("Expected both `preds` and `target` to be 1D arrays of scalar predictions")
        if self.approx == "qsketch":
            spec = self._defaults["joint"]
            self.joint = QuantileSketch(
                qsketch_rank_update(
                    self.joint.counts, jnp.asarray(preds), jnp.asarray(target),
                    spec.alpha, spec.min_value, spec.max_value,
                )
            )
            return
        if self.approx == "sketch":
            lo, hi = self.sketch_range if self.sketch_range is not None else (None, None)
            self.joint = RankSketch(
                sketch_rank_update(self.joint.counts, jnp.asarray(preds), jnp.asarray(target), lo, hi)
            )
            return
        self._append("preds_all", jnp.asarray(preds, dtype=jnp.float32))
        self._append("target_all", jnp.asarray(target, dtype=jnp.float32))

    def _group_fingerprint(self) -> Optional[Any]:
        # sketch-mode rank metrics (Spearman/Kendall) share ONE joint-histogram
        # update plane: equal sketch config -> one compute-group delta
        if self.approx == "qsketch":
            return qsketch_rank_group_key(self)
        if self.approx == "sketch":
            return rank_sketch_group_key(self)
        return super()._group_fingerprint()

    def _states_own_sync(self) -> bool:
        if self.approx in ("sketch", "qsketch"):
            return False  # sketch sync IS the psum plane
        from metrics_tpu.parallel.sharded_dispatch import rank_corr_applicable

        return rank_corr_applicable(self) is not None

    def collision_bound(self) -> Array:
        """Data-dependent resolution certificate of the sketch modes: the
        fraction of pairs colliding in one grid bucket on either variable —
        the only pairs the binned-rank statistic resolves as ties instead
        of exactly (see ``sketch.rank_collision_bound``)."""
        if self.approx not in ("sketch", "qsketch"):
            raise ValueError("collision_bound() needs approx='sketch' or 'qsketch'")
        return rank_collision_bound(self.joint.counts)

    def compute(self) -> Array:
        from metrics_tpu.parallel.sharded_dispatch import spearman_sharded

        if self.approx in ("sketch", "qsketch"):
            # both grids are strictly monotone: the binned-rank (midrank)
            # correlation over the joint counts is the statistic either way
            return spearman_from_joint(self.joint.counts)
        sharded = spearman_sharded(self)  # row-sharded epoch states: exact ring
        if sharded is not None:
            return sharded
        preds = as_values(self.preds_all)
        target = as_values(self.target_all)
        if preds.shape[0] == 0:
            return jnp.asarray(jnp.nan)  # no data: nan, matching the functional
        fn = _spearman_jitted if (self._jit is not False and not self._jit_failed) else _spearman_kernel
        return fn(preds, target)
