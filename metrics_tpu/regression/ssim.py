"""SSIM module.

Parity: reference torchmetrics/regression/ssim.py:24 — cat-states holding all
raw images (:77-78), so memory grows with the dataset. Two TPU-native
alternatives bound that memory:

- **streaming** (automatic when ``data_range`` is given and ``reduction`` is
  ``elementwise_mean``/``sum``): the per-pixel SSIM map is reduced at every
  ``update`` into two scalar sum-states — O(1) memory, jit-fusable, and
  cross-device sync is a single ``psum``. Equal to the stored-image compute
  up to float32 summation order (the global mean of concatenated maps is the
  ratio of accumulated sum and count).
- **bounded buffers**: pass ``capacity`` (max number of images) and
  ``image_shape`` (C, H, W) to keep reference semantics (e.g. inferred
  ``data_range``) with a fixed-size jit-safe PaddedBuffer.
"""
from typing import Any, Optional, Sequence, Tuple

import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.regression.ssim import (
    _check_ssim_params,
    _ssim_compute,
    _ssim_map,
    _ssim_update,
)
from metrics_tpu.utils.prints import rank_zero_warn, rank_zero_warn_once


class SSIM(Metric):
    """Accumulated structural similarity.

    With a static ``data_range`` and a mean/sum reduction the metric streams
    (O(1) sum-states); otherwise it stores images like the reference.

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.arange(0, 16 * 16, dtype=jnp.float32).reshape(1, 1, 16, 16) / 256
        >>> preds = target * 0.75
        >>> ssim = SSIM()
        >>> round(float(ssim(preds, target)), 4)
        0.924
    """

    def __init__(
        self,
        kernel_size: Sequence[int] = (11, 11),
        sigma: Sequence[float] = (1.5, 1.5),
        reduction: str = "elementwise_mean",
        data_range: Optional[float] = None,
        k1: float = 0.01,
        k2: float = 0.03,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        capacity: Optional[int] = None,
        image_shape: Optional[Tuple[int, int, int]] = None,
        streaming: Optional[bool] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            capacity=capacity,
        )
        _check_ssim_params(kernel_size, sigma)

        can_stream = data_range is not None and reduction in ("elementwise_mean", "sum")
        if streaming and not can_stream:
            raise ValueError(
                "`streaming=True` needs a static `data_range` and reduction"
                " 'elementwise_mean' or 'sum' (the per-update map reduction is"
                " exact only for those)."
            )
        if streaming is None:
            # an explicit bounded-buffer request wins over auto-streaming:
            # the caller asked for stored-image states
            streaming = can_stream and capacity is None and image_shape is None
        self.streaming = streaming

        if self.streaming:
            import numpy as np

            from metrics_tpu.utils.data import accum_int_dtype

            self.add_state("similarity", default=jnp.asarray(0.0), dist_reduce_fx="sum")
            # pixel counter in the package-wide accumulator dtype (int64 under
            # x64): int32 wraps at ~15k RGB 224x224 images, exactly the scale
            # streaming exists for; the shared overflow probe warns before that
            self.add_state("total", default=np.zeros((), dtype=accum_int_dtype()), dist_reduce_fx="sum")
        else:
            rank_zero_warn_once(
                "Metric `SSIM` will save all targets and"
                " predictions in buffer. For large datasets this may lead"
                " to large memory footprint."
            )
            self.add_state("y", default=[], dist_reduce_fx=None, item_shape=image_shape)
            self.add_state("y_pred", default=[], dist_reduce_fx=None, item_shape=image_shape)
        self.kernel_size = kernel_size
        self.sigma = sigma
        self.data_range = data_range
        self.k1 = k1
        self.k2 = k2
        self.reduction = reduction

    def update(self, preds: Array, target: Array) -> None:
        preds, target = _ssim_update(preds, target)
        if self.streaming:
            idx, _ = _ssim_map(
                preds, target, self.kernel_size, self.sigma, self.data_range, self.k1, self.k2
            )
            self.similarity = self.similarity + jnp.sum(idx)
            self.total = self.total + idx.size
        else:
            self._append("y_pred", preds)
            self._append("y", target)

    def compute(self) -> Array:
        if self.streaming:
            if self.reduction == "sum":
                return self.similarity
            return self.similarity / jnp.maximum(self.total, 1)
        from metrics_tpu.parallel.buffer import as_values

        preds = as_values(self.y_pred)
        target = as_values(self.y)
        return _ssim_compute(
            preds, target, self.kernel_size, self.sigma, self.reduction, self.data_range, self.k1, self.k2
        )
