"""SSIM module.

Parity: reference torchmetrics/regression/ssim.py:24 — cat-states holding all
raw images (:77-78), so memory grows with the dataset. To bound memory with
jit-safe PaddedBuffer states instead, pass both ``capacity`` (max number of
images) and ``image_shape`` (C, H, W).
"""
from typing import Any, Optional, Sequence, Tuple

from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.regression.ssim import _ssim_compute, _ssim_update
from metrics_tpu.utils.prints import rank_zero_warn


class SSIM(Metric):
    """Accumulated structural similarity (stores all images; memory grows with data).

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.arange(0, 16 * 16, dtype=jnp.float32).reshape(1, 1, 16, 16) / 256
        >>> preds = target * 0.75
        >>> ssim = SSIM()
        >>> round(float(ssim(preds, target)), 4)
        0.924
    """

    def __init__(
        self,
        kernel_size: Sequence[int] = (11, 11),
        sigma: Sequence[float] = (1.5, 1.5),
        reduction: str = "elementwise_mean",
        data_range: Optional[float] = None,
        k1: float = 0.01,
        k2: float = 0.03,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        capacity: Optional[int] = None,
        image_shape: Optional[Tuple[int, int, int]] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            capacity=capacity,
        )
        rank_zero_warn(
            "Metric `SSIM` will save all targets and"
            " predictions in buffer. For large datasets this may lead"
            " to large memory footprint."
        )

        self.add_state("y", default=[], dist_reduce_fx=None, item_shape=image_shape)
        self.add_state("y_pred", default=[], dist_reduce_fx=None, item_shape=image_shape)
        self.kernel_size = kernel_size
        self.sigma = sigma
        self.data_range = data_range
        self.k1 = k1
        self.k2 = k2
        self.reduction = reduction

    def update(self, preds: Array, target: Array) -> None:
        preds, target = _ssim_update(preds, target)
        self._append("y_pred", preds)
        self._append("y", target)

    def compute(self) -> Array:
        from metrics_tpu.parallel.buffer import as_values

        preds = as_values(self.y_pred)
        target = as_values(self.y)
        return _ssim_compute(
            preds, target, self.kernel_size, self.sigma, self.reduction, self.data_range, self.k1, self.k2
        )
