"""PSNR module.

Parity: reference torchmetrics/regression/psnr.py:24 — "sum" states when
``dim=None`` (:89-93); per-``dim`` mode uses cat-states; when ``data_range``
is unset, running min/max of the target are tracked with min/max reductions
(:102-103, where the reference passes ``torch.min``/``torch.max`` callables —
here the first-class 'min'/'max' reductions, which map to lax.pmin/pmax on
the mesh).
"""
from typing import Any, Callable, Optional, Sequence, Tuple, Union

import numpy as np
import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.regression.psnr import _psnr_compute, _psnr_update
from metrics_tpu.utils.data import accum_int_dtype, dim_zero_cat
from metrics_tpu.utils.prints import rank_zero_warn_once


class PSNR(Metric):
    r"""Accumulated peak signal-to-noise ratio.

    Example:
        >>> import jax.numpy as jnp
        >>> psnr = PSNR(data_range=8.0)
        >>> preds = jnp.array([[0.0, 1.0], [2.0, 3.0]])
        >>> target = jnp.array([[3.0, 2.0], [1.0, 0.0]])
        >>> round(float(psnr(preds, target)), 4)
        11.0721
    """

    def __init__(
        self,
        data_range: Optional[float] = None,
        base: float = 10.0,
        reduction: str = "elementwise_mean",
        dim: Optional[Union[int, Tuple[int, ...]]] = None,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
        )

        if dim is None and reduction != "elementwise_mean":
            rank_zero_warn_once(f"The `reduction={reduction}` will not have any effect when `dim` is None.")

        if dim is None:
            self.add_state("sum_squared_error", default=np.zeros(()), dist_reduce_fx="sum")
            self.add_state("total", default=np.zeros((), dtype=accum_int_dtype()), dist_reduce_fx="sum")
        else:
            self.add_state("sum_squared_error", default=[])
            self.add_state("total", default=[])

        if data_range is None:
            if dim is not None:
                raise ValueError("The `data_range` must be given when `dim` is not None.")
            self.data_range = None
            self.add_state("min_target", default=np.zeros(()), dist_reduce_fx="min")
            self.add_state("max_target", default=np.zeros(()), dist_reduce_fx="max")
        else:
            self.data_range = jnp.asarray(float(data_range))
        self.base = base
        self.reduction = reduction
        self.dim = tuple(dim) if isinstance(dim, Sequence) else dim

    def update(self, preds: Array, target: Array) -> None:
        sum_squared_error, n_obs = _psnr_update(preds, target, dim=self.dim)
        if self.dim is None:
            if self.data_range is None:
                # running min/max of targets (reference psnr.py:121-123)
                self.min_target = jnp.minimum(jnp.min(target), self.min_target)
                self.max_target = jnp.maximum(jnp.max(target), self.max_target)
            self.sum_squared_error = self.sum_squared_error + sum_squared_error
            self.total = self.total + n_obs
        else:
            self._append("sum_squared_error", sum_squared_error)
            self._append("total", n_obs)

    def compute(self) -> Array:
        data_range = self.data_range if self.data_range is not None else self.max_target - self.min_target

        if self.dim is None:
            sum_squared_error = self.sum_squared_error
            total = self.total
        else:
            sum_squared_error = dim_zero_cat([v.reshape(-1) for v in self.sum_squared_error])
            total = dim_zero_cat([v.reshape(-1) for v in self.total])
        return _psnr_compute(sum_squared_error, total, data_range, base=self.base, reduction=self.reduction)
