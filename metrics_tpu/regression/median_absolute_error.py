"""MedianAbsoluteError module: the robust error statistic the moment family
cannot express.

``MeanAbsoluteError`` keeps two scalars; the MEDIAN absolute error needs the
error distribution. This metric folds ``|preds - target|`` into a
constant-memory :class:`~metrics_tpu.parallel.qsketch.QuantileSketch`
(log-bucketed, relative accuracy ``alpha``) and reports its p50 — robust to
outliers the way the mean never is, mergeable across devices/processes/
windows by bit-exact integer addition, with the same data-dependent
certificate as :class:`~metrics_tpu.regression.quantile.Quantile`.
"""
from typing import Any, Callable, Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.parallel.qsketch import (
    QSKETCH_ALPHA,
    QSKETCH_MAX_VALUE,
    QSKETCH_MIN_VALUE,
    QuantileSketch,
    qsketch_update,
    qsketch_value_group_key,
    quantile_error_bound,
    quantile_from_counts,
    quantile_sketch_spec,
)
from metrics_tpu.utils.checks import _check_same_shape

__all__ = ["MedianAbsoluteError"]


class MedianAbsoluteError(Metric):
    r"""Median absolute error ``median(|preds - target|)`` over all data
    seen, to relative accuracy ``alpha``.

    The absolute errors live in the sketch's non-negative half-grid; errors
    below ``min_value`` report exactly ``0.0`` (absolute slack
    ``min_value``), NaN pairs are dropped via the masked scatter, ``±inf``
    errors clip into the overflow bucket (certificate-flagged).

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([2.5, 5.0, 4.0, 8.0])
        >>> preds = jnp.array([3.0, 5.0, 2.0, 7.0])
        >>> mdae = MedianAbsoluteError()
        >>> float(mdae(preds, target))  # doctest: +SKIP
        0.5
    """

    def __init__(
        self,
        alpha: float = QSKETCH_ALPHA,
        min_value: float = QSKETCH_MIN_VALUE,
        max_value: float = QSKETCH_MAX_VALUE,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
        jit: Optional[bool] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
            jit=jit,
        )
        spec = quantile_sketch_spec(alpha, min_value, max_value)
        self.alpha = spec.alpha
        self.min_value = spec.min_value
        self.max_value = spec.max_value
        self.add_state("qsketch", default=spec, dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        _check_same_shape(preds, target)
        err = jnp.abs(jnp.asarray(preds) - jnp.asarray(target))
        self.qsketch = QuantileSketch(
            qsketch_update(
                self.qsketch.counts, err, self.alpha, self.min_value, self.max_value
            )
        )

    def _group_fingerprint(self) -> Optional[Any]:
        # a distinct tag from the Quantile family: the update plane folds
        # |preds - target|, not raw values, so the deltas are not shareable
        return ("qsketch_mae",) + qsketch_value_group_key(self)[1:]

    def compute(self) -> Array:
        return quantile_from_counts(
            self.qsketch.counts, 0.5, self.alpha, self.min_value, self.max_value
        )

    def error_bound(self) -> Array:
        """Data-dependent certificate: ``|estimate - true median| <=
        alpha * true + min_value`` while the median rank resolves inside
        the certified span (``inf`` from the overflow bucket)."""
        return quantile_error_bound(
            self.qsketch.counts, 0.5, self.alpha, self.min_value, self.max_value
        )
