"""KendallRankCorrCoef module. Extension beyond the reference snapshot.

Ranks are global over the accumulated data, so the metric keeps cat-states
(bounded via ``capacity``), like [[SpearmanCorrcoef]]; the epoch compute is
the O(N^2) pairwise sign contraction in one jitted device program (see
``functional/regression/kendall.py``).

At pod scale, place the states with
``metrics_tpu.parallel.row_sharded(mesh)`` — ``compute()`` then runs the
same contraction ring-attention style (``sharded_epoch.py::sharded_kendall``)
with the quadratic cost split evenly across devices and O(capacity / n)
per-device memory.
"""
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.regression.kendall import _kendall_kernel, _warn_if_quadratic
from metrics_tpu.parallel.buffer import as_values
from metrics_tpu.utils.checks import _check_same_shape

_kendall_jitted = jax.jit(_kendall_kernel)


class KendallRankCorrCoef(Metric):
    r"""Accumulated Kendall rank correlation (tau-b, tie-corrected).

    Practical bound: the epoch compute is O(N^2) in the accumulated length,
    so pair it with ``capacity`` and keep the accumulated epoch below ~100k
    samples (the functional kernel warns beyond that); 1M rows would be
    ~10^12 pairwise ops.

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.array([1.0, 2.0, 3.0, 4.0])
        >>> target = jnp.array([1.0, 3.0, 2.0, 4.0])
        >>> kendall = KendallRankCorrCoef()
        >>> round(float(kendall(preds, target)), 4)
        0.6667
    """

    def __init__(
        self,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
        capacity: Optional[int] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
            capacity=capacity,
        )
        self.add_state("preds_all", default=[], dist_reduce_fx=None, item_shape=())
        self.add_state("target_all", default=[], dist_reduce_fx=None, item_shape=())

    def update(self, preds: Array, target: Array) -> None:
        _check_same_shape(preds, target)
        if preds.ndim != 1:
            raise ValueError("Expected both `preds` and `target` to be 1D arrays of scalar scores")
        self._append("preds_all", jnp.asarray(preds, dtype=jnp.float32))
        self._append("target_all", jnp.asarray(target, dtype=jnp.float32))

    def _states_own_sync(self) -> bool:
        from metrics_tpu.parallel.sharded_dispatch import rank_corr_applicable

        return rank_corr_applicable(self) is not None

    def compute(self) -> Array:
        from metrics_tpu.parallel.sharded_dispatch import kendall_sharded

        sharded = kendall_sharded(self)  # row-sharded epoch states: split O(N^2) ring
        if sharded is not None:
            return sharded
        preds = as_values(self.preds_all)
        target = as_values(self.target_all)
        if preds.shape[0] < 2:
            return jnp.asarray(jnp.nan)
        _warn_if_quadratic(preds.shape[0])
        fn = _kendall_jitted if (self._jit is not False and not self._jit_failed) else _kendall_kernel
        return fn(preds, target)
