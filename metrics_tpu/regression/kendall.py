"""KendallRankCorrCoef module. Extension beyond the reference snapshot.

Ranks are global over the accumulated data, so the metric keeps cat-states
(bounded via ``capacity``), like [[SpearmanCorrcoef]]; the epoch compute is
the O(N^2) pairwise sign contraction in one jitted device program (see
``functional/regression/kendall.py``).

At pod scale, place the states with
``metrics_tpu.parallel.row_sharded(mesh)`` — ``compute()`` then runs the
same contraction ring-attention style (``sharded_epoch.py::sharded_kendall``)
with the quadratic cost split evenly across devices and O(capacity / n)
per-device memory.
"""
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.regression.kendall import _kendall_kernel, _warn_if_quadratic
from metrics_tpu.parallel.buffer import as_values
from metrics_tpu.parallel.qsketch import (
    QSKETCH_RANK_ALPHA,
    QuantileSketch,
    qsketch_rank_group_key,
    qsketch_rank_spec,
    qsketch_rank_update,
)
from metrics_tpu.parallel.sketch import (
    RankSketch,
    canonicalize_approx,
    kendall_from_joint,
    rank_collision_bound,
    rank_sketch_group_key,
    rank_sketch_spec,
    sketch_rank_update,
)
from metrics_tpu.utils.checks import _check_same_shape
from metrics_tpu.utils.prints import rank_zero_warn_once

_kendall_jitted = jax.jit(_kendall_kernel)


class KendallRankCorrCoef(Metric):
    r"""Accumulated Kendall rank correlation (tau-b, tie-corrected).

    Practical bound: the epoch compute is O(N^2) in the accumulated length,
    so pair it with ``capacity`` and keep the accumulated epoch below ~100k
    samples (the functional kernel warns beyond that); 1M rows would be
    ~10^12 pairwise ops.

    ``approx="sketch"`` sidesteps both the O(samples) state AND the O(N^2)
    pairwise contraction: tau-b derives from a ``num_bins × num_bins``
    :class:`~metrics_tpu.parallel.sketch.RankSketch` joint histogram
    (concordance via 2-D suffix sums — O(num_bins^2), traffic-independent;
    same-bin pairs count as ties), the same sketch — and therefore the same
    compute group — as sketch-mode :class:`~metrics_tpu.regression.spearman.
    SpearmanCorrcoef`.

    ``approx="qsketch"`` bins the same joint histogram on the RANGE-FREE
    log-bucketed relative-accuracy grid (``alpha``; ``sketch_range`` must
    stay ``None``), keeping per-decade resolution on heavy-tailed values
    where the soft-sign squash collapses toward its end bins; shared with
    qsketch-mode Spearman, with :meth:`collision_bound` as the certificate.

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.array([1.0, 2.0, 3.0, 4.0])
        >>> target = jnp.array([1.0, 3.0, 2.0, 4.0])
        >>> kendall = KendallRankCorrCoef()
        >>> round(float(kendall(preds, target)), 4)
        0.6667
    """

    def __init__(
        self,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
        capacity: Optional[int] = None,
        approx: Optional[str] = None,
        num_bins: int = 512,
        sketch_range: Optional[Tuple[float, float]] = None,
        alpha: float = QSKETCH_RANK_ALPHA,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
            capacity=capacity,
        )
        self.approx = canonicalize_approx(approx, allowed=("sketch", "qsketch"))
        self.num_bins = num_bins
        self.sketch_range = None if sketch_range is None else tuple(sketch_range)
        self.alpha = float(alpha)
        if self.sketch_range is not None and len(self.sketch_range) != 2:
            raise ValueError(f"`sketch_range` must be None or a (lo, hi) pair, got {sketch_range!r}")
        if self.approx == "qsketch":
            if self.sketch_range is not None:
                raise ValueError(
                    "approx='qsketch' is range-free by construction (the log-bucketed"
                    " grid has no (lo, hi)); drop `sketch_range`, or use"
                    " approx='sketch' for the fixed linear grid"
                )
            self.add_state("joint", default=qsketch_rank_spec(self.alpha), dist_reduce_fx="sum")
            return
        if self.approx == "sketch":
            lo, hi = self.sketch_range if self.sketch_range is not None else (None, None)
            self.add_state("joint", default=rank_sketch_spec(num_bins, lo, hi), dist_reduce_fx="sum")
            return
        self.add_state("preds_all", default=[], dist_reduce_fx=None, item_shape=())
        self.add_state("target_all", default=[], dist_reduce_fx=None, item_shape=())
        rank_zero_warn_once(
            "Metric `KendallRankCorrCoef` stores every prediction and target in"
            " an O(samples) buffer state and computes an O(N^2) pairwise"
            " contraction at epoch end. Construct with `approx=\"qsketch\"` for"
            " a constant-memory RANGE-FREE joint rank sketch on the log-bucketed"
            " relative-accuracy grid, or `approx=\"sketch\"` for the fixed-grid"
            " variant (both psum-synced, O(bins^2) compute); exact buffers"
            " remain the default."
        )

    def update(self, preds: Array, target: Array) -> None:
        _check_same_shape(preds, target)
        if preds.ndim != 1:
            raise ValueError("Expected both `preds` and `target` to be 1D arrays of scalar scores")
        if self.approx == "qsketch":
            spec = self._defaults["joint"]
            self.joint = QuantileSketch(
                qsketch_rank_update(
                    self.joint.counts, jnp.asarray(preds), jnp.asarray(target),
                    spec.alpha, spec.min_value, spec.max_value,
                )
            )
            return
        if self.approx == "sketch":
            lo, hi = self.sketch_range if self.sketch_range is not None else (None, None)
            self.joint = RankSketch(
                sketch_rank_update(self.joint.counts, jnp.asarray(preds), jnp.asarray(target), lo, hi)
            )
            return
        self._append("preds_all", jnp.asarray(preds, dtype=jnp.float32))
        self._append("target_all", jnp.asarray(target, dtype=jnp.float32))

    def _group_fingerprint(self) -> Optional[Any]:
        # the same joint-histogram update plane as sketch-mode Spearman:
        # equal sketch config -> one shared compute-group delta
        if self.approx == "qsketch":
            return qsketch_rank_group_key(self)
        if self.approx == "sketch":
            return rank_sketch_group_key(self)
        return super()._group_fingerprint()

    def _states_own_sync(self) -> bool:
        if self.approx in ("sketch", "qsketch"):
            return False  # sketch sync IS the psum plane
        from metrics_tpu.parallel.sharded_dispatch import rank_corr_applicable

        return rank_corr_applicable(self) is not None

    def collision_bound(self) -> Array:
        """Data-dependent resolution certificate of the sketch modes: the
        colliding-pair fraction the binned statistic resolves as ties
        (see ``sketch.rank_collision_bound``)."""
        if self.approx not in ("sketch", "qsketch"):
            raise ValueError("collision_bound() needs approx='sketch' or 'qsketch'")
        return rank_collision_bound(self.joint.counts)

    def compute(self) -> Array:
        from metrics_tpu.parallel.sharded_dispatch import kendall_sharded

        if self.approx in ("sketch", "qsketch"):
            return kendall_from_joint(self.joint.counts)
        sharded = kendall_sharded(self)  # row-sharded epoch states: split O(N^2) ring
        if sharded is not None:
            return sharded
        preds = as_values(self.preds_all)
        target = as_values(self.target_all)
        if preds.shape[0] < 2:
            return jnp.asarray(jnp.nan)
        _warn_if_quadratic(preds.shape[0])
        fn = _kendall_jitted if (self._jit is not False and not self._jit_failed) else _kendall_kernel
        return fn(preds, target)
