"""ConcordanceCorrCoef module. Extension beyond the reference snapshot
(later torchmetrics ``regression/concordance.py``). Shares the Pearson
Chan-merge co-moment state verbatim — only the compute differs."""
from jax import Array

from metrics_tpu.functional.regression.concordance import comoments_concordance
from metrics_tpu.regression.pearson import PearsonCorrcoef


class ConcordanceCorrCoef(PearsonCorrcoef):
    r"""Accumulated Lin concordance correlation coefficient.

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.array([2.5, 0.0, 2.0, 8.0])
        >>> ccc = ConcordanceCorrCoef()
        >>> round(float(ccc(preds, target)), 4)
        0.9768
    """

    def compute(self) -> Array:
        return comoments_concordance(self.comoments)
