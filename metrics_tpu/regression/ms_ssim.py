"""MultiScaleSSIM module. Extension beyond the reference snapshot (later
torchmetrics ``image/ms_ssim.py``).

Streams per-image MS-SSIM values into sum/count states (requires a static
``data_range``, like streaming SSIM): O(1) memory, one psum to sync.
"""
from typing import Any, Callable, Optional, Sequence, Tuple

from jax import Array

from metrics_tpu.core.streaming import SumCountMetric
from metrics_tpu.functional.regression.ms_ssim import _DEFAULT_BETAS, multiscale_ssim


class MultiScaleSSIM(SumCountMetric):
    r"""Accumulated multi-scale SSIM (mean of per-image values).

    Args:
        data_range: REQUIRED static value range of the images (streaming
            accumulation cannot defer it to compute time).
        kernel_size / sigma / k1 / k2 / betas: see ``multiscale_ssim``.

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.arange(0, 96 * 96, dtype=jnp.float32).reshape(1, 1, 96, 96) / (96 * 96)
        >>> preds = target * 0.75
        >>> ms = MultiScaleSSIM(data_range=1.0, kernel_size=(5, 5))
        >>> round(float(ms(preds, target)), 4)
        0.9645
    """

    def __init__(
        self,
        data_range: float,
        kernel_size: Sequence[int] = (11, 11),
        sigma: Sequence[float] = (1.5, 1.5),
        k1: float = 0.01,
        k2: float = 0.03,
        betas: Sequence[float] = _DEFAULT_BETAS,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        if data_range is None:
            raise ValueError("streaming MultiScaleSSIM requires a static `data_range`")
        self.data_range = float(data_range)
        self.kernel_size = tuple(kernel_size)
        self.sigma = tuple(sigma)
        self.k1 = k1
        self.k2 = k2
        self.betas = tuple(betas)

    def _update_stats(self, preds: Array, target: Array) -> Tuple[Array, Any]:
        import jax.numpy as jnp

        per_image = multiscale_ssim(
            preds, target, self.kernel_size, self.sigma, "none", self.data_range,
            self.k1, self.k2, self.betas,
        )
        return jnp.sum(per_image), per_image.shape[0]
