"""MAPE / SMAPE / WMAPE modules. Extension beyond the reference snapshot
(later torchmetrics regression package). All are two-sum streaming states."""
from typing import Any, Callable, Optional

import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.regression.mape import _EPS, _mape_update, _smape_update, _wmape_update
from metrics_tpu.utils.data import accum_int_dtype


class _RatioSumMetric(Metric):
    """sum-of-ratios / count accumulation shared by MAPE and SMAPE."""

    def __init__(
        self,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        self.add_state("sum_ratio", default=np.zeros(()), dist_reduce_fx="sum")
        self.add_state("total", default=np.zeros((), dtype=accum_int_dtype()), dist_reduce_fx="sum")

    def compute(self) -> Array:
        return self.sum_ratio / jnp.maximum(self.total, 1).astype(jnp.float32)


class MeanAbsolutePercentageError(_RatioSumMetric):
    r"""Accumulated MAPE: mean of ``|preds - target| / max(|target|, eps)``.

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([1.0, 10.0, 1e6])
        >>> preds = jnp.array([0.9, 15.0, 1.2e6])
        >>> mape = MeanAbsolutePercentageError()
        >>> round(float(mape(preds, target)), 4)
        0.2667
    """

    def update(self, preds: Array, target: Array) -> None:
        sum_ratio, n_obs = _mape_update(preds, target)
        self.sum_ratio = self.sum_ratio + sum_ratio
        self.total = self.total + n_obs


class SymmetricMeanAbsolutePercentageError(_RatioSumMetric):
    r"""Accumulated SMAPE: mean of ``2 |p - t| / max(|p| + |t|, eps)``.

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([1.0, 10.0, 1e6])
        >>> preds = jnp.array([0.9, 15.0, 1.2e6])
        >>> smape = SymmetricMeanAbsolutePercentageError()
        >>> round(float(smape(preds, target)), 4)
        0.229
    """

    def update(self, preds: Array, target: Array) -> None:
        sum_ratio, n_obs = _smape_update(preds, target)
        self.sum_ratio = self.sum_ratio + sum_ratio
        self.total = self.total + n_obs


class WeightedMeanAbsolutePercentageError(Metric):
    r"""Accumulated WMAPE: ``sum |preds - target| / sum |target|``.

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([1.0, 10.0, 100.0])
        >>> preds = jnp.array([0.9, 15.0, 110.0])
        >>> wmape = WeightedMeanAbsolutePercentageError()
        >>> round(float(wmape(preds, target)), 4)
        0.136
    """

    def __init__(
        self,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        self.add_state("sum_abs_error", default=np.zeros(()), dist_reduce_fx="sum")
        self.add_state("sum_abs_target", default=np.zeros(()), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        abs_error, abs_target = _wmape_update(preds, target)
        self.sum_abs_error = self.sum_abs_error + abs_error
        self.sum_abs_target = self.sum_abs_target + abs_target

    def compute(self) -> Array:
        return self.sum_abs_error / jnp.maximum(self.sum_abs_target, _EPS)
