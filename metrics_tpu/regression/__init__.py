from metrics_tpu.regression.cosine_similarity import CosineSimilarity
from metrics_tpu.regression.explained_variance import ExplainedVariance
from metrics_tpu.regression.kl_divergence import KLDivergence
from metrics_tpu.regression.mean_absolute_error import MeanAbsoluteError
from metrics_tpu.regression.median_absolute_error import MedianAbsoluteError
from metrics_tpu.regression.quantile import Percentile, Quantile
from metrics_tpu.regression.mean_squared_error import MeanSquaredError
from metrics_tpu.regression.mean_squared_log_error import MeanSquaredLogError
from metrics_tpu.regression.pearson import PearsonCorrcoef
from metrics_tpu.regression.psnr import PSNR
from metrics_tpu.regression.r2score import R2Score
from metrics_tpu.regression.relative_squared import RelativeSquaredError
from metrics_tpu.regression.kendall import KendallRankCorrCoef
from metrics_tpu.regression.spearman import SpearmanCorrcoef
from metrics_tpu.regression.total_variation import TotalVariation
from metrics_tpu.regression.ssim import SSIM
from metrics_tpu.regression.mape import (
    MeanAbsolutePercentageError,
    SymmetricMeanAbsolutePercentageError,
    WeightedMeanAbsolutePercentageError,
)
from metrics_tpu.regression.tweedie import TweedieDevianceScore
from metrics_tpu.regression.ms_ssim import MultiScaleSSIM
from metrics_tpu.regression.concordance import ConcordanceCorrCoef
from metrics_tpu.regression.uqi import UniversalImageQualityIndex
from metrics_tpu.regression.spectral import (
    ErrorRelativeGlobalDimensionlessSynthesis,
    SpectralAngleMapper,
)
from metrics_tpu.regression.minkowski import LogCoshError, MinkowskiDistance
