"""SpectralAngleMapper / ERGAS modules. Extensions beyond the reference
snapshot (later torchmetrics image package). Both stream per-image values
through the sum/count base."""
from typing import Any, Callable, Optional, Tuple

import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.streaming import SumCountMetric
from metrics_tpu.functional.regression.spectral import (
    error_relative_global_dimensionless_synthesis,
    spectral_angle_mapper,
)


class SpectralAngleMapper(SumCountMetric):
    r"""Accumulated mean spectral angle (radians) over images seen.

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.stack([jnp.ones((1, 8, 8)), jnp.zeros((1, 8, 8))], axis=1)
        >>> preds = jnp.stack([jnp.ones((1, 8, 8)), jnp.ones((1, 8, 8))], axis=1)
        >>> sam = SpectralAngleMapper()
        >>> round(float(sam(preds, target)), 4)
        0.7854
    """

    def _update_stats(self, preds: Array, target: Array) -> Tuple[Array, Any]:
        values = spectral_angle_mapper(preds, target, reduction="none")
        return jnp.sum(values), values.shape[0]


class ErrorRelativeGlobalDimensionlessSynthesis(SumCountMetric):
    r"""Accumulated ERGAS (mean of per-image values; lower is better).

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.ones((1, 2, 8, 8))
        >>> ergas = ErrorRelativeGlobalDimensionlessSynthesis()
        >>> round(float(ergas(target * 0.9, target)), 4)
        40.0
    """

    def __init__(
        self,
        ratio: float = 4.0,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        if ratio <= 0:
            raise ValueError(f"`ratio` must be positive, got {ratio!r}")
        self.ratio = float(ratio)

    def _update_stats(self, preds: Array, target: Array) -> Tuple[Array, Any]:
        values = error_relative_global_dimensionless_synthesis(preds, target, self.ratio, reduction="none")
        return jnp.sum(values), values.shape[0]
