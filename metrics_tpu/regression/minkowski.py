"""LogCoshError / MinkowskiDistance modules. Extensions beyond the reference
snapshot (later torchmetrics regression package)."""
from typing import Any, Callable, Optional, Tuple


import numpy as np
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.core.streaming import SumCountMetric
from metrics_tpu.functional.regression.minkowski import _log_cosh_update, _minkowski_update


class LogCoshError(SumCountMetric):
    r"""Accumulated mean log-cosh error.

    Example:
        >>> import jax.numpy as jnp
        >>> metric = LogCoshError()
        >>> round(float(metric(jnp.array([0.5, 1.0, 2.5]), jnp.array([0.0, 1.0, 2.0]))), 4)
        0.0801
    """

    def _update_stats(self, preds: Array, target: Array) -> Tuple[Array, Any]:
        return _log_cosh_update(preds, target)


class MinkowskiDistance(Metric):
    r"""Accumulated Minkowski distance ``(sum |p - t|^p)^(1/p)`` over all
    data seen (the p-th powers are the sum state, so accumulation order and
    sharding do not change the result).

    Example:
        >>> import jax.numpy as jnp
        >>> metric = MinkowskiDistance(p=2)
        >>> round(float(metric(jnp.array([0.5, 1.0, 2.5]), jnp.array([0.0, 1.0, 2.0]))), 4)
        0.7071
    """

    def __init__(
        self,
        p: float = 2.0,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        if not p >= 1:
            raise ValueError(f"`p` must be >= 1, got {p!r}")
        self.p = float(p)
        self.add_state("sum_pow", default=np.zeros(()), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        self.sum_pow = self.sum_pow + _minkowski_update(preds, target, self.p)

    def compute(self) -> Array:
        return self.sum_pow ** (1.0 / self.p)
