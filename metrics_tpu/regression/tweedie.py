"""TweedieDevianceScore module. Extension beyond the reference snapshot
(later torchmetrics ``regression/tweedie_deviance.py``)."""
from typing import Any, Callable, Optional, Tuple

from jax import Array

from metrics_tpu.core.streaming import SumCountMetric
from metrics_tpu.functional.regression.tweedie import _tweedie_update


class TweedieDevianceScore(SumCountMetric):
    r"""Accumulated mean Tweedie deviance (``power`` 0 / 1 / 2 / (1, 2)).

    Example:
        >>> import jax.numpy as jnp
        >>> metric = TweedieDevianceScore(power=1)
        >>> round(float(metric(jnp.array([2.0, 0.5, 1.0]), jnp.array([1.5, 1.0, 1.0]))), 4)
        0.1744
    """

    def __init__(
        self,
        power: float = 0.0,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        if not (power in (0, 1, 2) or 1 < power < 2):
            raise ValueError(
                f"`power` must be 0, 1, 2, or in (1, 2) (compound Poisson-Gamma), got {power!r}"
            )
        self.power = power

    def _update_stats(self, preds: Array, target: Array) -> Tuple[Array, Any]:
        return _tweedie_update(preds, target, self.power)
