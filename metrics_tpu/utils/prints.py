"""Rank-zero gated warnings/logging.

Parity target: reference ``torchmetrics/utilities/prints.py`` (rank_zero_only
at prints.py:21, rank_zero_warn/info/debug at :47-49). TPU-native difference:
rank is ``jax.process_index()`` (multi-host JAX), with the ``LOCAL_RANK`` env
var still honored as an override for externally-launched process groups.
"""
import logging
import os
import warnings
from functools import wraps
from typing import Any, Callable

log = logging.getLogger("metrics_tpu")


def _current_rank() -> int:
    for env_var in ("LOCAL_RANK", "SLURM_PROCID"):
        if env_var in os.environ:
            return int(os.environ[env_var])
    try:
        import jax

        return jax.process_index()
    except Exception:  # pragma: no cover - jax always importable here
        return 0


def rank_zero_only(fn: Callable) -> Callable:
    """Decorator: run ``fn`` only on process 0.

    The rank is resolved lazily on first use (NOT at import): eagerly calling
    ``jax.process_index()`` would initialize the JAX backend at import time,
    before the user can call ``jax.distributed.initialize()`` or adjust
    platform config. An explicit ``rank_zero_only.rank = r`` override is
    honored and never recomputed.
    """

    @wraps(fn)
    def wrapped_fn(*args: Any, **kwargs: Any) -> Any:
        rank = getattr(rank_zero_only, "rank", None)
        if rank is None:
            rank = rank_zero_only.rank = _current_rank()
        if rank == 0:
            return fn(*args, **kwargs)
        return None

    return wrapped_fn


def _warn(*args: Any, **kwargs: Any) -> None:
    warnings.warn(*args, **kwargs)


def _info(*args: Any, **kwargs: Any) -> None:
    log.info(*args, **kwargs)


def _debug(*args: Any, **kwargs: Any) -> None:
    log.debug(*args, **kwargs)


rank_zero_debug = rank_zero_only(_debug)
rank_zero_info = rank_zero_only(_info)
rank_zero_warn = rank_zero_only(_warn)


# messages already emitted through rank_zero_warn_once (process lifetime)
_WARN_ONCE_SEEN: set = set()


def rank_zero_warn_once(message: str, *args: Any, **kwargs: Any) -> None:
    """``rank_zero_warn`` deduplicated by message text for the process
    lifetime.

    For advisory notices that are a property of a CONFIGURATION, not of an
    instance — e.g. the curve metrics' "will save all targets and
    predictions in buffer" capacity note, which otherwise fires once per
    metric per run in a multi-metric bench tail. Python's own warning
    registry dedups per call site, not per message, so six metric classes
    each warn separately without this guard. Tests can clear
    ``_WARN_ONCE_SEEN`` to re-arm.
    """
    if message in _WARN_ONCE_SEEN:
        return
    _WARN_ONCE_SEEN.add(message)
    rank_zero_warn(message, *args, **kwargs)
