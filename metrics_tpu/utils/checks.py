"""Classification input normalization and validation.

Behavioral parity target: reference ``torchmetrics/utilities/checks.py`` —
``_input_format_classification`` (checks.py:306-445, full input-type taxonomy in
its docstring) and ``_check_classification_inputs`` (checks.py:207-303).

TPU-native split: the reference interleaves *shape/dtype* logic (static) with
*value* logic (data-dependent raises, class-count inference from ``max()``).
XLA traces once with abstract values, so here:

* ``_resolve_case`` — the ``DataType`` taxonomy — depends only on ndim/dtype
  and is evaluated at trace time (a direct consequence of the reference's own
  rules at checks.py:87-112, which never look at values).
* value validation (non-negative targets, probabilities in [0,1], label bounds,
  rows-sum-to-1 — checks.py:29-57, 274-288) runs only on concrete arrays: on
  by default in the eager API, automatically skipped under ``jit`` tracing.
* class-count inference from data values (checks.py:426) is eager-only; under
  tracing, ``num_classes`` must be passed statically.
"""
from typing import Optional, Tuple

import threading
from contextlib import contextmanager

import numpy as np

import jax.numpy as jnp
from jax import Array

from metrics_tpu.utils.data import is_concrete, select_topk, to_onehot
from metrics_tpu.utils.enums import DataType
from metrics_tpu.utils.exceptions import TracingUnsupportedError


def _check_same_shape(preds: Array, target: Array) -> None:
    if preds.shape != target.shape:
        raise RuntimeError("Predictions and targets are expected to have the same shape")


def _is_float(x: Array) -> bool:
    return jnp.issubdtype(x.dtype, jnp.floating)


def _squeeze_excess_dims(preds: Array, target: Array) -> Tuple[Array, Array]:
    """Drop all size-1 dims except a size-1 leading batch dim (checks.py:394-398)."""
    if preds.shape and preds.shape[0] == 1:
        return jnp.expand_dims(jnp.squeeze(preds), 0), jnp.expand_dims(jnp.squeeze(target), 0)
    return jnp.squeeze(preds), jnp.squeeze(target)


def _resolve_case(preds: Array, target: Array) -> Tuple[DataType, int]:
    """Static (shape/dtype-only) resolution of the input case + implied classes.

    Mirrors the decision table of reference checks.py:60-119.
    """
    preds_float = _is_float(preds)
    if _is_float(target):
        raise ValueError("The `target` has to be an integer tensor.")

    if preds.ndim == target.ndim:
        if preds.shape != target.shape:
            raise ValueError(
                "The `preds` and `target` should have the same shape,"
                f" got `preds` with shape={preds.shape} and `target` with shape={target.shape}."
            )
        if preds.ndim == 1 and preds_float:
            case = DataType.BINARY
        elif preds.ndim == 1 and not preds_float:
            case = DataType.MULTICLASS
        elif preds.ndim > 1 and preds_float:
            case = DataType.MULTILABEL
        else:
            case = DataType.MULTIDIM_MULTICLASS
        # shapes are host ints — no device op for a static product
        implied_classes = int(np.prod(preds.shape[1:])) if preds.ndim > 1 else 1
    elif preds.ndim == target.ndim + 1:
        if not preds_float:
            raise ValueError("If `preds` have one dimension more than `target`, `preds` should be a float tensor.")
        if preds.shape[2:] != target.shape[1:]:
            raise ValueError(
                "If `preds` have one dimension more than `target`, the shape of `preds` should be"
                " (N, C, ...), and the shape of `target` should be (N, ...)."
            )
        implied_classes = preds.shape[1]
        case = DataType.MULTICLASS if preds.ndim == 2 else DataType.MULTIDIM_MULTICLASS
    else:
        raise ValueError(
            "Either `preds` and `target` both should have the (same) shape (N, ...), or `target` should be (N, ...)"
            " and `preds` should be (N, C, ...)."
        )
    return case, implied_classes


def _validate_values(
    preds: Array,
    target: Array,
    case: DataType,
    implied_classes: int,
    threshold: float,
    num_classes: Optional[int],
    is_multiclass: Optional[bool],
    sum_atol: float = 1e-8,
) -> None:
    """Value-dependent validation — concrete arrays only (reference checks.py:29-57, 81-84, 274-288).

    All checks are evaluated as on-device boolean flags and read back in ONE
    device-to-host transfer: through a remote-device tunnel each scalar
    readback costs a full round trip (~100 ms), so the reference's
    one-``.item()``-per-check structure is the single dominant cost of the
    eager API. Error precedence matches the reference's check order.
    """
    preds_float = _is_float(preds)
    multiclass_case = case in (DataType.MULTICLASS, DataType.MULTIDIM_MULTICLASS)

    # (condition-expression, message) in reference order; conditions are traced
    # lazily so inapplicable checks cost nothing
    checks = [(jnp.min(target) < 0, "The `target` has to be a non-negative tensor.")]
    if not preds_float:
        checks.append((jnp.min(preds) < 0, "If `preds` are integers, they have to be non-negative."))
    if preds_float:
        checks.append((
            (jnp.min(preds) < 0) | (jnp.max(preds) > 1),
            "The `preds` should be probabilities, but values were detected outside of [0,1] range.",
        ))
    if is_multiclass is False:
        checks.append((jnp.max(target) > 1, "If you set `is_multiclass=False`, then `target` should not exceed 1."))
        if not preds_float:
            checks.append((
                jnp.max(preds) > 1,
                "If you set `is_multiclass=False` and `preds` are integers, then `preds` should not exceed 1.",
            ))
    if preds.ndim == target.ndim and preds_float:
        checks.append((
            jnp.max(target) > 1,
            "If `preds` and `target` are of shape (N, ...) and `preds` are floats, `target` should be binary.",
        ))
    if multiclass_case and preds_float:
        checks.append((
            ~jnp.all(jnp.isclose(jnp.sum(preds, axis=1), 1.0, atol=sum_atol)),
            "Probabilities in `preds` must sum up to 1 across the `C` dimension.",
        ))
    if preds.shape != target.shape:
        checks.append((
            jnp.max(target) >= implied_classes,
            "The highest label in `target` should be smaller than the size of the `C` dimension of `preds`.",
        ))
    if num_classes and num_classes > 1 and multiclass_case:
        checks.append((
            jnp.max(target) >= num_classes,
            "The highest label in `target` should be smaller than `num_classes`.",
        ))
        if not preds_float:
            checks.append((
                jnp.max(preds) >= num_classes,
                "The highest label in `preds` should be smaller than `num_classes`.",
            ))

    flags_dev = jnp.stack([c for c, _ in checks])
    try:
        flags_dev.copy_to_host_async()  # overlap the readback with other work
    except (AttributeError, RuntimeError):
        pass

    def finalize() -> None:
        flags = np.asarray(flags_dev)  # ONE readback
        for flag, (_, message) in zip(flags, checks):
            if flag:
                raise ValueError(message)

    defer_or_run_value_check(finalize)


# ------------------------------------------------- deferred value-check window
# Device-to-host readbacks have ~100 ms latency through remote-device tunnels.
# Value checks need a readback before they can raise; inside a
# ``deferred_value_checks()`` window the raise is postponed (finalizers are
# collected, their async copies all in flight together) so one wait covers
# every check plus the result computation. Checks still raise in their
# original order. Thread-local: concurrent metric threads don't share windows.
_DEFERRED_CHECKS = threading.local()


@contextmanager
def deferred_value_checks():
    prev = getattr(_DEFERRED_CHECKS, "pending", None)
    _DEFERRED_CHECKS.pending = pending = []
    try:
        yield
    finally:
        _DEFERRED_CHECKS.pending = prev
    for finalize in pending:  # raises propagate only on clean exit
        finalize()


def defer_or_run_value_check(finalize) -> None:
    pending = getattr(_DEFERRED_CHECKS, "pending", None)
    if pending is None:
        finalize()
    else:
        pending.append(finalize)


# ---------------------------------------------- shared canonicalization memo
# A MetricCollection step canonicalizes the SAME (preds, target) pair once per
# compute group — e.g. sync8's Accuracy group and StatScores group each run
# ``_input_format_classification`` over the full batch. Inside a
# ``shared_input_format()`` window the first call's result is memoized by
# argument identity and equivalent-config key, so every further group reuses
# the one canonicalized pair. Keys use ``id()`` of the arrays: concrete arrays
# and jit tracers alike are stable for the window's lifetime (the window is
# one step call / one trace), and a miss only costs the redundant work we do
# today. Thread-local, nestable, and never active unless a collection opens
# the window.
_CANON_MEMO = threading.local()


@contextmanager
def shared_input_format():
    """Open a memoization window for :func:`_input_format_classification`."""
    prev = getattr(_CANON_MEMO, "table", None)
    _CANON_MEMO.table = {}
    try:
        yield
    finally:
        _CANON_MEMO.table = prev


def _canon_memo_key(
    preds: Array,
    target: Array,
    threshold: float,
    top_k: Optional[int],
    num_classes: Optional[int],
    is_multiclass: Optional[bool],
    validate: bool,
) -> tuple:
    # float multiclass inputs resolve num_classes to the C dim regardless of
    # whether the caller passed it — fold None and the matching explicit value
    # into one key so e.g. Accuracy(num_classes=None) shares with
    # StatScores(num_classes=C)
    effective = num_classes
    if preds.ndim == target.ndim + 1 and num_classes in (None, preds.shape[1]):
        effective = preds.shape[1]
    return (
        id(preds), id(target), float(threshold), top_k, effective,
        is_multiclass, bool(validate),
    )


def _validate_static(
    case: DataType,
    implied_classes: int,
    preds_float: bool,
    threshold: float,
    num_classes: Optional[int],
    is_multiclass: Optional[bool],
    top_k: Optional[int],
) -> None:
    """Shape/arg consistency checks that need no data values
    (reference checks.py:122-204, 280-301)."""
    if not 0 < threshold < 1:
        raise ValueError(f"The `threshold` should be a float in the (0,1) interval, got {threshold}")

    if num_classes:
        if case == DataType.BINARY:
            if num_classes > 2:
                raise ValueError("Your data is binary, but `num_classes` is larger than 2.")
            if num_classes == 2 and not is_multiclass:
                raise ValueError(
                    "Your data is binary and `num_classes=2`, but `is_multiclass` is not True."
                    " Set it to True if you want to transform binary data to multi-class format."
                )
            if num_classes == 1 and is_multiclass:
                raise ValueError(
                    "You have binary data and have set `is_multiclass=True`, but `num_classes` is 1."
                    " Either set `is_multiclass=None`(default) or set `num_classes=2`"
                    " to transform binary data to multi-class format."
                )
        elif case in (DataType.MULTICLASS, DataType.MULTIDIM_MULTICLASS):
            if num_classes == 1 and is_multiclass is not False:
                raise ValueError(
                    "You have set `num_classes=1`, but predictions are integers."
                    " If you want to convert (multi-dimensional) multi-class data with 2 classes"
                    " to binary/multi-label, set `is_multiclass=False`."
                )
            if num_classes > 1:
                if is_multiclass is False and implied_classes != num_classes:
                    raise ValueError(
                        "You have set `is_multiclass=False`, but the implied number of classes "
                        " (from shape of inputs) does not match `num_classes`."
                    )
                if preds_float and implied_classes > 1 and num_classes != implied_classes:
                    raise ValueError("The size of C dimension of `preds` does not match `num_classes`.")
        elif case == DataType.MULTILABEL:
            if is_multiclass and num_classes != 2:
                raise ValueError(
                    "Your have set `is_multiclass=True`, but `num_classes` is not equal to 2."
                    " If you are trying to transform multi-label data to 2 class multi-dimensional"
                    " multi-class, you should set `num_classes` to either 2 or None."
                )
            if not is_multiclass and num_classes != implied_classes:
                raise ValueError("The implied number of classes (from shape of inputs) does not match num_classes.")

    if top_k is not None:
        if case == DataType.BINARY:
            raise ValueError("You can not use `top_k` parameter with binary data.")
        if not isinstance(top_k, int) or top_k <= 0:
            raise ValueError("The `top_k` has to be an integer larger than 0.")
        if not preds_float:
            raise ValueError("You have set `top_k`, but you do not have probability predictions.")
        if is_multiclass is False:
            raise ValueError("If you set `is_multiclass=False`, you can not set `top_k`.")
        if case == DataType.MULTILABEL and is_multiclass:
            raise ValueError(
                "If you want to transform multi-label data to 2 class multi-dimensional"
                "multi-class data using `is_multiclass=True`, you can not use `top_k`."
            )
        if top_k >= implied_classes:
            raise ValueError("The `top_k` has to be strictly smaller than the `C` dimension of `preds`.")


def _check_classification_inputs(
    preds: Array,
    target: Array,
    threshold: float,
    num_classes: Optional[int],
    is_multiclass: Optional[bool],
    top_k: Optional[int],
    sum_atol: float = 1e-8,
) -> DataType:
    """Full validation; returns the resolved case. Value checks run only on
    concrete (non-traced) inputs — reference ``_check_classification_inputs``
    (checks.py:207-303)."""
    if preds.shape[:1] != target.shape[:1]:
        raise ValueError("The `preds` and `target` should have the same first dimension.")
    case, implied_classes = _resolve_case(preds, target)
    if preds.ndim == target.ndim + 1 and is_multiclass is False and implied_classes != 2:
        raise ValueError(
            "You have set `is_multiclass=False`, but have more than 2 classes in your data,"
            " based on the C dimension of `preds`."
        )
    _validate_static(case, implied_classes, _is_float(preds), threshold, num_classes, is_multiclass, top_k)
    if is_concrete(preds) and is_concrete(target):
        _validate_values(
            preds, target, case, implied_classes, threshold, num_classes, is_multiclass, sum_atol=sum_atol
        )
    return case


def _input_format_classification(
    preds: Array,
    target: Array,
    threshold: float = 0.5,
    top_k: Optional[int] = None,
    num_classes: Optional[int] = None,
    is_multiclass: Optional[bool] = None,
    validate: bool = True,
) -> Tuple[Array, Array, DataType]:
    """Normalize any (preds, target) pair into binary int arrays ``(N, C)`` or
    ``(N, C, X)`` plus the resolved :class:`DataType` case.

    Behavioral contract identical to reference checks.py:306-445 (see its
    docstring for the full taxonomy). Jit-safe whenever ``num_classes`` is
    given or implied by a ``C`` dim; value validation auto-skips under tracing.

    Inside a :func:`shared_input_format` window (opened by
    ``MetricCollection`` around one step) the result is memoized by argument
    identity, so a collection canonicalizes each batch ONCE across all its
    compute groups.
    """
    preds, target = jnp.asarray(preds), jnp.asarray(target)
    table = getattr(_CANON_MEMO, "table", None)
    key = None
    if table is not None:
        key = _canon_memo_key(
            preds, target, threshold, top_k, num_classes, is_multiclass, validate
        )
        hit = table.get(key)
        if hit is not None:
            return hit[2]
        # pin the key arrays in the table entry: ``id()`` stays unique for
        # the window's lifetime, so a freed array (or tracer) can never be
        # recycled into a colliding key
        memo_pin = (preds, target)
    preds, target = _squeeze_excess_dims(preds, target)

    # accumulate/compare in fp32 (reference upcasts fp16, checks.py:402-403; we also upcast bf16);
    # probability-sum validation tolerance scales with the *original* precision
    sum_atol = 1e-8
    if preds.dtype in (jnp.float16, jnp.bfloat16):
        sum_atol = float(jnp.finfo(preds.dtype).eps) * max(preds.shape[1] if preds.ndim > 1 else 2, 2)
        preds = preds.astype(jnp.float32)

    if validate:
        case = _check_classification_inputs(
            preds, target, threshold=threshold, num_classes=num_classes, is_multiclass=is_multiclass,
            top_k=top_k, sum_atol=sum_atol,
        )
    else:
        case, _ = _resolve_case(preds, target)

    preds_float = _is_float(preds)

    if case in (DataType.BINARY, DataType.MULTILABEL) and not top_k:
        preds = (preds >= threshold).astype(jnp.int32) if preds_float else preds.astype(jnp.int32)
        num_classes = num_classes if not is_multiclass else 2

    if case == DataType.MULTILABEL and top_k:
        preds = select_topk(preds, top_k)

    if case in (DataType.MULTICLASS, DataType.MULTIDIM_MULTICLASS) or is_multiclass:
        if _is_float(preds):
            num_classes = preds.shape[1]
            preds = select_topk(preds, top_k or 1)
        else:
            if num_classes is None:
                if not (is_concrete(preds) and is_concrete(target)):
                    raise TracingUnsupportedError(
                        "Inferring `num_classes` from data values is not possible under jit "
                        "tracing — pass `num_classes` explicitly."
                    )
                num_classes = int(max(jnp.max(preds), jnp.max(target))) + 1
            preds = to_onehot(preds, max(2, num_classes))

        target = to_onehot(target, max(2, num_classes))

        if is_multiclass is False:
            preds, target = preds[:, 1, ...], target[:, 1, ...]

    if (case in (DataType.MULTICLASS, DataType.MULTIDIM_MULTICLASS) and is_multiclass is not False) or is_multiclass:
        target = target.reshape(target.shape[0], target.shape[1], -1)
        preds = preds.reshape(preds.shape[0], preds.shape[1], -1)
    else:
        target = target.reshape(target.shape[0], -1)
        preds = preds.reshape(preds.shape[0], -1)

    # undo the trailing singleton the (N, C, -1) reshape adds for non-multidim data
    if preds.ndim > 2 and preds.shape[-1] == 1:
        preds, target = preds.squeeze(-1), target.squeeze(-1)

    result = preds.astype(jnp.int32), target.astype(jnp.int32), case
    if table is not None:
        table[key] = (*memo_pin, result)
    return result


def _input_format_classification_one_hot(
    num_classes: int,
    preds: Array,
    target: Array,
    threshold: float = 0.5,
    multilabel: bool = False,
) -> Tuple[Array, Array]:
    """Convert inputs to one-hot ``(C, N*...)`` layout (reference checks.py:448-494)."""
    if not (preds.ndim == target.ndim or preds.ndim == target.ndim + 1):
        raise ValueError("preds and target must have same number of dimensions, or one additional dimension for preds")

    if preds.ndim == target.ndim + 1:
        preds = jnp.argmax(preds, axis=1)

    if preds.ndim == target.ndim and jnp.issubdtype(preds.dtype, jnp.integer) and num_classes > 1 and not multilabel:
        preds = to_onehot(preds, num_classes=num_classes)
        target = to_onehot(target, num_classes=num_classes)
    elif preds.ndim == target.ndim and _is_float(preds):
        preds = (preds >= threshold).astype(jnp.int32)

    if preds.ndim > 1:
        preds = jnp.swapaxes(preds, 1, 0)
        target = jnp.swapaxes(target, 1, 0)

    return preds.reshape(num_classes, -1), target.reshape(num_classes, -1)
