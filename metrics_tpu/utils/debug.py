"""Debug-mode race detection (SURVEY §5).

The reference's only race guard is the barrier before all_gather; a rank that
calls ``compute()`` a different number of times deadlocks silently. The TPU
build is deterministic by construction inside jit, but the *host-plane* sync
has the same hazard. With the check enabled, every synced ``compute()`` first
gathers a per-metric sync sequence number and raises if the ranks disagree —
turning a silent desync (wrong pairing of collectives, eventual deadlock)
into an immediate error. Off by default: it costs one extra tiny collective
per synced compute, and every rank must enable it the same way.
"""

_SYNC_COUNT_CHECK = False


def enable_sync_count_check(value: bool = True) -> bool:
    """Toggle the cross-rank sync-sequence check; returns the previous value.

    Must be enabled (or disabled) identically on every process — the check
    itself is a collective.
    """
    global _SYNC_COUNT_CHECK
    old = _SYNC_COUNT_CHECK
    _SYNC_COUNT_CHECK = value
    return old


def sync_count_check_enabled() -> bool:
    return _SYNC_COUNT_CHECK
