"""Typed exception hierarchy for metrics_tpu.

Every error the library raises deliberately derives from
:class:`MetricsTPUError`, so callers can catch "anything metrics_tpu decided
to fail on" with one except clause while still matching specific failure
classes. Exceptions that replaced ad-hoc ``RuntimeError``/``TimeoutError``
raises keep those builtins as secondary bases, so pre-existing callers (and
tests) that matched the builtin keep working.
"""

__all__ = [
    "BufferOverflowError",
    "InjectedFaultError",
    "MetricsTPUError",
    "PreemptionError",
    "StateCorruptionError",
    "SyncTimeoutError",
    "TracingUnsupportedError",
]


class MetricsTPUError(Exception):
    """Base class for library errors."""


class TracingUnsupportedError(MetricsTPUError):
    """Raised when a value-dependent operation is attempted under jit tracing."""


class SyncTimeoutError(MetricsTPUError, TimeoutError):
    """A host-plane sync call exhausted its deadline/retry budget under the
    ``raise`` policy (see ``parallel.sync.SyncGuard``). The ``degrade``
    policy falls back to local-only state instead of raising this."""


class StateCorruptionError(MetricsTPUError):
    """A metric state (or a gathered sync payload) failed an integrity scan:
    non-finite values where none entered, or a saturated integer count."""


class BufferOverflowError(MetricsTPUError, RuntimeError):
    """More rows were appended into a ``PaddedBuffer`` than its capacity holds
    (the overflowed rows were dropped on device). Raised by the ``error``
    overflow policy; the ``warn_drop`` policy warns once and keeps the
    capacity-truncated rows (see ``parallel.buffer.set_overflow_policy``)."""


class PreemptionError(MetricsTPUError):
    """The process is being preempted mid-epoch (SIGTERM analogue; in tests,
    injected by the chaos harness). Never retried by the sync guard —
    callers checkpoint and re-raise/exit. Resume via the epoch watermark
    (``Metric.guarded_update``)."""


class InjectedFaultError(MetricsTPUError):
    """A transient fault injected by ``parallel.faults`` (simulating dropped
    participation or a failed collective). Retryable by the sync guard."""
