"""Exception types for metrics_tpu."""


class MetricsTPUError(Exception):
    """Base class for library errors."""


class TracingUnsupportedError(MetricsTPUError):
    """Raised when a value-dependent operation is attempted under jit tracing."""
