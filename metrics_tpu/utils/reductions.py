"""Score reduction helpers.

Parity targets: ``reduce`` (reference torchmetrics/utilities/distributed.py:20-40)
and ``class_reduce`` (:43-88). They live in ``utils`` here — in the TPU build the
``parallel`` package is reserved for actual cross-device communication.
"""
from typing import Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.utils.enums import AverageMethod


def reduce(to_reduce: Array, reduction: str) -> Array:
    """Reduce a tensor: ``'elementwise_mean'`` | ``'sum'`` | ``'none'``/None."""
    if reduction == "elementwise_mean":
        return jnp.mean(to_reduce)
    if reduction == "none" or reduction is None:
        return to_reduce
    if reduction == "sum":
        return jnp.sum(to_reduce)
    raise ValueError("Reduction parameter unknown.")


def class_reduce(num: Array, denom: Array, weights: Array, class_reduction: str = "none") -> Array:
    """Reduce per-class scores ``num/denom``: micro | macro | weighted | none.

    NaN-free by construction: 0/0 entries become 0, exactly as the reference's
    ``fraction[fraction != fraction] = 0`` guard does for every reduction mode.
    """
    valid_reduction = ("micro", "macro", "weighted", "none", None)
    fraction = jnp.sum(num) / jnp.sum(denom) if class_reduction == "micro" else num / denom

    # nan-guard: 0/0 becomes 0 (applies to micro as well, reference distributed.py:74)
    fraction = jnp.where(jnp.isnan(fraction), jnp.zeros_like(fraction), fraction)

    if class_reduction == "micro":
        return fraction
    if class_reduction == "macro":
        return jnp.mean(fraction)
    if class_reduction == "weighted":
        return jnp.sum(fraction * (weights / jnp.sum(weights)))
    if class_reduction == "none" or class_reduction is None:
        return fraction

    raise ValueError(f"Reduction parameter {class_reduction} unknown. Choose between one of these: {valid_reduction}")


# re-export averaging enum for convenience
__all__ = ["reduce", "class_reduce", "AverageMethod"]
