from metrics_tpu.utils.data import (
    ClassScores,
    apply_to_collection,
    dim_zero_cat,
    dim_zero_max,
    dim_zero_mean,
    dim_zero_min,
    dim_zero_sum,
    get_group_indexes,
    get_num_classes,
    select_topk,
    to_categorical,
    to_onehot,
)
from metrics_tpu.utils.enums import AverageMethod, DataType, MDMCAverageMethod
from metrics_tpu.utils.prints import rank_zero_debug, rank_zero_info, rank_zero_warn
from metrics_tpu.utils.reductions import class_reduce, reduce
