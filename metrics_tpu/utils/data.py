"""Tensor/data transforms shared by the functional kernels.

Parity targets (behavior, not code) in reference ``torchmetrics/utilities/data.py``:
``dim_zero_cat/sum/mean`` (data.py:24-38), ``to_onehot`` (:41-74),
``select_topk`` (:77-98), ``to_categorical`` (:101-118), ``get_num_classes``
(:121-150), ``apply_to_collection`` (:182-230), ``get_group_indexes`` (:233-259).

TPU-native differences:
 - one-hot / top-k are built from ``jax.nn.one_hot`` / ``jax.lax.top_k``
   (gather/scatter-free, MXU/VPU friendly) instead of ``Tensor.scatter_``.
 - ``_stable_1d_sort`` (reference data.py:153-179) is intentionally absent:
   XLA's sort is stable, so callers just use ``jnp.sort``/``jnp.argsort``.
 - class-count inference from data values is an eager-only convenience; under
   ``jax.jit`` tracing callers must pass ``num_classes`` statically.
"""
from typing import Any, Callable, List, Mapping, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.utils.prints import rank_zero_warn


def dim_zero_cat(x: Union[Array, List[Array]]) -> Array:
    """Concatenate a (list of) array(s) along dim 0."""
    x = x if isinstance(x, (list, tuple)) else [x]
    x = [jnp.atleast_1d(v) for v in x]
    return jnp.concatenate(x, axis=0)


class ClassScores(list):
    """Per-class results: a list (reference shape parity) with the backing
    device array attached.

    The reference returns ``average=None`` / multiclass curve summaries as a
    LIST of per-class scalars (reference functional/classification/auroc.py:100);
    iterating ``float(s)`` over such a list costs one device readback per
    class — ~100 ms each through a remote-device tunnel, C round trips for a
    C-class metric. The scores here are views of ONE ``(C,)`` device array,
    exposed as ``.array``: ``np.asarray(scores.array)`` reads every class
    back in a single transfer. Iteration / indexing / equality behave exactly
    like the reference's list, and the type is a registered pytree node whose
    children are the per-class elements, so ``tree_map`` / ``vmap`` / the
    batched-forward scan recurse into it exactly as they would a plain list
    (rebuilding re-stacks the backing array).
    """

    __slots__ = ("array",)

    def __init__(self, values):
        if isinstance(values, Array):  # incl. tracers: jax.Array is the ABC
            arr = values
            items = arr
        else:  # per-class elements (pytree unflatten, apply_to_collection)
            items = list(values)
            try:
                if items and all(isinstance(x, np.ndarray) for x in items):
                    # host elements (e.g. jax.device_get) must NOT round-trip
                    # back through the device — stack on the host
                    arr = np.stack(items)
                elif items:
                    arr = jnp.stack(items)
                else:
                    arr = jnp.zeros((0,), jnp.float32)
            except TypeError:
                # structure-only leaves (eval_shape ShapeDtypeStructs,
                # tree_map to None, ...): stay a plain list; the .array
                # contract only holds for array elements
                arr = None
        super().__init__(items if arr is None else arr)
        self.array = arr

    def __reduce__(self):
        if self.array is None:
            return (list, (list(self),))
        return (ClassScores, (self.array,))


jax.tree_util.register_pytree_node(
    ClassScores,
    lambda s: (tuple(s), None),
    lambda _, children: ClassScores(children),
)


def dim_zero_sum(x: Array) -> Array:
    return jnp.sum(x, axis=0)


def dim_zero_mean(x: Array) -> Array:
    return jnp.mean(x, axis=0)


def dim_zero_min(x: Array) -> Array:
    return jnp.min(x, axis=0)


def dim_zero_max(x: Array) -> Array:
    return jnp.max(x, axis=0)


def _flatten(x: Sequence) -> list:
    return [item for sublist in x for item in sublist]


def is_concrete(x: Any) -> bool:
    """True when ``x`` is a concrete (non-traced) array whose values are readable."""
    return not isinstance(x, jax.core.Tracer)


def in_tracing_context() -> bool:
    """True when called under an active trace (jit staging, vmap, grad, ...).

    Closure constants stay concrete at function entry even under jit, so
    ``is_concrete(arg)`` cannot tell whether downstream ops will produce
    tracers; the dynamic trace state answers that without dispatching any
    device computation.
    """
    try:
        from jax._src.core import trace_state_clean

        return not trace_state_clean()
    except ImportError:  # future jax moved it: fall back to a zero-dim op probe
        return isinstance(jnp.zeros((), jnp.int32) + 0, jax.core.Tracer)


def upcast_accum(x: Array) -> Array:
    """Upcast low-precision floats to fp32 before accumulation.

    The dtype policy (SURVEY §7 hard part 6): inputs may be bf16/fp16 (the
    TPU-native activation dtype; the reference upcasts fp16 in its
    classification formatting, checks.py:402-403) but sums of errors/moments
    accumulate in fp32 so epoch-scale reductions don't lose precision.
    """
    if x.dtype in (jnp.bfloat16, jnp.float16):
        return x.astype(jnp.float32)
    return x


def accum_int_dtype():
    """Dtype for count-accumulator states: int64 when x64 is enabled, else int32.

    The reference accumulates counts in int64 (torch ``.long()``); JAX
    canonicalizes int64 away unless ``jax_enable_x64`` is set. Pod-scale
    element counts (>2^31) therefore need ``jax.config.update("jax_enable_x64",
    True)`` — with it on, all accumulator states get full int64 parity.
    """
    return jnp.int64 if jax.config.jax_enable_x64 else jnp.int32


def to_onehot(label_tensor: Array, num_classes: Optional[int] = None) -> Array:
    """Convert an ``(N, ...)`` integer label array to a one-hot ``(N, C, ...)`` array.

    Mirrors reference ``to_onehot`` (data.py:41-74) incl. inferring ``C`` from
    ``label_tensor.max()+1`` when unset — that inference is eager-only.

    Example:
        >>> import jax.numpy as jnp
        >>> to_onehot(jnp.array([0, 1, 2]), num_classes=3)
        Array([[1, 0, 0],
               [0, 1, 0],
               [0, 0, 1]], dtype=int32)
    """
    if num_classes is None:
        if not is_concrete(label_tensor):
            raise ValueError(
                "`num_classes` must be given explicitly when tracing under jit; "
                "inference from data values requires concrete arrays."
            )
        num_classes = int(jnp.max(label_tensor)) + 1
    if label_tensor.dtype == jnp.bool_:
        label_tensor = label_tensor.astype(jnp.int32)
    onehot = jax.nn.one_hot(label_tensor, num_classes, dtype=jnp.int32)
    # (N, ..., C) -> (N, C, ...)
    return jnp.moveaxis(onehot, -1, 1)


def select_topk(prob_tensor: Array, topk: int = 1, dim: int = 1) -> Array:
    """Binary array with 1s at the ``topk`` largest entries along ``dim``.

    Mirrors reference ``select_topk`` (data.py:77-98).

    Example:
        >>> import jax.numpy as jnp
        >>> x = jnp.array([[1.1, 2.0, 3.0], [2.0, 1.0, 0.5]])
        >>> select_topk(x, topk=2)
        Array([[0, 1, 1],
               [1, 1, 0]], dtype=int32)
    """
    moved = jnp.moveaxis(prob_tensor, dim, -1)
    _, idx = jax.lax.top_k(moved, topk)
    onehot = jax.nn.one_hot(idx, moved.shape[-1], dtype=jnp.int32).sum(axis=-2)
    return jnp.moveaxis(onehot, -1, dim).astype(jnp.int32)


def to_categorical(tensor: Array, argmax_dim: int = 1) -> Array:
    """Argmax along ``argmax_dim`` (reference data.py:101-118).

    Example:
        >>> import jax.numpy as jnp
        >>> to_categorical(jnp.array([[0.2, 0.5], [0.9, 0.1]]))
        Array([1, 0], dtype=int32)
    """
    return jnp.argmax(tensor, axis=argmax_dim).astype(jnp.int32)


def get_num_classes(preds: Array, target: Array, num_classes: Optional[int] = None) -> int:
    """Infer/validate the number of classes (reference data.py:121-150). Eager-only inference."""
    if num_classes is None and not (is_concrete(preds) and is_concrete(target)):
        raise ValueError("`num_classes` must be given explicitly when tracing under jit.")
    if num_classes is None:
        num_pred_classes = int(jnp.max(preds)) + 1
        num_target_classes = int(jnp.max(target)) + 1
        num_classes = max(num_pred_classes, num_target_classes)
    elif is_concrete(preds) and is_concrete(target):
        num_target_classes = int(jnp.max(target)) + 1
        num_pred_classes = int(jnp.max(preds)) + 1 if jnp.issubdtype(preds.dtype, jnp.integer) else num_classes
        if num_classes != max(num_pred_classes, num_target_classes):
            rank_zero_warn(
                f"You have set {num_classes} number of classes which is"
                f" different from predicted ({num_pred_classes}) and"
                f" target ({num_target_classes}) number of classes",
                RuntimeWarning,
            )
    return num_classes


def apply_to_collection(
    data: Any,
    dtype: Union[type, tuple],
    function: Callable,
    *args: Any,
    **kwargs: Any,
) -> Any:
    """Recursively apply ``function`` to all elements of type ``dtype`` in a collection.

    Mirrors reference ``apply_to_collection`` (data.py:182-230).

    Example:
        >>> apply_to_collection({"a": 2, "b": [1, 2]}, int, lambda x: x * 2)
        {'a': 4, 'b': [2, 4]}
    """
    elem_type = type(data)

    if isinstance(data, dtype):
        return function(data, *args, **kwargs)

    if isinstance(data, Mapping):
        return elem_type({k: apply_to_collection(v, dtype, function, *args, **kwargs) for k, v in data.items()})

    if isinstance(data, tuple) and hasattr(data, "_fields"):  # namedtuple
        return elem_type(*(apply_to_collection(d, dtype, function, *args, **kwargs) for d in data))

    if isinstance(data, Sequence) and not isinstance(data, str):
        return elem_type([apply_to_collection(d, dtype, function, *args, **kwargs) for d in data])

    return data


def get_group_indexes(idx: Array) -> List[Array]:
    """Group positions by the value of ``idx`` (reference data.py:233-259).

    Eager/host-side for API parity; the TPU retrieval path avoids this entirely
    by using sorted segment ops (see ``metrics_tpu/functional/retrieval``).

    Example:
        >>> import jax.numpy as jnp
        >>> [g.tolist() for g in get_group_indexes(jnp.array([0, 0, 1, 1, 1]))]
        [[0, 1], [2, 3, 4]]
    """
    idx_np = np.asarray(idx)
    res: dict = {}
    for i, v in enumerate(idx_np.tolist()):
        res.setdefault(v, []).append(i)
    return [jnp.asarray(g, dtype=jnp.int32) for g in res.values()]
