"""Timing helpers for the baseline harness (SURVEY §5: the reference ships no
in-library tracing; its CI only records pytest durations. The TPU build adds
an explicit ``block_until_ready`` timer so per-metric costs are measurable
without a profiler attached; for deep traces use ``jax.profiler``.)"""
import time
from typing import Any, Callable, Dict

import jax


def time_fn(fn: Callable, *args: Any, iters: int = 50, warmup: int = 5, **kwargs: Any) -> float:
    """Wall-clock ms per call of ``fn(*args, **kwargs)``, device-synchronized.

    Warms up (compilation + caches), blocks on the last output, then times
    ``iters`` calls ending with ``jax.block_until_ready`` — the only correct
    way to time dispatch-asynchronous JAX code.
    """
    out = None
    for _ in range(warmup):
        out = fn(*args, **kwargs)
    if out is not None:
        jax.block_until_ready(out)
    start = time.perf_counter()
    for _ in range(iters):
        out = fn(*args, **kwargs)
    if out is not None:
        jax.block_until_ready(out)
    return (time.perf_counter() - start) / iters * 1e3


def profile_metric(metric: Any, *args: Any, iters: int = 50, **kwargs: Any) -> Dict[str, float]:
    """ms/call of a metric's pure ``update`` and ``compute`` on the given batch.

    Uses the pure view so repeated updates see identical shapes (no state
    growth) and nothing mutates the caller's metric.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import Accuracy
        >>> times = profile_metric(Accuracy(), jnp.array([1, 0]), jnp.array([1, 1]), iters=2)
        >>> sorted(times)
        ['compute_ms', 'update_ms']
    """
    pure = metric.pure()
    init = pure.init()
    update_ms = time_fn(lambda: pure.update(init, *args, **kwargs), iters=iters)
    state = pure.update(init, *args, **kwargs)
    compute_ms = time_fn(lambda: pure.compute(state), iters=iters)
    return {"update_ms": update_ms, "compute_ms": compute_ms}
