"""Version-compat shims over the moving parts of the jax API surface.

The TPU image ships a current jax where ``shard_map`` lives at ``jax.shard_map``
with a ``check_vma`` argument, mesh-axis sizes come from ``jax.lax.axis_size``,
and manual-axes varying types are managed with ``jax.typeof`` / ``jax.lax.pcast``.
The CPU CI image pins jax 0.4.37, where none of those exist: ``shard_map`` is
``jax.experimental.shard_map.shard_map(check_rep=...)``, the in-``shard_map``
axis size comes from ``jax.core.axis_frame``, and there is no varying/invariant
type system at all. Every call site in the library routes through this module
so both images run the same code path (the approach ``bench.py`` and
``tests/bases/test_compute_groups.py`` already used locally, centralized).

Resolution happens once at import: the shims bind the right implementation for
the running jax instead of re-probing per call (these sit on trace-time hot
paths).
"""
from typing import Any, Callable

import jax

__all__ = ["shard_map", "axis_size", "ensure_varying", "under_trace", "HAS_VMA"]

# Whether this jax has the varying-manual-axes (vma) type system for shard_map
# bodies. Without it, every value inside shard_map is implicitly varying and
# ``ensure_varying`` is the identity.
HAS_VMA = hasattr(jax, "typeof") and hasattr(jax.lax, "pcast")


if getattr(jax, "shard_map", None) is not None:

    def shard_map(fn: Callable, mesh: Any, in_specs: Any, out_specs: Any, check_vma: bool = True) -> Callable:
        """``jax.shard_map`` with the current-jax ``check_vma`` argument."""
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=check_vma)

else:
    from jax.experimental.shard_map import shard_map as _shard_map_old

    def shard_map(fn: Callable, mesh: Any, in_specs: Any, out_specs: Any, check_vma: bool = True) -> Callable:
        """Old-jax ``shard_map``; ``check_vma`` maps onto ``check_rep`` (the
        replication check is the closest ancestor of the vma check — both
        verify that ``out_specs``-replicated outputs really are invariant)."""
        return _shard_map_old(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=check_vma)


if hasattr(jax.lax, "axis_size"):

    def _one_axis_size(axis_name: str) -> int:
        return jax.lax.axis_size(axis_name)

else:

    def _one_axis_size(axis_name: str) -> int:
        # jax 0.4.37: ``jax.core.axis_frame(name)`` resolves the bound axis
        # and returns its size directly (an int under shard_map tracing)
        from jax.core import axis_frame

        frame = axis_frame(axis_name)
        return frame if isinstance(frame, int) else frame.size


def axis_size(axis_name: Any) -> int:
    """Size of a named mesh axis (or product over a tuple of axes — the
    flat world span of a 2-level mesh), from inside ``shard_map``/``pmap``."""
    if isinstance(axis_name, (tuple, list)):
        size = 1
        for a in axis_name:
            size *= _one_axis_size(a)
        return size
    return _one_axis_size(axis_name)


if HAS_VMA:

    def ensure_varying(x: Any, axis_name: str) -> Any:
        """Mark ``x`` varying over ``axis_name`` if it isn't already.

        Constants built inside a ``shard_map`` body (None-weight fallbacks,
        all-zero targets) are invariant-typed; feeding them into a ``ppermute``
        ring makes the loop carry's manual-axes type flip mid-loop. ``pvary``
        itself rejects already-varying input, hence the check.
        """
        vma = getattr(jax.typeof(x), "vma", frozenset())
        if axis_name in vma:
            return x
        return jax.lax.pcast(x, (axis_name,), to="varying")

else:

    def ensure_varying(x: Any, axis_name: str) -> Any:
        """No vma type system on this jax: every shard_map value is varying."""
        return x


def under_trace() -> bool:
    """Whether the caller is running under a jax trace (jit/vmap/scan body)."""
    try:
        import jax.core as _core

        return type(_core.trace_ctx.trace).__name__ != "EvalTrace"
    except AttributeError:
        pass
    try:
        from jax.core import trace_state_clean

        return not trace_state_clean()
    except ImportError:  # jax moved the API again; be conservative
        return False
