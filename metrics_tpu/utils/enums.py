"""String-valued enums shared across the library.

Behavioral parity target: reference ``torchmetrics/utilities/enums.py``
(``EnumStr`` at enums.py:18, ``DataType`` at :48, ``AverageMethod`` at :61,
``MDMCAverageMethod`` at :79) — re-designed, not copied: these are plain
``str`` subclass enums with case/space/dash-insensitive lookup.
"""
from enum import Enum
from typing import Optional, Union


class EnumStr(str, Enum):
    """String enum with forgiving lookup: case-insensitive, '-'/' ' treated as '_'."""

    @classmethod
    def from_str(cls, value: str) -> Optional["EnumStr"]:
        try:
            return cls[value.replace("-", "_").replace(" ", "_").upper()]
        except KeyError:
            return None

    def __eq__(self, other: Union[str, Enum, None]) -> bool:
        other = other.value if isinstance(other, Enum) else str(other)
        return self.value.lower() == other.lower()

    def __hash__(self) -> int:
        return hash(self.value.lower())


class DataType(EnumStr):
    """Classification input-type taxonomy (reference enums.py:48-58)."""

    BINARY = "binary"
    MULTILABEL = "multi-label"
    MULTICLASS = "multi-class"
    MULTIDIM_MULTICLASS = "multi-dim multi-class"


class AverageMethod(EnumStr):
    """Averaging strategies for per-class scores (reference enums.py:61-76)."""

    MICRO = "micro"
    MACRO = "macro"
    WEIGHTED = "weighted"
    NONE = "none"
    SAMPLES = "samples"


class MDMCAverageMethod(EnumStr):
    """Multi-dim multi-class handling (reference enums.py:79-83)."""

    GLOBAL = "global"
    SAMPLEWISE = "samplewise"
