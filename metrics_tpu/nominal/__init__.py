"""Stateful nominal-association metrics (an extension family; later torchmetrics ships ``nominal/``).

All four stream the same ``(num_classes_preds, num_classes_target)``
contingency matrix (one-hot MXU contraction, one sum-reducible int32
state); see ``metrics_tpu/functional/nominal.py`` for the formulas and
oracles.
"""
from metrics_tpu.nominal.association import (
    CramersV,
    PearsonsContingencyCoefficient,
    TheilsU,
    TschuprowsT,
)

__all__ = ["CramersV", "PearsonsContingencyCoefficient", "TheilsU", "TschuprowsT"]
